"""Device-batched composite window operators: the TPU twins of the
reference's GPU operator family (SURVEY.md §2.5).

* KeyFarmTPU       <- key_farm_gpu.hpp (751)
* WinFarmTPU       <- win_farm_gpu.hpp (782)
* PaneFarmTPU      <- pane_farm_gpu.hpp (1028): PLQ *or* WLQ on device
* WinMapReduceTPU  <- win_mapreduce_gpu.hpp (1046): MAP *or* REDUCE on device
* WinSeqFFATTPU    <- win_seqffat_gpu.hpp (734): lift on host, FlatFAT
                      aggregation on device (ops/flatfat_jax)
* KeyFFATTPU       <- key_ffat_gpu.hpp (345)

All reuse the CPU composites' WinOperatorConfig arithmetic; only the
engine replica type changes (WinSeqTPULogic instead of WinSeqLogic) --
mirroring how the reference swaps Win_Seq for Win_Seq_GPU inside the
same farm skeletons (win_farm_gpu.hpp:82-86).

A device stage's window function is a ``win_kind``: a builtin combine
name ('sum'/'count'/'mean'/'max'/'min'), a JAX callable
``fn(gwid, cols, mask) -> value`` (the __host__ __device__ functor
analogue, API:104-132), or for FFAT ops a (lift, combine[, neutral])
pair with the combine either builtin or a JAX binary function.
"""
from __future__ import annotations

from typing import Any, Callable

from ...core.basic import (OptLevel, OrderingMode, Pattern, Role,
                           RoutingMode, WinOperatorConfig, WinType)
from ...core.tuples import BasicRecord
from ...core.win_assign import pane_length
from ...runtime.emitters import StandardEmitter
from ...runtime.win_routing import KFEmitter, WFEmitter, WidOrderCollector, \
    WinMapEmitter
from ..base import Operator, StageSpec
from ..win_seq import WinSeqLogic
from .win_seq_tpu import (DEFAULT_BATCH_LEN, DEFAULT_INFLIGHT_DEPTH,
                          DEFAULT_MAX_BATCH_DELAY_MS,
                          DEFAULT_MAX_BUFFER_ELEMS, WinSeqTPULogic)


def _tpu_replicas(win_kind, win_len, slide_len, win_type, par, *,
                  batch_len, triggering_delay, result_factory, value_of,
                  enclosing: WinOperatorConfig, role: Role,
                  farm_kind: str, renumbering=False, emit_batches=False,
                  max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS,
                  inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                  max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS,
                  placement="device", adaptive_batch=False):
    """Build the worker set with the same config conventions as the CPU
    farms (win_farm.hpp:175 / key_farm worker configs)."""
    reps = []
    for i in range(par):
        if farm_kind == "wf":
            cfg = WinOperatorConfig(enclosing.id_inner, enclosing.n_inner,
                                    enclosing.slide_inner, i, par, slide_len)
            slide = slide_len * par
        elif farm_kind == "kf":
            cfg = WinOperatorConfig(enclosing.id_inner, enclosing.n_inner,
                                    enclosing.slide_inner, 0, 1, slide_len)
            slide = slide_len
        else:  # map stage / single engine
            cfg = WinOperatorConfig(enclosing.id_inner, enclosing.n_inner,
                                    enclosing.slide_inner, 0, 1, slide_len)
            slide = slide_len
        reps.append(WinSeqTPULogic(
            win_kind, win_len, slide, win_type, batch_len=batch_len,
            triggering_delay=triggering_delay, result_factory=result_factory,
            config=cfg, role=role,
            map_indexes=(i, par) if role == Role.MAP else (0, 1),
            parallelism=par, replica_index=i, renumbering=renumbering,
            value_of=value_of, emit_batches=emit_batches,
            max_buffer_elems=max_buffer_elems, inflight_depth=inflight_depth,
            max_batch_delay_ms=max_batch_delay_ms, placement=placement,
            adaptive_batch=adaptive_batch))
    return reps


class _TPUWinOp(Operator):
    def __init__(self, name, parallelism, routing, pattern, win_type):
        super().__init__(name, parallelism, routing, pattern)
        self.win_type = win_type
        self._renumbering = False

    def enable_renumbering(self):
        self._renumbering = True

    def _ordering(self):
        return (OrderingMode.ID if self.win_type == WinType.CB
                else OrderingMode.TS)


class KeyFarmTPU(_TPUWinOp):
    """Key-sharded device windows (key_farm_gpu.hpp:751).

    ``coalesce`` (default on): replicas of this farm all dispatch to the
    SAME local device -- a key split across N engine replicas buys no
    device parallelism, it only multiplies host dispatcher threads that
    contend for the ingest core and serialize launches.  The farm
    therefore lowers to ONE engine handling every key per launch (the
    engine batches many keys natively; the double-buffer protocol of
    win_seq_gpu.hpp:267-297 rides one launch stream).  Key-partitioned
    scale-out across chips is the mesh plane's job
    (operators/tpu/mesh_farm.KeyFarmMesh).  ``coalesce=False`` keeps
    the literal N-replica farm (the reference's per-GPU structure)."""

    def __init__(self, win_kind, win_len, slide_len, win_type,
                 parallelism=1, batch_len=DEFAULT_BATCH_LEN,
                 triggering_delay=0, name="key_farm_tpu",
                 result_factory=BasicRecord, value_of=None,
                 config: WinOperatorConfig = None, emit_batches=False,
                 max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS,
                 coalesce=True, inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                 max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS,
                 placement="device", adaptive_batch=False):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.KEY_FARM_TPU, win_type)
        self.placement = placement
        self.adaptive_batch = adaptive_batch
        self.args = (win_kind, win_len, slide_len, win_type)
        self.batch_len = batch_len
        self.triggering_delay = triggering_delay
        self.result_factory = result_factory
        self.value_of = value_of
        self.config = config or WinOperatorConfig(0, 1, 0, 0, 1, 0)
        self.emit_batches = emit_batches
        self.max_buffer_elems = max_buffer_elems
        self.coalesce = coalesce
        self.inflight_depth = inflight_depth
        self.max_batch_delay_ms = max_batch_delay_ms

    def stages(self):
        kind, win_len, slide_len, win_type = self.args
        # every kf replica runs the identical engine config (the key
        # subset comes only from the emitter hash), so one engine over
        # all keys computes the same windows
        par = 1 if self.coalesce else self.parallelism
        reps = _tpu_replicas(
            kind, win_len, slide_len, win_type, par,
            batch_len=self.batch_len, triggering_delay=self.triggering_delay,
            result_factory=self.result_factory, value_of=self.value_of,
            enclosing=self.config, role=Role.SEQ, farm_kind="kf",
            renumbering=self._renumbering, emit_batches=self.emit_batches,
            max_buffer_elems=self.max_buffer_elems,
            inflight_depth=self.inflight_depth,
            max_batch_delay_ms=self.max_batch_delay_ms,
            placement=self.placement, adaptive_batch=self.adaptive_batch)
        return [StageSpec(self.name, reps, KFEmitter(par),
                          self.routing, ordering_mode=self._ordering())]


class WinFarmTPU(_TPUWinOp):
    def __init__(self, win_kind, win_len, slide_len, win_type,
                 parallelism=1, batch_len=DEFAULT_BATCH_LEN,
                 triggering_delay=0, name="win_farm_tpu",
                 result_factory=BasicRecord, value_of=None, ordered=True,
                 opt_level=OptLevel.LEVEL0,
                 config: WinOperatorConfig = None, role: Role = Role.SEQ,
                 max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS,
                 inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                 max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS,
                 placement="device", adaptive_batch=False):
        super().__init__(name, parallelism, RoutingMode.COMPLEX,
                         Pattern.WIN_FARM_TPU, win_type)
        self.placement = placement
        self.adaptive_batch = adaptive_batch
        self.max_buffer_elems = max_buffer_elems
        self.inflight_depth = inflight_depth
        self.max_batch_delay_ms = max_batch_delay_ms
        self.args = (win_kind, win_len, slide_len, win_type)
        self.batch_len = batch_len
        self.triggering_delay = triggering_delay
        self.result_factory = result_factory
        self.value_of = value_of
        self.ordered = ordered
        self.opt_level = opt_level
        self.config = config or WinOperatorConfig(0, 1, 0, 0, 1, 0)
        self.role = role

    def stages(self):
        kind, win_len, slide_len, win_type = self.args
        cfg = self.config
        reps = _tpu_replicas(
            kind, win_len, slide_len, win_type, self.parallelism,
            batch_len=self.batch_len, triggering_delay=self.triggering_delay,
            result_factory=self.result_factory, value_of=self.value_of,
            enclosing=cfg, role=self.role, farm_kind="wf",
            max_buffer_elems=self.max_buffer_elems,
            inflight_depth=self.inflight_depth,
            max_batch_delay_ms=self.max_batch_delay_ms,
            placement=self.placement, adaptive_batch=self.adaptive_batch)
        emitter = WFEmitter(win_len, slide_len, self.parallelism, win_type,
                            self.role, id_outer=cfg.id_inner,
                            n_outer=cfg.n_inner, slide_outer=cfg.slide_inner)
        collector = (WidOrderCollector()
                     if self.ordered and self.opt_level == OptLevel.LEVEL0
                     else None)
        return [StageSpec(self.name, reps, emitter, self.routing,
                          ordering_mode=self._ordering(),
                          collector=collector)]


class PaneFarmTPU(_TPUWinOp):
    """PLQ or WLQ on device (pane_farm_gpu.hpp:105-106): the device stage
    takes a win_kind; the host stage takes a Python callable, or -- for
    a host WLQ -- a builtin name ('sum'/'max'/'min'), which runs the
    columnar pane->window combine (pane_combine.PaneCombineLogic)
    instead of the per-record engine.  ``emit_batches`` applies to that
    columnar WLQ only; callable/device WLQ stages emit records."""

    def __init__(self, plq: Any, wlq: Any, win_len, slide_len, win_type,
                 plq_parallelism=1, wlq_parallelism=1, plq_on_tpu=True,
                 wlq_on_tpu=False, batch_len=DEFAULT_BATCH_LEN,
                 triggering_delay=0, name="pane_farm_tpu",
                 result_factory=BasicRecord, value_of=None, ordered=True,
                 opt_level=OptLevel.LEVEL0,
                 config: WinOperatorConfig = None,
                 max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS,
                 inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                 max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS,
                 emit_batches=False, placement="device",
                 adaptive_batch=False):
        super().__init__(name, plq_parallelism + wlq_parallelism,
                         RoutingMode.COMPLEX, Pattern.PANE_FARM_TPU,
                         win_type)
        self.placement = placement
        self.adaptive_batch = adaptive_batch
        if plq_on_tpu == wlq_on_tpu:
            raise ValueError(
                "exactly one of PLQ/WLQ must run on device "
                "(pane_farm_gpu.hpp constraint, API:134)")
        if win_len <= slide_len:
            # pane_farm.hpp:170-173 (same check on the GPU twin): with
            # slide >= win the pane decomposition degenerates
            raise ValueError(
                f"Pane_Farm requires sliding windows (slide < win); got "
                f"win={win_len} slide={slide_len}. Inside a Win_Farm the "
                f"private slide is slide*replicas, so nesting needs "
                f"win > slide*replicas")
        self.plq = plq
        self.wlq = wlq
        self.win_len = win_len
        self.slide_len = slide_len
        self.plq_par = plq_parallelism
        self.wlq_par = wlq_parallelism
        self.plq_on_tpu = plq_on_tpu
        self.batch_len = batch_len
        self.triggering_delay = triggering_delay
        self.result_factory = result_factory
        self.value_of = value_of
        self.ordered = ordered
        self.opt_level = opt_level
        self.pane_len = pane_length(win_len, slide_len)
        self.max_buffer_elems = max_buffer_elems
        self.inflight_depth = inflight_depth
        self.max_batch_delay_ms = max_batch_delay_ms
        self.emit_batches = emit_batches
        # enclosing config: identity standalone, nested arithmetic when
        # replicated inside a Win_Farm/Key_Farm (win_farm_gpu.hpp:73-76)
        self.config = config or WinOperatorConfig(0, 1, slide_len,
                                                  0, 1, slide_len)
        if plq_on_tpu and isinstance(wlq, str):
            from .pane_combine import WLQ_KINDS
            if wlq not in WLQ_KINDS:
                raise ValueError(
                    f"host WLQ builtin must be one of "
                    f"{sorted(WLQ_KINDS)}: {wlq!r}")
        # a builtin-name WLQ on the host runs the columnar pane->window
        # combine instead of the per-record engine -- but only under an
        # identity config: PaneCombineLogic has no id_inner/n_inner
        # arithmetic, so nested copies (which offset and stripe window
        # ids per copy) must stay on the stock per-record WLQ
        cfg = self.config
        self._wlq_columnar = (plq_on_tpu and isinstance(wlq, str)
                              and cfg.n_outer == 1 and cfg.n_inner == 1
                              and cfg.id_outer == 0 and cfg.id_inner == 0)

    def _device_single(self, kind, win, slide, win_type, role, delay,
                       emit_batches=False):
        """One device engine replica (shared by the fused path and the
        par-1 stage branches -- the config arithmetic lives here)."""
        return _tpu_replicas(
            kind, win, slide, win_type, 1, batch_len=self.batch_len,
            triggering_delay=delay, result_factory=self.result_factory,
            value_of=self.value_of, enclosing=self.config, role=role,
            farm_kind="seq", emit_batches=emit_batches,
            max_buffer_elems=self.max_buffer_elems,
            inflight_depth=self.inflight_depth,
            max_batch_delay_ms=self.max_batch_delay_ms,
            placement=self.placement,
            adaptive_batch=self.adaptive_batch)[0]

    def _columnar_wlq(self, wlq_win, wlq_slide):
        from .pane_combine import PaneCombineLogic
        return PaneCombineLogic(self.wlq, wlq_win, wlq_slide,
                                result_factory=self.result_factory,
                                emit_batches=self.emit_batches)

    def _wlq_fn(self):
        """The host WLQ as a callable: builtin names map to the stock
        per-record aggregation (builtin_win_func) so nested copies
        (non-identity config) can run the per-record engine."""
        if not isinstance(self.wlq, str):
            return self.wlq
        from ..win_seq import builtin_win_func
        return builtin_win_func(self.wlq)

    def _host_single(self, fn, win, slide, win_type, role, delay=0):
        cfg = self.config
        return WinSeqLogic(
            fn, win, slide, win_type, triggering_delay=delay,
            result_factory=self.result_factory,
            config=WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                     cfg.slide_inner, 0, 1, slide),
            role=role)

    def _fused_stage(self):
        """LEVEL1/2 single/single thread fusion (ff_comb of
        optimize_PaneFarm, pane_farm.hpp:222-250): the device stage and
        the host stage run chained in one thread.  The device logic's
        async dispatcher keeps overlapping launches; the chained
        consumer runs on whichever thread flushes the batch."""
        from ...runtime.node import ChainedLogic
        pane = self.pane_len
        wlq_win = self.win_len // pane
        wlq_slide = self.slide_len // pane
        if self.plq_on_tpu:
            plq = self._device_single(self.plq, pane, pane, self.win_type,
                                      Role.PLQ, self.triggering_delay,
                                      emit_batches=self._wlq_columnar)
            wlq = (self._columnar_wlq(wlq_win, wlq_slide)
                   if self._wlq_columnar
                   else self._host_single(self._wlq_fn(), wlq_win,
                                          wlq_slide, WinType.CB, Role.WLQ))
        else:
            plq = self._host_single(self.plq, pane, pane, self.win_type,
                                    Role.PLQ, self.triggering_delay)
            wlq = self._device_single(self.wlq, wlq_win, wlq_slide,
                                      WinType.CB, Role.WLQ, 0)
        return [StageSpec(
            f"{self.name}_fused", [ChainedLogic(plq, wlq)],
            StandardEmitter(), RoutingMode.FORWARD,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS))]

    def stages(self):
        if (self.opt_level != OptLevel.LEVEL0
                and self.plq_par == 1 and self.wlq_par == 1):
            return self._fused_stage()
        cfg = self.config
        pane = self.pane_len
        stages = []
        # ---- PLQ ----
        if self.plq_on_tpu:
            reps = _tpu_replicas(
                self.plq, pane, pane, self.win_type, self.plq_par,
                batch_len=self.batch_len,
                triggering_delay=self.triggering_delay,
                result_factory=self.result_factory, value_of=self.value_of,
                enclosing=cfg, role=Role.PLQ,
                farm_kind="wf" if self.plq_par > 1 else "seq",
                emit_batches=self._wlq_columnar and self.plq_par == 1,
                max_buffer_elems=self.max_buffer_elems,
                inflight_depth=self.inflight_depth,
                max_batch_delay_ms=self.max_batch_delay_ms)
            # the enclosing offsets shift pane membership when this
            # operator is a nested copy (the configSeq construction,
            # win_farm.hpp:175; emitter without them routes panes
            # relative to 0 and starves the copy's workers)
            emitter = (WFEmitter(pane, pane, self.plq_par, self.win_type,
                                 Role.PLQ, id_outer=cfg.id_inner,
                                 n_outer=cfg.n_inner,
                                 slide_outer=cfg.slide_inner)
                       if self.plq_par > 1 else StandardEmitter())
            stages.append(StageSpec(
                f"{self.name}_plq", reps, emitter, RoutingMode.COMPLEX,
                ordering_mode=self._ordering(),
                collector=WidOrderCollector() if self.plq_par > 1 else None))
        else:
            from ..pane_farm import PaneFarm  # host PLQ stage via CPU engine
            host = PaneFarm(self.plq, lambda *a: None, self.win_len,
                            self.slide_len, self.win_type, self.plq_par, 1,
                            self.triggering_delay,
                            result_factory=self.result_factory,
                            ordered=True)
            stages.append(host.stages()[0])
        # ---- WLQ: CB windows over dense pane ids ----
        wlq_win = self.win_len // pane
        wlq_slide = self.slide_len // pane
        if not self.plq_on_tpu:  # WLQ on device
            reps = _tpu_replicas(
                self.wlq, wlq_win, wlq_slide, WinType.CB, self.wlq_par,
                batch_len=self.batch_len, triggering_delay=0,
                result_factory=self.result_factory, value_of=self.value_of,
                enclosing=cfg, role=Role.WLQ,
                farm_kind="wf" if self.wlq_par > 1 else "seq",
                max_buffer_elems=self.max_buffer_elems,
                inflight_depth=self.inflight_depth,
                max_batch_delay_ms=self.max_batch_delay_ms)
            emitter = (WFEmitter(wlq_win, wlq_slide, self.wlq_par,
                                 WinType.CB, Role.WLQ,
                                 id_outer=cfg.id_inner, n_outer=cfg.n_inner,
                                 slide_outer=cfg.slide_inner)
                       if self.wlq_par > 1
                       else StandardEmitter(keyed=True))
            stages.append(StageSpec(
                f"{self.name}_wlq", reps, emitter,
                RoutingMode.COMPLEX if self.wlq_par > 1 else RoutingMode.KEYBY,
                ordering_mode=OrderingMode.ID,
                collector=(WidOrderCollector()
                           if self.wlq_par > 1 and self.ordered else None)))
        elif self._wlq_columnar:  # host columnar combine (keyed)
            # keyed sharding sends each key's whole pane stream to one
            # replica, which fires its windows in wid order -- the same
            # per-key guarantee the WidOrderCollector gives the
            # window-sharded stock branches, so no collector is needed
            reps = [self._columnar_wlq(wlq_win, wlq_slide)
                    for _ in range(self.wlq_par)]
            stages.append(StageSpec(
                f"{self.name}_wlq", reps,
                StandardEmitter(keyed=True), RoutingMode.KEYBY,
                ordering_mode=OrderingMode.ID))
        else:  # WLQ on host
            if self.wlq_par > 1:
                from ..win_farm import WinFarm
                wlq = WinFarm(self._wlq_fn(), wlq_win, wlq_slide, WinType.CB,
                              self.wlq_par, 0, False, f"{self.name}_wlq",
                              self.result_factory, None, self.ordered,
                              self.opt_level, WinOperatorConfig(
                                  cfg.id_outer, cfg.n_outer, cfg.slide_outer,
                                  cfg.id_inner, cfg.n_inner, cfg.slide_inner),
                              Role.WLQ)
                stages.extend(wlq.stages())
            else:
                stages.append(StageSpec(
                    f"{self.name}_wlq",
                    [self._host_single(self._wlq_fn(), wlq_win, wlq_slide,
                                       WinType.CB, Role.WLQ)],
                    StandardEmitter(keyed=True),
                    RoutingMode.KEYBY, ordering_mode=OrderingMode.ID))
        return stages


class WinMapReduceTPU(_TPUWinOp):
    """MAP or REDUCE on device (win_mapreduce_gpu.hpp:109-110)."""

    def __init__(self, map_stage: Any, reduce_stage: Any, win_len, slide_len,
                 win_type, map_parallelism=2, reduce_parallelism=1,
                 map_on_tpu=True, batch_len=DEFAULT_BATCH_LEN,
                 triggering_delay=0, name="win_mr_tpu",
                 result_factory=BasicRecord, value_of=None, ordered=True,
                 config: WinOperatorConfig = None,
                 max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS,
                 inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                 max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS):
        super().__init__(name, map_parallelism + reduce_parallelism,
                         RoutingMode.COMPLEX, Pattern.WIN_MAPREDUCE_TPU,
                         win_type)
        self.map_stage = map_stage
        self.reduce_stage = reduce_stage
        self.win_len = win_len
        self.slide_len = slide_len
        self.map_par = map_parallelism
        self.reduce_par = reduce_parallelism
        self.map_on_tpu = map_on_tpu
        self.batch_len = batch_len
        self.triggering_delay = triggering_delay
        self.result_factory = result_factory
        self.value_of = value_of
        self.ordered = ordered
        self.max_buffer_elems = max_buffer_elems
        self.inflight_depth = inflight_depth
        self.max_batch_delay_ms = max_batch_delay_ms
        self.config = config or WinOperatorConfig(0, 1, slide_len,
                                                  0, 1, slide_len)

    def stages(self):
        cfg = self.config
        mp = self.map_par
        stages = []
        # ---- MAP ----
        if self.map_on_tpu:
            reps = []
            for i in range(mp):
                reps.append(WinSeqTPULogic(
                    self.map_stage, self.win_len, self.slide_len,
                    self.win_type, batch_len=self.batch_len,
                    triggering_delay=self.triggering_delay,
                    result_factory=self.result_factory,
                    config=WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                             cfg.slide_inner, 0, 1,
                                             self.slide_len),
                    role=Role.MAP, map_indexes=(i, mp), parallelism=mp,
                    replica_index=i, value_of=self.value_of,
                    max_buffer_elems=self.max_buffer_elems,
                    inflight_depth=self.inflight_depth,
                    max_batch_delay_ms=self.max_batch_delay_ms))
        else:
            reps = [WinSeqLogic(
                self.map_stage, self.win_len, self.slide_len, self.win_type,
                triggering_delay=self.triggering_delay,
                result_factory=self.result_factory,
                config=WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                         cfg.slide_inner, 0, 1,
                                         self.slide_len),
                role=Role.MAP, map_indexes=(i, mp), parallelism=mp,
                replica_index=i) for i in range(mp)]
        stages.append(StageSpec(
            f"{self.name}_map", reps, WinMapEmitter(mp, self.win_type),
            RoutingMode.COMPLEX, ordering_mode=self._ordering(),
            collector=WidOrderCollector()))
        # ---- REDUCE: CB tumbling windows of mp partials ----
        if self.map_on_tpu:  # reduce on host
            logic = [WinSeqLogic(
                self.reduce_stage, mp, mp, WinType.CB,
                result_factory=self.result_factory,
                config=WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                         cfg.slide_inner, 0, 1, mp),
                role=Role.REDUCE)]
        else:  # reduce on device
            logic = _tpu_replicas(
                self.reduce_stage, mp, mp, WinType.CB, 1,
                batch_len=self.batch_len, triggering_delay=0,
                result_factory=self.result_factory, value_of=self.value_of,
                enclosing=cfg, role=Role.REDUCE, farm_kind="seq",
                max_buffer_elems=self.max_buffer_elems,
                inflight_depth=self.inflight_depth,
                max_batch_delay_ms=self.max_batch_delay_ms)
        stages.append(StageSpec(
            f"{self.name}_reduce", logic, StandardEmitter(keyed=True),
            RoutingMode.KEYBY, ordering_mode=OrderingMode.ID))
        return stages


def _ffat_kind(combine: Any):
    """Normalize an FFAT combine spec to an engine kind."""
    if isinstance(combine, str):
        return combine  # builtin: scan / sparse-table paths
    if isinstance(combine, tuple) and len(combine) == 2:
        fn, neutral = combine
        return ("ffat", fn, float(neutral))
    raise ValueError("FFAT combine must be a builtin name or "
                     "(jax_binary_fn, neutral) tuple")


class WinSeqFFATTPU(_TPUWinOp):
    """Lift on host, associative combine on the device FlatFAT
    (win_seqffat_gpu.hpp)."""

    def __init__(self, lift: Callable, combine: Any, win_len, slide_len,
                 win_type, batch_len=DEFAULT_BATCH_LEN, triggering_delay=0,
                 name="win_seqffat_tpu", result_factory=BasicRecord,
                 max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS,
                 inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                 max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS):
        super().__init__(name, 1, RoutingMode.FORWARD,
                         Pattern.WIN_SEQFFAT_TPU, win_type)
        self.kind = _ffat_kind(combine)
        self.lift = lift
        self.max_buffer_elems = max_buffer_elems
        self.inflight_depth = inflight_depth
        self.max_batch_delay_ms = max_batch_delay_ms
        self.args = (win_len, slide_len, win_type, batch_len,
                     triggering_delay, result_factory)

    def stages(self):
        win_len, slide_len, win_type, batch_len, delay, rf = self.args
        logic = WinSeqTPULogic(
            self.kind, win_len, slide_len, win_type, batch_len=batch_len,
            triggering_delay=delay, result_factory=rf, value_of=self.lift,
            renumbering=self._renumbering,
            max_buffer_elems=self.max_buffer_elems,
            inflight_depth=self.inflight_depth,
            max_batch_delay_ms=self.max_batch_delay_ms)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing, ordering_mode=self._ordering())]


class KeyFFATTPU(_TPUWinOp):
    """Key-sharded device FFAT farm (key_ffat_gpu.hpp:18-35).  Same
    single-device coalescing as KeyFarmTPU (see there): identical
    replica configs, so one engine over all keys is equivalent."""

    def __init__(self, lift: Callable, combine: Any, win_len, slide_len,
                 win_type, parallelism=1, batch_len=DEFAULT_BATCH_LEN,
                 triggering_delay=0, name="key_ffat_tpu",
                 result_factory=BasicRecord,
                 max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS, coalesce=True,
                 inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                 max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.KEY_FFAT_TPU, win_type)
        self.kind = _ffat_kind(combine)
        self.lift = lift
        self.max_buffer_elems = max_buffer_elems
        self.coalesce = coalesce
        self.inflight_depth = inflight_depth
        self.max_batch_delay_ms = max_batch_delay_ms
        self.args = (win_len, slide_len, win_type, batch_len,
                     triggering_delay, result_factory)

    def stages(self):
        win_len, slide_len, win_type, batch_len, delay, rf = self.args
        par = 1 if self.coalesce else self.parallelism
        reps = [WinSeqTPULogic(
            self.kind, win_len, slide_len, win_type, batch_len=batch_len,
            triggering_delay=delay, result_factory=rf, value_of=self.lift,
            config=WinOperatorConfig(0, 1, 0, 0, 1, slide_len),
            parallelism=par, replica_index=i,
            renumbering=self._renumbering,
            max_buffer_elems=self.max_buffer_elems,
            inflight_depth=self.inflight_depth,
            max_batch_delay_ms=self.max_batch_delay_ms)
            for i in range(par)]
        return [StageSpec(self.name, reps, KFEmitter(par),
                          self.routing, ordering_mode=self._ordering())]
