"""WinMapReduceMesh: multi-chip Win_MapReduce -- intra-window striping
over the mesh 'win' axis, one graph operator.

BASELINE config #5 ("Win_MapReduce ... on v5e-8") as a first-class
operator, the mesh generalization of win_mapreduce_gpu.hpp:63: each
window's tuples are striped round-robin across the 'win' axis (the
WinMap_Emitter per-key round robin, wm_nodes.hpp:62, applied at chip
granularity), every chip folds its stripe locally (the MAP stage), and
the REDUCE is an XLA collective riding ICI -- psum/pmax/pmin for the
builtins, all_gather + pairwise combine for a custom FFAT fold
(parallel/sharded.compute_wmr).  Multiple keys ride the 'key' axis
simultaneously, so one launch computes key-rows x windows at once.

Host plane: window assignment, batching and emission are shared with
KeyFarmMesh (same dense-id CB / timestamp TB contract, anchoring,
hopping-gap filtering); only the launch layout differs -- KF ships each
key's series to ONE shard, WMR splits each WINDOW across ALL 'win'
shards.  The reference cannot express either beyond one process
(SURVEY.md §5 "no network backend").
"""
from __future__ import annotations


import numpy as np

from ...core.basic import Pattern, WinType
from ...core.tuples import BasicRecord, TupleBatch
from .mesh_farm import KeyFarmMesh, KeyFarmMeshLogic


class WinMapReduceMeshLogic(KeyFarmMeshLogic):
    """KeyFarmMesh's host plane with the striped launch layout."""

    def _launch(self, emit):
        if not self.ready:
            return
        ready, self.ready = self.ready, []
        eng = self.engine
        W = eng.n_win_shards
        K = eng.n_key_shards
        neutral = eng.neutral
        involved = self._involved_keys(ready)
        cons = {k: self._consolidate_key(k) for k in involved}
        row_of = {k: i for i, k in enumerate(involved)}
        # (row, slot) placement + the widest stripe of this launch
        slots = [0] * len(involved)
        placement = []
        segs = []
        stripe_len = 1
        for key, lwid, s_key, e_key in ready:
            ids, vals = cons[key]
            lo = int(np.searchsorted(ids, s_key, "left"))
            hi = int(np.searchsorted(ids, e_key, "left"))
            seg = vals[lo:hi]
            if eng.kind == "count":
                seg = np.ones(hi - lo, np.float64)
            segs.append(seg)
            stripe_len = max(stripe_len, -(-(hi - lo) // W))
            row = row_of[key]
            placement.append((key, lwid, row, slots[row]))
            slots[row] += 1
        B = max(slots)
        rows_pad = -(-len(involved) // K) * K  # 'key' axis divisibility
        stripes = np.full((rows_pad, W, B, stripe_len), neutral, np.float32)
        for (key, lwid, row, slot), seg in zip(placement, segs):
            pad = np.full(W * stripe_len, neutral, np.float32)
            pad[: len(seg)] = seg
            # element i -> stripe i % W, position i // W: the round-robin
            # striping of WinMap_Emitter as a reshape
            stripes[row, :, slot, :] = pad.reshape(stripe_len, W).T
        out = np.asarray(eng.compute_wmr(stripes))
        self.launched_batches += 1
        if self.emit_batches:
            n = len(placement)
            emit(TupleBatch({
                "key": np.fromiter((p[0] for p in placement), np.int64, n),
                "id": np.fromiter((p[1] for p in placement), np.int64, n),
                "ts": np.zeros(n, np.int64),
                "value": np.fromiter(
                    (out[row, slot] for _, _, row, slot in placement),
                    np.float64, n),
            }))
        else:
            for key, lwid, row, slot in placement:
                emit(BasicRecord(key, lwid, 0, float(out[row, slot])))
        self._evict_consumed(involved)


class WinMapReduceMesh(KeyFarmMesh):
    """``kind`` is a builtin combine name ('sum'/'count'/'max'/'min' --
    'mean' is rejected: stripe partials carry no count channel) or an
    FFAT spec ('ffat', lift, combine, neutral); lift is applied
    columnar on the host at ingest, the combine folds stripes on
    device and across chips (win_mapreduce_gpu.hpp:63 at mesh
    scale).  Shares KeyFarmMesh's operator shell; only the launch
    layout (logic class) and pattern differ."""

    _logic_cls = WinMapReduceMeshLogic
    _pattern = Pattern.WIN_MAPREDUCE_TPU

    def __init__(self, mesh, win_len: int, slide_len: int,
                 win_type: WinType, batch_windows: int = 1024,
                 name: str = "win_mr_mesh", emit_batches: bool = True,
                 kind="sum"):
        super().__init__(mesh, win_len, slide_len, win_type,
                         batch_windows, name, emit_batches, kind)
        if self.engine.kind == "mean":
            raise ValueError("WinMapReduceMesh does not support 'mean' "
                             "(stripe partials carry no count channel)")
