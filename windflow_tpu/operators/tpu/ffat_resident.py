"""Resident-tree FFAT window logic: the ``rebuild=false`` incremental
mode of the reference's Win_SeqFFAT_GPU.

Where the batch engine (WinSeqTPULogic with an ffat kind) rebuilds the
aggregator tree from a staged flat buffer every launch, this logic keeps
one FlatFAT per key **resident in HBM across batches** as a key-batched
forest (ops/flatfat_jax.BatchedFlatFAT) and only scatters the new
lifted leaves plus their root paths -- the circular-buffer tree update
of the reference (win_seqffat_gpu.hpp:150 ``rebuild`` flag;
UpdateTreeLevel_Kernel, flatfat_gpu.hpp:68-82).

Scope: CB windows over per-key arrival order (one tuple per leaf; ring
position = arrival index mod capacity), and TB windows over per-key
IN-ORDER timestamps -- ring eviction is keyed on the timestamp proof
that every window covering a leaf has fired (positions below
``searchsorted(ts, next_fire * slide)`` are dead), and the leaf ring
grows when a window span holds more tuples than the current capacity
(win_seqffat_gpu.hpp:444-...).  Out-of-order TB streams keep the
rebuild path.  Ring capacity starts at win_len + chunk headroom, and
every svc call fires + queries due windows before their leaves can be
overwritten.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ...core.basic import OrderingMode, Pattern, RoutingMode, WinType
from ...core.tuples import BasicRecord, TupleBatch
from ...runtime.emitters import StandardEmitter
from ...runtime.node import EOSMarker, NodeLogic
from ..base import Operator, StageSpec


class _ResidentKey:
    __slots__ = ("row", "count", "next_fire", "ts_ring",
                 "ts_vals", "ts_base", "max_ts", "anchored", "dead_idx")

    def __init__(self, row: int, capacity: int, tb: bool = False):
        self.row = row
        self.count = 0      # tuples received = next leaf id
        self.next_fire = 0  # next window (lwid) to fire
        if tb:
            # TB: host mirror of the leaf timestamps at absolute
            # positions [ts_base, count), for extent binary search and
            # the eviction proof.  ``dead_idx`` is the running cursor
            # of the fired frontier inside the mirror: the eviction
            # proof resumes its binary search there, so each svc call
            # scans only the mirror's NEW tail -- O(new tuples), not
            # O(history) -- and the mirror is sliced at the cursor
            # before it can grow past ~2x the live span
            self.ts_vals = np.empty(0, np.int64)
            self.ts_base = 0
            self.max_ts = -1
            self.anchored = False
            self.dead_idx = 0
        else:
            # host-side timestamp ring mirroring the leaf ring, so CB
            # results carry the last-extent-tuple ts like every other
            # path
            self.ts_ring = np.zeros(capacity, np.int64)


class WinSeqFFATResidentLogic(NodeLogic):
    def __init__(self, lift: Callable, combine: Callable, neutral: float,
                 win_len: int, slide_len: int, *,
                 win_type: WinType = WinType.CB,
                 result_factory=BasicRecord, initial_keys: int = 16):
        from ...ops.flatfat_jax import BatchedFlatFAT
        if win_len == 0 or slide_len == 0:
            raise ValueError("win_len and slide_len must be > 0")
        self.lift = lift
        self.combine = combine
        self.neutral = float(neutral)
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.is_tb = win_type == WinType.TB
        self.result_factory = result_factory
        # capacity: window span + one slide of update headroom, pow2.
        # CB: exact (one leaf per id).  TB: a starting estimate -- the
        # ring grows when a window span holds more tuples than this.
        need = win_len + slide_len
        self._chunk_headroom = max(slide_len, 1024)
        n = 1
        while n < need + self._chunk_headroom:
            n <<= 1
        self.capacity = n
        self.keys: Dict[Any, _ResidentKey] = {}
        self.forest = BatchedFlatFAT(combine, self.neutral,
                                     max(2, initial_keys), n)
        self.launched_batches = 0

    def _key_state(self, key) -> _ResidentKey:
        st = self.keys.get(key)
        if st is None:
            row = len(self.keys)
            if row >= self.forest.n_keys:
                self._grow_forest()
            st = self.keys[key] = _ResidentKey(row, self.capacity,
                                               self.is_tb)
        return st

    def _grow_forest(self) -> None:
        """Double the key capacity, copying the resident trees."""
        import jax.numpy as jnp
        old = self.forest.tree
        from ...ops.flatfat_jax import BatchedFlatFAT
        self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                     old.shape[0] * 2, self.capacity)
        self.forest.tree = jnp.concatenate(
            [old, jnp.full(old.shape, self.neutral, old.dtype)])

    def _grow_leaves(self, min_capacity: int) -> None:
        """TB ring overflow: a retained window span no longer fits the
        leaf ring.  Double the capacity and re-scatter every key's live
        leaves at their new ring positions (the circular-buffer resize
        of win_seqffat_gpu.hpp:444-...; rare, amortized O(1))."""
        assert self.is_tb, "CB rings are capacity-exact by construction"
        from ...ops.flatfat_jax import BatchedFlatFAT
        old_n = self.forest.n
        old_leaves = np.asarray(self.forest.tree)[:, old_n:2 * old_n]
        n = old_n
        while n < min_capacity:
            n <<= 1
        self.capacity = n
        self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                     self.forest.n_keys, n)
        for st in self.keys.values():
            live = np.arange(st.ts_base, st.count)
            for c in range(0, len(live), 4096):
                pos = live[c:c + 4096]
                self.forest.update(np.full(len(pos), st.row), pos,
                                   old_leaves[st.row, pos % old_n])

    # -- ingest --------------------------------------------------------
    def _count_launch(self, new_bytes: int, res: np.ndarray) -> None:
        """Per-launch accounting for the resident lane: only NEW bytes
        cross the transport (lifted leaves + positions in, fired
        results out) -- the resident forest itself never re-ships, so
        ``Device_bytes_per_launch`` measures exactly the incremental
        traffic, with the forest footprint on the separate
        ``Device_state_bytes_resident`` gauge."""
        self.launched_batches += 1
        if self.stats is not None:
            self.stats.num_launches += 1
            self.stats.bytes_to_device += new_bytes
            self.stats.bytes_from_device += res.nbytes
            self.stats.device_state_bytes = self.forest.state_bytes

    def device_resident_bytes(self) -> int:
        """Gauge hook (monitoring/stats.py): resident forest bytes."""
        return self.forest.state_bytes

    def _ingest_chunk(self, row, start_id, lifted, key_objs,
                      emit) -> None:
        """One FUSED forest launch per chunk (chunk small enough that
        no due window's leaves can be overwritten): scatter the new
        lifted leaves, recompute their root paths and answer every due
        window against the post-update tree -- decode -> fold ->
        trigger in a single jitted program.  New leaves are one
        CONSECUTIVE run per chunk, so the launch ships only the lifted
        values + a 12-byte (row, start, len) descriptor + extents --
        never positions, never state."""
        qk_rows: List[int] = []
        qs: List[int] = []
        qe: List[int] = []
        meta: List = []
        for key in key_objs:
            st = self.keys[key]
            while st.count >= st.next_fire * self.slide_len + self.win_len:
                lwid = st.next_fire
                start = lwid * self.slide_len
                qk_rows.append(st.row)
                qs.append(start)
                qe.append(start + self.win_len)
                meta.append((key, lwid))
                st.next_fire += 1
        lifted = np.asarray(lifted, np.float32)
        new_bytes = lifted.nbytes + 12 + 8 * len(qk_rows)
        res = self.forest.update_runs_query(
            [row], [start_id], [len(lifted)], lifted, qk_rows, qs, qe)
        self._count_launch(new_bytes, res)
        for (key, lwid), end, val in zip(meta, qe, res):
            out = self.result_factory()
            out.value = float(val)
            # CB convention: result ts = last tuple in the extent
            rts = int(self.keys[key].ts_ring[(end - 1)
                                             % self.capacity])
            out.set_control_fields(key, lwid, rts)
            emit(out)

    def _emit_windows(self, rows, qs, qe, meta, emit) -> None:
        """Query-only launch (EOS flush: no new leaves to scatter)."""
        res = self.forest.query(np.asarray(rows), np.asarray(qs),
                                np.asarray(qe))
        self._count_launch(8 * len(rows), res)
        for (key, lwid), end, val in zip(meta, qe, res):
            out = self.result_factory()
            out.value = float(val)
            # CB convention: result ts = last tuple in the extent
            rts = int(self.keys[key].ts_ring[(end - 1) % self.capacity])
            out.set_control_fields(key, lwid, rts)
            emit(out)

    # -- TB plane: timestamp-proof ring eviction -----------------------
    def _dead_count(self, st) -> int:
        """Mirror index of the fired frontier: leaves below it are dead
        (every window covering them has fired).  The binary search
        RESUMES at the running ``dead_idx`` cursor -- the frontier is
        monotone, so each call scans only the mirror's new tail and the
        proof stays O(new tuples) per svc call instead of re-sweeping
        the whole history mirror."""
        t = st.next_fire * self.slide_len
        st.dead_idx += int(np.searchsorted(st.ts_vals[st.dead_idx:],
                                           t, "left"))
        return st.dead_idx

    def _pos(self, st, t: int) -> int:
        """Absolute mirror position of the first leaf with ts >= t, for
        t at/above the fired frontier (resumes at the cursor: every
        leaf below it has ts < the frontier <= t)."""
        return st.ts_base + st.dead_idx + int(np.searchsorted(
            st.ts_vals[st.dead_idx:], t, "left"))

    def _ingest_tb(self, key, tss, vals, emit) -> None:
        st = self._key_state(key)
        # compare against max_ts, not the mirror tail: full mirror
        # eviction would otherwise make the guard vacuous and silently
        # drop a late tuple
        if not np.all(tss[:-1] <= tss[1:]) or (
                st.max_ts >= 0 and tss[0] < st.max_ts):
            raise ValueError(
                "resident TB FFAT requires per-key in-order timestamps; "
                "use the rebuild path (WinSeqFFATTPU) for out-of-order "
                "streams")
        if not st.anchored:
            # anchor the fire frontier at the first containing window
            first = int(tss[0])
            st.next_fire = (0 if first < self.win_len
                            else (first - self.win_len)
                            // self.slide_len + 1)
            st.anchored = True
        step = self._chunk_headroom
        for c in range(0, len(tss), step):
            d = min(c + step, len(tss))
            # timestamp proof: leaves with ts below the fired frontier
            # are dead (every window covering them already fired); if
            # the live span plus this chunk overflows the ring, grow it
            dead = st.ts_base + self._dead_count(st)
            live_after = st.count + (d - c) - dead
            if live_after > self.capacity:
                # slice every mirror to its exact dead frontier first so
                # [ts_base, count) spans <= capacity per key and old
                # ring positions are alias-free for the re-scatter
                for st2 in self.keys.values():
                    d2 = self._dead_count(st2)
                    st2.ts_vals = st2.ts_vals[d2:]
                    st2.ts_base += d2
                    st2.dead_idx = 0
                self._grow_leaves(int(live_after) + self._chunk_headroom)
            ids = np.arange(st.count, st.count + (d - c))
            st.ts_vals = np.concatenate([st.ts_vals, tss[c:d]])
            st.count += d - c
            st.max_ts = int(tss[d - 1])
            # one FUSED launch: scatter the chunk's leaves (one
            # consecutive run) + answer its due windows against the
            # post-update forest
            self._fire_tb(key, st, emit,
                          update=(st.row, int(ids[0]),
                                  vals[c:d].astype(np.float32)))

    def _fire_tb(self, key, st, emit, at_eos: bool = False,
                 update=None) -> None:
        rows, qs, qe, meta = [], [], [], []
        while True:
            s_ts = st.next_fire * self.slide_len
            if at_eos:
                if s_ts > st.max_ts:
                    break
            elif st.max_ts < s_ts + self.win_len:
                break
            sp = self._pos(st, s_ts)
            ep = self._pos(st, s_ts + self.win_len)
            rows.append(st.row)
            qs.append(sp)
            qe.append(ep)
            # TB result ts is window arithmetic, like every other engine
            meta.append((key, st.next_fire,
                         s_ts + self.win_len - 1))
            st.next_fire += 1
        res = None
        if update is not None:
            u_row, u_start, u_vals = update
            new_bytes = u_vals.nbytes + 12 + 8 * len(rows)
            res = self.forest.update_runs_query(
                [u_row], [u_start], [len(u_vals)], u_vals, rows, qs, qe)
            self._count_launch(new_bytes, res)
            if not rows:
                res = None
        elif rows:
            res = self.forest.query(np.asarray(rows), np.asarray(qs),
                                    np.asarray(qe))
            self._count_launch(8 * len(rows), res)
        if res is not None:
            for (key_, lwid, rts), s_, e_, val in zip(meta, qs, qe, res):
                out = self.result_factory()
                out.value = float(val) if e_ > s_ else 0.0  # masked
                out.set_control_fields(key_, lwid, rts)
                emit(out)
            # amortized mirror eviction at the fired frontier (the
            # same proof, via the cursor): the mirror never grows past
            # the live span + this slack
            dead = self._dead_count(st)
            if dead > 1024:
                st.ts_vals = st.ts_vals[dead:]
                st.ts_base += dead
                st.dead_idx = 0

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        if isinstance(item, TupleBatch):
            keys = item.key
            vals = item["value"]
            tss = item.ts
            if len(keys) > 1 and not np.all(keys[:-1] <= keys[1:]):
                order = np.argsort(keys, kind="stable")
                keys, vals, tss = keys[order], vals[order], tss[order]
            edges = np.nonzero(np.diff(keys))[0] + 1
            bounds = np.concatenate([[0], edges, [len(keys)]])
            # chunk so no key advances further than the ring headroom
            # between fire/query passes
            step = self._chunk_headroom
            for j in range(len(bounds) - 1):
                key = keys[bounds[j]].item()
                lo, hi = int(bounds[j]), int(bounds[j + 1])
                if self.is_tb:
                    self._ingest_tb(key, tss[lo:hi], vals[lo:hi], emit)
                    continue
                st = self._key_state(key)
                for c in range(lo, hi, step):
                    d = min(c + step, hi)
                    ids = np.arange(st.count, st.count + (d - c))
                    st.ts_ring[ids % self.capacity] = tss[c:d]
                    start_id = st.count
                    st.count += d - c
                    self._ingest_chunk(
                        st.row, start_id,
                        vals[c:d].astype(np.float32), [key], emit)
            return
        key, _tid, ts = item.get_control_fields()
        lifted = self.lift(item)
        if self.is_tb:
            self._ingest_tb(key, np.array([ts]),
                            np.array([lifted], np.float64), emit)
            return
        st = self._key_state(key)
        st.ts_ring[st.count % self.capacity] = ts
        st.count += 1
        self._ingest_chunk(st.row, st.count - 1, [lifted], [key], emit)

    def eos_flush(self, emit):
        """Fire partial tail windows whose extent clips at the stream
        end (the EOS flush of open windows, win_seq.hpp:514-579)."""
        if self.is_tb:
            for key, st in self.keys.items():
                if st.max_ts >= 0:
                    self._fire_tb(key, st, emit, at_eos=True)
            return
        rows, qs, qe, meta = [], [], [], []
        for key, st in self.keys.items():
            while st.next_fire * self.slide_len < st.count:
                lwid = st.next_fire
                start = lwid * self.slide_len
                rows.append(st.row)
                qs.append(start)
                qe.append(min(start + self.win_len, st.count))
                meta.append((key, lwid))
                st.next_fire += 1
        if rows:
            self._emit_windows(rows, qs, qe, meta, emit)

    # -- checkpoint ----------------------------------------------------
    def state_dict(self):
        if self.is_tb:
            keys = {k: (st.row, st.count, st.next_fire,
                        st.ts_vals.copy(), st.ts_base, st.max_ts,
                        st.anchored, st.dead_idx)
                    for k, st in self.keys.items()}
        else:
            keys = {k: (st.row, st.count, st.next_fire, st.ts_ring.copy())
                    for k, st in self.keys.items()}
        return {"keys": keys, "tree": np.asarray(self.forest.tree),
                "capacity": self.capacity}

    def load_state(self, state):
        import jax.numpy as jnp
        from ...ops.flatfat_jax import BatchedFlatFAT
        tree = state["tree"]
        self.capacity = state.get("capacity", self.capacity)
        # the forest must match the snapshot's row count EXACTLY: a
        # larger n_keys would let jnp clamp out-of-range rows silently,
        # aliasing new keys onto the last checkpointed tree
        self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                     tree.shape[0], self.capacity)
        self.forest.tree = jnp.asarray(tree)
        self.keys.clear()
        for k, fields in state["keys"].items():
            st = _ResidentKey(fields[0], self.capacity, self.is_tb)
            st.count, st.next_fire = fields[1], fields[2]
            if self.is_tb:
                st.ts_vals = np.asarray(fields[3]).copy()
                st.ts_base, st.max_ts, st.anchored = fields[4:7]
                # pre-cursor snapshots carry no dead_idx: 0 re-derives
                st.dead_idx = fields[7] if len(fields) > 7 else 0
            else:
                st.ts_ring = np.asarray(fields[3]).copy()
            self.keys[k] = st

    # -- tiered-state census (state/; audit/auditor._probe_tiers): the
    # forest keeps every key's window state in device memory -- the top
    # of the tier ladder, above the host store's hot/warm/cold --------
    def state_tier_of(self, key):
        return "device" if key in self.keys else None

    # -- keyed-state hooks (elastic/rescale.py): the resident forest IS
    # the per-key window state, so repartitioning pulls each key's LIVE
    # leaf span off the device and re-scatters it on the owner replica;
    # per-key blobs are fusion-invariant (same shape whether the engine
    # runs standalone or inside a fused segment) ----------------------
    def keyed_state_dict(self):
        tree = np.asarray(self.forest.tree)
        n = self.forest.n
        out: Dict[Any, dict] = {}
        for k, st in self.keys.items():
            if self.is_tb:
                lo = st.ts_base
            else:
                # windows from next_fire on read leaves >= the fired
                # frontier; earlier ring slots are dead by the proof
                lo = min(st.next_fire * self.slide_len, st.count)
            live = np.arange(lo, st.count, dtype=np.int64)
            leaves = (tree[st.row, n + (live % n)].copy() if len(live)
                      else np.empty(0, np.float32))
            blob = {"count": st.count, "next_fire": st.next_fire,
                    "lo": int(lo), "leaves": leaves}
            if self.is_tb:
                blob.update(ts_vals=st.ts_vals.copy(),
                            ts_base=st.ts_base, max_ts=st.max_ts,
                            anchored=st.anchored, dead_idx=st.dead_idx)
            else:
                blob["ts"] = st.ts_ring[live % self.capacity].copy()
            out[k] = blob
        return out

    def load_keyed_state(self, kv) -> None:
        from ...ops.flatfat_jax import BatchedFlatFAT
        self.keys.clear()
        need = self.capacity
        for blob in kv.values():
            # a source replica's ring may have grown (TB span growth):
            # size the fresh forest to the widest migrated span
            need = max(need, len(blob["leaves"]) + self._chunk_headroom)
        n = 1
        while n < need:
            n <<= 1
        self.capacity = n
        self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                     max(2, len(kv)), n)
        for k, blob in kv.items():
            st = _ResidentKey(len(self.keys), self.capacity, self.is_tb)
            st.count, st.next_fire = blob["count"], blob["next_fire"]
            if self.is_tb:
                st.ts_vals = np.asarray(blob["ts_vals"]).copy()
                st.ts_base = blob["ts_base"]
                st.max_ts = blob["max_ts"]
                st.anchored = blob["anchored"]
                st.dead_idx = blob.get("dead_idx", 0)
            self.keys[k] = st
            live = np.arange(blob["lo"], st.count, dtype=np.int64)
            if not self.is_tb and len(live):
                st.ts_ring[live % self.capacity] = blob["ts"]
            leaves = np.asarray(blob["leaves"], np.float32)
            for c in range(0, len(live), 4096):
                pos = live[c:c + 4096]
                self.forest.update(np.full(len(pos), st.row), pos,
                                   leaves[c:c + 4096])


class WinSeqFFATResident(Operator):
    """Standalone resident-tree FFAT operator (rebuild=false mode)."""

    def __init__(self, lift, combine, neutral, win_len, slide_len,
                 win_type: WinType = WinType.CB,
                 name="win_seqffat_resident", result_factory=BasicRecord):
        super().__init__(name, 1, RoutingMode.FORWARD,
                         Pattern.WIN_SEQFFAT_TPU)
        self.win_type = win_type
        self.kwargs = dict(lift=lift, combine=combine, neutral=neutral,
                           win_len=win_len, slide_len=slide_len,
                           win_type=win_type, result_factory=result_factory)

    def stages(self):
        logic = WinSeqFFATResidentLogic(**self.kwargs)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing,
                          ordering_mode=(OrderingMode.ID
                                         if self.win_type == WinType.CB
                                         else OrderingMode.TS))]
