"""Resident-tree FFAT window logic: the ``rebuild=false`` incremental
mode of the reference's Win_SeqFFAT_GPU.

Where the batch engine (WinSeqTPULogic with an ffat kind) rebuilds the
aggregator tree from a staged flat buffer every launch, this logic keeps
one FlatFAT per key **resident in HBM across batches** as a key-batched
forest (ops/flatfat_jax.BatchedFlatFAT) and only scatters the new
lifted leaves plus their root paths -- the circular-buffer tree update
of the reference (win_seqffat_gpu.hpp:150 ``rebuild`` flag;
UpdateTreeLevel_Kernel, flatfat_gpu.hpp:68-82).

Scope: count-based windows over per-key arrival order (one tuple per
leaf; ring position = arrival index mod capacity).  Time-based streams
keep the rebuild path (the builder routes them there).  Ring capacity
is sized to win_len + chunk headroom, and every svc call fires + queries
due windows before their leaves can be overwritten.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ...core.basic import (OrderingMode, Pattern, Role, RoutingMode,
                           WinType)
from ...core.tuples import BasicRecord, TupleBatch
from ...runtime.emitters import StandardEmitter
from ...runtime.node import EOSMarker, NodeLogic
from ..base import Operator, StageSpec


class _ResidentKey:
    __slots__ = ("row", "count", "next_fire", "ts_ring")

    def __init__(self, row: int, capacity: int):
        self.row = row
        self.count = 0      # tuples received = next leaf id
        self.next_fire = 0  # next window (lwid) to fire
        # host-side timestamp ring mirroring the leaf ring, so CB
        # results carry the last-extent-tuple ts like every other path
        self.ts_ring = np.zeros(capacity, np.int64)


class WinSeqFFATResidentLogic(NodeLogic):
    def __init__(self, lift: Callable, combine: Callable, neutral: float,
                 win_len: int, slide_len: int, *,
                 result_factory=BasicRecord, initial_keys: int = 16):
        from ...ops.flatfat_jax import BatchedFlatFAT
        if win_len == 0 or slide_len == 0:
            raise ValueError("win_len and slide_len must be > 0")
        self.lift = lift
        self.combine = combine
        self.neutral = float(neutral)
        self.win_len = win_len
        self.slide_len = slide_len
        self.result_factory = result_factory
        # capacity: window span + one slide of update headroom, pow2
        need = win_len + slide_len
        self._chunk_headroom = max(slide_len, 1024)
        n = 1
        while n < need + self._chunk_headroom:
            n <<= 1
        self.capacity = n
        self.keys: Dict[Any, _ResidentKey] = {}
        self.forest = BatchedFlatFAT(combine, self.neutral,
                                     max(2, initial_keys), n)
        self.launched_batches = 0

    def _key_state(self, key) -> _ResidentKey:
        st = self.keys.get(key)
        if st is None:
            row = len(self.keys)
            if row >= self.forest.n_keys:
                self._grow_forest()
            st = self.keys[key] = _ResidentKey(row, self.capacity)
        return st

    def _grow_forest(self) -> None:
        """Double the key capacity, copying the resident trees."""
        import jax.numpy as jnp
        old = self.forest.tree
        from ...ops.flatfat_jax import BatchedFlatFAT
        self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                     old.shape[0] * 2, self.capacity)
        self.forest.tree = jnp.concatenate(
            [old, jnp.full(old.shape, self.neutral, old.dtype)])

    # -- ingest --------------------------------------------------------
    def _ingest_chunk(self, rows, ids, lifted, key_objs, emit) -> None:
        """One forest update + fire/query pass (chunk small enough that
        no due window's leaves can be overwritten)."""
        self.forest.update(rows, ids, lifted)
        qk_rows: List[int] = []
        qs: List[int] = []
        qe: List[int] = []
        meta: List = []
        for key in key_objs:
            st = self.keys[key]
            while st.count >= st.next_fire * self.slide_len + self.win_len:
                lwid = st.next_fire
                start = lwid * self.slide_len
                qk_rows.append(st.row)
                qs.append(start)
                qe.append(start + self.win_len)
                meta.append((key, lwid))
                st.next_fire += 1
        if qk_rows:
            self._emit_windows(qk_rows, qs, qe, meta, emit)

    def _emit_windows(self, rows, qs, qe, meta, emit) -> None:
        res = self.forest.query(np.asarray(rows), np.asarray(qs),
                                np.asarray(qe))
        self.launched_batches += 1
        if self.stats is not None:
            self.stats.num_launches += 1
            self.stats.bytes_from_device += res.nbytes
        for (key, lwid), end, val in zip(meta, qe, res):
            out = self.result_factory()
            out.value = float(val)
            # CB convention: result ts = last tuple in the extent
            rts = int(self.keys[key].ts_ring[(end - 1) % self.capacity])
            out.set_control_fields(key, lwid, rts)
            emit(out)

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        if isinstance(item, TupleBatch):
            keys = item.key
            vals = item["value"]
            tss = item.ts
            if len(keys) > 1 and not np.all(keys[:-1] <= keys[1:]):
                order = np.argsort(keys, kind="stable")
                keys, vals, tss = keys[order], vals[order], tss[order]
            edges = np.nonzero(np.diff(keys))[0] + 1
            bounds = np.concatenate([[0], edges, [len(keys)]])
            # chunk so no key advances further than the ring headroom
            # between fire/query passes
            step = self._chunk_headroom
            for j in range(len(bounds) - 1):
                key = keys[bounds[j]].item()
                st = self._key_state(key)
                lo, hi = int(bounds[j]), int(bounds[j + 1])
                for c in range(lo, hi, step):
                    d = min(c + step, hi)
                    ids = np.arange(st.count, st.count + (d - c))
                    st.ts_ring[ids % self.capacity] = tss[c:d]
                    st.count += d - c
                    self._ingest_chunk(
                        np.full(d - c, st.row), ids,
                        vals[c:d].astype(np.float32), [key], emit)
            return
        key, _tid, ts = item.get_control_fields()
        st = self._key_state(key)
        lifted = self.lift(item)
        st.ts_ring[st.count % self.capacity] = ts
        st.count += 1
        self._ingest_chunk([st.row], [st.count - 1], [lifted], [key], emit)

    def eos_flush(self, emit):
        """Fire partial tail windows whose extent clips at the stream
        end (the EOS flush of open windows, win_seq.hpp:514-579)."""
        rows, qs, qe, meta = [], [], [], []
        for key, st in self.keys.items():
            while st.next_fire * self.slide_len < st.count:
                lwid = st.next_fire
                start = lwid * self.slide_len
                rows.append(st.row)
                qs.append(start)
                qe.append(min(start + self.win_len, st.count))
                meta.append((key, lwid))
                st.next_fire += 1
        if rows:
            self._emit_windows(rows, qs, qe, meta, emit)

    # -- checkpoint ----------------------------------------------------
    def state_dict(self):
        return {"keys": {k: (st.row, st.count, st.next_fire,
                             st.ts_ring.copy())
                         for k, st in self.keys.items()},
                "tree": np.asarray(self.forest.tree)}

    def load_state(self, state):
        import jax.numpy as jnp
        from ...ops.flatfat_jax import BatchedFlatFAT
        tree = state["tree"]
        # the forest must match the snapshot's row count EXACTLY: a
        # larger n_keys would let jnp clamp out-of-range rows silently,
        # aliasing new keys onto the last checkpointed tree
        self.forest = BatchedFlatFAT(self.combine, self.neutral,
                                     tree.shape[0], self.capacity)
        self.forest.tree = jnp.asarray(tree)
        self.keys.clear()
        for k, (row, count, nf, ts_ring) in state["keys"].items():
            st = _ResidentKey(row, self.capacity)
            st.count, st.next_fire = count, nf
            st.ts_ring = np.asarray(ts_ring).copy()
            self.keys[k] = st


class WinSeqFFATResident(Operator):
    """Standalone resident-tree FFAT operator (rebuild=false mode)."""

    def __init__(self, lift, combine, neutral, win_len, slide_len,
                 name="win_seqffat_resident", result_factory=BasicRecord):
        super().__init__(name, 1, RoutingMode.FORWARD,
                         Pattern.WIN_SEQFFAT_TPU)
        self.kwargs = dict(lift=lift, combine=combine, neutral=neutral,
                           win_len=win_len, slide_len=slide_len,
                           result_factory=result_factory)

    def stages(self):
        logic = WinSeqFFATResidentLogic(**self.kwargs)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing, ordering_mode=OrderingMode.ID)]
