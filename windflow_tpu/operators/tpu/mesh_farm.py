"""KeyFarmMesh: the multi-chip Key_Farm -- window state sharded across a
TPU mesh, one graph operator.

This is BASELINE config #4 ("key-sharded windows across 8 chips") as a
first-class operator: a single host logic partitions keys into
``n_key_shards`` shard-groups (hash % shards, the KF routing applied at
chip granularity), stages each shard's flat buffer into a
[K_shards, T_pad] array sharded over the mesh 'key' axis, and runs one
XLA program computing every shard's window sums in parallel -- the
collective-free steady state of key partitioning (keys never talk to
each other; ICI is only used when re-sharding).

The reference cannot express this at all (single process, SURVEY.md §5
"no network backend"); it is the mesh generalization of
key_farm_gpu.hpp.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ...core.basic import OrderingMode, Pattern, RoutingMode, WinType
from ...core.tuples import BasicRecord, TupleBatch
from ...core import win_assign as wa
from ...runtime.emitters import StandardEmitter
from ...runtime.node import EOSMarker, NodeLogic
from ..base import Operator, StageSpec


class _ShardKeyState:
    __slots__ = ("ids", "vals", "next_fire", "opened_max", "max_id")

    def __init__(self):
        self.ids: List[np.ndarray] = []
        self.vals: List[np.ndarray] = []
        self.next_fire = 0
        self.opened_max = -1
        self.max_id = -1


class KeyFarmMeshLogic(NodeLogic):
    """Single host logic driving the whole mesh (the host is the
    emitter plane; the mesh is the farm)."""

    def __init__(self, engine, win_len: int, slide_len: int,
                 win_type: WinType, batch_windows: int = 1024,
                 emit_batches: bool = True):
        self.engine = engine
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.batch_windows = batch_windows
        self.emit_batches = emit_batches
        self.n_shards = engine.n_key_shards
        self.keys: Dict[Any, _ShardKeyState] = {}
        self.ready: List = []  # (key, gwid, start, end)
        self.launched_batches = 0

    def _ingest_key(self, key, ids, vals):
        st = self.keys.get(key)
        if st is None:
            st = self.keys[key] = _ShardKeyState()
        if st.max_id < 0 and len(ids):
            # anchor at the first containing window (native parity)
            first = int(ids.min())
            if first >= self.win_len:
                st.next_fire = ((first - self.win_len)
                                // self.slide_len + 1)
        keep = ids >= st.next_fire * self.slide_len
        if self.win_len < self.slide_len:
            # hopping: ids in the inter-window gaps belong to no window
            # -- drop them BEFORE max_id/opened_max (win_seq_tpu does
            # the same), else a gap id either loses the final window
            # (if ignored) or fabricates empty ones (if counted)
            keep &= (ids % self.slide_len) < self.win_len
        ids, vals = ids[keep], vals[keep]
        if len(ids) == 0:
            return
        if self.engine.lift is not None:  # FFAT lift, columnar
            vals = np.asarray(self.engine.lift(vals))
        st.ids.append(ids)
        st.vals.append(vals)
        st.max_id = max(st.max_id, int(ids.max()))
        last_w = wa.last_window_of(st.max_id, 0, self.win_len,
                                   self.slide_len)
        if last_w >= 0:   # gap ids were filtered above, so >= 0 unless
            st.opened_max = max(st.opened_max, last_w)  # batch was empty
        while True:
            end = st.next_fire * self.slide_len + self.win_len
            if st.max_id < end or st.next_fire > st.opened_max:
                break
            self.ready.append((key, st.next_fire,
                               st.next_fire * self.slide_len, end))
            st.next_fire += 1

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        if isinstance(item, TupleBatch):
            keys = item.key
            ids = item.id if self.win_type == WinType.CB else item.ts
            vals = item["value"]
            order = np.argsort(keys, kind="stable")
            keys_s, ids_s, vals_s = keys[order], ids[order], vals[order]
            edges = np.nonzero(np.diff(keys_s))[0] + 1
            bounds = np.concatenate([[0], edges, [len(keys_s)]])
            for j in range(len(bounds) - 1):
                lo, hi = bounds[j], bounds[j + 1]
                self._ingest_key(keys_s[lo].item(), ids_s[lo:hi],
                                 vals_s[lo:hi])
        else:
            key, tid, ts = item.get_control_fields()
            id_ = tid if self.win_type == WinType.CB else ts
            self._ingest_key(key, np.array([id_]),
                             np.array([item.value]))
        if len(self.ready) >= self.batch_windows:
            self._launch(emit)

    def _involved_keys(self, ready):
        """Ready windows' keys, first-seen order."""
        involved, seen = [], set()
        for key, *_ in ready:
            if key not in seen:
                seen.add(key)
                involved.append(key)
        return involved

    def _consolidate_key(self, key):
        """Sort-merge one key's buffered chunks in place; returns the
        consolidated (ids, vals)."""
        st = self.keys[key]
        ids = np.concatenate(st.ids) if st.ids else np.empty(0, np.int64)
        vals = (np.concatenate(st.vals) if st.vals
                else np.empty(0, np.float64))
        order = np.argsort(ids, kind="stable")
        ids, vals = ids[order], vals[order]
        st.ids, st.vals = [ids], [vals]
        return ids, vals

    def _evict_consumed(self, involved):
        """Drop each key's prefix no window >= next_fire can reach."""
        for key in involved:
            st = self.keys[key]
            keep_from = st.next_fire * self.slide_len
            ids = st.ids[0]
            cut = np.searchsorted(ids, keep_from, "left")
            if cut:
                st.ids = [ids[cut:]]
                st.vals = [st.vals[0][cut:]]

    def _launch(self, emit):
        if not self.ready:
            return
        ready, self.ready = self.ready, []
        S = self.n_shards
        # per-shard flat buffers: consolidate each involved key's series
        shard_vals: List[List[np.ndarray]] = [[] for _ in range(S)]
        shard_len = [0] * S
        offsets: Dict[Any, tuple] = {}
        involved = self._involved_keys(ready)
        for key in involved:
            ids, vals = self._consolidate_key(key)
            sh = abs(hash(key)) % S
            offsets[key] = (sh, shard_len[sh], ids)
            shard_vals[sh].append(vals)
            shard_len[sh] += len(vals)
        T_pad = 1
        while T_pad < max(max(shard_len), 1):
            T_pad <<= 1
        B = len(ready)
        B_pad = 1
        while B_pad < B:
            B_pad <<= 1
        # pad with the combine's neutral: extents never read padding,
        # but max/min/ffat tree builds must not poison internal nodes
        values = np.full((S, T_pad), self.engine.neutral, np.float32)
        for sh in range(S):
            if shard_vals[sh]:
                flat = np.concatenate(shard_vals[sh])
                values[sh, : len(flat)] = flat
        starts = np.zeros((S, B_pad), np.int32)
        ends = np.zeros((S, B_pad), np.int32)
        slots = [0] * S
        placement = []
        for key, lwid, s_key, e_key in ready:
            sh, base, ids = offsets[key]
            slot = slots[sh]
            slots[sh] += 1
            starts[sh, slot] = base + np.searchsorted(ids, s_key, "left")
            ends[sh, slot] = base + np.searchsorted(ids, e_key, "left")
            placement.append((key, lwid, sh, slot))
        out = np.asarray(self.engine.compute_kf(values, starts, ends))
        self.launched_batches += 1
        if self.emit_batches:
            n = len(placement)
            emit(TupleBatch({
                "key": np.fromiter((p[0] for p in placement), np.int64, n),
                "id": np.fromiter((p[1] for p in placement), np.int64, n),
                "ts": np.zeros(n, np.int64),
                "value": np.fromiter(
                    (out[sh, slot] for _, _, sh, slot in placement),
                    np.float64, n),
            }))
        else:
            for key, lwid, sh, slot in placement:
                r = BasicRecord(key, lwid, 0, float(out[sh, slot]))
                emit(r)
        self._evict_consumed(involved)

    def eos_flush(self, emit):
        for key, st in self.keys.items():
            while st.next_fire <= st.opened_max:
                self.ready.append(
                    (key, st.next_fire, st.next_fire * self.slide_len,
                     st.next_fire * self.slide_len + self.win_len))
                st.next_fire += 1
            if len(self.ready) >= self.batch_windows:
                self._launch(emit)
        self._launch(emit)


class KeyFarmMesh(Operator):
    """``kind`` is a builtin combine name ('sum'/'count'/'mean'/'max'/
    'min') or an FFAT spec ('ffat', lift, combine, neutral) -- lift is
    applied columnar on the host at ingest, combine runs in the
    per-shard device FlatFAT (key_farm_gpu.hpp / key_ffat_gpu.hpp at
    mesh scale)."""

    _logic_cls = KeyFarmMeshLogic
    _pattern = Pattern.KEY_FARM_TPU

    def __init__(self, mesh, win_len: int, slide_len: int,
                 win_type: WinType, batch_windows: int = 1024,
                 name: str = "key_farm_mesh", emit_batches: bool = True,
                 kind="sum"):
        super().__init__(name, 1, RoutingMode.FORWARD, self._pattern)
        from ...parallel.sharded import ShardedWindowEngine
        self.win_type = win_type
        self.engine = ShardedWindowEngine(mesh, win_len, slide_len, kind)
        self.args = (win_len, slide_len, win_type, batch_windows,
                     emit_batches)

    def stages(self):
        win_len, slide_len, win_type, bw, eb = self.args
        logic = self._logic_cls(self.engine, win_len, slide_len, win_type,
                                bw, eb)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing,
                          ordering_mode=(OrderingMode.ID
                                         if win_type == WinType.CB
                                         else OrderingMode.TS))]
