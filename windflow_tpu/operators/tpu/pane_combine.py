"""Columnar WLQ: vectorized pane->window combine on the host.

The reference's Pane_Farm_GPU runs one of the two stages on device and
the other as a compiled C++ functor over pane RESULTS
(pane_farm_gpu.hpp:105-106).  The stock host WLQ here (WinSeqLogic)
processes pane records one at a time -- measured ~47us/record under
GIL contention, which made the whole farm slower than the single-stage
engine.  For builtin associative combines the WLQ is just an
alignment-insensitive reduction over each window's pane slice, so this
logic consumes the PLQ's columnar TupleBatches and fires all complete
windows of a batch with one numpy sliding-window reduction per key.

Window model (matches the stock WLQ stage of PaneFarmTPU): CB windows
of ``win`` panes sliding by ``slide`` panes over each key's dense pane
ids (the PLQ renumbers panes per key from 0).  Result ts is the last
contained pane's ts; EOS fires opened partial windows -- both exactly
the WinSeqLogic CB semantics the record path produces.

Only ``sum``/``max``/``min`` are accepted: they are insensitive to how
tuples landed in panes.  ``count``/``mean`` over pane RESULTS would
count/average panes, not tuples (an end-to-end count is
plq='count' + wlq='sum'), so they are rejected at construction.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ...core.tuples import BasicRecord, TupleBatch
from ...runtime.node import EOSMarker, NodeLogic

WLQ_KINDS = frozenset({"sum", "max", "min"})


class _KeyPanes:
    __slots__ = ("vals", "ts", "base", "next_fire", "pending")

    def __init__(self):
        self.vals = np.empty(0, np.float64)
        self.ts = np.empty(0, np.int64)
        self.base = 0       # pane id of vals[0] (evicted prefix count)
        self.next_fire = 0  # next window index to fire
        self.pending: Dict[int, tuple] = {}  # out-of-order panes by id


class PaneCombineLogic(NodeLogic):
    """Host columnar pane->window combine (the builtin-WLQ stage of
    PaneFarmTPU)."""

    def __init__(self, kind: str, win: int, slide: int, *,
                 result_factory=BasicRecord, emit_batches: bool = False):
        if kind not in WLQ_KINDS:
            raise ValueError(
                f"builtin WLQ combine must be one of {sorted(WLQ_KINDS)} "
                f"(count/mean over pane results would aggregate panes, "
                f"not tuples; use plq='count' + wlq='sum'): {kind!r}")
        if win <= 0 or slide <= 0 or slide > win:
            raise ValueError(f"need 0 < slide <= win panes, got "
                             f"win={win} slide={slide}")
        self.kind = kind
        self.win = win
        self.slide = slide
        self.result_factory = result_factory
        self.emit_batches = emit_batches
        self.keys: Dict[Any, _KeyPanes] = {}

    # -- ingest ------------------------------------------------------------
    def _append(self, st: _KeyPanes, ids, ts, vals) -> None:
        """Append panes, keeping vals/ts a contiguous id run from base.
        Out-of-order ids park in ``pending`` until the gap fills."""
        n = st.base + len(st.vals)  # next expected pane id
        if len(ids) and ids[0] == n and np.all(np.diff(ids) == 1):
            st.vals = np.concatenate([st.vals, vals])
            st.ts = np.concatenate([st.ts, ts])
            n += len(ids)
        else:
            for i, ts_i, v in zip(ids.tolist(), ts.tolist(), vals.tolist()):
                st.pending[i] = (ts_i, v)
        if st.pending:
            run_v: List[float] = []
            run_t: List[int] = []
            while n in st.pending:
                ts_i, v = st.pending.pop(n)
                run_t.append(ts_i)
                run_v.append(v)
                n += 1
            if run_v:
                st.vals = np.concatenate(
                    [st.vals, np.asarray(run_v, np.float64)])
                st.ts = np.concatenate(
                    [st.ts, np.asarray(run_t, np.int64)])

    # -- firing ------------------------------------------------------------
    def _windows(self, key, st: _KeyPanes, eos: bool):
        """All fireable windows of one key: complete ones, plus opened
        partials at EOS.  Returns (wids, tss, values) arrays."""
        n = st.base + len(st.vals)  # contiguous pane count
        W, S = self.win, self.slide
        if eos:  # every opened window fires, partial extents included
            w_hi = (n - 1) // S if n else -1
        else:    # only complete extents
            w_hi = (n - W) // S if n >= W else -1
        if w_hi < st.next_fire:
            return None
        ws = np.arange(st.next_fire, w_hi + 1, dtype=np.int64)
        starts = ws * S - st.base
        ends = np.minimum(starts + W, len(st.vals))
        if self.kind == "sum":
            # one cumsum covers all (overlapping) windows of the batch
            cs = np.concatenate([[0.0], np.cumsum(st.vals)])
            vals = cs[ends] - cs[starts]
        else:
            ufunc = np.maximum if self.kind == "max" else np.minimum
            # partial extents only occur at the tail (EOS)
            n_full = len(ws) - int((ends - starts < W).sum())
            vals = np.empty(len(ws), np.float64)
            if n_full:
                # complete extents share width W: one strided view,
                # one vectorized reduction over axis 1
                view = np.lib.stride_tricks.sliding_window_view(
                    st.vals, W)[starts[:n_full]]
                vals[:n_full] = (view.max(axis=1) if self.kind == "max"
                                 else view.min(axis=1))
            for j in range(n_full, len(ws)):  # EOS partials: few
                vals[j] = ufunc.reduce(st.vals[starts[j]:ends[j]])
        tss = st.ts[ends - 1]
        st.next_fire = w_hi + 1
        # evict panes no later window reaches
        cut = min(st.next_fire * S - st.base, len(st.vals))
        if cut > 0:
            st.vals = st.vals[cut:]
            st.ts = st.ts[cut:]
            st.base += cut
        return ws, tss, vals

    def _emit(self, key, fired, emit) -> None:
        ws, tss, vals = fired
        if self.emit_batches and isinstance(key, (int, np.integer)):
            emit(TupleBatch({"key": np.full(len(ws), key, np.int64),
                             "id": ws, "ts": tss, "value": vals}))
            return
        for w, ts, v in zip(ws.tolist(), tss.tolist(), vals.tolist()):
            out = self.result_factory()
            out.value = float(v)
            out.set_control_fields(key, w, ts)
            emit(out)

    # -- NodeLogic ---------------------------------------------------------
    def _key_state(self, key) -> _KeyPanes:
        st = self.keys.get(key)
        if st is None:
            st = self.keys[key] = _KeyPanes()
        return st

    def svc(self, item, channel_id, emit) -> None:
        if isinstance(item, EOSMarker):
            return  # triggering is purely count-based here
        if isinstance(item, TupleBatch):
            from .win_seq_tpu import _key_groups
            keys = item.key
            order, keys_s, bounds = _key_groups(keys)
            ids, tss, vals = item.id, item.ts, item["value"]
            if order is not None:
                ids, tss, vals = ids[order], tss[order], vals[order]
            for j in range(len(bounds) - 1):
                lo, hi = int(bounds[j]), int(bounds[j + 1])
                key = keys_s[lo].item()
                st = self._key_state(key)
                self._append(st, ids[lo:hi], tss[lo:hi],
                             vals[lo:hi].astype(np.float64))
                fired = self._windows(key, st, eos=False)
                if fired is not None:
                    self._emit(key, fired, emit)
            return
        key, pid, ts = item.get_control_fields()
        st = self._key_state(key)
        self._append(st, np.asarray([pid], np.int64),
                     np.asarray([ts], np.int64),
                     np.asarray([item.value], np.float64))
        fired = self._windows(key, st, eos=False)
        if fired is not None:
            self._emit(key, fired, emit)

    def eos_flush(self, emit) -> None:
        for key, st in self.keys.items():
            fired = self._windows(key, st, eos=True)
            if fired is not None:
                self._emit(key, fired, emit)

    # -- checkpoint --------------------------------------------------------
    def state_dict(self):
        return {"keys": {k: (st.vals.copy(), st.ts.copy(), st.base,
                             st.next_fire, dict(st.pending))
                         for k, st in self.keys.items()}}

    def load_state(self, state) -> None:
        self.keys = {}
        for k, (vals, ts, base, next_fire, pending) in \
                state["keys"].items():
            st = self.keys[k] = _KeyPanes()
            st.vals = np.asarray(vals, np.float64).copy()
            st.ts = np.asarray(ts, np.int64).copy()
            st.base = base
            st.next_fire = next_fire
            st.pending = dict(pending)
