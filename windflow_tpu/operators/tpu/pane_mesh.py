"""PaneFarmMesh: multi-chip Pane_Farm with the ring pane combine.

BASELINE config #3 ("pane partial agg + window combine") at mesh scale
as one graph operator: keys shard over the mesh 'key' axis, each key's
pane timeline chunks over the 'win' axis, and sliding windows spanning
chunk boundaries fetch neighbour panes with ``ppermute`` hops
(parallel/sharded.compute_pf_ring) -- the ring sequence-parallel
version of the reference's two-stage PLQ/WLQ decomposition
(pane_farm.hpp:178-214; pane partials per Li et al. SIGMOD'05).

Host plane: one logic pane-reduces each key's series on ingest (the
PLQ applied as a transport optimization, shipping partials not tuples)
and stages fixed-size **epochs** of ``P_total`` panes per key.  Windows
whose extent crosses an epoch's end are recomputed from the carried
tail panes of the next epoch, so every window is emitted exactly once.
Keys advance through epochs independently; each launch groups keys at
the same epoch (rows padded to the mesh's key-axis multiple).

Scope: builtin ``sum``/``count``/``max``/``min`` or FFAT lift+combine
windows (``mean`` is rejected: pane partials carry no count channel)
over dense per-key ids (CB) or timestamps (TB); win/slide must be
pane-aligned multiples.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ...core.basic import OrderingMode, Pattern, RoutingMode, WinType
from ...core.tuples import TupleBatch
from ...runtime.emitters import StandardEmitter
from ...runtime.node import EOSMarker, NodeLogic
from ..base import Operator, StageSpec


class _PaneKeyState:
    __slots__ = ("panes", "pane_base", "max_id", "partial", "partial_pane")

    def __init__(self, neutral: float = 0.0):
        self.panes: List[float] = []  # complete pane partials
        self.pane_base = 0            # global pane index of panes[0]
        self.max_id = -1
        self.partial = neutral        # open (incomplete) pane accumulator
        self.partial_pane = 0         # its global pane index


class PaneFarmMeshLogic(NodeLogic):
    def __init__(self, engine, win_len: int, slide_len: int,
                 win_type: WinType, panes_per_epoch: int = 64,
                 emit_batches: bool = True):
        self.engine = engine
        self.kind = engine.kind
        self.combine = engine.combine
        self.neutral = engine.neutral
        self.lift = engine.lift
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.emit_batches = emit_batches
        self.pane = int(np.gcd(win_len, slide_len))
        self.wpp = win_len // self.pane
        self.spp = slide_len // self.pane
        W = engine.n_win_shards
        # epoch size: multiple of (W * spp) and > wpp so at least one
        # window completes per epoch
        per = max(panes_per_epoch, self.wpp + self.spp)
        unit = W * self.spp
        self.p_total = ((per + unit - 1) // unit) * unit
        # windows fully inside one epoch; consumption advances by whole
        # windows (wpp and spp are coprime after the gcd, so the carry
        # tail is p_total - n_valid*spp, not wpp - spp)
        self.n_valid = (self.p_total - self.wpp) // self.spp + 1
        self.consumed_per_epoch = self.n_valid * self.spp
        self.keys: Dict[Any, _PaneKeyState] = {}
        self.launched_batches = 0

    # upper bound on panes materialized for one id/ts gap: beyond this
    # the stream is outside the dense-id contract (e.g. epoch-millis
    # timestamps with a mis-sized pane) and filling would OOM
    MAX_GAP_PANES = 1 << 20

    def _fold_chunk(self, partial: float, vals) -> float:
        """Fold one chunk of a pane's values into its open accumulator
        (the host PLQ, generalized over the combine kind)."""
        k = self.kind
        if k == "sum":
            return partial + float(vals.sum())
        if k == "count":
            return partial + float(len(vals))
        if k == "max":
            return max(partial, float(vals.max()))
        if k == "min":
            return min(partial, float(vals.min()))
        # ffat: host-side lift + pairwise combine tree (the __host__
        # half of the reference's combine contract,
        # flatfat_gpu.hpp:68-82) -- log2(n) array-level combine calls
        # per chunk, not one scalar dispatch per tuple
        from ...parallel.sharded import pairwise_fold
        seq = np.asarray(self.lift(vals) if self.lift is not None
                         else vals, np.float64)
        if not len(seq):
            return partial
        return float(self.combine(
            partial, pairwise_fold(seq, self.combine, self.neutral, np)))

    # -- host PLQ: pane pre-reduction ---------------------------------
    def _ingest_key(self, key, ids, vals) -> None:
        st = self.keys.get(key)
        if st is None:
            st = self.keys[key] = _PaneKeyState(self.neutral)
            # anchor the pane timeline at the first window containing
            # the first tuple (not pane 0): a large first id/ts (e.g.
            # epoch-millis TB streams) must not materialize ~1e9 empty
            # panes from an implicit 0 anchor
            first = int(ids[0]) // self.pane
            # first window whose extent can contain pane `first`, but
            # never anchored past it: sampling windows (spp > wpp)
            # leave inter-window gap panes, and pane_base must stay
            # <= first so ingest's gap accounting holds
            w0 = max(0, (first - self.wpp) // self.spp + 1)
            st.pane_base = min(w0, first // self.spp) * self.spp
            st.partial_pane = st.pane_base
        # pane index per tuple; ids must be non-decreasing per key
        p = ids // self.pane
        st.max_id = max(st.max_id, int(ids[-1]))
        lo = 0
        while lo < len(p):
            cur = int(p[lo])
            hi = int(np.searchsorted(p, cur + 1, "left"))
            if cur > st.partial_pane:
                gap = cur - st.partial_pane - 1
                if gap > self.MAX_GAP_PANES:
                    raise ValueError(
                        f"PaneFarmMesh: id/ts gap of {gap} empty panes "
                        f"for key {key!r} exceeds MAX_GAP_PANES "
                        f"({self.MAX_GAP_PANES}); stream violates the "
                        "dense-id scope (check pane/window sizing)")
                # panes up to cur-1 are complete
                st.panes.append(st.partial)
                st.panes.extend([self.neutral] * gap)  # empty panes
                st.partial = self.neutral
                st.partial_pane = cur
            st.partial = self._fold_chunk(st.partial, vals[lo:hi])
            lo = hi

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        if isinstance(item, TupleBatch):
            keys = item.key
            ids = item.id if self.win_type == WinType.CB else item.ts
            vals = item["value"]
            if len(keys) > 1 and not np.all(keys[:-1] <= keys[1:]):
                order = np.argsort(keys, kind="stable")
                keys, ids, vals = keys[order], ids[order], vals[order]
            edges = np.nonzero(np.diff(keys))[0] + 1
            bounds = np.concatenate([[0], edges, [len(keys)]])
            for j in range(len(bounds) - 1):
                lo, hi = int(bounds[j]), int(bounds[j + 1])
                self._ingest_key(keys[lo].item(), ids[lo:hi], vals[lo:hi])
        else:
            key, tid, ts = item.get_control_fields()
            id_ = tid if self.win_type == WinType.CB else ts
            self._ingest_key(key, np.array([id_]), np.array([item.value]))
        self._launch_ready(emit)

    # -- epoch launches over the ring ---------------------------------
    def _ready_keys(self) -> List[Any]:
        # a key is epoch-ready when it has p_total complete panes beyond
        # its epoch base (pane_base counts consumed panes already)
        return [k for k, st in self.keys.items()
                if len(st.panes) >= self.p_total]

    def _launch_ready(self, emit) -> None:
        while True:
            ready = self._ready_keys()
            if not ready:
                return
            self._launch(ready, emit, real_counts=None)

    def _launch(self, ready: List[Any], emit,
                real_counts: Dict[Any, int] = None) -> None:
        """One epoch over the ring.  Steady state (real_counts=None):
        emit the n_valid full windows and advance by whole windows,
        carrying the tail panes.  EOS (real_counts set): the timeline
        was zero-padded to p_total; emit every window starting inside
        the key's real panes (zeros give the partial tail sums), then
        drop the key's panes entirely."""
        S = self.engine.n_key_shards
        K = ((len(ready) + S - 1) // S) * S  # pad rows to the key axis
        # neutral-padded staging: clipped EOS tail windows then combine
        # only the real panes
        pane_vals = np.full((K, self.p_total, 1), self.neutral, np.float32)
        for r, key in enumerate(ready):
            panes = self.keys[key].panes
            take = min(self.p_total, len(panes))
            pane_vals[r, :take, 0] = panes[:take]
        out = np.asarray(self.engine.compute_pf_ring(pane_vals, 1))
        self.launched_batches += 1
        rec_keys: List = []
        rec_wids: List[int] = []
        rec_vals: List[float] = []
        for r, key in enumerate(ready):
            st = self.keys[key]
            base_win = st.pane_base // self.spp
            if real_counts is None:
                n_emit = self.n_valid
            else:
                # EOS: windows starting inside the real panes, clamped
                # to the epoch's unmasked range; later starts re-emerge
                # in the next EOS epoch after normal consumption
                n_emit = min(-(-real_counts[key] // self.spp),
                             self.n_valid)
            for w in range(n_emit):
                rec_keys.append(key)
                rec_wids.append(base_win + w)
                rec_vals.append(float(out[r, w]))
            if real_counts is None \
                    or real_counts[key] > self.consumed_per_epoch:
                st.panes = st.panes[self.consumed_per_epoch:]
                st.pane_base += self.consumed_per_epoch
            else:
                st.panes = []
        if not rec_keys:
            return
        if self.emit_batches:
            n = len(rec_keys)
            emit(TupleBatch({
                "key": np.asarray(rec_keys, np.int64),
                "id": np.asarray(rec_wids, np.int64),
                "ts": np.zeros(n, np.int64),
                "value": np.asarray(rec_vals, np.float64)}))
        else:
            from ...core.tuples import BasicRecord
            for k, w, v in zip(rec_keys, rec_wids, rec_vals):
                emit(BasicRecord(k, w, 0, v))

    def eos_flush(self, emit):
        # close each key's open pane, then drain EOS epochs: the staging
        # array pads short timelines with the combine's neutral, so
        # clipped tail windows come out as partial combines
        for st in self.keys.values():
            if st.max_id >= 0:
                st.panes.append(st.partial)
                st.partial = self.neutral
                st.partial_pane += 1
        while True:
            remaining = [k for k, st in self.keys.items() if st.panes]
            if not remaining:
                return
            real = {k: len(self.keys[k].panes) for k in remaining}
            self._launch(remaining, emit, real_counts=real)


class PaneFarmMesh(Operator):
    """Mesh-scale Pane_Farm over the ring collective (config #3)."""

    def __init__(self, mesh, win_len: int, slide_len: int,
                 win_type: WinType, panes_per_epoch: int = 64,
                 name: str = "pane_farm_mesh", emit_batches: bool = True,
                 kind="sum"):
        super().__init__(name, 1, RoutingMode.FORWARD,
                         Pattern.PANE_FARM_TPU)
        from ...parallel.sharded import ShardedWindowEngine
        # NOTE: unlike the farm-based Pane_Farm planes (sliding-only,
        # pane_farm.hpp:170-173), the epoch/ring decomposition has no
        # PLQ renumbering to misalign, so tumbling and hopping windows
        # are supported here (covered by test_mesh_farm geometry tests)
        self.win_type = win_type
        # the host pre-reduces panes, so the ring engine works in PANE
        # units: its window = wpp panes of width 1, slide = spp panes.
        # ``kind``: builtin combine or ('ffat', lift, combine, neutral);
        # 'mean' is rejected (panes carry no count channel).  A window
        # whose extent holds only empty panes combines to the kind's
        # neutral, not the single-chip engines' masked 0.
        if kind == "mean":
            raise ValueError(
                "PaneFarmMesh does not support 'mean': pane partials "
                "carry no count channel (use KeyFarmMesh)")
        pane = int(np.gcd(win_len, slide_len))
        self.engine = ShardedWindowEngine(mesh, win_len // pane,
                                          slide_len // pane, kind)
        self.args = (win_len, slide_len, win_type, panes_per_epoch,
                     emit_batches)

    def stages(self):
        win_len, slide_len, win_type, ppe, eb = self.args
        logic = PaneFarmMeshLogic(self.engine, win_len, slide_len,
                                  win_type, ppe, eb)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing,
                          ordering_mode=(OrderingMode.ID
                                         if win_type == WinType.CB
                                         else OrderingMode.TS))]
