"""Win_Seq_TPU: the device-batched keyed window engine.

Re-design of reference ``wf/win_seq_gpu.hpp`` (769 LoC): where the
reference archives tuples per key, batches ``batch_len`` fired windows,
copies them to pinned buffers and launches a CUDA kernel per batch on a
private stream (svc :391-645), this engine:

* keeps each key's series in growing host buffers (consolidated into
  sorted numpy arrays at flush time -- the pinned-staging analogue);
* accumulates descriptors of fired windows (key, gwid, extent) until
  ``batch_len``;
* assembles one flat ragged buffer + [start, end) extents and launches
  a jitted XLA program via `WindowComputeEngine` (ops/window_compute);
* overlaps host batching with device execution through async dispatch,
  flushing the *previous* batch's results lazily -- the double-buffered
  ``waitAndFlush`` protocol (win_seq_gpu.hpp:267-297).

Window-id assignment (config/role arithmetic) is identical to the host
engine, so this operator drops into every composite farm exactly like
Win_Seq_GPU does in the reference (win_farm_gpu.hpp:82-86).
"""
from __future__ import annotations

import threading as _threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ...core.basic import (OrderingMode, Pattern, Role, RoutingMode,
                           WinOperatorConfig, WinType)
from ...core.meta import default_hash
from ...core.tuples import BasicRecord, SynthChunk, TupleBatch
from ...core import win_assign as wa
from ...ops.window_compute import WindowComputeEngine
from ...runtime.emitters import StandardEmitter
from ...runtime.node import EOSMarker, NodeLogic
from ...telemetry.profiler import launch_span
from ..base import Operator, StageSpec

DEFAULT_BATCH_LEN = 256
# host staging-buffer capacity (elements) before a forced flush
DEFAULT_MAX_BUFFER_ELEMS = 1 << 19
# device launches kept in flight before the oldest is flushed.  8 deep
# (was 4): over a high-latency transport the pipeline must hold enough
# programs that one RTT amortizes over several launches; the adaptive
# batch resize below keeps per-launch latency bounded regardless
DEFAULT_INFLIGHT_DEPTH = 8
# partial-batch launch trigger (latency bound), milliseconds
DEFAULT_MAX_BATCH_DELAY_MS = 10.0

PLACEMENTS = ("device", "host", "auto")


class AdaptiveBatcher:
    """x2 / /2 device-batch resize driven by observed launch latency
    against the measured transport RTT floor -- the adaptation loop of
    the reference's pinned-buffer management (win_seq_gpu.hpp:574-592),
    re-aimed at a transport where the launch floor, not buffer size,
    is the cost.

    * launch latency ~ the floor (<= ``grow_below`` x): the launch is
      transport-bound -- the batch is too small to amortize the round
      trip; after ``patience`` consecutive such launches the batch
      DOUBLES.
    * launch latency >> the floor (>= ``shrink_above`` x): compute or
      queueing dominates and per-window latency grows with the batch;
      after ``patience`` such launches the batch HALVES.
    * in between: the operating point is good; streaks reset.

    Deterministic on a given latency trace (unit-tested against
    scripted traces).  The engine reads ``batch_len`` between launches,
    so resizes take effect on the next batch assembly."""

    __slots__ = ("batch_len", "floor_ms", "lo", "hi", "grow_below",
                 "shrink_above", "patience", "_grow", "_shrink",
                 "resizes")

    def __init__(self, batch_len: int, floor_ms: float, lo: int = 64,
                 hi: int = 1 << 16, grow_below: float = 2.0,
                 shrink_above: float = 8.0, patience: int = 3):
        if floor_ms <= 0:
            raise ValueError("floor_ms must be > 0")
        # an explicitly configured batch_len outside the default band
        # widens the band rather than being silently clamped away
        self.batch_len = max(1, int(batch_len))
        self.floor_ms = floor_ms
        self.lo = min(lo, self.batch_len)
        self.hi = max(hi, self.batch_len)
        self.grow_below = grow_below
        self.shrink_above = shrink_above
        self.patience = patience
        self._grow = 0
        self._shrink = 0
        self.resizes: List = []  # (direction, new_len) decision log

    def observe(self, launch_ms: float) -> int:
        if launch_ms <= self.grow_below * self.floor_ms:
            self._grow += 1
            self._shrink = 0
            if self._grow >= self.patience and self.batch_len < self.hi:
                self.batch_len = min(self.hi, self.batch_len * 2)
                self.resizes.append(("x2", self.batch_len))
                self._grow = 0
        elif launch_ms >= self.shrink_above * self.floor_ms:
            self._shrink += 1
            self._grow = 0
            if self._shrink >= self.patience and self.batch_len > self.lo:
                self.batch_len = max(self.lo, self.batch_len // 2)
                self.resizes.append(("/2", self.batch_len))
                self._shrink = 0
        else:
            self._grow = 0
            self._shrink = 0
        return self.batch_len


def _key_groups(keys: np.ndarray):
    """Stable-group a key column: (order, keys_sorted, bounds) with
    ``order`` None when the column is already sorted (saves the
    re-index on the columnar hot path)."""
    if len(keys) > 1 and not np.all(keys[:-1] <= keys[1:]):
        order = np.argsort(keys, kind="stable")
        keys_s = keys[order]
    else:
        order, keys_s = None, keys
    edges = np.nonzero(np.diff(keys_s))[0] + 1
    bounds = np.concatenate([[0], edges, [len(keys_s)]])
    return order, keys_s, bounds


class _AsyncDispatcher:
    """Dedicated launch thread: the ingest thread stages numpy buffers
    and hands them off; this thread pays the host->device transfer
    latency, keeps ``inflight_depth`` programs in flight, and emits
    completed results.  The reference overlaps CUDA streams with host
    batching on ONE thread (win_seq_gpu.hpp:267-297); over a
    high-latency PJRT transport the dispatch itself blocks for a round
    trip, so it must come off the ingest thread entirely."""

    __slots__ = ("logic", "work", "thread", "error", "aborting")

    def __init__(self, logic: "WinSeqTPULogic"):
        import queue as _q
        import threading as _t
        self.logic = logic
        self.work = _q.Queue(maxsize=max(1, logic.inflight_depth))
        self.error: Optional[BaseException] = None
        self.aborting = False
        self.thread = _t.Thread(target=self._run, daemon=True,
                                name="winseq-tpu-dispatch")
        self.thread.start()

    def submit(self, item) -> None:
        import queue as _q
        # bounded put re-checking for a dead/failed dispatcher: a plain
        # blocking put could hang forever if the thread errors out while
        # the queue is full (nothing would ever drain it)
        while True:
            if self.error is not None:
                raise RuntimeError("window dispatch thread failed") \
                    from self.error
            try:
                self.work.put(item, timeout=0.25)
                return
            except _q.Full:
                continue

    def drain(self) -> None:
        """EOS barrier: launch everything staged, flush every handle."""
        import queue as _q
        while True:  # the consumer drains even after an error, so the
            try:     # sentinel always fits eventually
                self.work.put(None, timeout=0.25)
                break
            except _q.Full:
                continue
        self.thread.join()
        if self.error is not None:
            raise RuntimeError("window dispatch thread failed") \
                from self.error

    def abort(self) -> None:
        """Node-error teardown: drop the backlog without launching it
        (no EOS barrier -- the downstream channel is closing)."""
        import queue as _q
        self.aborting = True
        try:
            self.work.put_nowait(None)
        except _q.Full:
            pass  # the run loop polls `aborting` on empty reads
        self.thread.join(timeout=30)

    def _run(self) -> None:
        from collections import deque
        import queue as _q
        logic = self.logic
        pending = deque()
        last_emit = None
        while True:
            try:
                # fine-grained poll while batches are in flight: their
                # async D2H lands mid-stream and must be emitted then,
                # not at the next launch (latency would otherwise grow
                # with the launch interval)
                item = self.work.get(timeout=0.005 if pending else 0.25)
            except _q.Empty:
                if self.aborting:
                    return
                while (pending and self.error is None
                       and not self.aborting and pending[0][0].ready()):
                    try:
                        logic._finish(pending.popleft(), last_emit)
                    except BaseException as e:
                        self.error = e
                continue
            if item is None:
                break
            if self.aborting or self.error is not None:
                continue  # failed/aborted: drain the queue, launch nothing
            (engine, cols, starts, ends, gwids, descs, birth, emit,
             nbytes_in) = item
            last_emit = emit
            try:
                t_sub = _time.perf_counter()
                # jax.profiler capture hook (telemetry/profiler.py):
                # a no-op unless WINDFLOW_JAX_PROFILE=1
                with launch_span("windflow/window_launch"):
                    handle = engine.compute(cols, starts, ends, gwids)
                logic.launched_batches += 1
                pending.append((handle, descs, birth, t_sub,
                                len(pending) + 1, nbytes_in))
                # flush at depth (backpressure) AND any batch whose
                # async D2H already landed -- otherwise results wait
                # for the pipeline to fill and latency grows with
                # inflight_depth instead of shrinking
                while (pending and not self.aborting
                       and (len(pending) >= logic.inflight_depth
                            or pending[0][0].ready())):
                    logic._finish(pending.popleft(), emit)
            except BaseException as e:  # surfaced on next submit / drain
                self.error = e
        while pending and self.error is None and not self.aborting:
            try:
                logic._finish(pending.popleft(), last_emit)
            except BaseException as e:
                self.error = e


class _TPUKeyState:
    __slots__ = ("sort_keys", "ts", "values", "pending_sort", "pending_ts",
                 "pending_val", "pending_chunks", "next_fire", "opened_max",
                 "max_id", "renumber_next", "emit_counter", "anchor",
                 "pane_synced", "min_new_id")

    def __init__(self, emit_counter_start=0):
        # resident-lane sync state (ops/window_compute.ResidentPaneCarry):
        # pane indices below ``pane_synced`` are final in the device
        # forest; ``min_new_id`` tracks the smallest id appended since
        # the last launch, so a launch ships only panes the new data
        # could have changed (None = everything dirty / nothing new)
        self.pane_synced = None
        self.min_new_id = None
        # consolidated sorted arrays
        self.sort_keys = np.empty(0, np.int64)
        self.ts = np.empty(0, np.int64)
        self.values = np.empty(0, np.float64)
        # unsorted pending appends (sorted at consolidation): scalar
        # lists for the record plane, array chunks for the batch plane
        self.pending_sort: List[int] = []
        self.pending_ts: List[int] = []
        self.pending_val: List[float] = []
        self.pending_chunks: List = []
        self.next_fire = 0        # next lwid to fire
        self.anchor = 0           # first window that can ever fire (set
                                  # from the first tuple, like the
                                  # native engine's anchor)
        self.opened_max = -1      # highest lwid opened by any tuple
        self.max_id = -1
        self.renumber_next = 0
        self.emit_counter = emit_counter_start


class WinSeqTPULogic(NodeLogic):
    # the runtime hands SynthChunk descriptors through un-materialized
    accepts_synth_chunks = True
    # async dispatch calls emit from the dispatcher thread AFTER svc
    # returns: the runtime must not hand this logic a buffered emit
    # (set per instance in __init__; inline dispatch is synchronous)
    sync_emit = False

    def __init__(self, win_kind: Any, win_len: int, slide_len: int,
                 win_type: WinType, *, batch_len: int = DEFAULT_BATCH_LEN,
                 triggering_delay: int = 0, result_factory=BasicRecord,
                 config: WinOperatorConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), parallelism: int = 1,
                 replica_index: int = 0, renumbering: bool = False,
                 value_of: Callable[[Any], float] = None,
                 closing_func: Callable = None, emit_batches: bool = False,
                 max_buffer_elems: int = DEFAULT_MAX_BUFFER_ELEMS,
                 inflight_depth: int = DEFAULT_INFLIGHT_DEPTH,
                 async_dispatch: bool = True,
                 max_batch_delay_ms: float = DEFAULT_MAX_BATCH_DELAY_MS,
                 placement: str = "device",
                 adaptive_batch: bool = False,
                 rtt_floor_ms: Optional[float] = None,
                 resident: Optional[bool] = None):
        if win_len == 0 or slide_len == 0:
            raise ValueError("win_len and slide_len must be > 0")
        if placement not in PLACEMENTS:
            raise ValueError(
                f"placement must be one of {PLACEMENTS}, not {placement!r}")
        # placement plane (graph/planner.py; docs/PLANNER.md): 'device'
        # keeps the XLA lane (status quo), 'host' swaps in the numpy
        # host engine at construction, 'auto' defers to the cost-based
        # planner at PipeGraph.start
        self.placement = placement
        self.resolved_placement = placement if placement != "auto" else None
        self.adaptive_batch = adaptive_batch
        self.rtt_floor_ms = rtt_floor_ms
        self._adaptive: Optional[AdaptiveBatcher] = None
        if placement == "host":
            from ...ops.host_compute import HostComputeEngine
            self.engine = HostComputeEngine(win_kind)  # builtin kinds only
        else:
            self.engine = WindowComputeEngine(win_kind)
        # direct-feed plane (ingest/feed.py): parallel feeder threads
        # call feed_columns concurrently; staging is single-writer
        self._feed_lock = _threading.Lock()
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.batch_len = max(1, batch_len)
        self.triggering_delay = triggering_delay
        self.result_factory = result_factory
        self.config = config or WinOperatorConfig()
        self.role = role
        self.map_indexes = map_indexes
        self.renumbering = renumbering
        self.value_of = value_of or (lambda t: t.value)
        self.closing_func = closing_func
        self.emit_batches = emit_batches
        self.keys: Dict[Any, _TPUKeyState] = {}
        # batch under assembly: descriptors (key, gwid, start_key, end_key)
        self.descriptors: List = []
        # in-flight batches, oldest first: (handle, descriptors, birth).
        # Depth > 1 keeps several device programs + async D2H copies in
        # flight so one high-latency transport roundtrip amortizes over
        # many launches (deepens the reference's 2-deep waitAndFlush
        # pipeline, win_seq_gpu.hpp:267-297).
        from collections import deque
        self.pending = deque()
        self.inflight_depth = max(1, inflight_depth)
        self.async_dispatch = async_dispatch
        self.sync_emit = not async_dispatch
        self._dispatcher: Optional[_AsyncDispatcher] = None
        self.ignored_tuples = 0
        self.launched_batches = 0
        self.last_launch_ms = 0.0  # newest submit->result wall (ms)
        # launch also when this much unshipped data is buffered, even if
        # the window batch is not full -- bounds host memory and keeps
        # device transfers pipelined (the adaptive resize analogue,
        # win_seq_gpu.hpp:574-592)
        self.max_buffer_elems = max_buffer_elems
        self._buffered_since_launch = 0
        # time-based launch trigger: a partial batch launches whenever
        # windows are ready and at least this long has passed since the
        # previous launch -- the latency half of the reference's
        # adaptive batch resize (win_seq_gpu.hpp:574-592), bounding
        # result latency at (delay + transport RTT) instead of
        # (full-batch fill time + RTT)
        self.max_batch_delay_ms = max_batch_delay_ms
        self._last_launch_t = 0.0
        # window-result latency samples (descriptor creation -> emission),
        # feeding the p99 metric of BASELINE.md
        self.latency_samples: List[float] = []
        self._batch_birth: Optional[float] = None
        # telemetry plane (telemetry/; docs/OBSERVABILITY.md): the
        # trace context of the most recent traced input crosses the
        # async dispatcher -- captured at svc, stamped with a device
        # hop and re-attached to the next finished result batch.  Set
        # on the ingest thread, consumed on the dispatcher thread:
        # gauge-grade for sampled traces, like the depth gauges
        self._trace_ctx = None
        self._trace_name = "win_seq_tpu"
        # whole-partition device step (graph/device_step.py): while a
        # chunk is traversing the fused chain the step logic holds all
        # intra-chunk launch triggers and calls flush_chunk() once at
        # the chunk boundary, so a device segment pays ONE launch per
        # ingest chunk instead of one per trigger site.  eos_flush /
        # quiesce / idle_tick stay unguarded -- they run between
        # chunks, where the hold is always clear.
        self.chunk_hold = False
        # the C++ columnar engine covers the hot standalone cases
        # (native/window_engine.cpp): builtin kinds, identity window
        # assignment, default value column, role SEQ -- or role PLQ,
        # whose only difference under an identity config is that output
        # ids are per-key dense counters (plq_renumbered_id degenerates
        # to the emit counter), applied on the flushed batch
        self._native = None
        # resident lane (docs/PLANNER.md "Resident state"): per-key
        # pane partials stay device-resident across launches; a launch
        # ships only new/changed partials.  True forces it on (and
        # takes the Python staging path -- the native engine stages its
        # own pane buffers), False opts out, None lets the planner
        # promote eligible device-lane engines.
        self.resident = resident
        self._resident = None
        self._plq_counters: Dict[Any, int] = {}
        # non-integral record keys (the reference's templated key types)
        # are interned into a reserved negative int64 range for the
        # columnar/native stores and translated back on emission
        self._key_intern: Dict[Any, int] = {}
        self._key_extern: Dict[int, Any] = {}
        self._saw_nonint_key = False
        cfg = self.config
        if (isinstance(win_kind, str)
                and win_kind in ("sum", "count", "max", "min", "mean")
                and role in (Role.SEQ, Role.PLQ)
                and cfg.n_outer == 1 and cfg.n_inner == 1
                and cfg.id_outer == 0 and cfg.id_inner == 0
                and value_of is None and resident is not True):
            try:
                from ...runtime.native import (NativeWindowEngine,
                                               native_available)
                if native_available():
                    # renumbering = per-key arrival-order ids, which the
                    # engine implements natively (ids implicit, always
                    # on the dense lane)
                    self._native = NativeWindowEngine(
                        win_len, slide_len, win_type == WinType.TB,
                        triggering_delay, renumber=renumbering,
                        kind=win_kind)
            except Exception:
                self._native = None
        if resident is True:
            self._enable_resident(required=True)

    # -- placement plane (graph/planner.py; docs/PLANNER.md) ---------------
    def apply_placement(self, placement: str,
                        rtt_floor_ms: Optional[float] = None) -> None:
        """Resolve this engine onto a lane.  Called by the planner at
        graph start (before any thread runs) for 'auto' engines, and
        for pinned ones to record the resolution + RTT floor.  Host
        resolution swaps the XLA engine for the numpy host engine and
        drops any cached helper engines so they rebuild on-lane."""
        from ...ops.host_compute import HostComputeEngine
        if placement not in ("device", "host"):
            raise ValueError(f"cannot resolve onto {placement!r}")
        self.resolved_placement = placement
        if rtt_floor_ms:
            self.rtt_floor_ms = rtt_floor_ms
        if placement == "host":
            # the host lane computes against the host staging store
            # directly: drop any resident device state (recomputable
            # from the retained series on a later flip back)
            self._resident = None
            for st in self.keys.values():
                st.pane_synced = None
                st.min_new_id = None
            if not isinstance(self.engine, HostComputeEngine):
                self.engine = HostComputeEngine(self.engine.kind)
                for cached in ("_count_eng", "_mean_eng"):
                    if hasattr(self, cached):
                        delattr(self, cached)
        elif isinstance(self.engine, HostComputeEngine):
            # online re-planning (graph/replanner.py) can flip a
            # host-resolved engine back: restore the XLA lane
            self.engine = WindowComputeEngine(self.engine.kind)
            for cached in ("_count_eng", "_mean_eng"):
                if hasattr(self, cached):
                    delattr(self, cached)

    def _make_engine(self, kind):
        """Helper-engine factory honouring the resolved lane (the
        count->sum and mean->pair engines must run where the main
        engine runs)."""
        if self.resolved_placement == "host":
            from ...ops.host_compute import HostComputeEngine
            return HostComputeEngine(kind)
        return WindowComputeEngine(kind)

    # -- resident lane (ops/window_compute.ResidentPaneCarry;
    # docs/PLANNER.md "Resident state & online re-planning") ---------------
    def resident_eligible(self) -> bool:
        """Shapes the resident pane carry serves: builtin monoid kind,
        pane length (gcd(win, slide)) long enough to pre-reduce, role
        SEQ on a device lane, Python staging (the native engine stages
        its own pane buffers).  Everything else keeps the rebuild
        path."""
        kind = getattr(self.engine, "kind", None)
        if not (isinstance(kind, str)
                and kind in ("sum", "count", "max", "min")):
            return False
        pane = int(np.gcd(self.win_len, self.slide_len))
        return (pane >= 16 and self.role == Role.SEQ
                and self._native is None
                and self.resolved_placement != "host")

    def _enable_resident(self, required: bool = False) -> bool:
        if self._resident is not None:
            return True
        if not self.resident_eligible():
            if required:
                raise ValueError(
                    "resident=True needs an eligible engine: builtin "
                    "sum/count/max/min kind, pane length (gcd(win, "
                    "slide)) >= 16, role SEQ and a device lane -- the "
                    "rebuild lane serves every other shape")
            return False
        from ...ops.window_compute import ResidentPaneCarry
        pane = int(np.gcd(self.win_len, self.slide_len))
        self._resident = ResidentPaneCarry(self.engine.kind,
                                           self.win_len // pane)
        for st in self.keys.values():
            st.pane_synced = None
        return True

    def maybe_enable_resident(self) -> bool:
        """Planner promotion hook (graph/planner.plan_graph): an
        undecided (resident=None) engine joins the resident lane when
        eligible; resident=False opts out, True forced it at
        construction."""
        if self.resident is False:
            return False
        return self._enable_resident()

    def _reset_resident(self) -> None:
        """Drop resident device state (restore / lane flip): the next
        launch re-ships live partials from the host retained series."""
        if self._resident is not None:
            self._resident.reset()
        for st in self.keys.values():
            st.pane_synced = None
            st.min_new_id = None

    def device_resident_bytes(self) -> int:
        """Gauge hook: bytes of window state resident in device memory
        (the ``Device_state_bytes_resident`` stats field)."""
        return (self._resident.state_bytes
                if self._resident is not None else 0)

    def svc_init(self) -> None:
        if self.stats is not None and self.stats.operator_name:
            self._trace_name = self.stats.operator_name
        # adaptive x2 / /2 batch resize (win_seq_gpu.hpp:574-592): only
        # meaningful against a launch floor, so the device lane measures
        # one (planner-provided, else probed once per process)
        if self.adaptive_batch and self._adaptive is None \
                and self.resolved_placement != "host":
            if not self.rtt_floor_ms:
                from ...graph.planner import rtt_floor_ms
                self.rtt_floor_ms = rtt_floor_ms()
            self._adaptive = AdaptiveBatcher(self.batch_len,
                                             self.rtt_floor_ms)

    # -- direct columnar feed (ingest/feed.py) -----------------------------
    def feed_columns(self, keys, ids, ts, vals, emit) -> None:
        """Thread-safe columnar ingest for parallel feeder threads:
        columns go straight into the staging store (the C++ engine when
        built) under the feed lock -- no channel hop, no per-tuple
        Python.  ``emit`` receives any results whose launch the ingest
        triggers (the async dispatcher keeps emitting after return)."""
        batch = TupleBatch({"key": np.asarray(keys, np.int64),
                            "id": np.asarray(ids, np.int64),
                            "ts": np.asarray(ts, np.int64),
                            "value": np.asarray(vals)})
        with self._feed_lock:
            self._svc_batch(batch, emit)

    def feed_eos(self, emit) -> None:
        """Drain hook for the direct-feed plane (pairs with
        ``feed_columns`` exactly like the record plane's feed_eos)."""
        with self._feed_lock:
            self.eos_flush(emit)

    # -- per-key helpers ---------------------------------------------------
    def _key_state(self, key) -> _TPUKeyState:
        st = self.keys.get(key)
        if st is None:
            start = self.map_indexes[0] if self.role == Role.MAP else 0
            st = self.keys[key] = _TPUKeyState(start)
        return st

    def _consolidate(self, st: _TPUKeyState) -> None:
        if not st.pending_sort and not st.pending_chunks:
            return
        chunks_sk = [c[0] for c in st.pending_chunks]
        chunks_ts = [c[1] for c in st.pending_chunks]
        chunks_v = [c[2] for c in st.pending_chunks]
        if st.pending_sort:
            chunks_sk.append(np.asarray(st.pending_sort, np.int64))
            chunks_ts.append(np.asarray(st.pending_ts, np.int64))
            chunks_v.append(np.asarray(st.pending_val, np.float64))
        st.pending_chunks.clear()
        sk = np.concatenate(chunks_sk)
        ts = np.concatenate(chunks_ts)
        vals = np.concatenate(chunks_v)
        order = np.argsort(sk, kind="stable")
        sk, ts, vals = sk[order], ts[order], vals[order]
        if len(st.sort_keys) and len(sk) and sk[0] < st.sort_keys[-1]:
            # out-of-order across consolidations (TB within delay): merge
            merged = np.concatenate([st.sort_keys, sk])
            order = np.argsort(merged, kind="stable")
            st.sort_keys = merged[order]
            st.ts = np.concatenate([st.ts, ts])[order]
            st.values = np.concatenate([st.values, vals])[order]
        else:
            st.sort_keys = np.concatenate([st.sort_keys, sk])
            st.ts = np.concatenate([st.ts, ts])
            st.values = np.concatenate([st.values, vals])
        st.pending_sort.clear()
        st.pending_ts.clear()
        st.pending_val.clear()

    def _evict(self, st: _TPUKeyState, initial_id: int) -> None:
        """Drop the prefix no window >= next_fire can reach (the archive
        purge, win_seq_gpu.hpp:612-614)."""
        keep_from = initial_id + st.next_fire * self.slide_len
        cut = np.searchsorted(st.sort_keys, keep_from, side="left")
        if cut:
            st.sort_keys = st.sort_keys[cut:]
            st.ts = st.ts[cut:]
            st.values = st.values[cut:]

    # -- batch plane -------------------------------------------------------
    def _finish(self, entry, emit) -> None:
        """Flush one in-flight batch: block on its handle, record the
        per-launch device time (submit -> result on host), sample the
        window-result latency, feed the adaptive batch resize, emit."""
        handle, descs, birth, t_sub, depth, nbytes_in = entry
        results = handle.block()
        now = _time.perf_counter()
        launch_ms = (now - t_sub) * 1e3
        self.last_launch_ms = launch_ms
        if len(self.latency_samples) < 100_000:
            self.latency_samples.append(now - birth)
        if self.stats is not None:  # single-writer: dispatcher thread
            self.stats.bytes_from_device += results.nbytes
            self.stats.device_time_ms += launch_ms
        if self._adaptive is not None:
            # x2 / /2 against the RTT floor; the new length applies to
            # the next batch assembly (ingest thread reads batch_len).
            # The wall includes queueing behind the other in-flight
            # launches on a serialized transport, so it is normalized
            # by the depth at submit: otherwise a saturated pipeline at
            # depth 8 always reads >= shrink_above x the floor and the
            # controller can only shrink under exactly the load it is
            # meant to optimize
            before = self.batch_len
            self.batch_len = self._adaptive.observe(launch_ms / depth)
            if self.batch_len != before and self.flight is not None:
                self.flight.record("batch_resize",
                                   operator=self._trace_name,
                                   old_len=before,
                                   new_len=self.batch_len,
                                   launch_ms=round(launch_ms, 3))
        # trace crossing (telemetry/): the sampled context captured at
        # svc gets an engine hop (submit -> result-on-host) and rides
        # the result batch to the sink.  On the device lane the
        # "@device" suffix keys the diagnosis plane's hop-class split
        # (device transport/compute vs host service --
        # diagnosis/attribution.py); the host lane's launches are host
        # service time and stamp plain
        tr = self._trace_ctx
        if tr is not None:
            self._trace_ctx = None
            name = self._trace_name
            if self.resolved_placement != "host":
                # device-lane hops carry launch count + transfer bytes
                # as gauge-grade hop meta so a whole-partition step
                # (graph/device_step.py) stays attributable as ONE
                # launch per chunk in the diagnosis plane
                tr.hop(name + "@device", t_sub, now,
                       meta={"launches": 1,
                             "bytes_in": int(nbytes_in),
                             "bytes_out": int(results.nbytes)})
            else:
                tr.hop(name, t_sub, now)
        self._emit_results(results, descs, emit, trace=tr)

    def _submit(self, cols, starts, ends, gwids, descs, birth, emit,
                engine=None) -> None:
        """Hand one staged batch to the device: via the dispatcher
        thread (default) or inline with the waitAndFlush protocol."""
        eng = engine or self.engine
        nbytes_in = (sum(int(np.asarray(c).nbytes) for c in cols.values())
                     + starts.nbytes + ends.nbytes + gwids.nbytes)
        if self.stats is not None:  # single-writer: ingest thread
            self.stats.num_launches += 1
            self.stats.bytes_to_device += nbytes_in
            self.stats.inputs_ignored = self.ignored_tuples
        if self.async_dispatch:
            if self._dispatcher is None:
                self._dispatcher = _AsyncDispatcher(self)
            self._dispatcher.submit(
                (eng, cols, starts, ends, gwids, descs, birth, emit,
                 nbytes_in))
        else:
            self._flush_pending(emit)  # waitAndFlush of the previous
            t_sub = _time.perf_counter()
            with launch_span("windflow/window_launch"):
                handle = eng.compute(cols, starts, ends, gwids)
            self.launched_batches += 1
            self.pending.append((handle, descs, birth, t_sub,
                                 len(self.pending) + 1, nbytes_in))
        self._buffered_since_launch = 0
        self._last_launch_t = _time.perf_counter()

    def _flush_pending(self, emit, drain: bool = False) -> None:
        """Emit completed in-flight batches: the oldest when the
        pipeline is at depth (waitAndFlush), any whose async D2H has
        landed, or all when draining (inline-dispatch mode only)."""
        while self.pending and (drain
                                or len(self.pending) >= self.inflight_depth
                                or self.pending[0][0].ready()):
            self._finish(self.pending.popleft(), emit)

    def _drain_all(self, emit) -> None:
        if self._dispatcher is not None:
            self._dispatcher.drain()
            self._dispatcher = None
        self._flush_pending(emit, drain=True)

    def _plq_renumber(self, d_keys: np.ndarray) -> np.ndarray:
        """Dense per-key output ids for the native PLQ lane: windows of
        a key arrive in firing order, so each gets the key's running
        emit counter (win_seq.hpp:484 with an identity config)."""
        out = np.empty(len(d_keys), np.int64)
        order, keys_s, bounds = _key_groups(d_keys)
        for j in range(len(bounds) - 1):
            lo, hi = int(bounds[j]), int(bounds[j + 1])
            key = int(keys_s[lo])
            start = self._plq_counters.get(key, 0)
            ids = np.arange(start, start + (hi - lo))
            if order is None:
                out[lo:hi] = ids
            else:
                out[order[lo:hi]] = ids
            self._plq_counters[key] = start + (hi - lo)
        return out

    # interned ids live below _INTERN_CEIL, far outside any plausible
    # user key, so a result batch can be tested for them vectorized
    _INTERN_BASE = -(1 << 62)
    _INTERN_CEIL = -(1 << 61)

    def _intern_key(self, key) -> int:
        iid = self._key_intern.get(key)
        if iid is None:
            iid = self._INTERN_BASE + len(self._key_intern)
            self._key_intern[key] = iid
            self._key_extern[iid] = key
        return iid

    def _emit_results(self, results, descs, emit, trace=None) -> None:
        if trace is not None:
            # the captured trace context rides the first emission of
            # this finished batch to the sink (batch lanes attach to
            # the whole result batch, record lanes to the first record)
            def emit(item, _e=emit, _t=trace):
                nonlocal trace
                if trace is not None:
                    trace = None
                    try:
                        item.trace = _t
                    except AttributeError:
                        pass
                _e(item)
        if isinstance(descs, tuple) and descs[0] == "native":
            # native-engine batch: columnar descriptor arrays
            _, d_keys, d_gwids, d_rts = descs
            if self.role == Role.PLQ:
                d_gwids = self._plq_renumber(d_keys)
            has_interned = (bool(self._key_extern) and len(d_keys)
                            and bool((d_keys < self._INTERN_CEIL).any()))
            if self.emit_batches and not has_interned:
                emit(TupleBatch({"key": d_keys, "id": d_gwids,
                                 "ts": d_rts,
                                 "value": np.asarray(results, np.float64)}))
            else:
                # per-record (also when interned keys must be restored:
                # a TupleBatch key column cannot carry them)
                ext = self._key_extern
                for i in range(len(d_keys)):
                    out = self.result_factory()
                    out.value = float(results[i])
                    k = int(d_keys[i])
                    out.set_control_fields(ext.get(k, k), int(d_gwids[i]),
                                           int(d_rts[i]))
                    emit(out)
            return
        if (self.emit_batches and self.role == Role.SEQ
                and (not self._saw_nonint_key    # O(1) common case
                     or all(isinstance(d[0], (int, np.integer))
                            for d in descs))):
            # columnar emission: one result TupleBatch per device batch
            # (any non-integral key in the batch falls through to
            # record emission below -- int and string keys can mix)
            out = TupleBatch({
                "key": np.fromiter((d[0] for d in descs), np.int64,
                                   len(descs)),
                "id": np.fromiter((d[1] for d in descs), np.int64,
                                  len(descs)),
                "ts": np.fromiter((d[4] for d in descs), np.int64,
                                  len(descs)),
                "value": np.asarray(results, np.float64),
            })
            emit(out)
            return
        for (key, gwid, _s, _e, rts, kd_key), val in zip(descs, results):
            out = self.result_factory()
            out.value = float(val)
            out.set_control_fields(key, gwid, rts)
            st = self.keys[kd_key]
            if self.role == Role.MAP:
                out.set_control_fields(key, st.emit_counter, rts)
                st.emit_counter += self.map_indexes[1]
            elif self.role == Role.PLQ:
                new_id = wa.plq_renumbered_id(default_hash(key),
                                              st.emit_counter, self.config)
                out.set_control_fields(key, new_id, rts)
                st.emit_counter += 1
            emit(out)

    # builtin associative kinds whose pane partials the host can
    # pre-reduce before shipping (the Pane_Farm decomposition, applied
    # as a transport optimization: ship partials, not tuples)
    _PANE_KINDS = {"sum": "sum", "count": "sum", "max": "max", "min": "min"}

    def _pane_partials(self, st: _TPUKeyState, base_key: int, n_panes: int,
                       pane: int, kind: str):
        """Per-pane host pre-reduction over one key's retained series."""
        edges = base_key + np.arange(n_panes + 1, dtype=np.int64) * pane
        pos = np.searchsorted(st.sort_keys, edges)
        if kind == "count":
            return np.diff(pos).astype(np.float64)
        from ...runtime.native import pane_reduce
        red = pane_reduce(st.values, pos, kind)  # exact [pos[i], pos[i+1])
        if red is not None:
            return red
        if kind == "sum":
            cs = np.concatenate([[0.0], np.cumsum(st.values)])
            return cs[pos[1:]] - cs[pos[:-1]]
        neutral = -np.inf if kind == "max" else np.inf
        ufunc = np.maximum if kind == "max" else np.minimum
        # reduceat over the non-empty panes' start edges only: empty
        # panes collapse to equal edges so each segment ends exactly at
        # the next non-empty pane's start, and clipping the buffer at
        # pos[-1] keeps retained tuples beyond the batch's last window
        # edge out of the final segment (reduceat runs it to the end)
        vals = st.values[:int(pos[-1])]
        out = np.full(n_panes, neutral)
        nonempty = np.nonzero(np.diff(pos) > 0)[0]
        if len(nonempty):
            out[nonempty] = ufunc.reduceat(vals, pos[nonempty])
        return out

    def _launch(self, emit) -> None:
        if not self.descriptors:
            return
        descs = self.descriptors
        self.descriptors = []
        # group descriptors per key (preserving order)
        keys_involved: List = []
        per_key: Dict = {}
        for i, d in enumerate(descs):
            if d[5] not in per_key:
                per_key[d[5]] = []
                keys_involved.append(d[5])
            per_key[d[5]].append(i)
        pane = int(np.gcd(self.win_len, self.slide_len))
        kind = self.engine.kind
        use_panes = (isinstance(kind, str) and kind in self._PANE_KINDS
                     and pane >= 16)
        if use_panes and self._resident is not None:
            self._launch_resident(descs, per_key, keys_involved, pane,
                                  kind, emit)
            return
        starts = np.empty(len(descs), np.int64)
        ends = np.empty(len(descs), np.int64)
        gwids = np.fromiter((d[1] for d in descs), np.int64, len(descs))
        bufs_v = []
        off = 0
        for k in keys_involved:
            st = self.keys[k]
            self._consolidate(st)
            idxs = per_key[k]
            if use_panes:
                # window extents are pane-aligned (pane = gcd(win, slide)
                # divides both the slide stride and the window length)
                base_key = min(descs[i][2] for i in idxs)
                max_end = max(descs[i][3] for i in idxs)
                n_panes = (max_end - base_key) // pane
                bufs_v.append(self._pane_partials(st, base_key, n_panes,
                                                  pane, kind))
                for i in idxs:
                    starts[i] = off + (descs[i][2] - base_key) // pane
                    ends[i] = off + (descs[i][3] - base_key) // pane
                off += n_panes
            else:
                bufs_v.append(st.values)
                for i in idxs:
                    starts[i] = off + np.searchsorted(st.sort_keys,
                                                      descs[i][2], "left")
                    ends[i] = off + np.searchsorted(st.sort_keys,
                                                    descs[i][3], "left")
                off += len(st.values)
            for i in idxs:  # CB: result ts = last tuple in extent
                if descs[i][4] < 0:
                    hi = int(np.searchsorted(st.sort_keys, descs[i][3],
                                             "left"))
                    lo = int(np.searchsorted(st.sort_keys, descs[i][2],
                                             "left"))
                    d = descs[i]
                    descs[i] = (d[0], d[1], d[2], d[3],
                                int(st.ts[hi - 1]) if hi > lo else 0, d[5])
        flat_vals = (np.concatenate(bufs_v) if bufs_v
                     else np.empty(0, np.float64))
        eng = self.engine
        if use_panes and kind == "count":
            eng = self._count_engine()
        birth = self._batch_birth or _time.perf_counter()
        self._batch_birth = None
        self._submit({"value": flat_vals}, starts, ends, gwids, descs,
                     birth, emit, engine=eng)
        # the staged flat buffer is dispatcher-owned now: evict consumed
        # prefixes
        for k in keys_involved:
            st = self.keys[k]
            self._evict(st, wa.initial_id_of_key(default_hash(k), self.config,
                                                 self.role))

    def _launch_resident(self, descs, per_key, keys_involved, pane,
                         kind, emit) -> None:
        """Resident-lane launch (docs/PLANNER.md "Resident state"):
        ship only NEW/changed pane partials plus window extents and
        answer the batch as pane-range queries against the
        device-resident forest -- one fused scatter+query program per
        launch, so the window carry never re-ships.  A pane is final
        once below the fired frontier (the acceptance gate drops
        tuples behind it), so ``pane_synced``/``min_new_id`` bound the
        dirty range to O(new data) per launch."""
        carry = self._resident
        spans = {}
        for k in keys_involved:
            idxs = per_key[k]
            initial_id = wa.initial_id_of_key(default_hash(k),
                                              self.config, self.role)
            lo_p = (min(descs[i][2] for i in idxs) - initial_id) // pane
            hi_p = -(-(max(descs[i][3] for i in idxs) - initial_id)
                     // pane)
            spans[k] = (initial_id, lo_p, hi_p)
            carry.row_of(k)
            if carry.needs_grow(hi_p - lo_p):
                # the batch's pane span (or key count) outgrew the
                # forest: swap in a bigger EMPTY one and mark EVERY
                # key dirty -- live partials recompute from the
                # retained host series, which eviction keeps exactly
                # down to the oldest unfired window.  (Never migrate
                # by copying: launches still queued on the dispatcher
                # scatter into the OLD forest object.)
                carry.grow(hi_p - lo_p + 64)
                for st2 in self.keys.values():
                    st2.pane_synced = None
        starts = np.empty(len(descs), np.int64)
        ends = np.empty(len(descs), np.int64)
        q_rows = np.empty(len(descs), np.int64)
        gwids = np.fromiter((d[1] for d in descs), np.int64, len(descs))
        run_rows, run_starts, run_lens, bufs = [], [], [], []
        for k in keys_involved:
            st = self.keys[k]
            self._consolidate(st)
            initial_id, lo_p, n_end = spans[k]
            row = carry.rows[k]
            if st.pane_synced is None:
                dirty_lo = lo_p
            else:
                dirty_lo = st.pane_synced
                if st.min_new_id is not None:
                    dirty_lo = min(dirty_lo,
                                   (st.min_new_id - initial_id) // pane)
                # panes below this batch's oldest window start are
                # dead (never read again): skip them even if unsynced
                dirty_lo = max(dirty_lo, lo_p)
            dirty_lo = min(dirty_lo, n_end)
            if n_end > dirty_lo:
                part = self._pane_partials(st, initial_id + dirty_lo
                                           * pane, n_end - dirty_lo,
                                           pane, kind)
                bufs.append(np.asarray(part, np.float32))
                # one CONSECUTIVE run of panes per key: ship a
                # (row, start, len) descriptor, never positions
                run_rows.append(row)
                run_starts.append(dirty_lo)
                run_lens.append(n_end - dirty_lo)
            for i in per_key[k]:
                starts[i] = (descs[i][2] - initial_id) // pane
                ends[i] = -(-(descs[i][3] - initial_id) // pane)
                q_rows[i] = row
                if descs[i][4] < 0:  # CB: result ts = last in extent
                    hi = int(np.searchsorted(st.sort_keys, descs[i][3],
                                             "left"))
                    lo = int(np.searchsorted(st.sort_keys, descs[i][2],
                                             "left"))
                    d = descs[i]
                    descs[i] = (d[0], d[1], d[2], d[3],
                                int(st.ts[hi - 1]) if hi > lo else 0,
                                d[5])
            st.pane_synced = n_end
            st.min_new_id = None
        cols = {
            "value": (np.concatenate(bufs) if bufs
                      else np.empty(0, np.float32)),
            "run_rows": np.asarray(run_rows, np.int32),
            "run_starts": np.asarray(run_starts, np.int64),
            "run_lens": np.asarray(run_lens, np.int32),
            "q_rows": q_rows,
        }
        birth = self._batch_birth or _time.perf_counter()
        self._batch_birth = None
        self._submit(cols, starts, ends, gwids, descs, birth, emit,
                     engine=carry.launch_engine())
        if self.stats is not None:  # single-writer: ingest thread
            self.stats.device_state_bytes = carry.state_bytes
        for k in keys_involved:
            self._evict(self.keys[k], spans[k][0])

    def _count_engine(self):
        # count over panes = sum of per-pane counts
        if not hasattr(self, "_count_eng"):
            self._count_eng = self._make_engine("sum")
        return self._count_eng

    # -- descriptor generation (window assignment) -------------------------
    def _fire_ready(self, key, st: _TPUKeyState, id_: int, hashcode: int,
                    emit) -> None:
        cfg = self.config
        first_gwid = wa.first_gwid_of_key(hashcode, cfg)
        initial_id = wa.initial_id_of_key(hashcode, cfg, self.role)
        slack = self.triggering_delay if self.win_type == WinType.TB else 0
        while True:
            lwid = st.next_fire
            start = initial_id + lwid * self.slide_len
            end = start + self.win_len
            # a window fires once a tuple beyond its extent (+delay) is seen
            if st.max_id < end + slack or lwid > st.opened_max:
                break
            gwid = wa.gwid_of_lwid(first_gwid, lwid, cfg)
            rts = (gwid * self.slide_len + self.win_len - 1
                   if self.win_type == WinType.TB else -1)  # CB: at launch
            if not self.descriptors:
                        self._batch_birth = _time.perf_counter()
            self.descriptors.append((key, gwid, start, end, rts, key))
            st.next_fire += 1
            if (len(self.descriptors) >= self.batch_len
                    and not self.chunk_hold):
                self._launch(emit)

    # -- columnar ingest (the zero-copy fast path: a whole TupleBatch is
    # partitioned by key and appended per key vectorized; the analogue of
    # the reference feeding batches straight from pinned staging) --------
    def _native_launch(self, emit, max_windows=None):
        """Stage ready windows from the C++ engine and launch one XLA
        program over the pane-partial buffer."""
        out = self._native.flush(max_windows or max(self.batch_len, 4096))
        if out is None:
            return
        vals, starts, ends, d_keys, d_gwids, d_rts = out[:6]
        birth = self._batch_birth or _time.perf_counter()
        # leftover ready windows (partial flush) restart the age clock
        self._batch_birth = (_time.perf_counter() if self._native.ready()
                             else None)
        cols = {"value": vals}
        # count windows sum their per-pane counts; mean windows divide
        # pane-sum totals by pane-count totals (pair program); max/min
        # fold partials through the matching sparse-table engine
        if self.engine.kind == "count":
            eng = self._count_engine()
        elif self.engine.kind == "mean":
            cols["count"] = out[6]
            eng = self._mean_engine()
        else:
            eng = None
        self._submit(cols, starts, ends, d_gwids,
                     ("native", d_keys, d_gwids, d_rts), birth, emit,
                     engine=eng)

    def _mean_engine(self):
        if not hasattr(self, "_mean_eng"):
            self._mean_eng = self._make_engine("mean_panes")
        return self._mean_eng

    def _launch_due(self) -> bool:
        return ((_time.perf_counter() - self._last_launch_t) * 1e3
                >= self.max_batch_delay_ms)

    def _svc_batch_native(self, batch: TupleBatch, emit):
        ids = batch.id if self.win_type == WinType.CB else batch.ts
        ready = self._native.ingest(batch.key, ids, batch.ts,
                                    batch["value"])
        if ready and self._batch_birth is None:
            self._batch_birth = _time.perf_counter()
        self._buffered_since_launch += len(batch)
        if (ready and not self.chunk_hold
                and (ready >= self.batch_len
                     or self._buffered_since_launch >= self.max_buffer_elems
                     or self._launch_due())):
            self._native_launch(emit)

    def _svc_batch(self, batch: TupleBatch, emit):
        if self._native is not None:
            self._svc_batch_native(batch, emit)
            return
        keys = batch.key
        ids = batch.id if self.win_type == WinType.CB else batch.ts
        vals = batch["value"]
        tss = batch.ts
        order, keys_s, bounds = _key_groups(keys)
        if order is None:
            ids_s, vals_s, tss_s = ids, vals, tss
        else:
            ids_s, vals_s, tss_s = ids[order], vals[order], tss[order]
        uniq = keys_s[bounds[:-1]]
        cfg = self.config
        for j, key in enumerate(uniq):
            key = key.item()
            lo, hi = bounds[j], bounds[j + 1]
            st = self._key_state(key)
            hashcode = default_hash(key)
            initial_id = wa.initial_id_of_key(hashcode, cfg, self.role)
            k_ids = ids_s[lo:hi]
            if self.renumbering:
                k_ids = np.arange(st.renumber_next,
                                  st.renumber_next + (hi - lo))
                st.renumber_next += hi - lo
            if st.max_id < 0 and len(k_ids):
                # first data: anchor the fire frontier at the first
                # containing window (native-engine parity; an
                # epoch-scale first id must not fire ~id/slide empty
                # windows)
                rel = int(k_ids.min()) - initial_id
                if rel >= self.win_len:
                    st.anchor = (rel - self.win_len) // self.slide_len + 1
                    st.next_fire = st.anchor
            # acceptance: drop tuples behind the already-fired frontier
            min_boundary = (self.win_len + (st.next_fire - 1) * self.slide_len
                            if st.next_fire > st.anchor
                            else st.anchor * self.slide_len)
            keep = k_ids >= initial_id + min_boundary
            if self.win_len < self.slide_len:  # hopping: drop gap tuples
                n = (k_ids - initial_id) // self.slide_len
                off = k_ids - initial_id
                keep &= (off >= n * self.slide_len) & \
                    (off < n * self.slide_len + self.win_len)
            n_drop = int((~keep).sum())
            if n_drop and st.next_fire > st.anchor:
                self.ignored_tuples += n_drop
            k_ids = k_ids[keep]
            if len(k_ids) == 0:
                continue
            st.pending_chunks.append(
                (k_ids.astype(np.int64), tss_s[lo:hi][keep],
                 vals_s[lo:hi][keep].astype(np.float64)))
            if self._resident is not None:
                mn = int(k_ids.min())
                if st.min_new_id is None or mn < st.min_new_id:
                    st.min_new_id = mn
            self._buffered_since_launch += len(k_ids)
            st.max_id = max(st.max_id, int(k_ids.max()))
            last_w = wa.last_window_of(st.max_id, initial_id, self.win_len,
                                       self.slide_len)
            if last_w >= 0:
                st.opened_max = max(st.opened_max, last_w)
            self._fire_ready(key, st, st.max_id, hashcode, emit)
        if (self.descriptors and not self.chunk_hold
                and (self._buffered_since_launch >= self.max_buffer_elems
                     or self._launch_due())):
            self._launch(emit)

    def svc(self, item, channel_id, emit):
        if self.telemetry is not None:
            tr = getattr(item, "trace", None)
            if tr is not None:   # crosses the dispatcher (see _finish)
                self._trace_ctx = tr
        if isinstance(item, TupleBatch):
            self._svc_batch(item, emit)
            return
        if isinstance(item, SynthChunk):
            # declared synthetic stream: the native engine generates and
            # folds the chunk in one pass (no host column materializes)
            if self._native is not None:
                ready = self._native.synth_ingest(
                    item.start, item.n, item.n_keys, item.vmod,
                    item.vscale, item.voff)
                if ready and self._batch_birth is None:
                    self._batch_birth = _time.perf_counter()
                self._buffered_since_launch += item.n
                if (ready and not self.chunk_hold
                        and (ready >= self.batch_len
                             or self._buffered_since_launch
                             >= self.max_buffer_elems
                             or self._launch_due())):
                    self._native_launch(emit)
            else:
                self._svc_batch(item.materialize(), emit)
            return
        if self._native is not None and not isinstance(item, EOSMarker):
            # route records through the native engine as 1-row columns so
            # mixed record/batch streams share one state store
            key, tid, ts = item.get_control_fields()
            if not isinstance(key, (int, np.integer)):
                key = self._intern_key(key)
            self._svc_batch_native(TupleBatch({
                "key": np.array([key], np.int64),
                "id": np.array([tid], np.int64),
                "ts": np.array([ts], np.int64),
                "value": np.array([self.value_of(item)], np.float64),
            }), emit)
            return
        if self._native is not None:
            return  # EOS markers: the native engine fires on eos_flush
        is_marker = isinstance(item, EOSMarker)
        t = item.record if is_marker else item
        key, tid, ts = t.get_control_fields()
        if not isinstance(key, (int, np.integer)):
            self._saw_nonint_key = True
        hashcode = default_hash(key)
        st = self._key_state(key)
        if self.renumbering and not is_marker:
            tid = st.renumber_next
            st.renumber_next += 1
            t.set_control_fields(key, tid, ts)
        id_ = tid if self.win_type == WinType.CB else ts
        cfg = self.config
        initial_id = wa.initial_id_of_key(hashcode, cfg, self.role)
        if not is_marker:
            if st.max_id < 0:
                rel = id_ - initial_id
                if rel >= self.win_len:
                    st.anchor = (rel - self.win_len) // self.slide_len + 1
                    st.next_fire = st.anchor
            min_boundary = (self.win_len + (st.next_fire - 1) * self.slide_len
                            if st.next_fire > st.anchor
                            else st.anchor * self.slide_len)
            if id_ < initial_id + min_boundary:
                if st.next_fire > st.anchor:
                    self.ignored_tuples += 1
                return
            last_w = wa.last_window_of(id_, initial_id, self.win_len,
                                       self.slide_len)
            if last_w < 0:
                return  # hopping gap
            st.opened_max = max(st.opened_max, last_w)
            st.pending_sort.append(id_)
            st.pending_ts.append(ts)
            st.pending_val.append(self.value_of(t))
            if self._resident is not None and (
                    st.min_new_id is None or id_ < st.min_new_id):
                st.min_new_id = id_
        st.max_id = max(st.max_id, id_)
        self._fire_ready(key, st, id_, hashcode, emit)
        if (self.descriptors and self._launch_due()
                and not self.chunk_hold):
            self._launch(emit)

    def eos_flush(self, emit):
        """Fire every opened window, then drain both batches (the
        reference computes leftovers on CPU at EOS,
        win_seq_gpu.hpp:648-710; we just launch a final batch)."""
        if self._native is not None:
            self._native.eos()
            while self._native.ready():
                self._native_launch(emit)
            self._drain_all(emit)
            return
        for key, st in self.keys.items():
            hashcode = default_hash(key)
            cfg = self.config
            first_gwid = wa.first_gwid_of_key(hashcode, cfg)
            initial_id = wa.initial_id_of_key(hashcode, cfg, self.role)
            for lwid in range(st.next_fire, st.opened_max + 1):
                start = initial_id + lwid * self.slide_len
                end = start + self.win_len
                gwid = wa.gwid_of_lwid(first_gwid, lwid, cfg)
                # CB: -1 sentinel -> _launch resolves the result ts to
                # the last tuple in the extent (same as the fired path)
                rts = (gwid * self.slide_len + self.win_len - 1
                       if self.win_type == WinType.TB else -1)
                self.descriptors.append((key, gwid, start, end, rts, key))
                st.next_fire += 1
                if len(self.descriptors) >= self.batch_len:
                    self._launch(emit)
        self._launch(emit)
        self._drain_all(emit)

    def idle_tick(self, emit) -> None:
        """Stalled-stream launch trigger (RtNode timed gets): windows
        that fired but sit staged/ready while no input arrives must
        still launch once the rate-limit allows -- otherwise a paused
        source withholds results until the next batch or EOS."""
        if self.pending:
            # inline-dispatch mode parks computed batches in `pending`
            # until the next launch; a stall must drain the ready ones
            self._flush_pending(emit)
        if not self._launch_due():
            return
        if self._native is not None:
            if self._native.ready():
                self._native_launch(emit)
        elif self.descriptors:
            self._launch(emit)

    def flush_chunk(self, emit) -> int:
        """Chunk-boundary launch for the whole-partition device step
        (graph/device_step.py): everything that fired while
        ``chunk_hold`` suppressed the intra-chunk triggers goes out as
        ONE launch.  Returns the number of launches issued (0 or 1) so
        the step logic can account launches-per-chunk."""
        if self._native is not None:
            ready = self._native.ready()
            if ready:
                self._native_launch(emit, max_windows=ready)
                return 1
            return 0
        if self.descriptors:
            self._launch(emit)
            return 1
        return 0

    def quiesce(self, emit) -> bool:
        """Live-checkpoint barrier hook (pipegraph.quiesce): drain every
        in-flight device batch, emitting its results, so ``state_dict``
        sees no pending work.  Returns True when anything was drained
        (the barrier loops until a drain pass emits nothing).  Called
        only while this node's thread is idle (sources paused, channels
        empty), so touching engine state is safe."""
        had = self._dispatcher is not None or bool(self.pending)
        self._drain_all(emit)
        return had

    # -- audit-plane hooks (audit/; docs/OBSERVABILITY.md): lock-free
    # gauge reads from the auditor thread against the live engine -----
    def audit_in_flight(self) -> dict:
        """Windows absorbed but not yet emitted: submitted device
        batches plus the batch under assembly -- the ``in_flight``
        term of the conservation ledger's device leg."""
        disp = self._dispatcher
        pend = len(self.pending) + (disp.depth() if disp is not None
                                    and hasattr(disp, "depth") else 0)
        return {"device_batches": pend,
                "staging": len(self.descriptors)}

    def keyed_state_census(self):
        """(key count, byte estimate) of the per-key window state.
        Python path: sampled _TPUKeyState arrays; native path: key
        count only (the engine owns the buffers)."""
        if self._native is not None:
            n = len(self._plq_counters) or len(self._key_intern)
            return (n, 0) if n else None
        keys = self.keys
        n = len(keys)
        if n == 0:
            return (0, 0)
        try:
            st = next(iter(keys.values()))
            per = (st.sort_keys.nbytes + st.ts.nbytes
                   + st.values.nbytes + 96)
        except (RuntimeError, StopIteration, AttributeError):
            per = 96  # resized under us: count-only estimate
        res = self.device_resident_bytes()
        if res:
            # ROADMAP item 4: resident-forest bytes surface as the
            # census "device" tier (metrics render them under
            # windflow_keyed_state_bytes{tier="device"})
            return (n, n * per, {"tiers": {"device": [n, int(res)]}})
        return (n, n * per)

    # -- checkpoint / resume (utils/checkpoint.py policy layer) --------
    def state_dict(self):
        """Pickle-friendly snapshot (quiescent contract: no device
        batches in flight).  Native-path state is the engine's versioned
        binary blob; Python-path state is the per-key store."""
        import copy
        st = {
            "descriptors": list(self.descriptors),
            "ignored_tuples": self.ignored_tuples,
            "launched_batches": self.launched_batches,
            "buffered": self._buffered_since_launch,
        }
        if self._native is not None:
            st["native"] = self._native.serialize()
            st["plq_counters"] = dict(self._plq_counters)
            if self._key_intern:
                st["key_intern"] = dict(self._key_intern)
        else:
            # deep copy: a live checkpoint resumes the stream after the
            # snapshot, and an aliased store would keep advancing
            st["keys"] = copy.deepcopy(self.keys)
        return st

    def load_state(self, state):
        self.descriptors = list(state.get("descriptors", []))
        self.ignored_tuples = state.get("ignored_tuples", 0)
        self.launched_batches = state.get("launched_batches", 0)
        self._buffered_since_launch = state.get("buffered", 0)
        if "native" in state:
            if self._native is None:
                raise RuntimeError(
                    "snapshot came from the native engine but this "
                    "replica runs the Python path")
            self._native.deserialize(state["native"])
            self._plq_counters = dict(state.get("plq_counters", {}))
            self._key_intern = dict(state.get("key_intern", {}))
            self._key_extern = {v: k for k, v in self._key_intern.items()}
        else:
            if self._native is not None:
                raise RuntimeError(
                    "snapshot came from the Python path but this "
                    "replica runs the native engine")
            import copy
            self.keys = copy.deepcopy(state["keys"])
            # re-derive the non-integral-key flag from the restored
            # store (every descriptor's key is in it): the columnar
            # emit shortcut keys off the flag, and a fresh replica
            # restoring string-keyed state would otherwise crash in
            # np.fromiter on the first launch
            self._saw_nonint_key = any(
                not isinstance(k, (int, np.integer)) for k in self.keys)
        # resident carry is NOT part of the snapshot (it is derivable
        # from the retained host series): drop it so the next launch
        # re-ships live partials -- restores stay lane-portable
        self._reset_resident()

    def svc_end(self):
        # error-path teardown: eos_flush already drained (and cleared)
        # the dispatcher on the normal path, so one still present here
        # means the node thread aborted -- stop launching its backlog
        if self._dispatcher is not None:
            self._dispatcher.abort()
            self._dispatcher = None
        if self.closing_func is not None:
            from ...core.context import RuntimeContext
            self.closing_func(RuntimeContext())


class WinSeqTPU(Operator):
    """Standalone device-batched window operator (builders_gpu.hpp:50
    analogue)."""

    def __init__(self, win_kind, win_len, slide_len, win_type,
                 batch_len=DEFAULT_BATCH_LEN, triggering_delay=0,
                 name="win_seq_tpu", result_factory=BasicRecord,
                 value_of=None, closing_func=None, emit_batches=False,
                 max_buffer_elems=DEFAULT_MAX_BUFFER_ELEMS,
                 inflight_depth=DEFAULT_INFLIGHT_DEPTH,
                 async_dispatch=True,
                 max_batch_delay_ms=DEFAULT_MAX_BATCH_DELAY_MS,
                 placement="device", adaptive_batch=False,
                 rtt_floor_ms=None, resident=None):
        super().__init__(name, 1, RoutingMode.FORWARD, Pattern.WIN_SEQ_TPU)
        self.win_type = win_type
        self.kwargs = dict(
            win_kind=win_kind, win_len=win_len, slide_len=slide_len,
            win_type=win_type, batch_len=batch_len,
            triggering_delay=triggering_delay, result_factory=result_factory,
            value_of=value_of, closing_func=closing_func,
            emit_batches=emit_batches, max_buffer_elems=max_buffer_elems,
            inflight_depth=inflight_depth, async_dispatch=async_dispatch,
            max_batch_delay_ms=max_batch_delay_ms, placement=placement,
            adaptive_batch=adaptive_batch, rtt_floor_ms=rtt_floor_ms,
            resident=resident)
        self._renumbering = False

    def enable_renumbering(self):
        self._renumbering = True

    def stages(self):
        logic = WinSeqTPULogic(renumbering=self._renumbering, **self.kwargs)
        return [StageSpec(
            self.name, [logic], StandardEmitter(), self.routing,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS))]
