"""Complex nesting: Win_Farm / Key_Farm replicating Pane_Farm or
Win_MapReduce instances -- host or device variants.

Re-design of the reference's nesting constructors (win_farm.hpp:259-378
for WF(PF), :379-... for WF(WMR); key_farm.hpp:254-... for KF(PF/WMR);
device nesting win_farm_gpu.hpp:73-76,:111-117 and key_farm_gpu.hpp:254
for WF_GPU(PF_GPU)/KF_GPU(WMR_GPU)) and MultiPipe's complex-nesting
dispatch (multipipe.hpp:1014-1099).  The same grouped-stage wiring
serves both planes: a device inner just contributes WinSeqTPULogic
replicas instead of WinSeqLogic ones.

Construction follows the reference exactly:
* WF(inner): R copies of the inner operator, copy i configured with
  ``WinOperatorConfig(0, 1, slide, i, R, slide)`` and private slide
  ``slide * R`` (win_farm.hpp:326: each copy owns every R-th window);
  the outer WFEmitter multicasts tuples to the copies whose windows
  contain them; the inner stages are **group-wired** so copy i's
  second stage consumes only copy i's first stage.
* KF(inner): R copies with identity configs; the outer KFEmitter sends
  each key's whole substream to one copy (keys never cross copies).
* CB windows inside a complex nesting require the broadcast +
  TS-renumbering plane (multipipe.hpp:1039-1051), available in
  DETERMINISTIC/PROBABILISTIC modes; MultiPipe rejects CB nesting in
  DEFAULT mode just like plain Win_Farm.
"""
from __future__ import annotations

from typing import List, Union

from ..core.basic import (OptLevel, Pattern, Role, RoutingMode, WinOperatorConfig)
from ..runtime.emitters import StandardEmitter, TreeEmitter
from ..runtime.win_routing import KFEmitter, WFEmitter, WidOrderCollector
from .base import Operator, StageSpec
from .pane_farm import PaneFarm
from .win_mapreduce import WinMapReduce
from .tpu.farms_tpu import PaneFarmTPU, WinMapReduceTPU

InnerOp = Union[PaneFarm, WinMapReduce, PaneFarmTPU, WinMapReduceTPU]


def _clone_inner(inner: InnerOp, idx: int, n_replicas: int,
                 outer_slide: int, private_slide: int) -> InnerOp:
    """Build copy ``idx`` of the inner operator with the nested config
    (the panewrap_farm_t construction, win_farm.hpp:324-374; the device
    twins follow win_farm_gpu.hpp:73-76 -- same arithmetic, device
    engine replicas)."""
    cfg = WinOperatorConfig(0, 1, outer_slide, idx, n_replicas, outer_slide)
    if isinstance(inner, PaneFarm):
        return PaneFarm(
            inner.plq_func, inner.wlq_func, inner.win_len, private_slide,
            inner.win_type, inner.plq_parallelism, inner.wlq_parallelism,
            inner.triggering_delay, inner.plq_incremental,
            inner.wlq_incremental, f"{inner.name}_{idx}",
            inner.result_factory, inner.closing_func, ordered=False,
            opt_level=inner.opt_level, config=cfg)
    if isinstance(inner, WinMapReduce):
        return WinMapReduce(
            inner.map_func, inner.reduce_func, inner.win_len, private_slide,
            inner.win_type, inner.map_parallelism, inner.reduce_parallelism,
            inner.triggering_delay, inner.map_incremental,
            inner.reduce_incremental, f"{inner.name}_{idx}",
            inner.result_factory, inner.closing_func, ordered=False,
            opt_level=inner.opt_level, config=cfg)
    if isinstance(inner, PaneFarmTPU):
        return PaneFarmTPU(
            inner.plq, inner.wlq, inner.win_len, private_slide,
            inner.win_type, inner.plq_par, inner.wlq_par,
            plq_on_tpu=inner.plq_on_tpu, wlq_on_tpu=not inner.plq_on_tpu,
            batch_len=inner.batch_len,
            max_buffer_elems=inner.max_buffer_elems,
            inflight_depth=inner.inflight_depth,
            max_batch_delay_ms=inner.max_batch_delay_ms,
            emit_batches=inner.emit_batches,
            triggering_delay=inner.triggering_delay,
            name=f"{inner.name}_{idx}", result_factory=inner.result_factory,
            value_of=inner.value_of, ordered=False,
            opt_level=inner.opt_level, config=cfg)
    if isinstance(inner, WinMapReduceTPU):
        return WinMapReduceTPU(
            inner.map_stage, inner.reduce_stage, inner.win_len,
            private_slide, inner.win_type, inner.map_par, inner.reduce_par,
            map_on_tpu=inner.map_on_tpu, batch_len=inner.batch_len,
            max_buffer_elems=inner.max_buffer_elems,
            inflight_depth=inner.inflight_depth,
            max_batch_delay_ms=inner.max_batch_delay_ms,
            triggering_delay=inner.triggering_delay,
            name=f"{inner.name}_{idx}", result_factory=inner.result_factory,
            value_of=inner.value_of, ordered=False, config=cfg)
    raise TypeError(f"cannot nest {type(inner).__name__}")


def _grouped_stages(copies: List[InnerOp], name: str) -> List[StageSpec]:
    """Flatten the copies' stages into grouped StageSpecs: stage s of
    the result holds stage s of every copy, with group ids wiring each
    copy's pipeline end-to-end."""
    per_copy = [c.stages() for c in copies]
    n_stages = len(per_copy[0])
    out: List[StageSpec] = []
    for s in range(n_stages):
        replicas, groups, group_emitters, group_collectors = [], [], [], []
        ordering = per_copy[0][s].ordering_mode
        for g, stages in enumerate(per_copy):
            st = stages[s]
            replicas.extend(st.replicas)
            groups.extend([g] * len(st.replicas))
            group_emitters.append(st.emitter_proto)
            group_collectors.append(st.collector)
        if all(c is None for c in group_collectors):
            group_collectors = None
        out.append(StageSpec(
            f"{name}_s{s}", replicas,
            emitter_proto=StandardEmitter(),  # replaced for stage 0 below
            routing=RoutingMode.COMPLEX, ordering_mode=ordering,
            groups=groups, group_emitters=group_emitters,
            group_collectors=group_collectors))
    return out


class NestedWinFarm(Operator):
    """Win_Farm whose workers are Pane_Farm / Win_MapReduce copies."""

    def __init__(self, inner: InnerOp, num_replicas: int,
                 name: str = "wf_nested", ordered: bool = True,
                 opt_level: OptLevel = OptLevel.LEVEL0):
        if num_replicas < 1:
            raise ValueError("number of inner replicas must be >= 1")
        total = num_replicas * inner.parallelism
        super().__init__(name, total, RoutingMode.COMPLEX, Pattern.WIN_FARM)
        if inner.used:
            raise RuntimeError(
                "inner operator already used in a nested structure")
        if (isinstance(inner, (PaneFarm, PaneFarmTPU))
                and inner.win_len <= inner.slide_len * num_replicas):
            # each copy runs with private slide = slide * num_replicas
            # (win_farm.hpp:326); Pane_Farm rejects slide >= win
            # (pane_farm.hpp:170-173), so fail here, eagerly, with the
            # nesting-level numbers
            raise ValueError(
                f"Win_Farm({num_replicas}) over a Pane_Farm with "
                f"win={inner.win_len} slide={inner.slide_len}: the "
                f"copies' private slide {inner.slide_len * num_replicas} "
                f">= win; Pane_Farm requires sliding windows "
                f"(pane_farm.hpp:170-173) -- reduce the replica count "
                f"or widen the window")
        inner.used = True
        self.inner = inner
        self.num_replicas = num_replicas
        self.ordered = ordered
        self.opt_level = opt_level
        self.win_type = inner.win_type
        self.win_len = inner.win_len
        self.slide_len = inner.slide_len
        self.role = Role.SEQ

    def stages(self):
        R = self.num_replicas
        slide = self.slide_len
        copies = [_clone_inner(self.inner, i, R, slide, slide * R)
                  for i in range(R)]
        stages = _grouped_stages(copies, self.name)
        # stage 0 inbound: outer WF emitter multicasting into the copies'
        # own first-stage emitters (the LEVEL2 Tree_Emitter fusion,
        # win_farm.hpp:202-227, here the only distribution mode)
        root = WFEmitter(self.win_len, slide, R, self.win_type, Role.SEQ,
                         id_outer=0, n_outer=1, slide_outer=slide)
        stages[0].emitter_proto = TreeEmitter(root,
                                              stages[0].group_emitters)
        stages[0].group_emitters = None  # stage 0 is fed ungrouped
        if self.ordered:
            stages[-1].collector = WidOrderCollector()
        return stages


class NestedKeyFarm(Operator):
    """Key_Farm whose workers are Pane_Farm / Win_MapReduce copies
    (key_farm.hpp nesting ctors :254-...)."""

    def __init__(self, inner: InnerOp, num_replicas: int,
                 name: str = "kf_nested",
                 opt_level: OptLevel = OptLevel.LEVEL0):
        if num_replicas < 1:
            raise ValueError("number of inner replicas must be >= 1")
        total = num_replicas * inner.parallelism
        super().__init__(name, total, RoutingMode.KEYBY, Pattern.KEY_FARM)
        if inner.used:
            raise RuntimeError(
                "inner operator already used in a nested structure")
        inner.used = True
        self.inner = inner
        self.num_replicas = num_replicas
        self.opt_level = opt_level
        self.win_type = inner.win_type
        self.win_len = inner.win_len
        self.slide_len = inner.slide_len

    def stages(self):
        R = self.num_replicas
        # keys are disjoint across copies: identity configs, same slide
        copies = [_clone_inner(self.inner, 0, 1, self.slide_len,
                               self.slide_len) for _ in range(R)]
        for i, c in enumerate(copies):
            c.name = f"{self.inner.name}_{i}"
        stages = _grouped_stages(copies, self.name)
        root = KFEmitter(R)
        stages[0].emitter_proto = TreeEmitter(root,
                                              stages[0].group_emitters)
        stages[0].group_emitters = None
        return stages
