"""SyntheticSource: a declared-parameter benchmark/test source.

The reference's tests all use synthetic sources built inline in each
binary (e.g. mp_common.hpp:125-163); windflow_tpu additionally makes
the standard fixture shape a *descriptor* so the whole pipeline can
lower onto the native C++ record plane (graph/native_lowering.py) and
run source->...->sink entirely off the Python interpreter.

Stream shape: ``n_events`` records, ``key = i % n_keys``,
``id = ts = i // n_keys`` (dense in-order per key),
``value = (i % vmod) * vscale + voff``.

The Python fallback (when the chain cannot lower) emits columnar
``TupleBatch`` chunks on the batch plane or per-record ``BasicRecord``
on the scalar plane, identical content either way.
"""
from __future__ import annotations


from ..core.basic import Pattern, RoutingMode
from ..core.context import RuntimeContext
from ..core.tuples import BasicRecord, SynthChunk
from ..runtime.emitters import StandardEmitter
from ..runtime.node import SourceLoopLogic
from .base import Operator, StageSpec


class _SynthLogic(SourceLoopLogic):
    def __init__(self, desc, batch: int, emit_batches: bool,
                 chunked: bool = False):
        self.desc = desc
        self.batch = batch
        self.emit_batches = emit_batches
        self.chunked = chunked
        self.sent = 0
        self.context = RuntimeContext(1, 0)

        def step(emit):
            d = self.desc
            i = self.sent
            if i >= d.n_events:
                return False
            n = min(self.batch, d.n_events - i)
            chunk = SynthChunk(i, n, d.n_keys, d.vmod, d.vscale, d.voff)
            self.sent = i + n
            if self.chunked:
                emit(chunk)
            elif self.emit_batches:
                emit(chunk.materialize())  # single source of the law
            else:
                b = chunk.materialize()
                for j in range(n):
                    emit(BasicRecord(int(b.key[j]), int(b.id[j]),
                                     int(b.ts[j]), float(b["value"][j])))
            return True

        super().__init__(step)

    # -- checkpoint: a declared source resumes from its offset ---------
    def state_dict(self):
        return {"sent": self.sent}

    def load_state(self, state) -> None:
        self.sent = state["sent"]


class SyntheticSource(Operator):
    """Descriptor source: key=i%K, id=ts=i//K, value=(i%vmod)*vscale+voff.

    ``emit_batches=True`` (default) emits TupleBatch chunks (columnar
    plane); False emits BasicRecords (scalar plane).  Either way the
    native lowering replaces it with the C++ synthetic generator when
    the rest of the chain lowers.
    """

    def __init__(self, n_events: int, n_keys: int = 1, vmod: int = 97,
                 vscale: float = 1.0, voff: float = 0.0,
                 batch: int = 65536, emit_batches: bool = True,
                 chunked: bool = False, name: str = "synthetic_source"):
        super().__init__(name, 1, RoutingMode.NONE, Pattern.SOURCE)
        self.n_events = n_events
        self.n_keys = max(1, n_keys)
        self.vmod = max(1, vmod)
        self.vscale = vscale
        self.voff = voff
        self.batch = batch
        self.emit_batches = emit_batches
        # chunked=True ships SynthChunk descriptors instead of columns;
        # device window stages fold them natively (win_seq_tpu), other
        # consumers materialize transparently
        self.chunked = chunked

    def stages(self):
        return [StageSpec(self.name,
                          [_SynthLogic(self, self.batch, self.emit_batches,
                                       self.chunked)],
                          StandardEmitter(), self.routing)]
