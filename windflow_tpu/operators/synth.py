"""SyntheticSource: a declared-parameter benchmark/test source.

The reference's tests all use synthetic sources built inline in each
binary (e.g. mp_common.hpp:125-163); windflow_tpu additionally makes
the standard fixture shape a *descriptor* so the whole pipeline can
lower onto the native C++ record plane (graph/native_lowering.py) and
run source->...->sink entirely off the Python interpreter.

Stream shape: ``n_events`` records, ``key = i % n_keys``,
``id = ts = i // n_keys`` (dense in-order per key),
``value = (i % vmod) * vscale + voff``.

The Python fallback (when the chain cannot lower) emits columnar
``TupleBatch`` chunks on the batch plane or per-record ``BasicRecord``
on the scalar plane, identical content either way.
"""
from __future__ import annotations

import numpy as np

from ..core.basic import Pattern, RoutingMode
from ..core.context import RuntimeContext
from ..core.tuples import BasicRecord, TupleBatch
from ..runtime.emitters import StandardEmitter
from ..runtime.node import SourceLoopLogic
from .base import Operator, StageSpec


class _SynthLogic(SourceLoopLogic):
    def __init__(self, desc, batch: int, emit_batches: bool):
        self.desc = desc
        self.batch = batch
        self.emit_batches = emit_batches
        self.sent = 0
        self.context = RuntimeContext(1, 0)

        def step(emit):
            d = self.desc
            i = self.sent
            if i >= d.n_events:
                return False
            n = min(self.batch, d.n_events - i)
            idx = i + np.arange(n)
            keys = idx % d.n_keys
            ids = idx // d.n_keys
            vals = (idx % d.vmod).astype(np.float64) * d.vscale + d.voff
            self.sent = i + n
            if self.emit_batches:
                emit(TupleBatch({"key": keys, "id": ids, "ts": ids,
                                 "value": vals}))
            else:
                for j in range(n):
                    emit(BasicRecord(int(keys[j]), int(ids[j]),
                                     int(ids[j]), float(vals[j])))
            return True

        super().__init__(step)


class SyntheticSource(Operator):
    """Descriptor source: key=i%K, id=ts=i//K, value=(i%vmod)*vscale+voff.

    ``emit_batches=True`` (default) emits TupleBatch chunks (columnar
    plane); False emits BasicRecords (scalar plane).  Either way the
    native lowering replaces it with the C++ synthetic generator when
    the rest of the chain lowers.
    """

    def __init__(self, n_events: int, n_keys: int = 1, vmod: int = 97,
                 vscale: float = 1.0, voff: float = 0.0,
                 batch: int = 65536, emit_batches: bool = True,
                 name: str = "synthetic_source"):
        super().__init__(name, 1, RoutingMode.NONE, Pattern.SOURCE)
        self.n_events = n_events
        self.n_keys = max(1, n_keys)
        self.vmod = max(1, vmod)
        self.vscale = vscale
        self.voff = voff
        self.batch = batch
        self.emit_batches = emit_batches

    def stages(self):
        return [StageSpec(self.name,
                          [_SynthLogic(self, self.batch, self.emit_batches)],
                          StandardEmitter(), self.routing)]
