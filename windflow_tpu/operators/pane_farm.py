"""Pane_Farm: two-stage pane decomposition of sliding windows.

Re-design of reference ``wf/pane_farm.hpp`` (1107 LoC; algorithm: Li et
al., "No pane, no gain", SIGMOD 2005, cited pane_farm.hpp:33-35):
windows are split into non-overlapping panes of length
``gcd(win, slide)``; a PLQ stage computes per-pane partials (tumbling
pane windows, role PLQ, renumbered dense pane ids per key), and a WLQ
stage combines panes into windows (CB windows of ``win/pane`` panes
sliding by ``slide/pane``, role WLQ).  The ML analogue is blockwise /
two-level sequence-parallel reduction over the time axis (SURVEY.md §5).
"""
from __future__ import annotations

from typing import Callable

from ..core.basic import (OptLevel, Pattern, Role, RoutingMode,
                          WinOperatorConfig, WinType)
from ..core.tuples import BasicRecord
from ..core.win_assign import pane_length
from .base import Operator
from .win_farm import WinFarm
from .win_seq import WinSeqLogic
from ..core.basic import OrderingMode
from ..runtime.emitters import StandardEmitter
from .base import StageSpec


class PaneFarm(Operator):
    def __init__(self, plq_func: Callable, wlq_func: Callable, win_len: int,
                 slide_len: int, win_type: WinType,
                 plq_parallelism: int = 1, wlq_parallelism: int = 1,
                 triggering_delay: int = 0, plq_incremental: bool = False,
                 wlq_incremental: bool = False, name: str = "pane_farm",
                 result_factory=BasicRecord, closing_func=None,
                 ordered: bool = True,
                 opt_level: OptLevel = OptLevel.LEVEL0,
                 config: WinOperatorConfig = None):
        super().__init__(name, plq_parallelism + wlq_parallelism,
                         RoutingMode.COMPLEX, Pattern.PANE_FARM)
        if win_len == 0 or slide_len == 0:
            raise ValueError("window length and slide cannot be zero")
        if win_len <= slide_len:
            # pane_farm.hpp:170-173: with slide >= win the pane
            # decomposition degenerates (the PLQ's dense pane
            # renumbering no longer matches the WLQ's pane selection
            # once the pane stream has gaps)
            raise ValueError(
                f"Pane_Farm requires sliding windows (slide < win); got "
                f"win={win_len} slide={slide_len}. Inside a Win_Farm the "
                f"private slide is slide*replicas, so nesting needs "
                f"win > slide*replicas")
        self.plq_func = plq_func
        self.wlq_func = wlq_func
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.plq_parallelism = plq_parallelism
        self.wlq_parallelism = wlq_parallelism
        self.triggering_delay = triggering_delay
        self.plq_incremental = plq_incremental
        self.wlq_incremental = wlq_incremental
        self.result_factory = result_factory
        self.closing_func = closing_func
        self.ordered = ordered
        self.opt_level = opt_level
        # default enclosing config (pane_farm.hpp:158)
        self.config = config or WinOperatorConfig(0, 1, slide_len,
                                                  0, 1, slide_len)
        self.pane_len = pane_length(win_len, slide_len)

    def _fused_logics(self):
        """PLQ + WLQ logics for the LEVEL1/2 thread fusion (the ff_comb
        branch of optimize_PaneFarm, pane_farm.hpp:222-250): both stages
        run in ONE thread via ChainedLogic.  Only valid when both
        parallelisms are 1; the farm-farm LEVEL2 merge maps onto this
        runtime as collector stripping, which the inner WinFarms already
        do at LEVEL1+."""
        cfg = self.config
        pane = self.pane_len
        plq = WinSeqLogic(
            self.plq_func, pane, pane, self.win_type,
            triggering_delay=self.triggering_delay,
            incremental=self.plq_incremental,
            result_factory=self.result_factory,
            closing_func=self.closing_func,
            config=WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                     cfg.slide_inner, 0, 1, pane),
            role=Role.PLQ)
        wlq_win = self.win_len // pane
        wlq_slide = self.slide_len // pane
        wlq = WinSeqLogic(
            self.wlq_func, wlq_win, wlq_slide, WinType.CB,
            incremental=self.wlq_incremental,
            result_factory=self.result_factory,
            closing_func=self.closing_func,
            config=WinOperatorConfig(cfg.id_inner, cfg.n_inner,
                                     cfg.slide_inner, 0, 1, wlq_slide),
            role=Role.WLQ)
        return plq, wlq

    # (both par-1 stage branches and the LEVEL1/2 fusion build their
    # logics through _fused_logics, so the config arithmetic and the
    # incremental flags live in exactly one place)

    def stages(self):
        if (self.opt_level != OptLevel.LEVEL0
                and self.plq_parallelism == 1
                and self.wlq_parallelism == 1):
            from ..runtime.node import ChainedLogic
            plq, wlq = self._fused_logics()
            return [StageSpec(
                f"{self.name}_fused", [ChainedLogic(plq, wlq)],
                StandardEmitter(), RoutingMode.FORWARD,
                ordering_mode=(OrderingMode.ID
                               if self.win_type == WinType.CB
                               else OrderingMode.TS))]
        cfg = self.config
        pane = self.pane_len
        # par-1 stages reuse the same logic construction as the fusion
        # path -- one place owns the config arithmetic
        plq_single, wlq_single = self._fused_logics()
        stages = []
        # ---- PLQ: tumbling panes (pane_farm.hpp:181-196) ----
        if self.plq_parallelism > 1:
            plq = WinFarm(self.plq_func, pane, pane, self.win_type,
                          self.plq_parallelism, self.triggering_delay,
                          self.plq_incremental, f"{self.name}_plq",
                          self.result_factory, self.closing_func,
                          ordered=True, opt_level=self.opt_level,
                          config=WinOperatorConfig(
                              cfg.id_outer, cfg.n_outer, cfg.slide_outer,
                              cfg.id_inner, cfg.n_inner, cfg.slide_inner),
                          role=Role.PLQ)
            stages.extend(plq.stages())
        else:
            stages.append(StageSpec(
                f"{self.name}_plq", [plq_single], StandardEmitter(),
                RoutingMode.FORWARD,
                ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                               else OrderingMode.TS)))
        # ---- WLQ: CB windows over dense pane ids (pane_farm.hpp:198-214) ----
        wlq_win = self.win_len // pane
        wlq_slide = self.slide_len // pane
        if self.wlq_parallelism > 1:
            wlq = WinFarm(self.wlq_func, wlq_win, wlq_slide, WinType.CB,
                          self.wlq_parallelism, 0, self.wlq_incremental,
                          f"{self.name}_wlq", self.result_factory,
                          self.closing_func, ordered=self.ordered,
                          opt_level=self.opt_level,
                          config=WinOperatorConfig(
                              cfg.id_outer, cfg.n_outer, cfg.slide_outer,
                              cfg.id_inner, cfg.n_inner, cfg.slide_inner),
                          role=Role.WLQ)
            stages.extend(wlq.stages())
        else:
            stages.append(StageSpec(
                f"{self.name}_wlq", [wlq_single], StandardEmitter(keyed=True),
                RoutingMode.KEYBY, ordering_mode=OrderingMode.ID))
        return stages
