"""Columnar-plane operators: sources and transforms over TupleBatch.

This plane has no reference counterpart -- it is the TPU-first design
choice (SURVEY.md §7 "Architecture stance"): the hot path moves columnar
micro-batches, not records, so host work is vectorized numpy and device
work is batched XLA.  The record-plane operators remain for API parity;
both planes share queues, emitters, windows and graphs.

* BatchSource:  fn(ctx) -> TupleBatch | None    (None = end of stream)
* BatchMap:     fn(batch) -> TupleBatch         (vectorized transform)
* BatchFilter:  fn(batch) -> bool ndarray       (vectorized predicate)
* Batch-aware sinks just receive TupleBatch items.
"""
from __future__ import annotations


from ..core.basic import OrderingMode, Pattern, RoutingMode
from ..core.context import RuntimeContext
from ..core.meta import with_context
from ..runtime.emitters import StandardEmitter
from ..runtime.node import EOSMarker, NodeLogic, SourceLoopLogic
from .base import Operator, StageSpec


class BatchSourceLogic(SourceLoopLogic):
    def __init__(self, fn, parallelism, replica_index, closing_func=None):
        self.context = RuntimeContext(parallelism, replica_index)
        self.user_fn = with_context(fn, 0, self.context)
        self.closing_func = closing_func

        def step(emit):
            batch = self.user_fn()
            if batch is None:
                return False
            emit(batch)
            return True

        super().__init__(step)

    def svc_end(self):
        if self.closing_func is not None:
            self.closing_func(self.context)


class BatchSource(Operator):
    def __init__(self, fn, parallelism=1, name="batch_source",
                 closing_func=None):
        super().__init__(name, parallelism, RoutingMode.NONE, Pattern.SOURCE)
        self.fn = fn
        self.closing_func = closing_func

    def stages(self):
        reps = [BatchSourceLogic(self.fn, self.parallelism, i,
                                 self.closing_func)
                for i in range(self.parallelism)]
        return [StageSpec(self.name, reps, StandardEmitter(), self.routing)]


class _BatchTransformLogic(NodeLogic):
    def __init__(self, fn):
        self.fn = fn

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            emit(item)
            return
        out = self.fn(item)
        if out is not None and len(out):
            emit(out)


class _BatchFilterLogic(NodeLogic):
    def __init__(self, fn):
        self.fn = fn

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            emit(item)
            return
        mask = self.fn(item)
        out = item.take(mask)
        if len(out):
            emit(out)


class BatchMap(Operator):
    """Vectorized transform; also accepts a value ``Expr`` which is
    evaluated over the batch columns (``BatchMap(F.value * 2)``)."""

    def __init__(self, fn, parallelism=1, name="batch_map", keyed=False):
        super().__init__(name, parallelism,
                         RoutingMode.KEYBY if keyed else RoutingMode.FORWARD,
                         Pattern.MAP)
        from ..core.expr import Expr
        self.expr = fn if isinstance(fn, Expr) else None
        if self.expr is not None:
            ev = self.expr.eval_columns
            fn = lambda b: b.with_cols(value=ev(b))  # noqa: E731
        self.fn = fn
        self.keyed = keyed

    def stages(self):
        reps = [_BatchTransformLogic(self.fn)
                for _ in range(self.parallelism)]
        return [StageSpec(self.name, reps,
                          StandardEmitter(keyed=self.keyed), self.routing,
                          ordering_mode=OrderingMode.TS)]

    def chain_logics(self):
        if self.keyed:
            return None
        return [_BatchTransformLogic(self.fn)
                for _ in range(self.parallelism)]


class BatchFilter(Operator):
    """Vectorized predicate; also accepts a boolean ``Expr``
    (``BatchFilter(F.value % 4 == 0)``)."""

    def __init__(self, fn, parallelism=1, name="batch_filter", keyed=False):
        super().__init__(name, parallelism,
                         RoutingMode.KEYBY if keyed else RoutingMode.FORWARD,
                         Pattern.FILTER)
        from ..core.expr import Expr
        self.expr = fn if isinstance(fn, Expr) else None
        if self.expr is not None:
            fn = self.expr.eval_columns
        self.fn = fn
        self.keyed = keyed

    def stages(self):
        reps = [_BatchFilterLogic(self.fn) for _ in range(self.parallelism)]
        return [StageSpec(self.name, reps,
                          StandardEmitter(keyed=self.keyed), self.routing,
                          ordering_mode=OrderingMode.TS)]

    def chain_logics(self):
        if self.keyed:
            return None
        return [_BatchFilterLogic(self.fn) for _ in range(self.parallelism)]
