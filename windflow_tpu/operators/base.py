"""Operator descriptor framework.

Reference analogue: ``wf/basic_operator.hpp`` (:49-89) plus the
structural role the ff_farm/ff_pipeline nests play.  A windflow_tpu
operator is a passive descriptor that yields one or more **stages**;
each stage contributes replica logics, the emitter the upstream uses to
route into it, its ordering requirement, and an optional farm-level
collector.  MultiPipe consumes stages to wire channels/threads -- the
flat, explicit substitute for the reference's "matrioska" ff_a2a
nesting (multipipe.hpp:236-341).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..core.basic import OrderingMode, Pattern, RoutingMode
from ..runtime.emitters import Emitter
from ..runtime.node import NodeLogic


@dataclass
class StageSpec:
    """One farm stage inside an operator."""

    name: str
    replicas: List[NodeLogic]
    emitter_proto: Emitter              # cloned per upstream producer
    routing: RoutingMode
    # field the DETERMINISTIC/PROBABILISTIC collector must order on when
    # one is inserted in front of each replica (None = operator does not
    # care; graph mode decides)
    ordering_mode: Optional[OrderingMode] = None
    # farm-level collector merging replica outputs (e.g. ordered WF)
    collector: Optional[NodeLogic] = None
    # complex nesting (WF/KF over PF/WMR, multipipe.hpp:1014-1099):
    # group id per replica; a grouped stage receives only from upstream
    # tails of the same group (the per-worker sub-pipelines of the
    # reference's replicated inner operators)
    groups: Optional[List[int]] = None
    # per-group inbound emitter prototypes (used instead of
    # emitter_proto when the PREVIOUS stage was grouped)
    group_emitters: Optional[List[Emitter]] = None
    # per-group farm collectors (e.g. each inner PLQ's ordered collector)
    group_collectors: Optional[List[NodeLogic]] = None
    # per-operator error policy ('fail'|'skip'|'dead_letter'), filled
    # from the operator descriptor at wiring (resilience/policies.py);
    # applies to the stage's replica nodes, never to collectors
    error_policy: Optional[str] = None
    # distributed-runtime worker pin, filled from the operator
    # descriptor at wiring (distributed/; docs/DISTRIBUTED.md)
    worker: Optional[int] = None
    # elastic scaling (elastic/; docs/ELASTIC.md): the operator's
    # ElasticSpec plus a ``(replica_index, parallelism) -> NodeLogic``
    # factory, filled by MultiPipe.add for single-stage operators that
    # declared .with_elasticity(...).  _append_stage registers the
    # wired stage with the graph's elastic registry.
    elastic: Optional[object] = None
    elastic_factory: Optional[object] = None
    # supervised replica restart (durability/supervision.py;
    # docs/RESILIENCE.md): True + a non-None elastic_factory makes the
    # stage's replicas individually rebuildable after a crash.  Filled
    # from the operator's .with_restartable() mark by MultiPipe.add.
    restartable: bool = False


class Operator:
    """Base descriptor: name, parallelism, routing, pattern."""

    # (class-level default so pre-existing Operator subclasses that
    # override __init__ without chaining still read as unpinned)
    worker: Optional[int] = None

    def __init__(self, name: str, parallelism: int, routing: RoutingMode,
                 pattern: Pattern):
        if parallelism < 1:
            raise ValueError(f"operator {name}: parallelism must be >= 1")
        self.name = name
        self.parallelism = parallelism
        self.routing = routing
        self.pattern = pattern
        self.used = False  # one operator object per graph position (ref basic_operator)
        # per-tuple svc failure handling (resilience/policies.py);
        # builders set it via .with_error_policy(...)
        self.error_policy = "fail"
        # ElasticSpec when the builder declared .with_elasticity(...)
        # (elastic/; docs/ELASTIC.md); None = fixed parallelism
        self.elasticity = None
        # distributed-runtime worker pin (.with_worker(i)); None =
        # placed by the partition planner (docs/DISTRIBUTED.md)
        self.worker = None
        # .with_restartable(): replicas individually healable under
        # RuntimeConfig.supervision (durability/supervision.py)
        self.restartable = False

    # -- to be provided by subclasses --------------------------------------
    def stages(self) -> List[StageSpec]:
        raise NotImplementedError

    # chainable operators (Filter/Map/FlatMap/Sink) additionally expose
    # fresh per-replica logics for thread fusion (multipipe.hpp:345-390)
    def chain_logics(self) -> Optional[List[NodeLogic]]:
        return None

    # elastically scalable operators expose a fresh-replica factory for
    # runtime rescaling: ``factory(replica_index, parallelism) ->
    # NodeLogic`` (elastic/rescale.py).  None = this operator kind
    # cannot be rescaled at runtime.
    def elastic_logic_factory(self):
        return None

    def is_window_operator(self) -> bool:
        return self.pattern in (
            Pattern.WIN_SEQ, Pattern.WIN_FARM, Pattern.KEY_FARM,
            Pattern.PANE_FARM, Pattern.WIN_MAPREDUCE, Pattern.WIN_SEQFFAT,
            Pattern.KEY_FFAT, Pattern.WIN_SEQ_TPU, Pattern.WIN_FARM_TPU,
            Pattern.KEY_FARM_TPU, Pattern.PANE_FARM_TPU,
            Pattern.WIN_MAPREDUCE_TPU, Pattern.WIN_SEQFFAT_TPU,
            Pattern.KEY_FFAT_TPU)

    def __repr__(self):
        return (f"{type(self).__name__}(name={self.name!r}, "
                f"parallelism={self.parallelism})")
