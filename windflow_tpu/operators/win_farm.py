"""Win_Farm: window parallelism -- consecutive windows of each key are
round-robined across workers.

Re-design of reference ``wf/win_farm.hpp`` (769 LoC): farm of Win_Seq
engines each owning every ``parallelism``-th window via a private slide
``slide * parallelism`` (win_farm.hpp:171-180), a WFEmitter multicasting
tuples to the workers whose windows contain them, and an optional
ordered collector.  The enclosing config's inner level shifts to the
workers' outer level (configSeq construction, win_farm.hpp:175).
"""
from __future__ import annotations

from typing import Callable

from ..core.basic import (OptLevel, OrderingMode, Pattern, Role, RoutingMode,
                          WinOperatorConfig, WinType)
from ..core.tuples import BasicRecord
from ..runtime.win_routing import WFEmitter, WidOrderCollector
from .base import Operator, StageSpec
from .win_seq import WinSeqLogic


class WinFarm(Operator):
    def __init__(self, win_func: Callable, win_len: int, slide_len: int,
                 win_type: WinType, parallelism: int = 1,
                 triggering_delay: int = 0, incremental: bool = False,
                 name: str = "win_farm", result_factory=BasicRecord,
                 closing_func=None, ordered: bool = True,
                 opt_level: OptLevel = OptLevel.LEVEL0,
                 config: WinOperatorConfig = None, role: Role = Role.SEQ):
        super().__init__(name, parallelism, RoutingMode.COMPLEX,
                         Pattern.WIN_FARM)
        if win_len == 0 or slide_len == 0:
            raise ValueError("window length and slide cannot be zero")
        self.win_func = win_func
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.triggering_delay = triggering_delay
        self.incremental = incremental
        self.result_factory = result_factory
        self.closing_func = closing_func
        self.ordered = ordered
        self.opt_level = opt_level
        self.config = config or WinOperatorConfig(0, 1, 0, 0, 1, 0)
        self.role = role

    def stages(self):
        cfg = self.config
        par = self.parallelism
        private_slide = self.slide_len * par
        replicas = []
        for i in range(par):
            worker_cfg = WinOperatorConfig(
                cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                i, par, self.slide_len)
            replicas.append(WinSeqLogic(
                self.win_func, self.win_len, private_slide, self.win_type,
                triggering_delay=self.triggering_delay,
                incremental=self.incremental,
                result_factory=self.result_factory,
                closing_func=self.closing_func, config=worker_cfg,
                role=self.role, parallelism=par, replica_index=i))
        emitter = WFEmitter(self.win_len, self.slide_len, par, self.win_type,
                            self.role, id_outer=cfg.id_inner,
                            n_outer=cfg.n_inner, slide_outer=cfg.slide_inner)
        # LEVEL1+ strips the ordered collector (optimize_WinFarm,
        # win_farm.hpp:199-201)
        collector = (WidOrderCollector()
                     if self.ordered and self.opt_level == OptLevel.LEVEL0
                     else None)
        return [StageSpec(
            self.name, replicas, emitter, self.routing,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS),
            collector=collector)]
