"""Key_Farm: key parallelism -- sub-streams sharded by key hash.

Re-design of reference ``wf/key_farm.hpp`` (754 LoC): a farm of Win_Seq
engines, each owning the *entire* window sequence of its keys
(kf_nodes routing, no collector -- key_farm.hpp:161-173).  The ML
analogue is sharding by batch/head dimension (SURVEY.md §2.4).
"""
from __future__ import annotations

from typing import Callable

from ..core.basic import (OptLevel, OrderingMode, Pattern, Role, RoutingMode,
                          WinOperatorConfig, WinType)
from ..core.tuples import BasicRecord
from ..runtime.win_routing import KFEmitter
from .base import Operator, StageSpec
from .win_seq import WinSeqLogic


class KeyFarm(Operator):
    def __init__(self, win_func: Callable, win_len: int, slide_len: int,
                 win_type: WinType, parallelism: int = 1,
                 triggering_delay: int = 0, incremental: bool = False,
                 name: str = "key_farm", result_factory=BasicRecord,
                 closing_func=None, opt_level: OptLevel = OptLevel.LEVEL0,
                 config: WinOperatorConfig = None):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.KEY_FARM)
        if win_len == 0 or slide_len == 0:
            raise ValueError("window length and slide cannot be zero")
        self.win_kind_name = win_func if isinstance(win_func, str) else None
        if self.win_kind_name is not None:
            from .win_seq import builtin_win_func
            win_func = builtin_win_func(self.win_kind_name)
            incremental = False
        self.win_func = win_func
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.triggering_delay = triggering_delay
        self.incremental = incremental
        self.result_factory = result_factory
        self.closing_func = closing_func
        self.opt_level = opt_level
        self.config = config or WinOperatorConfig(0, 1, 0, 0, 1, 0)
        self._renumbering = False

    def enable_renumbering(self):
        """CB windows in DEFAULT mode: per-key dense re-assignment of ids
        on arrival at the engine (win_seq.hpp:342-347)."""
        self._renumbering = True

    def stages(self):
        cfg = self.config
        par = self.parallelism
        replicas = []
        for i in range(par):
            worker_cfg = WinOperatorConfig(
                cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                0, 1, self.slide_len)
            replicas.append(WinSeqLogic(
                self.win_func, self.win_len, self.slide_len, self.win_type,
                triggering_delay=self.triggering_delay,
                incremental=self.incremental,
                result_factory=self.result_factory,
                closing_func=self.closing_func, config=worker_cfg,
                role=Role.SEQ, parallelism=par, replica_index=i,
                renumbering=self._renumbering))
        return [StageSpec(
            self.name, replicas, KFEmitter(par), self.routing,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS))]
