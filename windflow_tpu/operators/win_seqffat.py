"""Win_SeqFFAT: sequential incremental window engine on a FlatFAT tree.

Re-design of reference ``wf/win_seqffat.hpp`` (706 LoC): user provides a
**lift** (tuple -> partial) and an associative **combine**
(partial x partial -> partial); per-key state is a FlatFAT aggregator
tree plus a pending buffer, giving O(log win_len) amortized cost per
tuple instead of re-scanning the window (Tangwongsan VLDB'15).  CB path
fires every ``slide`` tuples once ``win_len`` are present
(win_seqffat.hpp:365-432); TB path fires on timestamp proof
(win_seqffat.hpp:444-).
"""
from __future__ import annotations

import bisect
from typing import Any, Callable, Dict, List

from ..core.basic import OrderingMode, Pattern, RoutingMode, WinType
from ..core.context import RuntimeContext
from ..core.flatfat import FlatFAT
from ..core.meta import with_context
from ..core.tuples import BasicRecord
from ..runtime.emitters import StandardEmitter
from ..runtime.node import EOSMarker, NodeLogic
from .base import Operator, StageSpec


class _FFATKeyState:
    __slots__ = ("tree", "content_keys", "pending_keys", "pending_vals",
                 "next_lwid", "max_id", "renumber_next")

    def __init__(self, tree: FlatFAT):
        self.tree = tree
        self.content_keys: List[int] = []   # sort keys of values in tree
        self.pending_keys: List[int] = []   # sorted sort-keys of pending
        self.pending_vals: List = []        # lifted values, parallel list
        self.next_lwid = 0
        self.max_id = -1
        self.renumber_next = 0


class WinSeqFFATLogic(NodeLogic):
    def __init__(self, lift_func: Callable, combine_func: Callable,
                 win_len: int, slide_len: int, win_type: WinType, *,
                 triggering_delay: int = 0, result_factory=BasicRecord,
                 closing_func=None, parallelism: int = 1,
                 replica_index: int = 0, renumbering: bool = False):
        if win_len == 0 or slide_len == 0:
            raise ValueError("win_len and slide_len must be > 0")
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.triggering_delay = triggering_delay
        self.result_factory = result_factory
        self.closing_func = closing_func
        self.renumbering = renumbering
        self.context = RuntimeContext(parallelism, replica_index)
        # lift: (tuple, result) -> None   (API:55-58)
        self.lift = with_context(lift_func, 2, self.context)
        # combine: (a, b, out) -> None    (API:59-61)
        self.combine = with_context(combine_func, 3, self.context)
        self.keys: Dict[Any, _FFATKeyState] = {}
        self.ignored_tuples = 0

    # -- FlatFAT plumbing --------------------------------------------------
    def _combine2(self, a, b):
        out = self.result_factory()
        self.combine(a, b, out)
        return out

    def _new_tree(self, capacity: int) -> FlatFAT:
        return FlatFAT(self._combine2, self.result_factory, capacity)

    def _key_state(self, key) -> _FFATKeyState:
        st = self.keys.get(key)
        if st is None:
            cap = self.win_len if self.win_type == WinType.CB else 64
            st = self.keys[key] = _FFATKeyState(self._new_tree(cap))
        return st

    def _grow(self, st: _FFATKeyState, needed: int) -> None:
        """TB windows have no tuple-count bound: rebuild the tree with
        doubled capacity when full (the reference pre-sizes from
        batch_len; we grow adaptively)."""
        cap = st.tree.capacity
        while cap < needed:
            cap *= 2
        if cap == st.tree.capacity:
            return
        values = []
        old = st.tree
        # drain old tree values in order via removal of leaves
        idx = old.front
        for _ in range(old.count):
            values.append(old.tree[old.n + idx])
            idx = (idx + 1) % old.n
        st.tree = self._new_tree(cap)
        if values:
            st.tree.insert_bulk(values)

    # -- windows -----------------------------------------------------------
    def _win_bounds(self, lwid: int):
        start = lwid * self.slide_len
        return start, start + self.win_len

    def _fire(self, key, st: _FFATKeyState, lwid: int, emit) -> None:
        start, end = self._win_bounds(lwid)
        # evict values that precede the window
        n_evict = bisect.bisect_left(st.content_keys, start)
        if n_evict:
            st.tree.remove(n_evict)
            del st.content_keys[:n_evict]
        # hopping (win < slide): pending may hold gap tuples that
        # arrived before this fire (e.g. the previous window's trigger
        # tuple); they belong to NO window -- discard, never insert
        # (win_seq.hpp:388-411 gap semantics)
        gap = bisect.bisect_left(st.pending_keys, start)
        if gap:
            del st.pending_keys[:gap]
            del st.pending_vals[:gap]
            self.ignored_tuples += gap
        # insert pending values inside the window extent
        cut = bisect.bisect_left(st.pending_keys, end)
        if cut:
            vals = st.pending_vals[:cut]
            self._grow(st, len(st.content_keys) + len(vals))
            st.tree.insert_bulk(vals)
            st.content_keys.extend(st.pending_keys[:cut])
            del st.pending_keys[:cut]
            del st.pending_vals[:cut]
        result = st.tree.get_result()
        if self.win_type == WinType.CB:
            result.set_control_fields(key, lwid, 0)
        else:
            result.set_control_fields(
                key, lwid, lwid * self.slide_len + self.win_len - 1)
        emit(result)

    def svc(self, item, channel_id, emit):
        is_marker = isinstance(item, EOSMarker)
        t = item.record if is_marker else item
        key, tid, ts = t.get_control_fields()
        st = self._key_state(key)
        if self.renumbering and not is_marker:
            tid = st.renumber_next
            st.renumber_next += 1
            t.set_control_fields(key, tid, ts)
        id_ = tid if self.win_type == WinType.CB else ts
        if not is_marker:
            if st.next_lwid > 0 and id_ < st.next_lwid * self.slide_len:
                # tuple precedes the next open window: late, ignore
                # (win_seqffat drops tuples of already-fired windows)
                self.ignored_tuples += 1
                return
            lifted = self.result_factory()
            self.lift(t, lifted)
            i = bisect.bisect_right(st.pending_keys, id_)
            st.pending_keys.insert(i, id_)
            st.pending_vals.insert(i, lifted)
            st.max_id = max(st.max_id, id_)
        # fire every window proven complete by id_
        fire_slack = 0 if self.win_type == WinType.CB else self.triggering_delay
        while id_ >= self._win_bounds(st.next_lwid)[1] + fire_slack:
            self._fire(key, st, st.next_lwid, emit)
            st.next_lwid += 1

    def eos_flush(self, emit):
        """Flush every window containing buffered data
        (win_seqffat eosnotify)."""
        for key, st in self.keys.items():
            cand = []
            if st.pending_keys:
                cand.append(st.pending_keys[-1])
            if st.content_keys:
                cand.append(st.content_keys[-1])
            if not cand:
                continue
            last = max(cand)
            while st.next_lwid * self.slide_len <= last:
                self._fire(key, st, st.next_lwid, emit)
                st.next_lwid += 1

    def svc_end(self):
        if self.closing_func is not None:
            self.closing_func(self.context)

    def state_dict(self):
        # FlatFAT trees hold closures (combine); snapshot their live
        # values and rebuild the trees on load
        snap = {}
        for key, st in self.keys.items():
            vals = []
            idx = st.tree.front
            for _ in range(st.tree.count):
                vals.append(st.tree.tree[st.tree.n + idx])
                idx = (idx + 1) % st.tree.n
            snap[key] = {
                "tree_values": vals, "capacity": st.tree.n,
                "content_keys": list(st.content_keys),
                "pending_keys": list(st.pending_keys),
                "pending_vals": list(st.pending_vals),
                "next_lwid": st.next_lwid, "max_id": st.max_id,
                "renumber_next": st.renumber_next,
            }
        return {"keys": snap, "ignored": self.ignored_tuples}

    def load_state(self, state):
        self.keys.clear()
        for key, snap in state["keys"].items():
            st = _FFATKeyState(self._new_tree(snap["capacity"]))
            if snap["tree_values"]:
                st.tree.insert_bulk(snap["tree_values"])
            st.content_keys = list(snap["content_keys"])
            st.pending_keys = list(snap["pending_keys"])
            st.pending_vals = list(snap["pending_vals"])
            st.next_lwid = snap["next_lwid"]
            st.max_id = snap["max_id"]
            st.renumber_next = snap["renumber_next"]
            self.keys[key] = st
        self.ignored_tuples = state["ignored"]


class WinSeqFFAT(Operator):
    def __init__(self, lift_func, combine_func, win_len, slide_len, win_type,
                 triggering_delay=0, name="win_seqffat",
                 result_factory=BasicRecord, closing_func=None):
        super().__init__(name, 1, RoutingMode.FORWARD, Pattern.WIN_SEQFFAT)
        self.win_type = win_type
        self.kwargs = dict(
            lift_func=lift_func, combine_func=combine_func, win_len=win_len,
            slide_len=slide_len, win_type=win_type,
            triggering_delay=triggering_delay, result_factory=result_factory,
            closing_func=closing_func)
        self._renumbering = False

    def enable_renumbering(self):
        self._renumbering = True

    def stages(self):
        logic = WinSeqFFATLogic(renumbering=self._renumbering, **self.kwargs)
        return [StageSpec(
            self.name, [logic], StandardEmitter(), self.routing,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS))]


class KeyFFAT(Operator):
    """Key-parallel farm of Win_SeqFFAT engines
    (reference ``wf/key_ffat.hpp``:65-170: KF_Emitter routing, no
    collector)."""

    def __init__(self, lift_func, combine_func, win_len, slide_len, win_type,
                 parallelism=1, triggering_delay=0, name="key_ffat",
                 result_factory=BasicRecord, closing_func=None):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.KEY_FFAT)
        self.win_type = win_type
        self.kwargs = dict(
            lift_func=lift_func, combine_func=combine_func, win_len=win_len,
            slide_len=slide_len, win_type=win_type,
            triggering_delay=triggering_delay, result_factory=result_factory,
            closing_func=closing_func)
        self._renumbering = False

    def enable_renumbering(self):
        self._renumbering = True

    def stages(self):
        from ..runtime.win_routing import KFEmitter
        replicas = [WinSeqFFATLogic(parallelism=self.parallelism,
                                    replica_index=i,
                                    renumbering=self._renumbering,
                                    **self.kwargs)
                    for i in range(self.parallelism)]
        return [StageSpec(
            self.name, replicas, KFEmitter(self.parallelism), self.routing,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS))]
