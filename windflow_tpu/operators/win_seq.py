"""Win_Seq: the sequential keyed window engine.

Re-design of reference ``wf/win_seq.hpp`` (623 LoC): per-key descriptors
holding a StreamArchive + open windows, distributed window-id assignment
via WinOperatorConfig (svc :319-511), EOS flush of open windows
(:514-579).  Building block of every composite window operator.

Two query styles (API:44-100):
* non-incremental: ``win_func(gwid, Iterable, result[, ctx])`` runs on
  the archived window extent at fire time;
* incremental: ``winupdate_func(gwid, tuple, result[, ctx])`` folds each
  IN tuple as it arrives (no archive kept).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List

from ..core.archive import StreamArchive
from ..core.basic import (OrderingMode, Pattern, Role, RoutingMode,
                          WinOperatorConfig, WinType, WinEvent)
from ..core.context import RuntimeContext
from ..core.iterable import Iterable
from ..core.meta import default_hash, with_context
from ..core.tuples import BasicRecord
from ..core.window import TriggererCB, TriggererTB, Window
from ..core import win_assign as wa
from ..runtime.emitters import StandardEmitter
from ..runtime.node import EOSMarker, NodeLogic
from .base import Operator, StageSpec


def _sort_by_id(t):
    return t.get_control_fields()[1]


def _sort_by_ts(t):
    return t.get_control_fields()[2]


class _KeyDescriptor:
    """Per-key state (win_seq.hpp:98-127)."""

    __slots__ = ("archive", "wins", "next_lwid", "last_lwid", "next_ids",
                 "emit_counter")

    def __init__(self, sort_key, emit_counter_start: int = 0):
        self.archive = StreamArchive(sort_key)
        self.wins: List[Window] = []
        self.next_lwid = 0    # next window to open
        self.last_lwid = -1   # last window fired
        self.next_ids = 0     # renumbering counter
        self.emit_counter = emit_counter_start


class WinSeqLogic(NodeLogic):
    def __init__(self, win_func: Callable, win_len: int, slide_len: int,
                 win_type: WinType, *, triggering_delay: int = 0,
                 incremental: bool = False,
                 result_factory: Callable[[], Any] = BasicRecord,
                 closing_func: Callable = None,
                 config: WinOperatorConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), parallelism: int = 1,
                 replica_index: int = 0, renumbering: bool = False):
        if win_len == 0 or slide_len == 0:
            raise ValueError("win_len and slide_len must be > 0")
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.triggering_delay = triggering_delay
        self.is_nic = not incremental
        self.result_factory = result_factory
        self.closing_func = closing_func
        self.config = config or WinOperatorConfig()
        self.role = role
        self.map_indexes = map_indexes
        self.renumbering = renumbering
        self.context = RuntimeContext(parallelism, replica_index)
        base = 3  # (gwid, data, result)
        self.win_func = with_context(win_func, base, self.context)
        # module-level sort keys keep per-key state picklable
        # (utils/checkpoint.py)
        self._sort_key = (_sort_by_id if win_type == WinType.CB
                          else _sort_by_ts)
        self.keys: Dict[Any, _KeyDescriptor] = {}
        self.ignored_tuples = 0

    # -- helpers -----------------------------------------------------------
    def _key_descriptor(self, key) -> _KeyDescriptor:
        kd = self.keys.get(key)
        if kd is None:
            start = self.map_indexes[0] if self.role == Role.MAP else 0
            kd = self.keys[key] = _KeyDescriptor(self._sort_key, start)
        return kd

    def _make_window(self, key, lwid: int, gwid: int, initial_id: int) -> Window:
        if self.win_type == WinType.CB:
            trig = TriggererCB(self.win_len, self.slide_len, lwid, initial_id)
        else:
            trig = TriggererTB(self.win_len, self.slide_len, lwid, initial_id,
                               self.triggering_delay)
        w = Window(key, lwid, gwid, trig, self.win_type, self.win_len,
                   self.slide_len)
        w.init_result(self.result_factory())
        return w

    def _emit_result(self, key, kd: _KeyDescriptor, result, emit) -> None:
        """Role-specific renumbering of outgoing results
        (win_seq.hpp:478-487): MAP stripes dense ids across the reduce
        windows; PLQ renumbers panes densely per key."""
        if self.role == Role.MAP:
            _, _, ts = result.get_control_fields()
            result.set_control_fields(key, kd.emit_counter, ts)
            kd.emit_counter += self.map_indexes[1]
        elif self.role == Role.PLQ:
            hashcode = default_hash(key)
            new_id = wa.plq_renumbered_id(hashcode, kd.emit_counter,
                                          self.config)
            _, _, ts = result.get_control_fields()
            result.set_control_fields(key, new_id, ts)
            kd.emit_counter += 1
        emit(result)

    # -- node interface ----------------------------------------------------
    def svc(self, item, channel_id, emit):
        is_marker = isinstance(item, EOSMarker)
        t = item.record if is_marker else item
        key, tid, ts = t.get_control_fields()
        hashcode = default_hash(key)
        id_ = tid if self.win_type == WinType.CB else ts
        kd = self._key_descriptor(key)
        if self.renumbering:  # CB windows in DEFAULT mode (win_seq.hpp:342-347)
            assert self.win_type == WinType.CB
            id_ = kd.next_ids
            kd.next_ids += 1
            t.set_control_fields(key, id_, ts)
        cfg = self.config
        first_gwid_key = wa.first_gwid_of_key(hashcode, cfg)
        initial_id = wa.initial_id_of_key(hashcode, cfg, self.role)
        # first tuple of this key: anchor window creation at its first
        # containing window -- an epoch-scale first id/ts must not
        # materialize ~id/slide empty leading windows (matches the
        # native engine and the on-demand creation of win_seq.hpp:
        # 417-428)
        if (kd.next_lwid == 0 and kd.last_lwid < 0 and not kd.wins
                and not is_marker):
            rel = id_ - initial_id
            if rel >= self.win_len:
                kd.next_lwid = (rel - self.win_len) // self.slide_len + 1
        # ignore tuples predating the last fired window (win_seq.hpp:358-380)
        min_boundary = (self.win_len + kd.last_lwid * self.slide_len
                        if kd.last_lwid >= 0 else 0)
        if id_ < initial_id + min_boundary:
            if kd.last_lwid >= 0:
                self.ignored_tuples += 1
            return
        last_w = wa.last_window_of(id_, initial_id, self.win_len,
                                   self.slide_len)
        if last_w < 0 and not is_marker:
            return  # hopping-window gap (win_seq.hpp:388-411)
        if self.is_nic and not is_marker:
            kd.archive.insert(t)
        # open new windows up to last_w (win_seq.hpp:417-428)
        for lwid in range(kd.next_lwid, last_w + 1):
            gwid = wa.gwid_of_lwid(first_gwid_key, lwid, cfg)
            kd.wins.append(self._make_window(key, lwid, gwid, initial_id))
            kd.next_lwid += 1
        # evaluate all open windows (win_seq.hpp:429-494)
        cnt_fired = 0
        for win in kd.wins:
            event = win.on_tuple(t)
            if event == WinEvent.IN:
                if not self.is_nic and not is_marker:
                    self.win_func(win.gwid, t, win.result)
            elif event == WinEvent.FIRED:
                t_s, t_e = win.first_tuple, win.last_tuple
                if self.is_nic:
                    if t_s is None:
                        it = Iterable([], 0, 0)
                    else:
                        lo, hi = kd.archive.win_range(t_s, t_e)
                        it = Iterable(kd.archive.items(), lo, hi)
                    self.win_func(win.gwid, it, win.result)
                if t_s is not None:
                    kd.archive.purge(t_s)
                cnt_fired += 1
                kd.last_lwid += 1
                self._emit_result(key, kd, win.result, emit)
        del kd.wins[:cnt_fired]

    def eos_flush(self, emit):
        """Flush every open window of every key (win_seq.hpp:514-579)."""
        for key, kd in self.keys.items():
            for win in kd.wins:
                if self.is_nic:
                    t_s, t_e = win.first_tuple, win.last_tuple
                    if t_s is None:
                        it = Iterable([], 0, 0)
                    else:
                        lo, hi = kd.archive.win_range(t_s, t_e)
                        it = Iterable(kd.archive.items(), lo, hi)
                    self.win_func(win.gwid, it, win.result)
                self._emit_result(key, kd, win.result, emit)
            kd.wins.clear()

    def svc_end(self):
        if self.closing_func is not None:
            self.closing_func(self.context)

    def state_dict(self):
        return {"keys": self.keys, "ignored": self.ignored_tuples}

    def load_state(self, state):
        self.keys = state["keys"]
        self.ignored_tuples = state["ignored"]


def builtin_win_func(kind: str):
    """Non-incremental window function for a builtin aggregate name
    (sum/count/mean/max/min).  Empty windows produce the masked neutral
    0, matching the columnar/native planes (window_compute.py)."""
    if kind == "sum":
        def f(gwid, it, res):
            res.value = sum(t.value for t in it)
    elif kind == "count":
        def f(gwid, it, res):
            res.value = float(len(it))
    elif kind == "mean":
        def f(gwid, it, res):
            res.value = (sum(t.value for t in it) / len(it)
                         if len(it) else 0.0)
    elif kind == "max":
        def f(gwid, it, res):
            res.value = max((t.value for t in it), default=0.0)
    elif kind == "min":
        def f(gwid, it, res):
            res.value = min((t.value for t in it), default=0.0)
    else:
        raise ValueError(f"unknown builtin window kind {kind!r}")
    return f


class WinSeq(Operator):
    """Standalone sequential window operator (parallelism 1).

    ``win_func`` may be a callable or a builtin aggregate name
    ("sum"/"count"/"max"/"min") -- builtin names additionally let the
    chain lower onto the native C++ record pipeline
    (graph/native_lowering.py)."""

    def __init__(self, win_func, win_len, slide_len, win_type,
                 triggering_delay=0, incremental=False, name="win_seq",
                 result_factory=BasicRecord, closing_func=None):
        super().__init__(name, 1, RoutingMode.FORWARD, Pattern.WIN_SEQ)
        self.win_kind_name = win_func if isinstance(win_func, str) else None
        if self.win_kind_name is not None:
            win_func = builtin_win_func(self.win_kind_name)
            incremental = False
        self.kwargs = dict(
            win_func=win_func, win_len=win_len, slide_len=slide_len,
            win_type=win_type, triggering_delay=triggering_delay,
            incremental=incremental, result_factory=result_factory,
            closing_func=closing_func)
        self.win_type = win_type
        self._renumbering = False

    def enable_renumbering(self):
        self._renumbering = True

    def make_logic(self, renumbering=False) -> WinSeqLogic:
        return WinSeqLogic(renumbering=renumbering, **self.kwargs)

    def stages(self):
        return [StageSpec(
            self.name, [self.make_logic(renumbering=self._renumbering)],
            StandardEmitter(), self.routing,
            ordering_mode=(OrderingMode.ID if self.win_type == WinType.CB
                           else OrderingMode.TS))]
