"""MultiPipe: a linear (then split/merged) pipeline of operators.

Re-design of reference ``wf/multipipe.hpp`` (2587 LoC).  Where the
reference nests ff_a2a "matrioska" structures (multipipe.hpp:236-341),
windflow_tpu wires an explicit flat graph of RtNode threads and
channels: per-replica inbound collectors in DETERMINISTIC/PROBABILISTIC
modes (multipipe.hpp:697-705), emitter clones per upstream producer,
farm-level collectors after ordered window farms, and thread-fusion
``chain`` for FORWARD operators (multipipe.hpp:345-390).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional

from ..core.basic import Mode, OrderingMode, RoutingMode, WinType
from ..operators.base import Operator, StageSpec
from ..runtime.emitters import StandardEmitter
from ..runtime.node import Outlet, RtNode
from ..runtime.ordering import KSlackLogic, OrderingLogic
from ..runtime.queues import Channel, make_channel


# re-export: ChainedLogic moved to runtime.node so operators (PaneFarm
# LEVEL2 fusion) can use it without importing the graph layer
from ..runtime.node import ChainedLogic  # noqa: F401


class MultiPipe:
    def __init__(self, graph, name: str):
        self.graph = graph
        self.name = name
        self.nodes: List[RtNode] = []   # every thread of this pipe
        self.tails: List[RtNode] = []   # nodes whose outputs are unbound
        self.has_source = False
        self.has_sink = False
        self.children: List["MultiPipe"] = []  # after split
        self.merged_into: Optional[MultiPipe] = None
        self._op_names: List[str] = []
        self._ops: List[Operator] = []  # descriptors, for native lowering

    # -- internal wiring ---------------------------------------------------
    def _check_open(self):
        if self.has_sink:
            raise RuntimeError(f"MultiPipe {self.name}: already terminated "
                               "by a sink")
        if self.children:
            raise RuntimeError(f"MultiPipe {self.name}: already split; use "
                               "select()")
        if self.merged_into is not None:
            raise RuntimeError(f"MultiPipe {self.name}: already merged")
        if not self.has_source:
            raise RuntimeError(f"MultiPipe {self.name}: add a source first")

    def _mark_used(self, op: Operator):
        if op.used:
            raise RuntimeError(f"operator {op.name} already used in a graph")
        op.used = True
        self._ops.append(op)

    def _collector_for(self, ordering_mode: Optional[OrderingMode],
                       n_channels: int, win_type: Optional[WinType] = None):
        """Mode-dependent inbound collector (multipipe.hpp:697-705)."""
        mode = self.graph.mode
        if mode == Mode.DEFAULT or ordering_mode is None:
            return None
        if mode == Mode.DETERMINISTIC:
            return OrderingLogic(ordering_mode, n_channels)
        # PROBABILISTIC: K-slack; CB windows additionally need dense ids
        km = (OrderingMode.TS_RENUMBERING
              if ordering_mode in (OrderingMode.ID,
                                   OrderingMode.TS_RENUMBERING)
              else OrderingMode.TS)
        return KSlackLogic(km, on_drop=self.graph._count_dropped)
    def _append_stage(self, stage: StageSpec,
                      win_type: Optional[WinType] = None):
        n = len(stage.replicas)
        cfg = self.graph.config
        grouped = (stage.group_emitters is not None
                   and all(getattr(t, "group", None) is not None
                           for t in self.tails) and len(self.tails) > 0)
        if grouped:
            n_producers = max(1, len([t for t in self.tails
                                      if t.group == self.tails[0].group]))
        else:
            n_producers = len(self.tails)
        # per-replica inbound channel (collector front-end when required)
        collector_logics = [
            self._collector_for(stage.ordering_mode, n_producers, win_type)
            for _ in range(n)]
        entry_channels: List[Channel] = [make_channel(cfg) for _ in range(n)]
        # emitter clone per upstream producer (reference: emitter combined
        # into each tail node, multipipe.hpp:302-338)
        if stage.elastic is not None and grouped:
            raise ValueError(
                f"stage {stage.name!r} cannot be elastic behind grouped "
                "(complex-nesting) tails (docs/ELASTIC.md)")
        elastic_outlets = []
        if grouped:
            # complex nesting: tails of group g feed only the replicas of
            # group g, through that group's emitter prototype
            group_members = {}
            for i, g in enumerate(stage.groups):
                group_members.setdefault(g, []).append(i)
            for tail in self.tails:
                members = group_members[tail.group]
                em = stage.group_emitters[tail.group].clone()
                em.set_n_destinations(len(members))
                dests = [(entry_channels[i],
                          entry_channels[i].register_producer())
                         for i in members]
                tail.outlets.append(Outlet(em, dests))
        else:
            for tail in self.tails:
                em = stage.emitter_proto.clone()
                em.set_n_destinations(n)
                from ..runtime.emitters import TreeEmitter
                if isinstance(em, TreeEmitter) and stage.groups is not None:
                    sizes: List[int] = []
                    for g in stage.groups:
                        while g >= len(sizes):
                            sizes.append(0)
                        sizes[g] += 1
                    em.set_child_widths(sizes)
                dests = [(ch, ch.register_producer())
                         for ch in entry_channels]
                outlet = Outlet(em, dests)
                tail.outlets.append(outlet)
                elastic_outlets.append(outlet)
        new_nodes: List[RtNode] = []
        replica_nodes: List[RtNode] = []
        for i, logic in enumerate(stage.replicas):
            if collector_logics[i] is not None:
                rep_ch = make_channel(cfg)
                coll_node = RtNode(
                    f"{self.name}/{stage.name}.coll{i}", collector_logics[i],
                    entry_channels[i], [])
                coll_node.is_collector = True
                fwd = StandardEmitter()
                fwd.set_n_destinations(1)
                coll_node.outlets.append(
                    Outlet(fwd, [(rep_ch, rep_ch.register_producer())]))
                new_nodes.append(coll_node)
                in_ch = rep_ch
            else:
                in_ch = entry_channels[i]
            node = RtNode(f"{self.name}/{stage.name}.{i}", logic, in_ch, [])
            if stage.error_policy is not None:
                node.error_policy = stage.error_policy
            node.worker_pin = stage.worker
            node.group = stage.groups[i] if stage.groups is not None else None
            if self.graph.config.tracing:
                node.stats = self.graph.stats.register(
                    f"{self.name}/{stage.name}", str(i))
            new_nodes.append(node)
            replica_nodes.append(node)
        if stage.group_collectors is not None:
            # complex nesting: one collector per inner-copy group (e.g.
            # each replicated PLQ's ordered collector); the next grouped
            # stage consumes from its group's collector
            coll_nodes = []
            for g, coll in enumerate(stage.group_collectors):
                members = [rn for rn, gg in zip(replica_nodes, stage.groups)
                           if gg == g]
                if coll is None:
                    coll_nodes.extend(members)
                    continue
                cch = make_channel(cfg)
                cnode = RtNode(f"{self.name}/{stage.name}.coll.g{g}", coll,
                               cch, [])
                cnode.is_collector = True
                cnode.group = g
                if hasattr(coll, "set_n_channels"):
                    coll.set_n_channels(len(members))
                for rn in members:
                    fwd = StandardEmitter()
                    fwd.set_n_destinations(1)
                    rn.outlets.append(
                        Outlet(fwd, [(cch, cch.register_producer())]))
                new_nodes.append(cnode)
                coll_nodes.append(cnode)
            self.tails = coll_nodes
        elif stage.collector is not None:
            cch = make_channel(cfg)
            cnode = RtNode(f"{self.name}/{stage.name}.collector",
                           stage.collector, cch, [])
            cnode.is_collector = True
            if hasattr(stage.collector, "set_n_channels"):
                stage.collector.set_n_channels(len(replica_nodes))
            for rn in replica_nodes:
                fwd = StandardEmitter()
                fwd.set_n_destinations(1)
                rn.outlets.append(Outlet(fwd, [(cch, cch.register_producer())]))
            new_nodes.append(cnode)
            self.tails = [cnode]
        else:
            self.tails = replica_nodes
        self.nodes.extend(new_nodes)
        self._op_names.append(stage.name)
        if stage.elastic is not None:
            self._register_elastic(stage, replica_nodes, elastic_outlets)
        if stage.restartable:
            self._register_restartable(stage, replica_nodes)

    def _register_restartable(self, stage: StageSpec,
                              replica_nodes) -> None:
        """Register a wired restartable stage with the graph's
        supervised registry (durability/supervision.py): the replica
        supervisor rebuilds crashed replicas of these groups from the
        last committed epoch instead of failing the graph."""
        from ..durability.supervision import SupervisedGroup
        key = f"{self.name}/{stage.name}"
        if key in self.graph.supervised:
            raise RuntimeError(f"restartable operator {key!r} already "
                               "registered")
        for node in replica_nodes:
            node.supervised_group = key
        self.graph.supervised[key] = SupervisedGroup(
            key, self, stage.elastic_factory, list(replica_nodes))

    def _register_elastic(self, stage: StageSpec, replica_nodes,
                          outlets) -> None:
        """Register a wired elastic stage with the graph (rescale
        registry + always-on stats records for the load signals)."""
        from ..elastic.rescale import ElasticHandle
        key = f"{self.name}/{stage.name}"
        if key in self.graph.elastic:
            raise RuntimeError(f"elastic operator {key!r} already "
                               "registered")
        for i, node in enumerate(replica_nodes):
            node.elastic_group = key
            # load signals need service-time samples even when tracing
            # is off; records registered here keep monitoring
            # attribution consistent with the traced path
            if node.stats is None:
                node.stats = self.graph.stats.register(key, str(i))
        self.graph.elastic[key] = ElasticHandle(
            key, stage.elastic, self, stage.elastic_factory,
            replica_nodes, outlets,
            error_policy=stage.error_policy or "fail")

    # -- public API (multipipe.hpp add/chain surface) ----------------------
    def add_source(self, source: Operator) -> "MultiPipe":
        if self.has_source:
            raise RuntimeError("source already present")
        self._mark_used(source)
        stage = source.stages()[0]
        if stage.worker is None:
            stage.worker = getattr(source, "worker", None)
        for i, logic in enumerate(stage.replicas):
            node = RtNode(f"{self.name}/{stage.name}", logic, None, [])
            node.worker_pin = stage.worker
            # per-source trace-sampling override (telemetry/;
            # SourceBuilder.with_tracing): None defers to
            # RuntimeConfig.trace_sample, 0 opts out
            node.trace_sample = getattr(source, "trace_sample", None)
            if self.graph.config.tracing:
                node.stats = self.graph.stats.register(
                    f"{self.name}/{stage.name}", str(i))
            self.nodes.append(node)
            self.tails.append(node)
        self.has_source = True
        self._op_names.append(stage.name)
        return self

    def add(self, op: Operator) -> "MultiPipe":
        self._check_open()
        self._mark_used(op)
        win_type = getattr(op, "win_type", None)
        # Win_Farm with CB windows is rejected in DEFAULT mode: window
        # multicast cannot renumber consistently (multipipe.hpp:1002-1006)
        from ..core.basic import Pattern, Role
        if (self.graph.mode == Mode.DEFAULT and win_type == WinType.CB
                and op.pattern in (Pattern.WIN_FARM, Pattern.WIN_FARM_TPU)
                and getattr(op, "role", Role.SEQ) == Role.SEQ):
            raise RuntimeError(
                "Win_Farm with count-based windows cannot be used in "
                "DEFAULT mode; use DETERMINISTIC mode")
        # CB windows in DEFAULT mode: renumber ids on arrival
        # (win_seq.hpp:342-347 via multipipe wiring)
        if (self.graph.mode == Mode.DEFAULT and win_type == WinType.CB
                and hasattr(op, "enable_renumbering")):
            op.enable_renumbering()
        stages = op.stages()
        self._prepare_elastic(op, stages)
        self._prepare_restartable(op, stages)
        for i, stage in enumerate(stages):
            if stage.error_policy is None:
                stage.error_policy = getattr(op, "error_policy", "fail")
            if stage.worker is None:
                stage.worker = getattr(op, "worker", None)
            if i == 0:
                self._swap_cb_broadcast(stage, win_type)
            self._append_stage(stage, win_type)
        return self

    def _prepare_elastic(self, op: Operator, stages: List[StageSpec]) -> None:
        """Validate and mark an elastic declaration (docs/ELASTIC.md):
        runtime rescaling needs a single collector-less stage whose
        operator kind exposes a fresh-replica factory, in DEFAULT mode
        (ordering collectors would pin per-channel identity the rescale
        cannot preserve).  _append_stage registers the wired stage."""
        spec = getattr(op, "elasticity", None)
        if spec is None:
            return
        factory = op.elastic_logic_factory()
        if (factory is None or len(stages) != 1
                or stages[0].collector is not None
                or stages[0].groups is not None
                or stages[0].group_emitters is not None):
            raise ValueError(
                f"operator {op.name!r} cannot be elastic: runtime "
                "rescaling supports single-stage Filter/Map/FlatMap/"
                "Accumulator operators (docs/ELASTIC.md)")
        if self.graph.mode != Mode.DEFAULT:
            raise ValueError(
                "elastic operators require Mode.DEFAULT: ordering/"
                "K-slack collectors bind per-channel state the rescale "
                "protocol does not migrate (docs/ELASTIC.md)")
        stages[0].elastic = spec
        stages[0].elastic_factory = factory

    def _prepare_restartable(self, op: Operator,
                             stages: List[StageSpec]) -> None:
        """Validate and mark a .with_restartable() declaration
        (docs/RESILIENCE.md "Supervised replica restart").  The replica
        rebuild reuses the elastic-plane recipe, so the structural
        requirements are the elastic ones: a single collector-less
        stage whose operator kind exposes a fresh-replica factory, in
        DEFAULT mode."""
        if not getattr(op, "restartable", False):
            return
        factory = op.elastic_logic_factory()
        if (factory is None or len(stages) != 1
                or stages[0].collector is not None
                or stages[0].groups is not None
                or stages[0].group_emitters is not None):
            raise ValueError(
                f"operator {op.name!r} cannot be restartable: replica "
                "supervision supports single-stage Filter/Map/FlatMap/"
                "Accumulator operators with a fresh-replica factory "
                "(docs/RESILIENCE.md)")
        if self.graph.mode != Mode.DEFAULT:
            raise ValueError(
                "restartable operators require Mode.DEFAULT: ordering/"
                "K-slack collectors bind per-channel state the replica "
                "rebuild does not migrate (docs/RESILIENCE.md)")
        stages[0].restartable = True
        if stages[0].elastic_factory is None:
            stages[0].elastic_factory = factory

    def _swap_cb_broadcast(self, stage: StageSpec, win_type) -> None:
        """CB windows entering a window-multicast (WF-rooted) stage in
        DETERMINISTIC/PROBABILISTIC mode: the upstream ids need not be
        per-key dense (filters upstream drop tuples), so id-based
        multicast membership is wrong.  The reference swaps the emitter
        for a Broadcast_Emitter and renumbers densely in per-replica
        TS-ordering collectors (multipipe.hpp:1039-1051); each replica
        then keeps only the windows its config owns."""
        from ..core.basic import Role
        from ..runtime.emitters import BroadcastEmitter, TreeEmitter
        from ..runtime.win_routing import WFEmitter
        if (self.graph.mode == Mode.DEFAULT or win_type != WinType.CB
                or stage.routing != RoutingMode.COMPLEX):
            return
        em = stage.emitter_proto
        root = em.root if isinstance(em, TreeEmitter) else em
        if not isinstance(root, WFEmitter):
            return
        # MAP stages distribute by per-key round-robin STRIPING, not by
        # window membership: workers do not self-select stripes, so the
        # broadcast plane does not apply (Win_MapReduce keeps its
        # emitter tree)
        if any(getattr(r, "role", None) == Role.MAP
               for r in stage.replicas):
            return
        stage.emitter_proto = BroadcastEmitter()
        stage.group_emitters = None
        stage.ordering_mode = OrderingMode.TS_RENUMBERING

    def chain(self, op: Operator) -> "MultiPipe":
        """Thread-fuse a FORWARD operator into the current tail nodes when
        parallelism matches; falls back to add() otherwise
        (multipipe.hpp:345-390; chain exists only for Filter/Map/
        FlatMap/Sink)."""
        self._check_open()
        pin = getattr(op, "worker", None)
        if pin is not None and any(t.worker_pin is not None
                                   and t.worker_pin != pin
                                   for t in self.tails):
            # thread fusion would co-locate by construction: a pin that
            # differs from the tail's must keep its own node so the
            # partition planner can cut the edge (docs/DISTRIBUTED.md)
            return self.add(op)
        if getattr(op, "elasticity", None) is not None \
                or any(t.elastic_group is not None for t in self.tails):
            # thread fusion and runtime rescaling are mutually
            # exclusive: a fused replica cannot be rebuilt/rewired per
            # operator (docs/ELASTIC.md); wire through a channel instead
            return self.add(op)
        if getattr(op, "error_policy", "fail") != "fail" \
                or any(t.error_policy != "fail" for t in self.tails):
            # thread fusion would merge error-policy scopes: a fused
            # node has ONE policy, so a skip/dead-letter operator would
            # swallow its upstream half's errors -- and a 'fail'
            # operator fused into a policied tail would inherit that
            # tail's policy.  Keep policy scope per-operator instead
            return self.add(op)
        logics = op.chain_logics()
        if logics is None and self.graph.mode == Mode.DEFAULT \
                and len(self.tails) == 1:
            # single-replica fusion: any single-stage operator with one
            # replica and no collector can run inline in the tail thread
            stages = op.stages()
            if (len(stages) == 1 and len(stages[0].replicas) == 1
                    and stages[0].collector is None):
                self._mark_used(op)
                self.tails[0].logic = ChainedLogic(self.tails[0].logic,
                                                   stages[0].replicas[0])
                if pin is not None:
                    # the pin survives chaining by pinning the merged
                    # node (a chained operator shares its tail's thread
                    # by construction, so the whole node moves)
                    self.tails[0].worker_pin = pin
                self._op_names.append(f"{op.name}(chained)")
                return self
        if (logics is None or len(logics) != len(self.tails)
                or self.graph.mode != Mode.DEFAULT):
            return self.add(op)
        self._mark_used(op)
        for tail, logic in zip(self.tails, logics):
            tail.logic = ChainedLogic(tail.logic, logic)
            if pin is not None:
                tail.worker_pin = pin
        self._op_names.append(f"{op.name}(chained)")
        return self

    def add_sink(self, sink: Operator) -> "MultiPipe":
        self.add(sink)
        self.has_sink = True
        return self

    def chain_sink(self, sink: Operator) -> "MultiPipe":
        self.chain(sink)
        self.has_sink = True
        return self

    # -- split / merge (pipegraph executes; multipipe.hpp:2478-2583) -------
    def split(self, split_fn: Callable[[Any], Any],
              n_branches: int) -> "MultiPipe":
        self._check_open()
        return self.graph._execute_split(self, split_fn, n_branches)

    def select(self, i: int) -> "MultiPipe":
        if not self.children:
            raise RuntimeError("select() on a non-split MultiPipe")
        if not 0 <= i < len(self.children):
            raise IndexError(i)
        return self.children[i]

    def merge(self, *others: "MultiPipe") -> "MultiPipe":
        self._check_open()
        return self.graph._execute_merge(self, others)

    # -- execution ---------------------------------------------------------
    def all_nodes(self) -> List[RtNode]:
        out = list(self.nodes)
        for c in self.children:
            out.extend(c.all_nodes())
        return out

    def thread_count(self) -> int:
        return len(self.all_nodes())
