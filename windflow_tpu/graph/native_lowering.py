"""Transparent lowering of record chains onto the native C++ pipeline.

When a PipeGraph is a single linear MultiPipe of *declared* operators
-- SyntheticSource/BatchSource, Filter/Map with ``Expr`` descriptors,
a builtin-kind WinSeq/KeyFarm window aggregate, and a Sink -- the whole
chain runs record-at-a-time inside native/record_pipeline.cpp instead
of Python threads: the fused C++ chain with KeyFarm-parallelism
key-sharding.  Anything undeclared (arbitrary Python callables, rich
closing functions, splits/merges, tracing, non-DEFAULT modes) keeps the
regular Python-plane execution -- lowering is an optimization, never a
semantic change.

This is the framework-level answer to the reference's "compile the
user's C++ functor into the operator" model (meta.hpp): declared
expressions compile onto C++ descriptors; opaque Python stays on the
interpreted plane.

The reference architecture itself (one thread per operator over SPSC
queues) is available as ``NativeRecordPipeline(mode="threaded")`` and
is what bench.py measures as the honest baseline.
"""
from __future__ import annotations

from typing import Optional

from ..core.basic import Mode, WinType
from ..core.expr import match_affine, match_predicate


def _lower_plan(graph) -> Optional[dict]:
    """Inspect the graph; return a lowering plan or None."""
    from ..operators.basic_ops import Filter, Map, Sink
    from ..operators.batch_ops import BatchFilter, BatchMap, BatchSource
    from ..operators.key_farm import KeyFarm
    from ..operators.synth import SyntheticSource
    from ..operators.win_seq import WinSeq
    from ..runtime.native import native_available

    cfg = graph.config
    if not getattr(cfg, "native_record_lowering", True):
        return None
    if graph.mode != Mode.DEFAULT or cfg.tracing or cfg.trace_runtime:
        return None
    # resilience features live in the RtNode/channel plane: a lowered
    # run has no replicas for a FaultPlan to bind to, no channels for
    # the watchdog to monitor, and no per-tuple svc boundary for error
    # policies, so their presence forfeits lowering
    if getattr(cfg, "fault_plan", None) is not None \
            or getattr(cfg, "watchdog_timeout_s", None):
        return None
    # elastic operators (elastic/; docs/ELASTIC.md) need the threaded
    # replica plane: a lowered run has no replicas to rescale
    if getattr(graph, "elastic", None):
        return None
    if len(graph.pipes) != 1:
        return None
    mp = graph.pipes[0]
    if mp.children or mp.merged_into is not None or not mp.has_sink:
        return None
    ops = getattr(mp, "_ops", None)
    if not ops or len(ops) < 2:
        return None
    if any(getattr(op, "error_policy", "fail") != "fail" for op in ops):
        return None
    if not native_available():
        return None

    plan = {"middles": [], "window": None, "shards": 1}
    # -- source --
    src = ops[0]
    if isinstance(src, SyntheticSource):
        plan["source"] = ("synth", src)
    elif isinstance(src, BatchSource) and src.parallelism == 1 \
            and src.closing_func is None:
        plan["source"] = ("feed", src)
    else:
        return None
    # -- middles + window + sink --
    from ..core.tuples import BasicRecord
    middles, rest = list(ops[1:]), []
    for pos, op in enumerate(middles):
        if isinstance(op, (Filter, BatchFilter)) and not op.keyed:
            e = getattr(op, "expr", None)
            if e is None or getattr(op, "closing_func", None) is not None:
                return None
            m = match_predicate(e)
            if m is None:
                return None
            plan["middles"].append(("filter", m))
        elif isinstance(op, (Map, BatchMap)) and not op.keyed:
            e = getattr(op, "expr", None)
            if e is None or getattr(op, "closing_func", None) is not None:
                return None
            m = match_affine(e)
            if m is None:
                return None
            plan["middles"].append(("map", m))
        elif isinstance(op, (WinSeq, KeyFarm)):
            if op.win_kind_name is None:
                return None
            if isinstance(op, WinSeq):
                delay = op.kwargs.get("triggering_delay", 0)
                factory = op.kwargs.get("result_factory", BasicRecord)
            else:
                delay = op.triggering_delay
                factory = op.result_factory
                if op.closing_func is not None:
                    return None
                plan["shards"] = max(1, op.parallelism)
            # a custom result class would change the sink's record type
            if delay != 0 or factory is not BasicRecord:
                return None
            plan["window"] = op
            rest = middles[pos + 1:]
            break
        else:
            return None
    # after the window only the sink may follow: a post-window Filter/
    # Map must see window RESULTS, which the native chain cannot express
    if plan["window"] is None or len(rest) != 1:
        return None
    sink = rest[0]
    if not isinstance(sink, Sink) or sink.closing_func is not None:
        return None
    plan["sink"] = sink
    return plan


def _window_geometry(w):
    """(win_len, slide_len, is_tb) of a declared window operator --
    WinSeq keeps them in kwargs, KeyFarm as attributes."""
    win_type = w.win_type
    is_tb = (win_type == WinType.TB if isinstance(win_type, WinType)
             else bool(win_type))
    if hasattr(w, "kwargs"):
        return w.kwargs["win_len"], w.kwargs["slide_len"], is_tb
    return w.win_len, w.slide_len, is_tb


def _columnar_synth_spec(plan):
    """Fold a declared SyntheticSource chain into the columnar engine's
    synthesis law: affine value-maps compose into (vscale, voff), and
    value-predicate filters fold to a residue MASK -- the synthetic
    value of event e depends only on e % vmod, so each predicate is
    decidable per residue at plan time.  Returns (mask|None, vtab)
    when the whole chain folds, else None (record-plane fallback).
    ``vtab`` is the per-residue value table computed by applying the
    map chain SEQUENTIALLY -- bit-identical floats to the per-event
    record plane, where composing the affines into one (scale, offset)
    pair could differ by ULPs exactly at filter boundaries.

    A window whose tuples are ALL filtered out never opens on the
    record plane, while the masked engine would fire it empty, so
    masks are only accepted when every FULL window provably contains
    an unmasked tuple: win_len must cover a full residue cycle and
    every per-key residue class must keep at least one unmasked
    residue.  (The EOS tail window needs no extra proof: the engine
    advances triggering only on surviving tuples, so an all-masked
    tail never opens -- matching the record plane.)"""
    import math

    import numpy as np

    w = plan["window"]
    if w.win_kind_name not in ("sum", "count", "mean"):
        return None  # max/min finalization stays on the record plane
    src = plan["source"][1]
    vmod = src.vmod
    # per-residue values, evolved SEQUENTIALLY through the map chain
    # (mirrors the record plane's per-event float ops bit for bit)
    vals = np.arange(vmod, dtype=np.float64) * src.vscale + src.voff
    mask = None
    for mk, m in plan["middles"]:
        if mk == "map":
            field, scale, offset, square = m
            if field != "value" or square:
                return None  # value law must stay affine in e % vmod
            vals = vals * scale + offset
        else:
            if m[0] == "mod_eq":
                if m[1] != "value":
                    return None
                keep = (vals % m[2]) == m[3]
            else:
                op, field, c = m
                if field != "value":
                    return None
                keep = {"lt": vals < c, "le": vals <= c, "gt": vals > c,
                        "ge": vals >= c, "eq": vals == c}[op]
            mask = keep if mask is None else (mask & keep)
    if mask is not None:
        if getattr(w, "_renumbering", False):
            return None  # renumbering compacts ids AFTER the filter
        g = math.gcd(src.n_keys, vmod)
        win_len, _, _ = _window_geometry(w)
        if win_len < vmod // g:
            return None  # a window might not cover a residue cycle
        for c in range(g):
            if not mask[c::g].any():
                return None  # keys of this class would have no tuples
        mask = mask.astype(np.uint8)
    return mask, vals


def _run_columnar_synth(graph, plan, mask, vtab) -> bool:
    """Execute the folded chain: fused C++ generate+filter+fold, numpy
    window finalization over the staged pane partials, record-plane
    emission contract at the sink."""
    import numpy as np

    from ..core.context import RuntimeContext
    from ..core.meta import with_context
    from ..core.tuples import BasicRecord
    from ..runtime.native import NativeWindowEngine

    w = plan["window"]
    src = plan["source"][1]
    win_len, slide_len, is_tb = _window_geometry(w)
    kind = w.win_kind_name
    # ids are dense from 0, so the renumber lane would assign the same
    # ids (no filters reach here with renumbering -- see the spec fn)
    eng = NativeWindowEngine(win_len, slide_len, is_tb, 0,
                             renumber=False, kind=kind)
    sink_ctx = RuntimeContext(1, 0)
    sink_fn = with_context(plan["sink"].fn, 1, sink_ctx)

    def drain():
        while True:
            out = eng.flush(1 << 20)
            if out is None:
                return
            vals, starts, ends, d_keys, d_gwids, d_rts = out[:6]
            cs = np.concatenate([[0.0], np.cumsum(vals)])
            wins = cs[ends] - cs[starts]
            if kind == "mean":
                cc = np.concatenate([[0.0], np.cumsum(out[6])])
                wins = wins / np.maximum(cc[ends] - cc[starts], 1.0)
            for j in range(len(d_keys)):
                sink_fn(BasicRecord(int(d_keys[j]), int(d_gwids[j]),
                                    int(d_rts[j]), float(wins[j])))

    graph._started = True
    step = 1 << 20
    i = 0
    while i < src.n_events:
        c = min(step, src.n_events - i)
        eng.synth_ingest(i, c, src.n_keys, src.vmod, 1.0, 0.0, mask,
                         vtab)
        drain()
        i += c
    eng.eos()
    drain()
    graph._ended = True
    graph._lowered = True
    graph._lowered_columnar = True
    sink_fn(None)
    return True


def try_run_native(graph) -> bool:
    """Run the graph on the native record plane if it lowers.
    Returns True when the run completed natively."""
    plan = _lower_plan(graph)
    if plan is None:
        return False
    if plan["source"][0] == "synth":
        spec = _columnar_synth_spec(plan)
        if spec is not None:
            return _run_columnar_synth(graph, plan, *spec)
    from ..core.context import RuntimeContext
    from ..core.meta import with_context
    from ..core.tuples import BasicRecord
    from ..runtime.native import NativeRecordPipeline

    w = plan["window"]
    win_len, slide_len, is_tb = _window_geometry(w)
    renumber = getattr(w, "_renumbering", False)

    rp = NativeRecordPipeline("fused", plan["shards"], store_results=True)
    for kind, m in plan["middles"]:
        if kind == "map":
            field, scale, offset, square = m
            if square:
                rp.add_map_affine(scale, offset, square=True)
            elif field == "value":
                rp.add_map_affine(scale, offset)
            else:
                rp.add_map_load(field, scale, offset)
        else:
            if m[0] == "mod_eq":
                rp.add_filter(m[1], "mod_eq", m=m[2], r=m[3])
            else:
                rp.add_filter(m[1], m[0], const=m[2])
    rp.add_window(win_len, slide_len, is_tb, w.win_kind_name,
                  renumber=renumber)

    src_kind, src = plan["source"]
    if src_kind == "synth":
        rp.set_synth(src.n_events, src.n_keys, src.vmod, src.vscale,
                     src.voff)
    else:
        rp.set_feed()

    sink_ctx = RuntimeContext(1, 0)
    sink_fn = with_context(plan["sink"].fn, 1, sink_ctx)

    graph._started = True
    rp.start()
    feeder = None
    if src_kind == "feed":
        import threading

        feed_err = []

        def _feed():
            try:
                src_ctx = RuntimeContext(1, 0)
                src_fn = with_context(src.fn, 0, src_ctx)
                while True:
                    batch = src_fn()
                    if batch is None:
                        break
                    rp.feed(batch.key, batch.id, batch.ts, batch["value"])
            except BaseException as e:  # noqa: BLE001
                feed_err.append(e)
            finally:
                # ALWAYS close the feed: an unclosed ring leaves shard
                # workers spinning and poll() blocked forever
                rp.feed_eos()

        # feed from a side thread so results drain concurrently (the
        # C++ store would otherwise buffer every window until EOS)
        feeder = threading.Thread(target=_feed, name="native-feeder",
                                  daemon=True)
        feeder.start()
    while True:
        keys, wids, ts, vals, done = rp.poll()
        for j in range(len(keys)):
            sink_fn(BasicRecord(int(keys[j]), int(wids[j]), int(ts[j]),
                                float(vals[j])))
        if done:
            break
    if feeder is not None:
        feeder.join()
    _count, _total, dropped = rp.wait()
    if dropped:
        graph._count_dropped(int(dropped))
    graph._ended = True
    graph._lowered = True
    if feeder is not None and feed_err:
        from .pipegraph import NodeFailureError
        raise NodeFailureError(
            f"node {plan['source'][1].name} failed: "
            f"{feed_err[0]!r}") from feed_err[0]
    sink_fn(None)
    return True
