"""Graph compile pass: automatic stage fusion (OptLevel.LEVEL2).

Runs inside ``PipeGraph.start`` on the fully wired RtNode/channel graph,
before any thread starts and before the ingest plane wraps channels in
credit proxies.  It realizes what the reference does with ``ff_comb``
thread fusion at opt-level 2 (multipipe.hpp:345-390, the
``optimize_PaneFarm`` fusion of pane_farm.hpp:222-250), but graph-wide
and automatic: maximal runs of adjacent stages collapse into single
replica threads whose segments feed each other inline, removing the
channel hop (one condition-variable round trip per item) between them.

Two shapes fuse, to a fixpoint:

1. **Linear (1:1)** -- node A's only outlet is a plain StandardEmitter
   with ONE destination channel, that channel has A as its ONLY
   producer, and its consumer B is an ordinary replica.  A absorbs B.
   This is exact: B received precisely A's emissions, in order, with
   channel_id 0.
2. **Parallel stage pattern (n:n)** -- n tails each round-robin a
   non-keyed FORWARD StandardEmitter over the same n consumer channels
   (same parallelism).  Tail i absorbs consumer i pairwise.  Item ->
   replica assignment changes from round-robin interleave to 1:1, which
   is unobservable for FORWARD stages (their consumers already receive
   arbitrary interleavings); the output multiset is unchanged.

Never fused:

* ordering/K-slack collectors (``OrderingLogic``/``KSlackLogic``) and
  farm collector nodes -- the "collector-free" rule: their channel_id /
  merge semantics are the channel's;
* ingest sources (``IngestSourceLogic``) as the absorbing head -- their
  outlet channel is the credit-accounting boundary (ingest/wiring.py
  wraps it after this pass runs);
* anything routed by a non-Standard emitter (broadcast, splitting,
  tree, window multicast) or with multiple outlets.

Contracts preserved per fused segment (see runtime.node.FusedLogic):
error policy + dead-letter attribution, fault-injection clocks
(a FaultPlan targeting a fused-away operator still fires), per-operator
stats records, quiesce/checkpoint (snapshots stay keyed by the original
node names, so they restore across fusion-level changes).
"""
from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Tuple

from ..core.basic import OptLevel
from ..runtime.emitters import StandardEmitter
from ..runtime.node import FusedLogic, FusedSegment, RtNode
from ..runtime.ordering import KSlackLogic, OrderingLogic


def _is_collector(node: RtNode) -> bool:
    # structural flag set by multipipe._append_stage at wiring; the
    # logic-type check is defense in depth for collectors wired by
    # other paths
    return getattr(node, "is_collector", False) \
        or isinstance(node.logic, (OrderingLogic, KSlackLogic))


def _is_elastic(node: RtNode) -> bool:
    # elastic replicas (elastic/rescale.py) are a fusion barrier like
    # the ingest credit boundary: the rescale protocol rebuilds replica
    # threads and rewires their channels at runtime, which requires the
    # operator's nodes to stay their own threads with their own
    # channels.  Supervised replicas (durability/supervision.py) are
    # barred for the same reason: the supervisor rebuilds a crashed
    # replica in place, reusing its channel and outlets.
    return getattr(node, "elastic_group", None) is not None \
        or getattr(node, "supervised_group", None) is not None


def _partition_splits(graph, a: RtNode, b: RtNode) -> bool:
    """Distributed-runtime fusion barrier (distributed/partition.py):
    a fused node runs as ONE replica thread in ONE worker process, so
    two nodes the partition plan assigns to different workers must not
    fuse -- the edge between them is exactly the cut the shuffle
    transport carries.  No-op outside distributed runs (plan absent)."""
    plan = getattr(graph, "_dist_plan", None)
    if plan is None:
        return False
    from ..distributed.partition import node_owner
    try:
        return node_owner(a, plan) != node_owner(b, plan)
    except KeyError:
        return False  # node outside the plan (defensive): fuse freely


def _is_ingest_head(node: RtNode) -> bool:
    try:
        from ..ingest.sources import IngestSourceLogic
    except ImportError:  # pragma: no cover - ingest plane always present
        return False
    logic = node.logic
    if isinstance(logic, FusedLogic):
        logic = logic.segments[0].logic
    return isinstance(logic, IngestSourceLogic)


def _segments_of(node: RtNode) -> List[FusedSegment]:
    if isinstance(node.logic, FusedLogic):
        return node.logic.segments
    seg = FusedSegment(node.logic, node.name, node.error_policy)
    seg.stats = node.stats  # keep the operator's registered record:
    #                         monitoring attribution survives fusion
    return [seg]


def _has_idle_tick(node: RtNode) -> bool:
    logic = node.logic
    if isinstance(logic, FusedLogic):
        return any(hasattr(s.logic, "idle_tick") for s in logic.segments)
    return hasattr(logic, "idle_tick")


def _has_async_emit(node: RtNode) -> bool:
    logic = node.logic
    if isinstance(logic, FusedLogic):
        return not logic.sync_emit
    return not getattr(logic, "sync_emit", True)


def _tick_safe(a: RtNode, b: RtNode) -> bool:
    """Idle ticks (time-bounded device launches on stalled streams) are
    driven by the consuming node's timed channel gets, on the consume
    thread.  Two shapes would break that contract:

    * a SOURCE head absorbing a ticking logic -- the fused node has no
      channel, so ticks never fire and a stalled source withholds
      fired windows;
    * an ASYNC-emitting segment (device engine dispatcher) upstream of
      a ticking one -- the downstream segment's svc would run on the
      dispatcher thread while its idle_tick runs on the consume
      thread, racing on unsynchronized engine state (at LEVEL0 the
      downstream node's channel serialized both).

    Keep such consumers on their own thread.  (Async upstream of a
    NON-ticking segment is fine: all its svc calls serialize on the
    dispatcher thread, and eos_flush runs after the dispatcher join.)"""
    if not _has_idle_tick(b):
        return True
    return a.channel is not None and not _has_async_emit(a)


def _single_forward_dest(node: RtNode):
    """(channel, outlet) when this node forwards everything to exactly
    one destination channel it exclusively produces into."""
    if len(node.outlets) != 1:
        return None
    outlet = node.outlets[0]
    if type(outlet.emitter) is not StandardEmitter:
        return None
    if len(outlet.dests) != 1:
        return None
    ch = outlet.dests[0][0]
    if ch.n_producers != 1:
        return None
    return ch, outlet


def _merge(graph, a: RtNode, b: RtNode) -> None:
    """Fuse consumer ``b`` into producer ``a`` (both unstarted)."""
    segments = _segments_of(a) + _segments_of(b)
    fused = FusedLogic(segments)
    fused.pool = getattr(graph, "buffer_pool", None)
    a.logic = fused
    a.outlets = b.outlets
    # the fused node reports under a joined name; per-segment identity
    # (policies, stats, faults, checkpoint keys) stays on the segments
    a.name = f"{a.name}+{b.name.rsplit('/', 1)[-1]}"
    a.error_policy = "fail"  # segments guard themselves
    a.stats = None           # per-segment records instead
    for pipe in graph.pipes:
        if b in pipe.nodes:
            pipe.nodes.remove(b)
        if b in pipe.tails:
            pipe.tails[pipe.tails.index(b)] = a


def _consumers_by_channel(graph) -> dict:
    return {id(n.channel): n for n in graph._all_nodes()
            if n.channel is not None}


def _try_linear(graph, consumers: dict) -> bool:
    for a in graph._all_nodes():
        if _is_ingest_head(a) or _is_collector(a) or _is_elastic(a):
            continue
        sfd = _single_forward_dest(a)
        if sfd is None:
            continue
        ch, _outlet = sfd
        b = consumers.get(id(ch))
        if b is None or b is a or _is_collector(b) or _is_elastic(b) \
                or not _tick_safe(a, b) or _partition_splits(graph, a, b):
            continue
        _merge(graph, a, b)
        return True
    return False


def _try_stage_pattern(graph, consumers: dict) -> bool:
    """n:n FORWARD fusion: n tails round-robining over the same n
    channels pair off with the n consumers."""
    nodes = graph._all_nodes()
    # group candidate producers by their (identical) destination set
    groups: dict = {}
    for a in nodes:
        if _is_ingest_head(a) or _is_collector(a) or _is_elastic(a):
            continue
        if len(a.outlets) != 1:
            continue
        outlet = a.outlets[0]
        em = outlet.emitter
        if type(em) is not StandardEmitter or em.keyed:
            continue
        if len(outlet.dests) < 2:
            continue
        key = tuple(id(ch) for ch, _pid in outlet.dests)
        groups.setdefault(key, []).append(a)
    for key, producers in groups.items():
        n = len(key)
        if len(producers) != n:
            continue
        chans = [producers[0].outlets[0].dests[i][0] for i in range(n)]
        if any(ch.n_producers != n for ch in chans):
            continue  # someone else also feeds these consumers
        cons = [consumers.get(cid) for cid in key]
        if any(c is None or _is_collector(c) or _is_elastic(c)
               for c in cons):
            continue
        if len({id(c) for c in cons}) != n or \
                any(c in producers for c in cons):
            continue
        if any(not _tick_safe(a, b) for a, b in zip(producers, cons)):
            continue
        if any(_partition_splits(graph, a, b)
               for a, b in zip(producers, cons)):
            continue
        for a, b in zip(producers, cons):
            a.outlets = []      # drop the fan-out wiring first
            _merge(graph, a, b)
        return True
    return False


def fuse_graph(graph) -> List[str]:
    """Run the compile pass; returns the fused node names (report)."""
    if getattr(graph.config, "opt_level", OptLevel.LEVEL2) \
            < OptLevel.LEVEL2:
        return []
    changed = True
    while changed:
        consumers = _consumers_by_channel(graph)
        changed = _try_linear(graph, consumers)
        if not changed:
            changed = _try_stage_pattern(graph, consumers)
    return [n.name for n in graph._all_nodes()
            if isinstance(n.logic, FusedLogic)]


# ---------------------------------------------------------------------------
# Introspection helpers: fusion-transparent logic lookup (tests, wiring,
# checkpoint all need "the WinSeqTPULogic of this graph" regardless of
# whether the pass folded it into a neighbour).
# ---------------------------------------------------------------------------

def iter_logics(graph) -> Iterator[Tuple[str, object]]:
    """Yield (original_node_name, logic) for every operator replica,
    seeing through FusedLogic wrappers."""
    for node in graph._all_nodes():
        if isinstance(node.logic, FusedLogic):
            for seg in node.logic.segments:
                yield seg.name, seg.logic
        else:
            yield node.name, node.logic


def find_logic(graph, pred: Callable[[object], bool],
               name_substr: str = "") -> Optional[object]:
    """First replica logic matching ``pred`` (and, optionally, whose
    original node name contains ``name_substr``)."""
    for name, logic in iter_logics(graph):
        if name_substr in name and pred(logic):
            return logic
    return None
