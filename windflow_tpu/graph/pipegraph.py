"""PipeGraph: the application container.

Re-design of reference ``wf/pipegraph.hpp`` (915 LoC): owns the
application tree of MultiPipes (AppNode :67-79), ``add_source`` :560-574,
``run`` = start + wait_end :580-736, split/merge executors :289-503, and
the dropped-tuple counter :104/:763-766.
"""
from __future__ import annotations

import threading
import time as _time
from typing import List, Optional, Sequence

from ..core.basic import Mode, RuntimeConfig
from ..operators.base import Operator
from ..resilience.cancel import CancelToken
# NodeFailureError's historical home is this module; it now lives in
# resilience.errors (shared with the watchdog) and is re-exported here
from ..resilience.errors import NodeFailureError, StallError  # noqa: F401
from ..resilience.policies import DeadLetterStore
from ..runtime.emitters import SplittingEmitter
from ..runtime.node import RtNode
from .multipipe import MultiPipe


class _AppNode:
    """Application-tree node (pipegraph.hpp:67-79)."""

    def __init__(self, mp: Optional[MultiPipe] = None, parent=None):
        self.mp = mp
        self.parent = parent
        self.children: List["_AppNode"] = []


class PipeGraph:
    def __init__(self, name: str = "pipegraph", mode: Mode = Mode.DEFAULT,
                 config: RuntimeConfig = None):
        self.name = name
        self.mode = mode
        self.config = config or RuntimeConfig(mode=mode)
        self.config.mode = mode
        self.root = _AppNode()
        self.pipes: List[MultiPipe] = []
        self._dropped = 0
        self._dropped_lock = threading.Lock()
        self._pause_ctl = None  # SourcePauseControl, attached at start()
        from ..monitoring.stats import GraphStats
        self.stats = GraphStats(name)
        self._started = False
        self._ended = False
        self._monitor = None
        self._pipe_seq = 0
        # failure containment (resilience/): graph-wide cancellation,
        # dead-letter quarantine, stall watchdog
        self._cancel = CancelToken()
        self.dead_letters = DeadLetterStore()
        self._watchdog = None
        # telemetry plane (telemetry/; docs/OBSERVABILITY.md): the
        # always-on bounded flight recorder (record() no-ops when the
        # capacity is configured 0) and the tracing hub, built at
        # start() when tracing sampling is enabled
        from ..telemetry import FlightRecorder
        self.flight = FlightRecorder(self.config.flight_recorder_events)
        self.telemetry = None
        # pooled zero-copy interchange (core/tuples.ColumnPool): one
        # arena per graph, shared by partition sub-batches, SynthChunk
        # materialization and the batched consume loops
        if self.config.buffer_pool:
            from ..core.tuples import ColumnPool
            self.buffer_pool = ColumnPool()
        else:
            self.buffer_pool = None
        # names of nodes the LEVEL2 compile pass fused (graph/fuse.py),
        # filled at start()
        self.fused_nodes: List[str] = []
        # elastic scaling plane (elastic/; docs/ELASTIC.md): registry of
        # rescalable operators (name -> ElasticHandle, filled at
        # wiring), one rescale at a time, and the load-driven
        # controller thread (started at start() when the registry is
        # non-empty)
        self.elastic = {}
        self._rescale_lock = threading.Lock()
        self._controller = None
        # supervised replica self-healing (durability/supervision.py;
        # docs/RESILIENCE.md): registry of restartable operators
        # (key -> SupervisedGroup, filled at wiring) and the healing
        # thread, built at start() when RuntimeConfig.supervision is
        # set on top of the durability plane
        self.supervised = {}
        self._supervisor = None
        # audit plane (audit/; docs/OBSERVABILITY.md): the online
        # flow-conservation ledger + frontier tracker + skew census
        # thread, built at start() when RuntimeConfig.audit is on
        self.auditor = None
        # diagnosis plane (diagnosis/; docs/OBSERVABILITY.md): critical-
        # path attribution, bottleneck walk, gauge history + regression
        # bands, built at start() when RuntimeConfig.diagnosis is on
        self.diagnosis = None
        # durability plane (durability/; docs/RESILIENCE.md): aligned
        # epoch barriers + manifest commits + exactly-once sink
        # release, built at start() when RuntimeConfig.durability is set
        self.durability = None
        # tiered keyed state (state/; docs/RESILIENCE.md "Tiered state
        # & memory pressure"): the TieredStateManager splitting
        # RuntimeConfig.state_budget_bytes across capable keyed
        # replicas, built at start() when the budget is set
        self.tiered_state = None
        # distributed runtime plane (distributed/; docs/DISTRIBUTED.md):
        # the partition plan (node name -> worker id, computed before
        # the fusion pass) and the live transport handle, built at
        # start() when RuntimeConfig.distributed is set
        self._dist_plan = None
        self._dist = None
        # online re-planner (graph/replanner.py; docs/PLANNER.md):
        # built at start() when RuntimeConfig.replan is on
        self.replanner = None

    # -- construction ------------------------------------------------------
    def _new_pipe(self) -> MultiPipe:
        mp = MultiPipe(self, f"pipe{self._pipe_seq}")
        self._pipe_seq += 1
        self.pipes.append(mp)
        return mp

    def add_source(self, source: Operator) -> MultiPipe:
        """Create a root MultiPipe fed by ``source``
        (pipegraph.hpp:560-574)."""
        mp = self._new_pipe()
        mp.add_source(source)
        self.root.children.append(_AppNode(mp, self.root))
        return mp

    def _count_dropped(self, n: int) -> None:
        with self._dropped_lock:
            self._dropped += n

    def get_num_dropped_tuples(self) -> int:
        return self._dropped

    # -- split / merge executors (pipegraph.hpp:289-503) -------------------
    def _find_app_node(self, node: _AppNode, mp: MultiPipe) -> Optional[_AppNode]:
        if node.mp is mp:
            return node
        for c in node.children:
            found = self._find_app_node(c, mp)
            if found is not None:
                return found
        return None

    def _execute_split(self, mp: MultiPipe, split_fn, n_branches: int) -> MultiPipe:
        """Open n child MultiPipes fed through a SplittingEmitter
        (pipegraph.hpp:289-328)."""
        if n_branches < 2:
            raise ValueError("split requires >= 2 branches")
        app = self._find_app_node(self.root, mp)
        if app is None:
            raise RuntimeError("MultiPipe not part of this graph")
        children = []
        for b in range(n_branches):
            child = self._new_pipe()
            child.name = f"{mp.name}.b{b}"
            child.has_source = True  # fed by the parent, not by a Source op
            children.append(child)
            app.children.append(_AppNode(child, app))
        # wire: each tail gets a SplittingEmitter whose branch b leads to
        # the (future) first operator of child b.  We defer binding by
        # giving each child a relay channel the parent writes into.
        from ..runtime.queues import make_channel
        from ..runtime.node import NodeLogic, Outlet

        class _Relay(NodeLogic):
            def svc(self, item, channel_id, emit):
                emit(item)

        relay_nodes = []
        for child in children:
            ch = make_channel(self.config)
            relay = RtNode(f"{child.name}/relay", _Relay(), ch, [])
            child.nodes.append(relay)
            child.tails = [relay]
            relay_nodes.append((ch, relay))
        for tail in mp.tails:
            em = SplittingEmitter(split_fn, n_branches)
            em.set_n_destinations(n_branches)
            dests = [(ch, ch.register_producer()) for ch, _ in relay_nodes]
            tail.outlets.append(Outlet(em, dests))
        mp.children = children
        mp.tails = []
        return mp

    def _execute_merge(self, mp: MultiPipe,
                       others: Sequence[MultiPipe]) -> MultiPipe:
        """Merge sibling MultiPipes into a fresh one whose first operator
        receives the union of their streams (pipegraph.hpp:331-503; the
        merge-full/ind/partial distinction collapses here because wiring
        is explicit)."""
        all_pipes = [mp, *others]
        # validity checks (pipegraph.hpp:186-286 analogues)
        seen_ids = set()
        for p in all_pipes:
            if id(p) in seen_ids:
                raise RuntimeError("cannot merge a MultiPipe with itself")
            seen_ids.add(id(p))
            if p.graph is not self:
                raise RuntimeError(
                    "cannot merge MultiPipes from different PipeGraphs")
            if p.merged_into is not None:
                raise RuntimeError(
                    f"MultiPipe {p.name} was already merged")
            if p.children:
                raise RuntimeError(
                    f"MultiPipe {p.name} was split; merge its branches "
                    "(select(i)) instead")
            if p.has_sink:
                raise RuntimeError("cannot merge a terminated MultiPipe")
            if not p.tails:
                raise RuntimeError(f"MultiPipe {p.name} has no open tail")
        merged = self._new_pipe()
        merged.name = "+".join(p.name for p in all_pipes)
        merged.has_source = True
        merged.tails = [t for p in all_pipes for t in p.tails]
        app = self._find_app_node(self.root, mp)
        parent = app.parent if app is not None else self.root
        parent.children.append(_AppNode(merged, parent))
        for p in all_pipes:
            p.merged_into = merged
        return merged

    # -- execution (pipegraph.hpp:580-736) ---------------------------------
    def _all_nodes(self) -> List[RtNode]:
        seen = set()
        out = []
        for p in self.pipes:
            for n in p.nodes:
                if id(n) not in seen:
                    seen.add(id(n))
                    out.append(n)
        return out

    def start(self) -> None:
        if self._started:
            raise RuntimeError("PipeGraph already started")
        for p in self.pipes:
            if not p.has_sink and not p.children and p.merged_into is None \
                    and p.tails:
                raise RuntimeError(
                    f"MultiPipe {p.name} has no sink; terminate every "
                    "branch before run()")
        self._started = True
        if self.config.tracing:
            from ..monitoring.monitor import MonitoringThread
            self._monitor = MonitoringThread(self)
            self._monitor.start()
        # telemetry hub (telemetry/trace.py): sampled end-to-end
        # tracing + latency histograms ride the tracing surface;
        # trace_sample=0 with no per-source with_tracing override keeps
        # the counter plane with ZERO per-item stamping (node.telemetry
        # stays None).  A positive per-source override builds the hub
        # even under a global 0 -- the builder docs promise it wins.
        if self.config.tracing and (
                self.config.trace_sample > 0
                or any((n.trace_sample or 0) > 0
                       for n in self._all_nodes() if n.channel is None)):
            from ..telemetry import TelemetryHub
            self.telemetry = TelemetryHub(self.stats,
                                          self.config.trace_sample)
            self.stats.enable_histograms()
        # wire the live-checkpoint pause gate into every source replica
        # and every node (consumer idle ticks pause with the barrier),
        # plus the failure-containment plumbing: the CancelToken learns
        # every channel, every node learns the token / dead-letter
        # store / any bound fault-injection state
        from ..runtime.node import FusedLogic, SourcePauseControl, \
            source_loop_of
        self._pause_ctl = SourcePauseControl()
        # distributed runtime (distributed/partition.py): the partition
        # plan must exist BEFORE the fusion pass (its partition barrier
        # keeps fused nodes inside one worker) and is a pure function
        # of the wired pre-fusion topology + pins, so every worker
        # computes the same plan independently
        if self.config.distributed is not None \
                and self._dist_plan is None:
            from ..distributed.partition import plan_partition
            plan_partition(self)
        # graph compile pass (graph/fuse.py): at OptLevel.LEVEL2 (the
        # default; RuntimeConfig.opt_level opts out) adjacent
        # single-producer FORWARD stages fuse into single replicas.
        # Runs BEFORE the ingest wiring so credit proxies wrap the
        # post-fusion channel set, and BEFORE the binding loop below so
        # fault plans bind per fused segment.
        from .fuse import fuse_graph
        self.fused_nodes = fuse_graph(self)
        # distributed runtime (distributed/wiring.py): prune to this
        # worker's partition and wire the shuffle transport -- AFTER
        # fusion (the node set is final) and BEFORE the planner /
        # ingest wiring / audit attachment, so those planes see only
        # the owned nodes and the post-distribution destination set
        if self.config.distributed is not None:
            from ..distributed.wiring import distribute_graph
            distribute_graph(self)
        # cost-based placement planner (graph/planner.py;
        # docs/PLANNER.md): resolve every window engine's lane
        # ('auto' -> measured cost model; pins pass through), hand the
        # device lanes the measured RTT floor for the adaptive batch
        # resize, and give placed engines stats records so per-launch
        # device timing is observable without tracing.  AFTER fusion
        # (segments carry the engines now), BEFORE any thread starts.
        from .planner import plan_graph
        self.placements = plan_graph(self)
        for d in self.placements:
            self.flight.record("placement", **d)
        # online re-planning (graph/replanner.py; docs/PLANNER.md):
        # the start-time decision becomes a running hypothesis -- a
        # re-planner riding the diagnosis tick flips a lane mid-run
        # when the measured launch walls contradict the projection
        if self.config.replan and self.placements:
            if not self.config.diagnosis:
                raise RuntimeError(
                    "RuntimeConfig.replan needs the diagnosis plane: "
                    "re-planning rides the diagnosis tick (leave "
                    "RuntimeConfig.diagnosis at its default True)")
            from .replanner import RePlanner
            self.replanner = RePlanner(self)
        # whole-partition device step (graph/device_step.py; ROADMAP
        # item 3): AFTER fusion + placement (it lowers the post-fusion
        # node set by resolved lane), BEFORE the binding loop / ingest
        # wiring so step nodes bind like any other fused node.  Merges
        # forward edges into device-eligible consumers (including
        # source heads) and puts every device-lane window engine under
        # chunk-granular launch control: one launch per ingest chunk.
        from .device_step import lower_device_steps
        self.step_nodes = lower_device_steps(self)
        for name in self.step_nodes:
            self.flight.record("device_step", node=name)
        # attach the column pool to every node and emitter (pooled
        # materialization + partition sub-batches)
        if self.buffer_pool is not None:
            for n in self._all_nodes():
                n.pool = self.buffer_pool
                for o in n.outlets:
                    o.emitter.pool = self.buffer_pool
        # ingest plane (ingest/wiring.py): wrap ingest outlet channels
        # in credit proxies, register gates/stages with the CancelToken
        # and bind the microbatch controller to downstream engines --
        # BEFORE the channel loop below so consumers register their
        # (proxied) channels with the token
        from ..ingest.wiring import wire_ingest
        wire_ingest(self)
        fault_plan = getattr(self.config, "fault_plan", None)
        hub = self.telemetry
        # global-scheduler plane (scheduler/leases.py): the tenant's
        # fair-share lease gates every consume loop and unblocks on
        # cancel like any registered channel (it exposes poison())
        sched_lease = getattr(self.config, "sched_lease", None)
        if sched_lease is not None:
            self._cancel.register(sched_lease)
        for n in self._all_nodes():
            n.pause_ctl = self._pause_ctl
            n.cancel_token = self._cancel
            n.sched_lease = sched_lease
            n.dead_letters = self.dead_letters
            # telemetry plane: every node/logic learns the flight
            # recorder; under active tracing sampling the hub is bound
            # too (source nodes get a deterministic 1-in-N sampler,
            # consumers stamp hops / close traces)
            n.flight = self.flight
            n.logic.flight = self.flight
            if getattr(n.logic, "uses_dead_letters", False):
                # late-data quarantine (eventtime/ logics, K-slack
                # collectors): the logic itself dead-letters event-time
                # drops with its runtime identity attached
                n.logic.dead_letters = self.dead_letters
                n.logic.node_name = n.name
            if hub is not None:
                n.telemetry = hub
                n.logic.telemetry = hub
                if n.channel is None:
                    # per-source builder override (with_tracing): an
                    # explicit 0 opts this source out, None defers to
                    # the global period (which may itself be 0)
                    eff = n.trace_sample \
                        if n.trace_sample is not None \
                        else self.config.trace_sample
                    if eff > 0:
                        if isinstance(n.logic, FusedLogic):
                            # fused source head: emissions go segment
                            # to segment, never through RtNode._emit,
                            # so the first segment's exit samples
                            n.logic.trace_sampler = hub.sampler_for(
                                n.logic.segments[0].name, eff)
                        else:
                            n.trace_sampler = hub.sampler_for(
                                n.name, eff)
            if isinstance(n.logic, FusedLogic):
                # per-segment identity: dead letters, fault clocks (a
                # FaultPlan targeting a fused-away operator still fires)
                for seg in n.logic.segments:
                    seg.dead_letters = self.dead_letters
                    seg.logic.flight = self.flight
                    if getattr(seg.logic, "uses_dead_letters", False):
                        seg.logic.dead_letters = self.dead_letters
                        seg.logic.node_name = seg.name
                    if hub is not None:
                        seg.logic.telemetry = hub
                    if fault_plan is not None:
                        seg.faults = fault_plan.for_node(seg.name)
            elif fault_plan is not None:
                n.faults = fault_plan.for_node(n.name)
            if fault_plan is not None:
                # put-level faults (drop_put/dup_put) act at the
                # Outlet layer, with or without the audit plane
                n.bind_outlet_faults()
            if n.channel is not None:
                self._cancel.register(n.channel)
            if n.channel is None:
                src = source_loop_of(n.logic)
                if src is not None:
                    src.pause_control = self._pause_ctl
                    # cancellation check at generation-step boundaries:
                    # a fully fused source chain has no channel whose
                    # poisoning could unblock it (runtime/node.py
                    # SourceLoopLogic.eos_flush)
                    src.cancel_token = self._cancel
                    # adaptive-skew watermarked bodies
                    # (eventtime/watermarks.py skew="auto") announce
                    # their bound revisions on the flight recorder
                    uf = getattr(src, "user_fn", None)
                    if getattr(uf, "_wants_flight", False):
                        uf.flight = self.flight
                        uf.source_name = n.name
        # tiered keyed state (state/; docs/RESILIENCE.md "Tiered state
        # & memory pressure"): under RuntimeConfig.state_budget_bytes,
        # swap capable keyed logics' dict stores for TieredKeyedStores
        # (hot/warm/cold under the keyed_state_dict contract).  AFTER
        # flight/dead-letter/fault binding (the stores record
        # state_pressure/spill_abort and shed into dead_letters),
        # BEFORE the audit plane (the auditor hands its hot-key
        # sketches to the stores it finds)
        if getattr(self.config, "state_budget_bytes", None):
            from ..state import attach_tiered_state
            self.tiered_state = attach_tiered_state(self)
        # audit plane (audit/; docs/OBSERVABILITY.md): attach the
        # per-edge delivery books, outlet put-fault state and KEYBY
        # hot-key sketches AFTER fusion/ingest wiring and fault binding
        # (books align with the post-fusion channel set; put faults
        # bind to the segment whose emissions cross the channel) and
        # BEFORE any replica thread emits
        if self.config.audit:
            from ..audit import GraphAuditor
            self.auditor = GraphAuditor(self)
            self.auditor.attach()
        # diagnosis plane (diagnosis/; docs/OBSERVABILITY.md): built
        # after the wiring above so its one-time topology snapshot sees
        # the post-fusion operator chains.  No thread of its own --
        # ticks ride the monitor/auditor cadences and explain() calls
        if self.config.diagnosis:
            from ..diagnosis import DiagnosisPlane
            self.diagnosis = DiagnosisPlane(self)
            self.stats.set_topology(self.diagnosis.edges)
        elif self.config.slo is not None:
            # the SLO plane has no tick of its own -- it rides the
            # diagnosis tick; a declared objective that silently never
            # evaluates would be worse than a loud refusal
            raise RuntimeError(
                "RuntimeConfig.slo needs the diagnosis plane: SLO "
                "burn rates are evaluated on the diagnosis tick "
                "(leave RuntimeConfig.diagnosis at its default True)")
        # durability plane (durability/; docs/RESILIENCE.md): the epoch
        # coordinator + per-node barrier aligners/injectors.  AFTER the
        # audit books (barriers ride Outlet.send_to, so per-edge
        # delivery books count them symmetrically) and fault binding
        # (crash_at_epoch fires through the bound NodeFaults), BEFORE
        # any replica thread runs
        if self.config.durability is not None:
            from ..durability import EpochCoordinator
            self.durability = EpochCoordinator(self)
            self.durability.attach()
        # supervised replica self-healing (durability/supervision.py):
        # opt-in via RuntimeConfig.supervision, and only on top of the
        # durability plane -- the heal rewinds the graph to the last
        # committed epoch, which does not exist without one.  Built
        # BEFORE the replica threads start: the supervisor's pre-start
        # state capture is the rewind point until the first commit.
        if self.config.supervision is not None:
            if self.durability is None:
                raise RuntimeError(
                    "RuntimeConfig.supervision needs the durability "
                    "plane: a supervised restart rewinds to the last "
                    "committed epoch (set RuntimeConfig.durability)")
            if self.supervised:
                from ..durability.supervision import ReplicaSupervisor
                self._supervisor = ReplicaSupervisor(self)
                for grp in self.supervised.values():
                    for n in grp.replicas:
                        n.supervisor = self._supervisor
        for n in self._all_nodes():
            n.start()
        if self.auditor is not None:
            self.auditor.start()
        if self.durability is not None:
            self.durability.start()
        if self._supervisor is not None:
            self._supervisor.start()
        # watchdog AFTER the replica threads: it treats "no node alive"
        # as graph completion, so starting it first would let it exit
        # before the first node ever ran
        if self.config.watchdog_timeout_s:
            from ..resilience.watchdog import StallWatchdog
            self._watchdog = StallWatchdog(
                self, self.config.watchdog_timeout_s,
                cancel=self.config.watchdog_cancel)
            self._watchdog.start()
        # elastic controller LAST: its sampler reads live replica
        # stats, and its decisions call rescale() on a running graph
        if self.elastic:
            from ..elastic.controller import start_controller
            self._controller = start_controller(self)

    def cancel(self, reason: Optional[BaseException] = None) -> bool:
        """Poison every channel: blocked replicas unwind and wait_end
        returns.  Idempotent; returns False if already cancelled."""
        return self._cancel.cancel(reason, origin="user")

    def _join_all(self):
        """Join every node; once the graph is cancelled, give each
        remaining thread a bounded grace period (a replica stuck inside
        user code cannot be killed from Python -- it is recorded as
        stuck and abandoned; threads are daemonic).  Returns
        (errors, stuck) lists."""
        grace = self.config.cancel_grace_s
        errors, stuck = [], []
        # dedup by node OBJECT (held in the set): an id()-keyed set
        # could skip a rescale-added replica that reuses a freed
        # retired node's address
        joined = set()
        while True:
            # re-list each pass: a concurrent elastic rescale may add
            # replica nodes while this join loop is already running
            pending = [n for n in self._all_nodes() if n not in joined]
            if not pending:
                break
            for n in pending:
                joined.add(n)
                grace_deadline = None
                while n.is_alive():
                    n.join(timeout=0.1)
                    if not n.is_alive():
                        break
                    if self._cancel.cancelled:
                        now = _time.monotonic()
                        if grace_deadline is None:
                            grace_deadline = now + grace
                        elif now > grace_deadline:
                            stuck.append(n.name)
                            break
                if n.error is not None:
                    errors.append((n.name, n.error))
        return errors, stuck

    def wait_end(self) -> None:
        errors, stuck = self._join_all()
        if self._supervisor is not None:
            # a heal in flight holds the sources paused, so _join_all
            # cannot return mid-heal; stopping here just retires the
            # healing thread (and any replica it swapped in joined
            # through the re-listing join loop above)
            self._supervisor.stop()
        self._ended = True
        if self.replanner is not None:
            self.replanner.stop()
        if self._dist is not None:
            # distributed plane: flush the wire tails (acks settle the
            # senders' replay buffers, so the ledger closes over the
            # socket edges) before the auditor's final check
            self._dist.stop(
                clean=not errors and not self._cancel.cancelled)
        if self._controller is not None:
            self._controller.stop()
        if self._watchdog is not None:
            self._watchdog.stop()
        if self.durability is not None:
            # a failed/cancelled run strands its in-flight epochs;
            # stop() records them as epoch_abort next to the failure
            self.durability.stop(
                clean=not errors and not self._cancel.cancelled)
        if self.auditor is not None:
            # final ledger closure BEFORE the monitor's last snapshot
            # and the stats dump, so both carry the settled books.
            # Only a cleanly-ended graph must balance: a failure or
            # cancellation legitimately strands in-flight tuples.
            self.auditor.stop()
            if not errors and not self._cancel.cancelled:
                final = self.auditor.final_check()
                if final:
                    # post-mortem evidence next to the violation events
                    self.flight.dump(self.config.log_dir, self.name,
                                     keep=self.config.snapshot_keep)
        if self._monitor is not None:
            self._monitor.stop()
        if self.config.tracing:
            self._dump_logs()
        if self.config.trace_runtime:
            self._dump_runtime_stats()
        if errors:
            # post-mortem history first: the flight recorder's last-N
            # events (rescales, resizes, sheds, svc failures...) next
            # to the failure that ends the graph
            self.flight.record(
                "node_failure", nodes=[name for name, _e in errors],
                stuck=stuck)
            self.flight.dump(self.config.log_dir, self.name,
                             keep=self.config.snapshot_keep)
            err = NodeFailureError.from_pairs(errors, stuck)
            raise err from errors[0][1]
        if self._cancel.cancelled:
            # cancelled without any replica error: a watchdog stall or
            # a user cancel() -- surface the recorded reason
            reason = self._cancel.reason
            if isinstance(reason, BaseException):
                raise reason
            raise NodeFailureError(
                f"graph {self.name!r} was cancelled "
                f"(origin: {self._cancel.origin})")

    def _dump_runtime_stats(self) -> None:
        """Raw channel stats per consumer node (the -DTRACE_FASTFLOW
        queue/thread dump, pipegraph.hpp:711-733).  Counters are
        best-effort under concurrent producers (tracing-grade)."""
        import json
        import os
        rows = []
        for n in self._all_nodes():
            ch = n.channel
            if ch is None:
                continue
            rows.append({
                "node": n.name,
                "channel_impl": type(ch).__name__,
                "capacity": getattr(ch, "capacity", None),
                "producers": ch.n_producers,
                "puts": getattr(ch, "puts", 0),
                "gets": getattr(ch, "gets", 0),
                "high_watermark": getattr(ch, "high_watermark", 0),
                "residual": ch.qsize(),
            })
        from ..distributed.identity import worker_suffix
        os.makedirs(self.config.log_dir, exist_ok=True)
        path = os.path.join(
            self.config.log_dir,
            f"{os.getpid()}_{self.name}{worker_suffix()}_runtime.json")
        with open(path, "w") as f:
            json.dump({"graph": self.name, "channels": rows}, f, indent=1)
        from ..monitoring.monitor import rotate_snapshots
        rotate_snapshots(self.config.log_dir, self.config.snapshot_keep)

    def _dump_logs(self) -> None:
        """Write per-graph stats JSON + graphviz DOT + a rendered SVG
        diagram under log_dir (pipegraph.hpp:683-709 dumps
        <pid>_<op>.json + a PDF/SVG diagram)."""
        import os
        from ..monitoring.monitor import graph_to_dot, graph_to_svg
        self.refresh_gauges()
        if self.diagnosis is not None:
            # final tick: the dumped Diagnosis/History blocks carry the
            # end-of-run state (sustained-pressure EWMAs survive the
            # drain, so an offline doctor still names the bottleneck)
            self.diagnosis.maybe_tick(force=True)
        from ..distributed.identity import worker_suffix
        d = self.config.log_dir
        os.makedirs(d, exist_ok=True)
        # worker-id component (distributed/identity.py): two workers of
        # one graph on one box must never clobber each other's dumps
        stem = f"{os.getpid()}_{self.name}{worker_suffix()}"
        with open(os.path.join(d, f"{stem}.json"), "w") as f:
            f.write(self.stats.to_json(self.get_num_dropped_tuples(),
                                       self.dead_letters.count(),
                                       flight_events=self.flight.snapshot()))
        with open(os.path.join(d, f"{stem}.dot"), "w") as f:
            f.write(graph_to_dot(self))
        with open(os.path.join(d, f"{stem}.svg"), "w") as f:
            f.write(graph_to_svg(self))
        from ..monitoring.monitor import rotate_snapshots
        rotate_snapshots(d, self.config.snapshot_keep)

    def run(self) -> None:
        if not self._started:
            from .native_lowering import try_run_native
            if try_run_native(self):
                return
        self.start()
        self.wait_end()

    def thread_count(self) -> int:
        return len(self._all_nodes())

    # -- live checkpoint barrier (mid-stream quiesce/snapshot; the
    # reference has no checkpointing at all, SURVEY.md §5) -------------
    def _source_nodes(self):
        return [n for n in self._all_nodes() if n.channel is None]

    def _wait_drained(self, deadline: float) -> None:
        """Block until the pipeline is drained: every channel empty and
        every consumer node between items, stable across several polls.
        Cooperative single-process drain detection, not a distributed
        snapshot protocol: a thread descheduled for the whole stability
        window exactly between channel pop and its in-flight counter
        could in principle evade it."""
        import time
        consumers = [n for n in self._all_nodes() if n.channel is not None]
        stable = 0
        last_done = -1
        while stable < 5:
            if time.monotonic() > deadline:
                raise RuntimeError("live checkpoint: pipeline failed to "
                                   "drain (timeout)")
            total_done = sum(n.done for n in consumers)
            idle = all(n.taken == n.done for n in consumers
                       if n.is_alive())
            empty = all(n.channel.qsize() == 0 for n in consumers
                        if n.is_alive())
            # durability plane: items parked in a barrier aligner's
            # holdback buffer are in flight even though taken == done
            aligned = all(n.epochs is None or not n.epochs.busy
                          for n in consumers if n.is_alive())
            if idle and empty and aligned and total_done == last_done:
                stable += 1
            else:
                stable = 0
            last_done = total_done
            time.sleep(0.002)

    def quiesce(self, timeout: float = 120.0) -> None:
        """Pause sources at a step boundary and drain the pipeline to a
        globally quiescent state: channels empty, nodes between items,
        no device batches in flight (each window engine's ``quiesce``
        hook drains its dispatcher, whose emissions are drained in
        turn).  The graph must be started and not ended."""
        import time
        if not self._started or self._ended:
            raise RuntimeError("quiesce() needs a running graph")
        deadline = time.monotonic() + timeout
        if self.durability is not None:
            # serialize with the epoch plane FIRST: an epoch held open
            # across the source pause could never align (parked sources
            # inject no barriers) and its holdback buffers would defeat
            # the drain.  hold_epochs stops the cadence and waits for
            # in-flight epochs to commit while the graph keeps flowing.
            self.durability.hold_epochs(timeout)
        self._pause_ctl.request_pause()
        # wait for every still-running source to ack the pause
        while True:
            alive = [n for n in self._source_nodes() if n.is_alive()]
            with self._pause_ctl._cond:
                acked = self._pause_ctl.paused_count
            if acked >= len(alive):
                break
            if time.monotonic() > deadline:
                self._pause_ctl.resume()
                if self.durability is not None:
                    self.durability.release_epochs()
                raise RuntimeError("live checkpoint: sources failed to "
                                   "pause (timeout)")
            time.sleep(0.002)
        try:
            while True:
                self._wait_drained(deadline)
                emitted = False
                for n in self._all_nodes():
                    q = getattr(n.logic, "quiesce", None)
                    if q is not None and n.is_alive():
                        emitted = bool(q(n._emit)) or emitted
                if not emitted:
                    return
        except BaseException:
            # a failed drain must not leave the sources parked forever
            self._pause_ctl.resume()
            if self.durability is not None:
                self.durability.release_epochs()
            raise

    def resume(self) -> None:
        self._pause_ctl.resume()
        if self.durability is not None:
            self.durability.release_epochs()

    # -- elastic scaling plane (elastic/; docs/ELASTIC.md) --------------
    def rescale(self, operator: str, new_parallelism: int,
                trigger: str = "manual", timeout: float = 60.0):
        """Rescale a running elastic operator to ``new_parallelism``
        replicas with the pause-drain-migrate protocol
        (elastic/rescale.py): quiesce, repartition keyed state by the
        emitter's ``hash % parallelism`` contract, rebuild/retire
        replica threads and rewire channels, resume.  In-flight tuples
        are conserved (the pipeline is drained before any rewiring).

        ``operator`` is the registry key (``"<pipe>/<name>"``) or any
        unique substring of one (e.g. the builder name).  Returns the
        recorded :class:`~windflow_tpu.elastic.RescaleEvent`, or None
        when already at ``new_parallelism``."""
        if not self._started:
            raise RuntimeError("rescale() needs a started graph")
        if self._ended:
            raise RuntimeError("rescale() after wait_end()")
        handle = self.elastic.get(operator)
        if handle is None:
            matches = [h for k, h in self.elastic.items() if operator in k]
            if len(matches) != 1:
                raise KeyError(
                    f"no unique elastic operator matching {operator!r}; "
                    f"registered: {sorted(self.elastic)}")
            handle = matches[0]
        from ..elastic.rescale import rescale_operator
        dur = self.durability
        if dur is not None:
            # durability plane: barriers and rescales serialize PER
            # EPOCH, not under one global lock -- stop the epoch
            # cadence, let in-flight epochs commit while the graph
            # keeps flowing, then rescale inside the gap
            dur.hold_epochs(timeout)
        try:
            with self._rescale_lock:
                event = rescale_operator(self, handle, new_parallelism,
                                         trigger, timeout)
            if dur is not None:
                # refresh aligner producer counts for the rewired
                # channel set (retired producers already announced
                # themselves with final barriers) and give the new
                # replicas aligners before the cadence resumes
                dur.rewire()
        finally:
            if dur is not None:
                dur.release_epochs()
        if event is not None:
            self.flight.record("rescale", **event.to_dict())
        return event

    # -- online re-planning (graph/replanner.py; docs/PLANNER.md) -------
    def replace_lane(self, operator: str, lane: str,
                     trigger: str = "manual", timeout: float = 60.0,
                     evidence: Optional[dict] = None):
        """Flip a placed window engine's lane device<->host mid-run
        with zero lost tuples: serialize with elastic rescales under
        the rescale lock, hold the epoch cadence (a flip between two
        epochs restores exactly-once, like a rescale), drain the
        pipeline to a quiescent cut -- channels empty, no device
        batches in flight -- then swap the engine and resume.  Keyed
        window state lives in the host staging store on both lanes
        (resident device state is derivable from it and dropped on a
        host flip), so the swap migrates nothing and loses nothing.

        Records a ``replacement`` flight event the doctor explains.
        Returns the event dict, or None when already on ``lane``."""
        if lane not in ("device", "host"):
            raise ValueError(f"lane must be 'device' or 'host', "
                             f"not {lane!r}")
        if not self._started:
            raise RuntimeError("replace_lane() needs a started graph")
        if self._ended:
            raise RuntimeError("replace_lane() after wait_end()")
        target = None
        for name, logic, _entry in getattr(self, "placed_engines", []):
            if name == operator:
                target = logic
                break
        if target is None:
            raise KeyError(
                f"no placed window engine named {operator!r}; placed: "
                f"{sorted(n for n, _l, _e in getattr(self, 'placed_engines', []))}")
        old = target.resolved_placement
        if old == lane:
            return None
        dur = self.durability
        if dur is not None:
            dur.hold_epochs(timeout)
        t0 = _time.monotonic()
        try:
            with self._rescale_lock:
                self.quiesce(timeout)
                try:
                    target.apply_placement(lane)
                    if lane == "device":
                        # re-promote eligible engines onto the
                        # resident lane (the host flip dropped it)
                        maybe = getattr(target,
                                        "maybe_enable_resident", None)
                        if maybe is not None:
                            maybe()
                finally:
                    self.resume()
            if dur is not None:
                dur.rewire()
        finally:
            if dur is not None:
                dur.release_epochs()
        event = {"operator": operator, "old": old, "new": lane,
                 "trigger": trigger,
                 "duration_ms": round((_time.monotonic() - t0) * 1e3, 1)}
        if evidence:
            event["evidence"] = evidence
        self.flight.record("replacement", **event)
        return event

    # -- SLO plane (slo/; docs/OBSERVABILITY.md "SLO plane") ------------
    def with_slo(self, p99_ms: Optional[float] = None,
                 min_throughput_rps: Optional[float] = None,
                 max_frontier_lag_s: Optional[float] = None,
                 **kw) -> "PipeGraph":
        """Declare this graph's service-level objectives (chainable,
        before ``start``).  Shorthand for setting
        ``RuntimeConfig.slo = SloConfig(...)``; extra keywords
        (``target``, ``window_scale``, ``fast_burn``...) pass through.
        The SLO is evaluated on the diagnosis tick, so it needs
        ``RuntimeConfig.diagnosis`` (the default) to stay on."""
        if self._started:
            raise RuntimeError("with_slo() must be called before start()")
        from ..slo import SloConfig
        self.config.slo = SloConfig(
            p99_ms=p99_ms, min_throughput_rps=min_throughput_rps,
            max_frontier_lag_s=max_frontier_lag_s, **kw)
        return self

    def refresh_gauges(self) -> None:
        """Update the per-replica gauge fields of the stats records
        (inbound channel depth; ingest credit-wait seconds) from the
        live runtime objects.  Called before every stats JSON render
        (monitoring reporter + log dump); cheap -- lock-free depth
        reads (runtime/queues.Channel.depth)."""
        from ..runtime.node import FusedLogic
        if self._dist is not None:
            # distributed plane: refresh the per-edge wire books
            # (stats-JSON ``Wire`` block, merged cross-worker by
            # distributed/observe.py)
            self.stats.set_wire(self._dist.wire_block())
        for n in self._all_nodes():
            logic = n.logic
            rec = n.stats
            if rec is None and isinstance(logic, FusedLogic):
                # the channel consumer inside a fused node is its first
                # segment; gauge attribution follows
                rec = logic.segments[0].stats
                logic = logic.segments[0].logic
            if rec is None:
                continue
            ch = n.channel
            if ch is not None:
                rec.queue_depth = ch.depth
                # measured since PR 1 on both channel planes
                # (runtime/queues.py:73 / native.py:209), exported here
                rec.queue_high_watermark = getattr(ch,
                                                   "high_watermark", 0)
            # resident-lane gauge (docs/PLANNER.md "Resident state"):
            # bytes of per-key window state living in device memory --
            # every fused segment's engine reports into its own record
            pairs = ([(seg.logic, seg.stats)
                      for seg in n.logic.segments]
                     if isinstance(n.logic, FusedLogic)
                     else [(logic, rec)])
            for lg, r in pairs:
                resid = getattr(lg, "device_resident_bytes", None)
                if resid is not None and r is not None:
                    try:
                        r.device_state_bytes = resid()
                    except Exception:
                        pass  # engine mid-swap: keep the last reading
            gate = getattr(logic, "gate", None)  # ingest source replicas
            if gate is not None:
                wait = gate.wait_time_s
                # flight-recorder credit-stall events: one per refresh
                # interval in which the source spent noticeable time
                # blocked on credits (>50 ms of new wait since the last
                # gauge refresh)
                last = getattr(rec, "_flight_wait_s", 0.0)
                if wait - last > 0.05:
                    self.flight.record("credit_stall", node=n.name,
                                       wait_s=round(wait, 3),
                                       delta_s=round(wait - last, 3))
                rec._flight_wait_s = wait
                rec.credit_wait_s = wait

    # -- diagnosis plane (diagnosis/; docs/OBSERVABILITY.md) ------------
    def explain(self) -> dict:
        """The structured doctor report for this graph: dominant
        bottleneck per sink, critical-path hop-class breakdown of the
        traced e2e latency, active regression episodes, conservation /
        skew status and the flight-recorder tail.  Works on a running
        graph (live gauges) and after ``wait_end`` (the sustained
        EWMAs and high-watermarks keep the verdict through the drain);
        the same pure fold backs the dashboard's ``GET /explain`` and
        ``python -m windflow_tpu.doctor``."""
        if not self._started:
            raise RuntimeError("explain() needs a started graph")
        import json as _json
        from ..diagnosis.report import build_report
        self.refresh_gauges()
        if self.diagnosis is not None:
            self.diagnosis.maybe_tick(force=True)
        stats = _json.loads(self.stats.to_json(
            self.get_num_dropped_tuples(), self.dead_letters.count()))
        return build_report(stats, self.flight.snapshot())

    def live_checkpoint(self, path: str, timeout: float = 120.0) -> int:
        """Mid-stream snapshot to a ``restore_graph``-compatible file.

        With the durability plane on (``RuntimeConfig.durability``)
        this is NON-STOP: it forces one aligned epoch and waits for its
        commit -- no source pause, no drain, the graph keeps emitting
        throughout -- then mirrors the committed states to ``path``.
        Without it, the legacy barrier applies: quiesce (pause sources,
        drain channels and in-flight device batches), snapshot, resume.
        Returns the number of replicas captured.  Restores pair with
        source replay from the captured offsets."""
        import pickle
        from ..utils.checkpoint import write_snapshot
        if not self._started or self._ended:
            # both paths need a live graph: the legacy barrier pauses
            # running sources, and a forced epoch can only commit while
            # the coordinator thread and the sinks are alive
            raise RuntimeError("live_checkpoint() needs a running graph")
        if self.durability is not None:
            epoch, blobs = self.durability.checkpoint_now(timeout)
            states = {name: pickle.loads(b) for name, b in blobs.items()}
            write_snapshot(path, states, epoch=epoch)
            self.flight.record("checkpoint_epoch", path=path, epoch=epoch,
                               replicas=len(states), non_stop=True)
            return len(states)
        from ..utils.checkpoint import graph_state
        # serialize with elastic rescales: SourcePauseControl is a
        # non-counting boolean, so a concurrent rescale's resume()
        # would un-park sources mid-snapshot (and vice versa)
        with self._rescale_lock:
            self.quiesce(timeout)
            try:
                state = graph_state(self)
                write_snapshot(path, state)
            finally:
                self.resume()
        self.flight.record("checkpoint_epoch", path=path,
                           replicas=len(state))
        return len(state)
