"""Online device<->host re-planning: the placement decision as a
running hypothesis (docs/PLANNER.md "Resident state & online
re-planning").

The start-time planner (graph/planner.py) projects a device rate from
the probed RTT floor, the calibrated host rate and the operator's
bytes/launch -- and PR 6's MEASURED note documents exactly how that
projection fails: the model treated on-device compute as free, which
is true on a real TPU behind a 70 ms tunnel and false on cpu-fallback,
so 'auto' kept resolving 'device' against the evidence.

This module closes the loop.  Riding the diagnosis tick (no thread of
its own for the *decision*), it

* measures each auto-placed engine's per-launch wall from the stats
  record deltas (``Device_time_ms`` / ``Device_launches``, normalized
  by the in-flight depth exactly like the adaptive batcher, since the
  raw wall of a saturated serialized transport includes pipeline
  queueing);
* splits it at the RTT floor into transport + compute -- the same rule
  the attribution plane uses for ``@device`` hops -- and feeds the
  measured compute back into the cost model's per-box calibration
  (``record_device_compute``), so the NEXT start-time decision already
  projects with evidence;
* re-runs the pure ``decide_placement`` with the measured inputs; when
  the verdict contradicts the engine's current lane for
  ``RuntimeConfig.replan_ticks`` consecutive ticks, it requests a lane
  flip.

Flips execute on the re-planner's own worker thread (a flip quiesces
the graph -- seconds, not microseconds -- and must not stall the
monitor cadence): ``PipeGraph.replace_lane`` serializes with elastic
rescales under the rescale lock, holds the epoch plane's cadence like
a rescale does, drains the pipeline to a quiescent cut (so zero tuples
are in flight), swaps the engine's lane, and resumes.  Every flip is a
``replacement`` flight event carrying the measured evidence, folded
into the doctor report's ``Replacements`` block.

Pinned lanes are never re-planned (the operator said so); custom/FFAT
combines have no host twin and are skipped.
"""
from __future__ import annotations

import queue
import threading
import traceback
from typing import Dict, List, Optional

from .planner import (DEFAULT_TRANSFER_MBPS, PlacementInputs,
                      decide_placement, flush_device_calibration,
                      host_rate_tps, launch_profile,
                      record_device_compute, rtt_floor_ms)

# launches that must land inside a tick window before its measurement
# counts (a 1-launch delta is noise)
MIN_LAUNCHES = 2


def replan_decision(lane: str, measured_ms_per_launch: Optional[float],
                    tuples_per_launch: float, bytes_per_launch: float,
                    rtt_ms: float, host_tps: float,
                    calibrated_compute_ms: float = 0.0) -> dict:
    """Pure per-tick verdict for one engine (unit-tested): which lane
    SHOULD this engine be on, given what was measured?

    * device lane with a fresh measurement: the measured per-launch
      wall replaces the projection wholesale -- measured compute =
      wall minus floor minus transfer (the attribution split) goes
      into the model, and the decision re-runs.
    * host lane (or no fresh launches): the decision re-runs with the
      box's calibrated compute -- a host engine can win the chip back
      when the calibration says compute is cheap enough.

    Returns the ``decide_placement`` dict plus ``measured_ms`` /
    ``device_compute_ms`` evidence."""
    transfer_ms = bytes_per_launch / (DEFAULT_TRANSFER_MBPS * 1e3)
    if lane == "device" and measured_ms_per_launch is not None:
        compute_ms = max(0.0,
                         measured_ms_per_launch - rtt_ms - transfer_ms)
    else:
        compute_ms = max(0.0, calibrated_compute_ms)
    out = decide_placement(PlacementInputs(
        rtt_floor_ms=rtt_ms, host_rate_tps=host_tps,
        tuples_per_launch=tuples_per_launch,
        bytes_per_launch=bytes_per_launch,
        device_compute_ms=compute_ms))
    if measured_ms_per_launch is not None:
        out["measured_ms"] = round(measured_ms_per_launch, 3)
    return out


class RePlanner:
    """Per-graph online re-planner (built by ``PipeGraph.start`` when
    ``RuntimeConfig.replan`` is on and the planner placed engines)."""

    def __init__(self, graph):
        self.graph = graph
        self.ticks_needed = max(1, int(graph.config.replan_ticks))
        # (name, logic, entry) of auto-placed engines with a host twin:
        # pins are the operator's word, custom combines have no twin
        self.engines = [
            (name, logic, entry)
            for name, logic, entry in getattr(graph, "placed_engines", [])
            if entry.get("reason") is None
            and isinstance(getattr(logic.engine, "kind", None), str)]
        self._last: Dict[str, tuple] = {}     # name -> (launches, ms)
        self._streak: Dict[str, tuple] = {}   # name -> (want, count)
        # per-engine measured compute from its own device stints: a
        # host-resolved engine is judged by ITS evidence first, the
        # box-wide calibration only as a fallback
        self._measured_compute: Dict[str, float] = {}
        self.flips: List[dict] = []
        self._inflight = False
        self._work: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.engines:
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name="windflow-replanner")
            self._thread.start()

    # -- decision side (called from the diagnosis tick) ----------------
    def tick(self) -> None:
        if not self.engines or self._inflight:
            return
        try:
            self._tick()
        except Exception:  # pragma: no cover -- observation must
            traceback.print_exc()  # never take the graph down

    def _measure(self, name: str, logic) -> Optional[float]:
        """Per-launch wall over this tick window, depth-normalized
        (the adaptive batcher's discipline: a saturated pipeline's raw
        wall always includes depth x queueing)."""
        rec = logic.stats
        if rec is None:
            return None
        launches, ms = rec.num_launches, rec.device_time_ms
        prev = self._last.get(name)
        self._last[name] = (launches, ms)
        if prev is None:
            return None
        d_launch = launches - prev[0]
        d_ms = ms - prev[1]
        if d_launch < MIN_LAUNCHES or d_ms <= 0:
            return None
        return d_ms / d_launch / max(1, logic.inflight_depth)

    def _tick(self) -> None:
        rtt = rtt_floor_ms()
        host = host_rate_tps()
        from .planner import device_compute_ms_per_launch
        calib = device_compute_ms_per_launch()
        for name, logic, entry in self.engines:
            lane = logic.resolved_placement
            if lane not in ("device", "host"):
                continue
            measured = (self._measure(name, logic)
                        if lane == "device" else None)
            tuples, bytes_ = launch_profile(logic)
            verdict = replan_decision(
                lane, measured, tuples, bytes_, rtt, host,
                self._measured_compute.get(name, calib))
            if lane == "device" and measured is not None:
                # feed the measured split (replan_decision derived it
                # from this wall) back into the per-box calibration --
                # in-process only; the file is flushed once at stop()
                compute = verdict.get("device_compute_ms", 0.0)
                self._measured_compute[name] = compute
                record_device_compute(compute, persist=False)
            want = verdict["placement"]
            prev_want, count = self._streak.get(name, (None, 0))
            if want == lane or (lane == "device" and measured is None):
                # a device lane is never flipped on stale box-wide
                # calibration alone: its own fresh launches must
                # contradict it (the host lane has no launches to
                # measure, so calibration IS its evidence)
                self._streak[name] = (None, 0)
                continue
            count = count + 1 if prev_want == want else 1
            self._streak[name] = (want, count)
            if count >= self.ticks_needed and not self._inflight:
                self._streak[name] = (None, 0)
                self._inflight = True
                self._work.put((name, logic, entry, want, verdict))

    # -- actuation side (worker thread: a flip quiesces the graph) -----
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._work.get(timeout=0.25)
            except queue.Empty:
                continue
            if item is None:
                return
            name, logic, entry, want, verdict = item
            try:
                event = self.graph.replace_lane(name, want,
                                                trigger="replan",
                                                evidence=verdict)
                if event is not None:
                    self.flips.append(event)
                    entry["placement"] = want
                    entry["replanned"] = True
                    self.graph.stats.set_placements(
                        self.graph.placements)
            except Exception:  # graph ending mid-flip etc: log, keep
                traceback.print_exc()  # observing
            finally:
                self._inflight = False

    def stop(self) -> None:
        self._stop.set()
        self._work.put(None)
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self._measured_compute:
            # one durable write per run: the next process's start-time
            # planner projects with this run's measured compute
            flush_device_calibration()
