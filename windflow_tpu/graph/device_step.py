"""Graph compile pass: whole-partition device step (ROADMAP item 3).

Runs inside ``PipeGraph.start`` AFTER LEVEL2 fusion (graph/fuse.py) and
the placement planner (graph/planner.py), on the post-fusion node set
with every window engine's lane resolved.  It is the logical end of
LEVEL2: where fusion removed the *channel hop* between adjacent stages,
this pass removes the *launch* between adjacent device work -- an
entire device-placed segment (decode -> filter/map -> KEYBY partition
-> resident window update+query -> fired-result extraction) executes as
ONE XLA program invocation per ingest chunk, with all window state
living in the engines' donated carry (ops/window_compute.py resident
lane).  Python touches the stream once per chunk, not once per
operator-trigger.

Two steps, to a fixpoint:

1. **Merge** -- a producer whose single FORWARD destination (plain
   ``StandardEmitter``, or a degenerate ``KFEmitter`` at parallelism 1,
   which routes identically) is a device-eligible consumer absorbs it,
   exactly like ``fuse._merge``.  Unlike LEVEL2 this includes SOURCE
   heads ahead of ticking window engines: the tick-safety bar existed
   because a channel-less fused node never idle-ticks, but under
   chunk-granular flushing nothing is left staged *between* chunks --
   every chunk boundary launches what the chunk fired, and the async
   dispatcher drains its own in-flight batches.

2. **Upgrade** -- every node containing a device-lane
   ``WinSeqTPULogic`` swaps its logic for a :class:`DeviceStepLogic`
   (a ``FusedLogic`` subclass, so segment identity, checkpoint keys,
   fault clocks, per-segment stats and the binding loop all behave
   identically).  The step logic holds the engines' intra-chunk launch
   triggers (``chunk_hold``) while a chunk traverses the inline chain
   and flushes each engine ONCE at the chunk boundary.

Never lowered: ingest heads (credit-accounting boundary), collectors,
elastic/supervised replicas, partition-split edges, async-emitting
producers -- the same barriers as LEVEL2, minus tick safety.

Everything downstream keeps working because nothing about the node
contract changes: audit conservation reads per-segment stats under the
original names, epochs fence at the chunk boundary via the existing
quiesce hook, checkpoints stay keyed by pre-fusion node names
(fusion-invariance), the PR 15 replanner still flips individual
engines device<->host through the segment list (a host-flipped engine
simply flushes its host program once per chunk), and bitwise
equivalence vs the unfused LEVEL2 graph holds because launch *grouping*
was never semantically observable (the wall-clock partial-launch
trigger already grouped nondeterministically).

Opt out with ``RuntimeConfig.device_step=False`` / WINDFLOW_DEVICE_STEP=0.
"""
from __future__ import annotations

from typing import List

from ..core.basic import OptLevel
from ..core.tuples import SynthChunk, TupleBatch
from ..operators.tpu.win_seq_tpu import WinSeqTPULogic
from ..runtime.emitters import StandardEmitter
from ..runtime.node import (FusedLogic, RtNode, _FusedDownstreamError,
                            source_loop_of)
from ..runtime.win_routing import KFEmitter
from .fuse import (_consumers_by_channel, _has_async_emit, _is_collector,
                   _is_elastic, _is_ingest_head, _merge, _partition_splits,
                   _segments_of)


class DeviceStepLogic(FusedLogic):
    """A fused chain driven at chunk granularity: while a data chunk
    (TupleBatch / SynthChunk) traverses the inline segments, every
    window engine's intra-chunk launch trigger is held
    (``WinSeqTPULogic.chunk_hold``); at the chunk boundary each engine
    flushes everything the chunk fired as ONE launch.  Control items
    (watermarks, epoch barriers, EOS markers, records) pass through
    unheld -- they are boundaries, not stream data.

    ``chunks_in`` / ``chunk_launches`` are the dispatcher-side counters
    the ``19_device_step`` bench asserts launches-per-chunk from."""

    def __init__(self, segments):
        super().__init__(segments)
        # (segment index, engine) for every window engine in the chain,
        # computed AFTER the base class flattened nested fusion
        self._step_engines = [
            (k, s.logic) for k, s in enumerate(self.segments)
            if isinstance(s.logic, WinSeqTPULogic)]
        self.chunks_in = 0
        self.chunk_launches = 0

    # -- chunk boundary helpers -----------------------------------------
    def _hold(self):
        for _k, eng in self._step_engines:
            eng.chunk_hold = True

    def _release(self):
        for _k, eng in self._step_engines:
            eng.chunk_hold = False

    def _flush_boundary(self):
        """One launch per engine for everything the chunk fired.  An
        engine's flush emits through its own exit, so downstream
        segments (and the node's outward emit) see results exactly as
        they would from an intra-chunk launch."""
        launches = 0
        for k, eng in self._step_engines:
            launches += eng.flush_chunk(self._exits[k])
        self.chunk_launches += launches

    # -- NodeLogic surface ----------------------------------------------
    def svc(self, item, channel_id, emit):
        if not self._step_engines \
                or not isinstance(item, (TupleBatch, SynthChunk)):
            super().svc(item, channel_id, emit)
            return
        self.chunks_in += 1
        self._hold()
        try:
            super().svc(item, channel_id, emit)
        finally:
            # released even when the chain raised -- but the boundary
            # flush below is then skipped: a crashing chunk must not
            # launch its partial firings (recovery replays the chunk)
            self._release()
        try:
            self._flush_boundary()
        except _FusedDownstreamError as w:
            raise w.error

    def eos_flush(self, emit):
        """Channel-less step head: the source generation loop runs in
        here (runtime/node.py SourceLoopLogic), every ``step(emit)``
        call emitting one chunk into segment 0's exit.  Wrap that exit
        so each generated chunk gets the same hold -> traverse -> flush
        cycle as the channel-fed path; epoch barriers / watermarks are
        injected between steps and pass through at the boundary."""
        if not self._step_engines or source_loop_of(self) is None:
            super().eos_flush(emit)
            return
        self._emit_out = emit
        exit0 = self._exits[0]

        def step_exit(item):
            if not isinstance(item, (TupleBatch, SynthChunk)):
                exit0(item)
                return
            self.chunks_in += 1
            self._hold()
            try:
                exit0(item)
            finally:
                self._release()
            self._flush_boundary()

        try:
            for k, seg in enumerate(self.segments):
                seg.logic.eos_flush(step_exit if k == 0
                                    else self._exits[k])
        except _FusedDownstreamError as w:
            raise w.error


# ---------------------------------------------------------------------------
# the compile pass
# ---------------------------------------------------------------------------

def _logics_of(node: RtNode) -> list:
    if isinstance(node.logic, FusedLogic):
        return [s.logic for s in node.logic.segments]
    return [node.logic]


def _has_device_engine(node: RtNode) -> bool:
    return any(isinstance(lg, WinSeqTPULogic)
               and getattr(lg, "resolved_placement", "host") != "host"
               for lg in _logics_of(node))


def _foreign_tickers(node: RtNode) -> bool:
    """A ticking logic that is NOT a window engine: chunk-boundary
    flushing cannot stand in for its idle ticks, so it bars the
    source-head merge (the merged node would never tick)."""
    return any(hasattr(lg, "idle_tick")
               and not isinstance(lg, WinSeqTPULogic)
               for lg in _logics_of(node))


def _forward_dest(node: RtNode):
    """(channel,) when this node forwards everything, unmodified and in
    order, to exactly one destination channel it exclusively produces
    into.  Like fuse._single_forward_dest plus the degenerate KEYBY
    case: a KFEmitter at parallelism 1 sends every item to its one
    worker untouched, so absorbing across it is exact."""
    if len(node.outlets) != 1:
        return None
    outlet = node.outlets[0]
    em = outlet.emitter
    if type(em) is not StandardEmitter and \
            not (type(em) is KFEmitter and em.pardegree == 1):
        return None
    if len(outlet.dests) != 1:
        return None
    ch = outlet.dests[0][0]
    if ch.n_producers != 1:
        return None
    return ch


def _try_step_merge(graph, consumers: dict) -> bool:
    for a in graph._all_nodes():
        if _is_ingest_head(a) or _is_collector(a) or _is_elastic(a) \
                or _has_async_emit(a):
            continue
        ch = _forward_dest(a)
        if ch is None:
            continue
        b = consumers.get(id(ch))
        if b is None or b is a or _is_collector(b) or _is_elastic(b) \
                or _partition_splits(graph, a, b):
            continue
        if not _has_device_engine(b):
            continue
        if a.channel is None and (_foreign_tickers(a)
                                  or _foreign_tickers(b)):
            continue  # source head: merged node never idle-ticks
        _merge(graph, a, b)
        return True
    return False


def lower_device_steps(graph) -> List[str]:
    """Run the pass; returns the step node names (report)."""
    if getattr(graph.config, "opt_level", OptLevel.LEVEL2) \
            < OptLevel.LEVEL2:
        return []
    if not getattr(graph.config, "device_step", True):
        return []
    changed = True
    while changed:
        changed = _try_step_merge(graph, _consumers_by_channel(graph))
    stepped = []
    for node in graph._all_nodes():
        if isinstance(node.logic, DeviceStepLogic) \
                or not _has_device_engine(node):
            continue
        logic = DeviceStepLogic(_segments_of(node))
        logic.pool = getattr(graph, "buffer_pool", None)
        node.logic = logic
        node.error_policy = "fail"  # segments guard themselves
        node.stats = None           # per-segment records instead
        stepped.append(node.name)
    return stepped
