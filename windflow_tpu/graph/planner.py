"""Cost-based placement planner: device vs host lane per window operator.

Runs inside ``PipeGraph.start`` (right after the LEVEL2 fusion pass,
before any replica thread starts).  The VERDICT-round-5 embarrassment
it exists to fix: device placement used to be a *structural* choice --
build a ``WinSeqTPU`` and every launch pays the transport round trip,
whether or not the batch amortizes it.  On a high-latency PJRT tunnel
(~70 ms RTT floor) small-window application configs ran *faster on the
CPU fallback than on device*.

The planner decides per engine replica, from **measured** quantities:

* ``rtt_floor_ms`` -- median round trip of one tiny launch, probed
  once per process at the first auto-placed graph start (the same
  probe bench.py reads against p99; override:
  ``WINDFLOW_RTT_FLOOR_MS``);
* ``host_rate_tps`` -- the host/native engine's sustained fold rate,
  micro-calibrated once per box (~1M synthetic tuples through
  ``NativeWindowEngine``; numpy fallback) and cached in
  ``bench_runs/host_calibration.json``; override:
  ``WINDFLOW_HOST_RATE_TPS``;
* ``tuples_per_launch`` / ``bytes_per_launch`` -- derived from the
  operator's window parameters (batch_len windows x slide tuples each;
  pane-partial staging bytes), the same arithmetic the engine's
  staging uses.

Decision rule (pure; deterministic; unit-tested): the device lane's
projected rate is ``tuples_per_launch / (rtt_floor + transfer_time)``;
it wins only when it beats the measured host rate by ``DEVICE_MARGIN``
(ties go to the host lane -- its rate was measured, the device's is
projected).  ``.with_placement('device'|'host')`` on the TPU builders
pins a lane and bypasses the model; ``'auto'`` opts in.  Decisions are
recorded on the graph and surfaced in the stats JSON (``Placements``).

The same module owns the *strategy* half of the decision table:
:func:`select_strategy` maps (win_kind, win_len, slide_len, key
cardinality) to the parallelization pattern (win_seq / win_farm /
pane_farm / ffat / key_farm) the reference makes the user pick by hand
(builders_gpu.hpp), and :func:`plan_window_operator` builds the chosen
operator.  docs/PLANNER.md has the full table.
"""
from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

# device must beat the measured host rate by this factor to win an
# 'auto' placement: the host number is measured on this box, the device
# number is a projection over a shared transport
DEVICE_MARGIN = 1.2

# assumed effective host->device transfer bandwidth when none was
# measured (MB/s); deliberately conservative for a relayed transport
DEFAULT_TRANSFER_MBPS = 200.0

_CALIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "bench_runs",
    "host_calibration.json")
_DEV_CALIB_PATH = os.path.join(os.path.dirname(_CALIB_PATH),
                               "device_calibration.json")

_probe_lock = threading.Lock()
_rtt_floor_ms: Optional[float] = None
_host_rate_tps: Optional[float] = None
_device_compute_ms: Optional[float] = None


# ---------------------------------------------------------------------------
# measured inputs
# ---------------------------------------------------------------------------

def rtt_floor_ms() -> float:
    """Measured device round-trip floor (ms), probed once per process:
    the latency any single launch pays on this transport."""
    global _rtt_floor_ms
    env = os.environ.get("WINDFLOW_RTT_FLOOR_MS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass  # malformed override: fall back to the probe
    with _probe_lock:
        if _rtt_floor_ms is not None:
            return _rtt_floor_ms
        try:
            import jax
            import jax.numpy as jnp
            import numpy as np
            f = jax.jit(lambda v: jnp.cumsum(v))
            v = np.zeros(2048, np.float32)
            np.asarray(f(v))  # compile outside the timed reps
            lats = []
            for _ in range(5):
                t0 = time.perf_counter()
                np.asarray(f(v))
                lats.append((time.perf_counter() - t0) * 1e3)
            lats.sort()
            _rtt_floor_ms = max(0.01, lats[len(lats) // 2])
        except Exception:
            _rtt_floor_ms = 1.0  # no usable backend: nominal floor
        return _rtt_floor_ms


def _calibrate_host_rate() -> float:
    """Sustained host-engine fold rate (tuples/s) over ~1M synthetic
    tuples -- the native columnar engine when built, else a numpy
    cumsum proxy for the pure-Python plane."""
    import numpy as np
    n = 1 << 20
    try:
        from ..runtime.native import NativeWindowEngine, native_available
        if native_available():
            eng = NativeWindowEngine(4096, 2048, True, kind="sum")
            t0 = time.perf_counter()
            eng.synth_ingest(0, n, 64)
            eng.eos()
            while eng.ready():
                eng.flush(1 << 14)
            return n / max(1e-9, time.perf_counter() - t0)
    except Exception:
        pass
    vals = np.random.default_rng(0).random(n)
    t0 = time.perf_counter()
    np.cumsum(vals)
    np.add.reduceat(vals, np.arange(0, n, 2048))
    return n / max(1e-9, time.perf_counter() - t0)


def host_rate_tps() -> float:
    """Host-engine sustained rate, cached per box in
    ``bench_runs/host_calibration.json`` (keyed by hostname + core
    count, so a checkout moved between boxes re-calibrates)."""
    global _host_rate_tps
    env = os.environ.get("WINDFLOW_HOST_RATE_TPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass  # malformed override: fall back to the calibration
    with _probe_lock:
        if _host_rate_tps is not None:
            return _host_rate_tps
        key = f"{socket.gethostname()}/{os.cpu_count()}"
        try:
            with open(_CALIB_PATH) as f:
                cached = json.load(f)
            if cached.get("box") == key:
                _host_rate_tps = float(cached["host_rate_tps"])
                return _host_rate_tps
        except (OSError, ValueError, KeyError):
            pass
        _host_rate_tps = _calibrate_host_rate()
        try:
            os.makedirs(os.path.dirname(_CALIB_PATH), exist_ok=True)
            with open(_CALIB_PATH, "w") as f:
                json.dump({"box": key,
                           "host_rate_tps": round(_host_rate_tps, 1),
                           "calibrated_at": time.time()}, f, indent=1)
        except OSError:
            pass  # read-only checkout: keep the in-process cache
        return _host_rate_tps


def device_compute_ms_per_launch() -> float:
    """Measured on-device compute per launch (ms), from a prior
    attribution capture cached per box -- the PR 6 MEASURED note's
    exact miss: the original model treated on-device compute as FREE
    (true on a real TPU behind a 70 ms tunnel, false on cpu-fallback),
    so cpu-fallback boxes kept resolving 'device' against the
    evidence.  Sources, in priority order: the
    ``WINDFLOW_DEVICE_COMPUTE_MS`` env override, the in-process value
    the re-planner recorded this run, the per-box cache file
    (``bench_runs/device_calibration.json``, written alongside
    host_calibration.json whenever a device lane's attribution is
    measured).  0.0 when never measured -- the original free-compute
    projection, unchanged."""
    env = os.environ.get("WINDFLOW_DEVICE_COMPUTE_MS")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass  # malformed override: fall back to the cache
    global _device_compute_ms
    with _probe_lock:
        if _device_compute_ms is not None:
            return _device_compute_ms
        key = f"{socket.gethostname()}/{os.cpu_count()}"
        try:
            with open(_DEV_CALIB_PATH) as f:
                cached = json.load(f)
            if cached.get("box") == key:
                # cache the file value in-process (the EWMA of any
                # later measurement folds onto it) so the monitor-
                # cadence callers never re-read the file
                _device_compute_ms = max(
                    0.0, float(cached["device_compute_ms"]))
                return _device_compute_ms
        except (OSError, ValueError, KeyError, TypeError):
            pass
        return 0.0


def record_device_compute(ms_per_launch: float,
                          persist: bool = True) -> None:
    """Feed a measured device-compute figure back into the cost model
    (called by the online re-planner when it attributes a device
    lane's launches).  EWMA-folded into the in-process value; with
    ``persist`` also mirrored to the per-box cache so the NEXT
    process's start-time planner already projects with evidence (the
    re-planner records per tick with persist=False and flushes once
    at stop)."""
    global _device_compute_ms
    ms = max(0.0, float(ms_per_launch))
    with _probe_lock:
        if _device_compute_ms is None:
            _device_compute_ms = ms
        else:
            _device_compute_ms += 0.25 * (ms - _device_compute_ms)
    if persist:
        flush_device_calibration()


def flush_device_calibration() -> None:
    """Write the in-process device-compute EWMA to the per-box cache
    file (one durable write, best-effort)."""
    with _probe_lock:
        value = _device_compute_ms
    if value is None:
        return
    try:
        os.makedirs(os.path.dirname(_DEV_CALIB_PATH), exist_ok=True)
        with open(_DEV_CALIB_PATH, "w") as f:
            json.dump({"box": f"{socket.gethostname()}/{os.cpu_count()}",
                       "device_compute_ms": round(value, 4),
                       "calibrated_at": time.time()}, f, indent=1)
    except OSError:
        pass  # read-only checkout: keep the in-process value


# ---------------------------------------------------------------------------
# the cost model (pure functions of measured inputs)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PlacementInputs:
    """Everything the placement decision reads (so tests can pin it)."""

    rtt_floor_ms: float
    host_rate_tps: float
    tuples_per_launch: float
    bytes_per_launch: float
    transfer_mbps: float = DEFAULT_TRANSFER_MBPS
    # measured on-device compute per launch (ms); 0.0 = never measured
    # (the legacy free-compute projection)
    device_compute_ms: float = 0.0


def device_rate_tps(inp: PlacementInputs) -> float:
    """Projected device-lane throughput: one launch amortizes
    ``tuples_per_launch`` ingested tuples over (RTT floor + transfer
    time + measured on-device compute).  Pipelining (inflight_depth)
    overlaps launches, but the floor still bounds the *per-launch*
    cost on a serialized transport, so the projection is deliberately
    un-pipelined -- conservative toward the host lane."""
    transfer_ms = inp.bytes_per_launch / (inp.transfer_mbps * 1e3)
    period_ms = inp.rtt_floor_ms + transfer_ms + inp.device_compute_ms
    return inp.tuples_per_launch / max(1e-9, period_ms / 1e3)


def decide_placement(inp: PlacementInputs) -> dict:
    """'device' | 'host' plus the projections that led there.
    Deterministic: same inputs, same decision."""
    dev = device_rate_tps(inp)
    host = inp.host_rate_tps
    placement = "device" if dev > host * DEVICE_MARGIN else "host"
    out = {
        "placement": placement,
        "device_rate_tps": round(dev, 1),
        "host_rate_tps": round(host, 1),
        "rtt_floor_ms": round(inp.rtt_floor_ms, 3),
        "tuples_per_launch": round(inp.tuples_per_launch, 1),
        "bytes_per_launch": round(inp.bytes_per_launch, 1),
    }
    if inp.device_compute_ms > 0:
        out["device_compute_ms"] = round(inp.device_compute_ms, 3)
    return out


def launch_profile(logic) -> tuple:
    """(tuples_per_launch, bytes_per_launch) from window parameters:
    a full batch of ``batch_len`` windows advances the stream by
    ``slide_len`` tuples each; staging ships pane partials (f32) plus
    packed extents, results come back one f32 per window.

    For TB windows ``slide_len`` is in *timestamp units*, so this
    assumes dense timestamps (~one tuple per unit, what every synth /
    bench source produces).  A sparse timestamped stream carries fewer
    tuples per launch than projected, flattering the device lane --
    pin ``.with_placement('host')`` or set ``WINDFLOW_RTT_FLOOR_MS``
    for such feeds (docs/PLANNER.md, "cost-model assumptions")."""
    b = max(1, int(logic.batch_len))
    tuples = float(b) * max(1, int(logic.slide_len))
    pane = max(1, math.gcd(int(logic.win_len), int(logic.slide_len)))
    panes_per_window = max(1, int(logic.win_len) // pane)
    # staged flat buffer: ~one new pane per fired window plus the
    # window-spanning carry; extents 2 x int32; results f32
    staged = b + panes_per_window
    bytes_ = 4.0 * staged + 8.0 * b + 4.0 * b
    return tuples, bytes_


# ---------------------------------------------------------------------------
# graph pass
# ---------------------------------------------------------------------------

def plan_graph(graph) -> List[dict]:
    """Resolve every window engine replica's placement.  Pinned lanes
    ('device'/'host') pass through; 'auto' consults the cost model.
    Each resolved engine gets the measured RTT floor (feeding the
    adaptive batch resize) and -- tracing or not -- a stats record, so
    per-launch device timing is always observable for placed
    operators.  Returns the recorded decision list (also stored on
    ``graph.placements`` and in the stats JSON)."""
    from ..operators.tpu.ffat_resident import WinSeqFFATResidentLogic
    from ..operators.tpu.win_seq_tpu import WinSeqTPULogic
    from ..runtime.node import FusedLogic

    decisions: List[dict] = []
    placed: List[tuple] = []
    seen: set = set()
    replica_ids: dict = {}  # per-operator-name counter for stats keys

    # tenant-aware device placement (scheduler/devices.py): under a
    # device-scheduling Server, every lane resolved to the device
    # acquires a lease from the worker's registry.  Leases are
    # grant-and-record (the graph still runs), but the grant's
    # contention bit is annotated into the decision and the arbiter
    # reads the registry to demote a low-priority co-lessee when a
    # higher-priority tenant breaches on the contended chip.
    dev_leases = getattr(graph, "device_leases", None)
    lease_tenant = getattr(graph, "tenant_name", None) or graph.name
    lease_prio = getattr(graph, "tenant_priority", 0)

    def _lease(entry: dict, name: str, resident: bool) -> None:
        if dev_leases is None or entry["placement"] != "device":
            return
        entry["lease"] = dev_leases.acquire(
            lease_tenant, name, priority=lease_prio, resident=resident)

    for node in graph._all_nodes():
        if isinstance(node.logic, FusedLogic):
            pairs = [(seg.name, seg.logic, seg) for seg in
                     node.logic.segments]
        else:
            pairs = [(node.name, node.logic, node)]
        for name, logic, holder in pairs:
            if id(logic) in seen:
                continue
            if isinstance(logic, WinSeqFFATResidentLogic):
                # the resident FFAT engine is structurally
                # device-bound; it is recorded (and given a stats
                # record, so per-launch device timing + the resident
                # byte gauges are observable untraced) but never
                # lane-planned
                seen.add(id(logic))
                rid = replica_ids.get(name, 0)
                replica_ids[name] = rid + 1
                if holder.stats is None:
                    holder.stats = graph.stats.register(name, str(rid))
                entry = {"placement": "device",
                         "reason": "resident ffat: device only",
                         "resident": True, "operator": name}
                _lease(entry, name, resident=True)
                decisions.append(entry)
                continue
            if not isinstance(logic, WinSeqTPULogic):
                continue
            seen.add(id(logic))
            pinned = getattr(logic, "placement", "device")
            if pinned == "auto":
                if not isinstance(logic.engine.kind, str):
                    # custom / FFAT combines have no host program
                    entry = {"placement": "device",
                             "reason": "custom combine: device only"}
                else:
                    tuples, bytes_ = launch_profile(logic)
                    entry = decide_placement(PlacementInputs(
                        rtt_floor_ms=rtt_floor_ms(),
                        host_rate_tps=host_rate_tps(),
                        tuples_per_launch=tuples,
                        bytes_per_launch=bytes_,
                        device_compute_ms=device_compute_ms_per_launch()))
                logic.apply_placement(entry["placement"],
                                      rtt_floor_ms=entry.get(
                                          "rtt_floor_ms"))
            else:
                entry = {"placement": pinned, "reason": "pinned"}
                logic.apply_placement(pinned)
            # resident promotion (docs/PLANNER.md "Resident state"):
            # eligible device-lane engines keep their per-key pane
            # partials resident in device memory across launches --
            # the default lane; .with_resident(False) opts out
            if entry["placement"] == "device" \
                    and getattr(logic, "maybe_enable_resident",
                                None) is not None \
                    and logic.maybe_enable_resident():
                entry["resident"] = True
            rid = replica_ids.get(name, 0)
            replica_ids[name] = rid + 1
            if holder.stats is None:
                holder.stats = graph.stats.register(name, str(rid))
            entry["operator"] = name
            # the lease's Resident bit marks NON-demotable lanes: a
            # custom/FFAT combine has no host program, so the arbiter
            # must never pick it for a device->host demotion.  A
            # promoted-resident window engine stays demotable -- its
            # device state is derivable from the host staging store
            # and replace_lane drops it losslessly.
            _lease(entry, name,
                   resident=not isinstance(
                       getattr(logic.engine, "kind", None), str))
            decisions.append(entry)
            placed.append((name, logic, entry))
    graph.placements = decisions
    # live registry for the online re-planner (graph/replanner.py):
    # decision entries paired with their engine objects
    graph.placed_engines = placed
    graph.stats.set_placements(decisions)
    return decisions


# ---------------------------------------------------------------------------
# strategy selection (the decision table of docs/PLANNER.md)
# ---------------------------------------------------------------------------

# pane length below which pane decomposition stops paying (matches
# ingest/wiring.MIN_PREREDUCE_PANE)
MIN_PANE = 16
# window/slide overlap ratio from which an incremental FlatFAT tree
# beats per-window recomputation when panes are too short to pre-reduce
FFAT_OVERLAP = 8
# key cardinality from which key-sharded farms beat a single engine
KEY_FARM_MIN_KEYS = 2

_PANE_KINDS = ("sum", "count", "max", "min")
_FFAT_KINDS = ("sum", "max", "min")


def select_strategy(win_kind, win_len: int, slide_len: int,
                    key_cardinality: int = 1) -> str:
    """Deterministic parallelization-strategy choice from window
    parameters (the decision table in docs/PLANNER.md):

    1. associative builtin + long panes + a genuine slide (slide <
       win; tumbling windows share no panes) -> 'pane_farm' (ship
       partials, not tuples: transfer shrinks by the pane length);
    2. heavy overlap (win/slide >= 8) on a semigroup combine whose
       panes are too short to pre-reduce -> 'ffat' (incremental tree
       amortizes the recompute the overlap would otherwise multiply);
    3. many keys -> 'key_farm' (key-sharded engines; the emitter hash
       is the parallelism);
    4. single key, long windows -> 'win_farm' (round-robin window
       parallelism is the only axis left);
    5. otherwise -> 'win_seq' (one engine; batching alone).
    """
    if win_len <= 0 or slide_len <= 0:
        raise ValueError("win_len and slide_len must be > 0")
    pane = math.gcd(win_len, slide_len)
    builtin = isinstance(win_kind, str)
    # pane decomposition needs a genuine slide (PaneFarm rejects
    # tumbling shapes): tumbling windows have no pane sharing to win
    if builtin and win_kind in _PANE_KINDS and pane >= MIN_PANE \
            and slide_len < win_len:
        return "pane_farm"
    if builtin and win_kind in _FFAT_KINDS and pane < MIN_PANE \
            and win_len // slide_len >= FFAT_OVERLAP:
        return "ffat"
    if key_cardinality >= KEY_FARM_MIN_KEYS:
        return "key_farm"
    if win_len >= (1 << 16):
        return "win_farm"
    return "win_seq"


def plan_window_operator(win_kind, win_len: int, slide_len: int,
                         win_type, key_cardinality: int = 1,
                         parallelism: int = 2, **kwargs):
    """Build the operator :func:`select_strategy` picks (the planner's
    builder-level entry point; every knob in ``kwargs`` reaches the
    chosen operator's constructor)."""
    from ..operators.tpu.farms_tpu import (KeyFarmTPU, PaneFarmTPU,
                                           WinFarmTPU, WinSeqFFATTPU)
    from ..operators.tpu.win_seq_tpu import WinSeqTPU

    strategy = select_strategy(win_kind, win_len, slide_len,
                               key_cardinality)
    if strategy == "pane_farm":
        return PaneFarmTPU(win_kind, win_kind, win_len, slide_len,
                           win_type, **kwargs)
    if strategy == "ffat":
        # the FFAT tree is device-pinned (no host twin of the
        # incremental combine): reject lane knobs loudly, like the
        # builders' _check_placement_supported, instead of a
        # data-dependent TypeError from the constructor
        if kwargs.pop("placement", "device") != "device" \
                or kwargs.pop("adaptive_batch", False):
            raise ValueError(
                "strategy 'ffat' is device-pinned: placement/"
                "adaptive_batch are not supported for this window shape")
        lift = (lambda t: t.value)
        return WinSeqFFATTPU(lift, win_kind, win_len, slide_len,
                             win_type, **kwargs)
    if strategy == "key_farm":
        return KeyFarmTPU(win_kind, win_len, slide_len, win_type,
                          parallelism=parallelism, **kwargs)
    if strategy == "win_farm":
        return WinFarmTPU(win_kind, win_len, slide_len, win_type,
                          parallelism=parallelism, **kwargs)
    return WinSeqTPU(win_kind, win_len, slide_len, win_type, **kwargs)
