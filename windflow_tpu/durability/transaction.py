"""The exactly-once sink contract (docs/RESILIENCE.md "Exactly-once
epochs").

``SinkBuilder(fn).with_exactly_once()`` swaps the plain SinkLogic for
one of two wrappers:

* **transactional** (default): effects buffer per epoch; the barrier
  seals the open buffer (``epoch_mark``) and the coordinator releases
  sealed buffers *after* the epoch's manifest is durably committed
  (``commit_epoch``).  A crash discards every unreleased buffer with
  the failed graph, and the restarted run regenerates exactly those
  effects from the restored epoch -- no duplicate, no loss.  A clean
  end releases everything (the complete stream is the implicit final
  commit).
* **idempotent** (``with_exactly_once("idempotent")``): effects apply
  immediately, tagged with the epoch id they belong to -- the contract
  for side channels that tolerate replays keyed by epoch (the
  stats/dead-letter surfaces, external stores with epoch-keyed
  upserts).  The sink callable must be an epoch-keyed writer
  (``write(epoch, item)``, e.g. :class:`EpochTaggedStore`); recovery
  truncates it above the restored epoch (``truncate_above``) and the
  replay re-applies the truncated epochs identically.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..operators.basic_ops import SinkLogic
from ..runtime.node import EOSMarker, NodeLogic


class TransactionalSinkLogic(SinkLogic):
    """Buffer-per-epoch sink: release on durable commit, flush on clean
    EOS, discard (implicitly, with the process/graph) on crash."""

    def __init__(self, fn, parallelism=1, replica_index=0,
                 closing_func=None):
        super().__init__(fn, parallelism, replica_index, closing_func)
        self._lock = threading.Lock()
        self._emit_lock = threading.Lock()  # serializes fn() calls:
        # the coordinator releases committed buffers from its own
        # thread while the replica may be flushing at EOS
        self._buf: List[Any] = []
        self._sealed: Dict[int, List[Any]] = {}
        self.effects_released = 0
        self.effects_failed = 0
        # graph dead-letter store + replica name, bound by the
        # coordinator: a sink-fn error during release must quarantine
        # the offending effect and keep going -- the epoch is already
        # durably committed, so nothing will ever regenerate it
        self._dead_letters = None
        self._name = "transactional_sink"
        # True once an EpochCoordinator adopted this sink: per-sink EOS
        # then defers release to the coordinator's graph-level final
        # commit -- one branch ending cleanly must not release
        # uncommitted effects that another branch's later crash would
        # regenerate on restart (duplicates).  False (no durability
        # plane) keeps the legacy flush-at-EOS behaviour.
        self._coordinated = False

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        with self._lock:
            self._buf.append(item)

    # -- durability hooks ----------------------------------------------
    def epoch_mark(self, epoch: int) -> None:
        """Barrier passage (replica thread): everything buffered so far
        belongs to ``epoch``."""
        with self._lock:
            self._sealed[epoch] = self._buf
            self._buf = []

    def _apply(self, runs) -> int:
        """Deliver released effects one by one; a sink-fn Exception
        quarantines THAT effect in the dead-letter store and keeps
        going (the epoch is committed -- a restart will never
        regenerate it, so dropping the rest of the run would be
        silent loss).  Non-Exception BaseExceptions propagate, as on
        the normal svc path."""
        n = 0
        for run in runs:
            for it in run:
                try:
                    self.fn(it)
                    n += 1
                except Exception as e:
                    self.effects_failed += 1
                    if self._dead_letters is not None:
                        self._dead_letters.add(self._name, it, e)
        self.effects_released += n
        return n

    def commit_epoch(self, epoch: int) -> int:
        """Coordinator thread, after the manifest is durable: release
        every sealed buffer up to ``epoch``, in epoch order."""
        with self._lock:
            ready = sorted(e for e in self._sealed if e <= epoch)
            runs = [self._sealed.pop(e) for e in ready]
        with self._emit_lock:
            return self._apply(runs)

    def _release_all(self) -> int:
        with self._lock:
            runs = [self._sealed.pop(e) for e in sorted(self._sealed)]
            runs.append(self._buf)
            self._buf = []
        with self._emit_lock:
            n = self._apply(runs)
            self.fn(None)
        return n

    def final_release(self) -> int:
        """Graph-level clean-end release (EpochCoordinator.stop): every
        replica joined without error, the final manifest is durable --
        the remaining sealed + open buffers are the final commit."""
        return self._release_all()

    def epoch_rewind(self, committed: int) -> int:
        """Supervised replica restart (durability/supervision.py): the
        stream rewinds to epoch ``committed``, so every uncommitted
        buffer -- sealed above it or still open -- is about to be
        REGENERATED by the source replay.  Discard them; releasing
        later would duplicate.  Returns the discarded count."""
        with self._lock:
            drop = [e for e in self._sealed if e > committed]
            n = sum(len(self._sealed.pop(e)) for e in drop)
            n += len(self._buf)
            self._buf = []
        return n

    def eos_flush(self, emit):
        if self._coordinated:
            # a durable graph releases at the COORDINATOR's final
            # commit, after every sink branch ended cleanly: this
            # sink's own EOS is not a safe commit point (another
            # branch may still crash, and the restart would regenerate
            # whatever released here)
            return
        # legacy (no durability plane): clean end of stream = the
        # remaining buffers are the final commit.  (A crashed graph
        # never reaches eos_flush -- its channels raise GraphCancelled
        # -- which is exactly the discard contract.)
        self._release_all()


class EpochTaggedStore:
    """Thread-safe epoch-keyed effect store: the reference
    implementation of the idempotent sink target.  Survives restart
    attempts (the caller owns it across graph rebuilds); recovery
    truncates it above the restored epoch before the replay re-applies
    those epochs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_epoch: Dict[int, List[Any]] = {}

    def write(self, epoch: int, item: Any) -> None:
        with self._lock:
            self._by_epoch.setdefault(epoch, []).append(item)

    def truncate_above(self, epoch: int) -> int:
        """Drop every effect of epochs > ``epoch`` (the un-committed
        tail a crashed attempt may have applied); returns the count."""
        with self._lock:
            drop = [e for e in self._by_epoch if e > epoch]
            n = sum(len(self._by_epoch.pop(e)) for e in drop)
        return n

    def items(self) -> List[Any]:
        with self._lock:
            return [it for e in sorted(self._by_epoch)
                    for it in self._by_epoch[e]]

    def epochs(self) -> List[int]:
        with self._lock:
            return sorted(self._by_epoch)

    def count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._by_epoch.values())


class IdempotentSinkLogic(NodeLogic):
    """Apply-immediately sink writing through an epoch-keyed store
    (``write(epoch, item)``): effects between barriers ``e-1`` and
    ``e`` are tagged epoch ``e`` -- the same epoch whose manifest
    commit makes them permanent."""

    def __init__(self, store, parallelism=1, replica_index=0,
                 closing_func: Optional[Callable] = None):
        if not hasattr(store, "write"):
            raise TypeError(
                "with_exactly_once('idempotent') needs an epoch-keyed "
                "writer with write(epoch, item) -- e.g. an "
                "EpochTaggedStore -- not a plain callable")
        from ..core.context import RuntimeContext
        self.store = store
        self.context = RuntimeContext(parallelism, replica_index)
        self.closing_func = closing_func
        self._epoch = 1

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        self.store.write(self._epoch, item)

    def epoch_mark(self, epoch: int) -> None:
        self._epoch = epoch + 1

    def epoch_resume(self, committed: int) -> None:
        """Restored run (coordinator attach): effects before the first
        new barrier belong to the epoch after the restored one."""
        self._epoch = committed + 1

    def epoch_rewind(self, committed: int) -> int:
        """Supervised replica restart: the source replay is about to
        re-apply every effect above ``committed`` -- truncate them
        from the store so the replay lands them exactly once, and
        re-anchor the tag counter."""
        n = self.store.truncate_above(committed)
        self._epoch = committed + 1
        return n

    def eos_flush(self, emit):
        done = getattr(self.store, "eos", None)
        if done is not None:
            done()

    def svc_end(self):
        if self.closing_func is not None:
            self.closing_func(self.context)
