"""Crash-safe epoch-manifest store (docs/RESILIENCE.md "Exactly-once
epochs").

One manifest per committed epoch under ``DurabilityConfig.path``:
``{magic, schema, epoch, states, offsets, meta}`` where ``states`` maps
pre-fusion node names to pickled ``state_dict`` blobs and ``offsets``
maps source names to their frontier at injection.  Every commit goes
through write-temp + flush + fsync + atomic rename (plus a best-effort
directory fsync), so a crash mid-commit leaves either the previous
manifest set intact or the new manifest complete -- never a truncated
file at the final path.  ``latest()`` is the tolerant reader: a torn,
truncated or wrong-schema manifest is skipped (newest-first) with an
``epoch_abort`` flight event naming the file, falling back to the
previous committed epoch instead of crashing the restart in
``pickle.load``.

Schema 2 (``DurabilityConfig(delta=True)``; durability/delta.py): a
keyed replica's ``states`` entry may be ``{"keyed_chain": [BlobRef,
...]}`` referencing content-addressed blobs under ``<path>/blobs/``
instead of inline bytes.  Blobs are written (atomically, skip-if-
exists) BEFORE the manifest that references them, so a committed
manifest's chain is always durable.  Readers resolve chains back to
inline bytes; ``latest()`` treats an unresolvable chain as one more
skippable damage mode with its own ``epoch_abort(blob_missing)``
event.  Blob GC is mark-and-sweep over the retained manifests after
each retire pass (and skips entirely when any retained manifest fails
to parse -- never delete what a manifest might still reference).
"""
from __future__ import annotations

import os
import pickle
import re
from typing import Dict, List, Optional, Tuple

MANIFEST_MAGIC = "windflow-epoch-manifest"
# max schema this runtime reads; commits write 1 (inline states only)
# or 2 (some states entries are blob chains) so pre-delta runtimes
# keep reading full-snapshot manifests
MANIFEST_SCHEMA = 2
_NAME_RE = re.compile(r"^epoch-(\d+)\.ckpt$")


def atomic_write_bytes(path: str, blob: bytes) -> None:
    """Write-temp + fsync + atomic rename; shared with the graph
    snapshot writer (utils/checkpoint.py)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        # persist the rename itself: without the directory fsync a
        # power loss can roll back to the old directory entry
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # e.g. platforms that refuse O_RDONLY on directories


def load_pickle(path: str, what: str) -> object:
    """Unpickle ``path``, converting every decode failure mode of a
    torn/damaged file into one actionable RuntimeError naming it.
    Shared by the manifest reader below and the graph-snapshot reader
    (utils/checkpoint.py).  OSErrors (missing file) propagate."""
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except (pickle.UnpicklingError, EOFError, AttributeError,
            MemoryError, ValueError) as e:
        raise RuntimeError(
            f"{what} {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e}); it was written without the "
            "atomic-rename protocol or damaged on disk -- restore "
            "from an older snapshot/epoch manifest") from e


def validate_header(payload, path: str, magic: str, max_schema: int,
                    what: str) -> None:
    """Header contract shared by manifests and graph snapshots:
    foreign magic, newer schema and missing state maps all raise
    actionable errors naming the file."""
    if not isinstance(payload, dict) or payload.get("magic") != magic:
        raise RuntimeError(f"{path!r} is not a windflow {what}")
    if payload.get("schema", 0) > max_schema:
        raise RuntimeError(
            f"{what} {path!r} has schema {payload.get('schema')} "
            f"newer than this runtime supports ({max_schema}); "
            "upgrade windflow_tpu to restore it")
    if not isinstance(payload.get("states"), dict):
        raise RuntimeError(
            f"{what} {path!r} carries no state map (partial write?); "
            "restore from an older snapshot")


class EpochStore:
    """Manifest directory owner: atomic commits, bounded retention,
    tolerant newest-first reads."""

    def __init__(self, path: str, retained: int = 3):
        from .delta import BlobStore
        self.dir = path
        self.retained = max(1, int(retained))
        self.blobs = BlobStore(os.path.join(path, "blobs"))
        self.fault_plan = None   # FaultPlan.fail_write (set at attach)
        os.makedirs(self.dir, exist_ok=True)

    def manifest_path(self, epoch: int) -> str:
        return os.path.join(self.dir, f"epoch-{epoch:012d}.ckpt")

    def _epochs_on_disk(self) -> List[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for n in names:
            m = _NAME_RE.match(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- commit --------------------------------------------------------
    def commit(self, epoch: int, states: Dict[str, bytes],
               offsets: Dict[str, float],
               meta: Optional[dict] = None,
               blob_writes: Optional[Dict[str, bytes]] = None
               ) -> Tuple[str, int]:
        """Atomically persist epoch ``epoch``; returns (path, bytes
        written for this epoch: manifest + fresh blobs).  ``blob_writes``
        (digest -> payload) land BEFORE the manifest so a crash between
        the two leaves an unreferenced blob, never a dangling chain."""
        fp = self.fault_plan
        nbytes = 0
        if blob_writes:
            for digest, payload_b in blob_writes.items():
                self.blobs.write(digest, payload_b, fault_plan=fp)
                nbytes += len(payload_b)
        chains = any(isinstance(v, dict) and "keyed_chain" in v
                     for v in states.values())
        payload = {"magic": MANIFEST_MAGIC,
                   "schema": 2 if chains else 1,
                   "epoch": int(epoch), "states": dict(states),
                   "offsets": dict(offsets), "meta": dict(meta or {})}
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        path = self.manifest_path(epoch)
        if fp is not None and fp.write_should_fail("manifest"):
            import errno
            raise OSError(errno.ENOSPC,
                          "injected disk full (epoch manifest)")
        atomic_write_bytes(path, blob)
        self._retire()
        self._gc_blobs()
        return path, len(blob) + nbytes

    def write_torn(self, epoch: int, states: Dict[str, bytes],
                   offsets: Dict[str, float]) -> str:
        """FaultPlan.torn_commit: simulate a NON-atomic writer dying
        mid-commit -- a truncated payload at the FINAL path (the
        failure the atomic rename protocol exists to prevent), which
        the tolerant reader must skip on the next restart."""
        payload = {"magic": MANIFEST_MAGIC, "schema": MANIFEST_SCHEMA,
                   "epoch": int(epoch), "states": dict(states),
                   "offsets": dict(offsets), "meta": {}}
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        path = self.manifest_path(epoch)
        with open(path, "wb") as f:
            f.write(blob[:max(16, len(blob) // 3)])
        return path

    def _retire(self) -> None:
        epochs = self._epochs_on_disk()
        for e in epochs[:-self.retained]:
            try:
                os.remove(self.manifest_path(e))
            except OSError:
                pass

    def _gc_blobs(self) -> None:
        """Mark-and-sweep blob GC over the retained manifests.  A
        retained manifest that fails to parse vetoes the whole sweep:
        its references are unknown, and deleting a blob it still needs
        would turn one damaged epoch into an unrestorable store."""
        from .delta import chain_refs
        on_disk = self.blobs.digests_on_disk()
        if not on_disk:
            return
        live = set()
        for e in self._epochs_on_disk():
            try:
                m = self._load_raw(e)
            except RuntimeError:
                return  # unknown references: never sweep
            for ref in chain_refs(m.get("states", {})):
                live.add(ref.digest)
        for d in on_disk:
            if d not in live:
                self.blobs.unlink(d)

    # -- tolerant read -------------------------------------------------
    def _load_raw(self, epoch: int) -> dict:
        """One manifest, header-validated, chains UNresolved."""
        path = self.manifest_path(epoch)
        try:
            payload = load_pickle(path, "epoch manifest")
        except OSError as e:
            raise RuntimeError(
                f"epoch manifest {path!r} is unreadable "
                f"({type(e).__name__}: {e})") from e
        validate_header(payload, path, MANIFEST_MAGIC, MANIFEST_SCHEMA,
                        "epoch manifest")
        return payload

    def resolve_states(self, states: Dict[str, object]) -> Dict[str, bytes]:
        """Replace every ``{"keyed_chain": [...]}`` entry with inline
        packed-keyed bytes (delta.KEYED_STATE_MARKER payloads), leaving
        schema-1 inline bytes untouched.  Raises RuntimeError on a
        missing/corrupt blob."""
        from .delta import pack_keyed, resolve_chain
        out: Dict[str, bytes] = {}
        for name, v in states.items():
            if isinstance(v, dict) and "keyed_chain" in v:
                out[name] = pack_keyed(
                    resolve_chain(self.blobs, v["keyed_chain"]))
            else:
                out[name] = v
        return out

    def load(self, epoch: int) -> dict:
        """One manifest, validated and chain-resolved (``states`` holds
        inline bytes regardless of schema); raises RuntimeError with
        the path named on a torn/foreign/newer-schema file or an
        unresolvable blob chain."""
        payload = self._load_raw(epoch)
        payload["states"] = self.resolve_states(payload["states"])
        return payload

    def latest(self, flight=None) -> Tuple[Optional[int], Optional[dict]]:
        """Newest loadable manifest, skipping damaged ones newest-first
        (each skip recorded as an ``epoch_abort`` flight event when a
        recorder is given): a torn manifest is ``manifest_corrupt``, a
        manifest whose blob chain lost a link is ``blob_missing``.
        (None, None) when nothing is committed."""
        for e in reversed(self._epochs_on_disk()):
            try:
                payload = self._load_raw(e)
            except RuntimeError as err:
                if flight is not None:
                    flight.record("epoch_abort", epoch=e,
                                  reason="manifest_corrupt",
                                  path=self.manifest_path(e),
                                  error=str(err))
                continue
            try:
                payload["states"] = self.resolve_states(
                    payload["states"])
                return e, payload
            except RuntimeError as err:
                if flight is not None:
                    flight.record("epoch_abort", epoch=e,
                                  reason="blob_missing",
                                  path=self.manifest_path(e),
                                  error=str(err))
        return None, None
