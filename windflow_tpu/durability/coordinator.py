"""EpochCoordinator: the durability-plane thread (docs/RESILIENCE.md
"Exactly-once epochs").

One per started PipeGraph when ``RuntimeConfig.durability`` is set.
Every ``epoch_interval_s`` it announces a new epoch (a monotone
``epoch_seq`` the source injectors poll at their step boundaries);
barriers then ride the graph on the replicas' own threads
(durability/barrier.py) while this thread only *collects*: per-replica
state blobs as cuts complete, per-source offsets at injection, sink
acks at terminal alignment.  When every live sink has acked epoch
``e`` the coordinator commits: the manifest is written atomically
(durability/store.py), ``checkpoint_epoch``/``epoch_commit`` flight
events fire with the epoch id, transactional sink buffers release, and
the ``Durability`` stats block (-> ``/metrics``
``windflow_epoch{,_lag_seconds,_commit_seconds}``) updates.

Rescale interaction: barriers and rescales serialize **per epoch** --
``hold_epochs`` stops announcing and waits for in-flight epochs to
commit (the graph keeps flowing meanwhile), the rescale runs, then
``rewire`` refreshes aligner producer counts for the new channel set
and ``release_epochs`` resumes the cadence.  No global lock couples a
barrier in flight to a rescale in flight.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional, Tuple

from .store import EpochStore


class _PendingEpoch:
    __slots__ = ("states", "offsets", "acks", "injected", "t0",
                 "stall_reported")

    def __init__(self, now: float):
        self.states: Dict[str, bytes] = {}
        self.offsets: Dict[str, float] = {}
        self.acks: set = set()
        self.injected: set = set()
        self.t0 = now
        self.stall_reported = False


class EpochCoordinator(threading.Thread):
    def __init__(self, graph):
        super().__init__(name=f"windflow-epochs-{graph.name}", daemon=True)
        self.graph = graph
        dcfg = graph.config.durability
        self.interval_s = max(0.005, float(dcfg.epoch_interval_s))
        self.stall_s = max(self.interval_s * float(dcfg.stall_factor), 0.5)
        self.store = EpochStore(dcfg.path, dcfg.retained)
        # FaultPlan.fail_write("manifest"/"blob") injection point
        self.store.fault_plan = getattr(graph.config, "fault_plan", None)
        # incremental snapshots (durability/delta.py): keyed replicas
        # capture per-key and this thread's encoders turn each capture
        # into content-addressed blob chains, O(changed keys) per commit
        self.delta = bool(getattr(dcfg, "delta", False))
        self._chain_max = int(getattr(dcfg, "delta_chain_max", 8))
        self._encoders: Dict[str, object] = {}
        self.delta_bytes = 0      # blob+manifest bytes of last commit
        # monotone announce counter, read lock-free by source injectors.
        # Epoch ids continue ACROSS restarts (run_with_epochs stamps the
        # restored epoch on the graph before start): if numbering reset
        # per attempt, a second failure could find a stale higher-
        # numbered manifest from the first run and rewind past effects
        # the second run already released -- duplicates
        restored = getattr(graph, "_epoch_restored", None)
        self.epoch_seq = int(restored or 0)
        self.committed = int(restored or 0)
        self.commits = 0
        self.aborts = 0
        self.last_commit_s = 0.0
        self._last_commit_t: Optional[float] = None
        self.stalled = False
        self.restored_from: Optional[int] = (int(restored)
                                             if restored else None)
        self._pending: Dict[int, _PendingEpoch] = {}
        # end-of-stream bookkeeping: nodes past their final barrier and
        # their final states (valid for every later epoch -- a finished
        # replica processed its whole input)
        self._finished: set = set()
        self._final_states: Dict[str, bytes] = {}
        self._sources: List[str] = []
        self._sinks: set = set()
        self._txn_sinks: List = []
        # distributed plane (distributed/; docs/DISTRIBUTED.md): wire
        # edges act as pseudo-sinks (a barrier leaving the worker) and
        # pseudo-sources (a barrier arriving off the wire).  A worker
        # with no local sources is a FOLLOWER: it never announces
        # epochs itself -- epoch ids are global, owned by the source
        # worker's coordinator, and observed here via remote_epoch.
        self._wire_sinks: set = set()
        self._wire_sources: List[str] = []
        self.follower = False
        self._gap = 0                 # >0: epoch announcing held (rescale)
        # epoch currently inside _commit (popped from _pending but not
        # yet durable): checkpoint_now/hold_epochs must not mistake the
        # manifest-write window for "dropped"/"drained"
        self._committing: Optional[int] = None
        self._cond = threading.Condition()
        self._stopping = False
        self.last_manifest: Optional[dict] = None

    # -- wiring (PipeGraph.start / after a rescale) --------------------
    def attach(self) -> None:
        """First wiring pass; additionally enforces that every source
        is barrier-capable (driven by a SourceLoopLogic step loop) and
        uniquely named (offset/state capture is keyed by replica name,
        and parallel source replicas share one -- a silent collision
        would restore only one replica's offset and break
        exactly-once)."""
        import warnings
        from .barrier import iter_named_logics
        from ..runtime.node import source_loop_of
        from ..utils.checkpoint import _is_stateful
        src_names = []
        for n in self.graph._all_nodes():
            if n.channel is not None:
                continue
            src_names.append(n.name)
            if source_loop_of(n.logic) is None:
                raise RuntimeError(
                    f"durability: source node {n.name!r} is not driven "
                    "by a SourceLoopLogic generation loop, so epoch "
                    "barriers cannot be injected at it "
                    "(docs/RESILIENCE.md)")
            if not any(_is_stateful(lg)
                       for _name, lg in iter_named_logics(n)):
                # epochs still commit (and measure) fine, but a restart
                # cannot rewind this source: it would replay from the
                # beginning against state restored at the epoch --
                # duplicates.  DurabilityConfig(strict=True) makes this
                # fatal (exactly-once must not silently degrade);
                # otherwise loud, not fatal: overhead benches and
                # commit-only runs legitimately use stateless sources.
                msg = (f"durability: source {n.name!r} has no "
                       "state_dict (offset not checkpointable) -- "
                       "restarts will replay it from the start, "
                       "degrading exactly-once to at-least-once "
                       "(docs/RESILIENCE.md)")
                if getattr(self.graph.config.durability, "strict",
                           False):
                    raise RuntimeError(
                        msg + "; DurabilityConfig(strict=True) forbids "
                        "this -- give the source a checkpointable "
                        "offset or drop strict")
                warnings.warn(msg, RuntimeWarning, stacklevel=3)
        dups = sorted({x for x in src_names if src_names.count(x) > 1})
        if dups:
            raise RuntimeError(
                f"durability: source replicas share node names {dups} "
                "(source parallelism > 1): epoch offsets/states are "
                "keyed by replica name, so the manifest would keep "
                "only one replica's position.  Use parallelism-1 "
                "sources (or uniquely named ones) under the durability "
                "plane (docs/RESILIENCE.md)")
        self.rewire()
        if self.committed:
            # restored run: epoch-aware sinks resume their numbering
            # from the restored epoch (idempotent effects before the
            # first new barrier belong to epoch committed+1)
            for n in self.graph._all_nodes():
                for _name, logic in iter_named_logics(n):
                    resume = getattr(logic, "epoch_resume", None)
                    if resume is not None:
                        resume(self.committed)

    def attach_node(self, node) -> None:
        """Aligner wiring for one rescale-created replica, BEFORE its
        thread starts (elastic/rescale.py ``_grow`` -- the consume
        loop resolves the durable dispatch path once); ``rewire()``
        refreshes the rest of the plane after the rescale completes.
        The audit plane's ``GraphAuditor.attach_node`` is the
        precedent."""
        from .barrier import EpochAligner
        from ..audit.ledger import unwrap
        node.epoch_coord = self
        node.epochs = EpochAligner(
            node, self, getattr(unwrap(node.channel), "n_producers", 1))

    def rewire(self) -> None:
        """(Re)attach aligners/injectors to the live node set.  Called
        at start and after every rescale (under an epoch gap, so no
        alignment is in flight): existing aligners keep their
        ``finished`` sets -- retired producers announced themselves
        with final barriers -- and only refresh their producer counts;
        new replicas get fresh aligners."""
        from .barrier import EpochAligner, EpochInjector, iter_named_logics
        from ..audit.ledger import unwrap
        from ..runtime.node import source_loop_of
        g = self.graph
        sinks, sources, txn = set(), [], []
        wire_out = set(getattr(g, "_wire_out_edges", ()))
        wire_in = list(getattr(g, "_wire_in_edges", ()))
        with self._cond:
            for n in g._all_nodes():
                n.epoch_coord = self
                if not n.outlets:
                    sinks.add(n.name)
                if n.channel is not None:
                    n_prod = getattr(unwrap(n.channel), "n_producers", 1)
                    if n.epochs is None:
                        n.epochs = EpochAligner(n, self, n_prod)
                    else:
                        n.epochs.n_producers = max(1, int(n_prod))
                else:
                    src = source_loop_of(n.logic)
                    if src is not None:
                        if getattr(src, "epoch_injector", None) is None:
                            src.epoch_injector = EpochInjector(n, self)
                        sources.append(n.name)
                for name, logic in iter_named_logics(n):
                    if hasattr(logic, "commit_epoch"):
                        txn.append(logic)
                        # per-sink EOS defers release to the final
                        # commit below (transaction.py); release-time
                        # sink-fn errors quarantine per effect
                        logic._coordinated = True
                        logic._dead_letters = g.dead_letters
                        logic._name = name
            self._wire_sinks = wire_out
            self._wire_sources = wire_in
            self.follower = not sources and bool(wire_in)
            self._sinks = sinks | wire_out
            self._sources = sources + wire_in
            self._txn_sinks = txn
        # the transport acks/finishes through the coordinator: bind it
        dist = getattr(g, "_dist", None)
        if dist is not None:
            for s in dist.senders.values():
                s.epoch_coord = self

    # -- collection (replica threads) ----------------------------------
    def add_snapshot(self, epoch: int, states: Dict[str, bytes]) -> None:
        with self._cond:
            p = self._pending.get(epoch)
            if p is not None:
                p.states.update(states)

    def source_offset(self, epoch: int, name: str, frontier) -> None:
        with self._cond:
            p = self._pending.get(epoch)
            if p is not None:
                p.offsets[name] = frontier
                p.injected.add(name)

    def sink_ack(self, epoch: int, name: str) -> None:
        with self._cond:
            p = self._pending.get(epoch)
            if p is not None:
                p.acks.add(name)
                self._cond.notify_all()

    def node_finished(self, name: str, states: Dict[str, bytes]) -> None:
        """EOS hook (RtNode.run): the node's final state backfills any
        epoch it will never cut for."""
        with self._cond:
            self._finished.add(name)
            for k, v in states.items():
                self._final_states[k] = v
            self._cond.notify_all()

    def remote_epoch(self, epoch: int, name: str, frontier=None) -> None:
        """A barrier for ``epoch`` arrived off the wire (distributed
        plane, receiver thread, BEFORE the barrier enters the consumer
        channel).  Epoch ids are global -- announced by the source
        worker's coordinator -- so a follower catches its ``epoch_seq``
        up here, creating the pending entries the local cuts will fill;
        a worker that also has local sources (the leader hearing its
        own epochs echoed through a cycle) just records the injection."""
        if epoch < 1:
            return
        first = False
        with self._cond:
            if epoch > self.epoch_seq:
                for e in range(self.epoch_seq + 1, epoch + 1):
                    if e > self.committed and e not in self._pending:
                        self._pending[e] = _PendingEpoch(_time.monotonic())
                        first = True
                self.epoch_seq = epoch
            p = self._pending.get(epoch)
            if p is not None:
                p.injected.add(name)
                if frontier is not None:
                    p.offsets[name] = frontier
            self._cond.notify_all()
        if first:
            self.graph.flight.record("epoch_observe", epoch=epoch,
                                     edge=name)

    # -- epoch cadence -------------------------------------------------
    def begin_epoch(self) -> int:
        g = self.graph
        with self._cond:
            self.epoch_seq += 1
            e = self.epoch_seq
            self._pending[e] = _PendingEpoch(_time.monotonic())
        g.flight.record("epoch_begin", epoch=e)
        return e

    def run(self) -> None:
        next_tick = _time.monotonic() + self.interval_s
        while True:
            with self._cond:
                self._cond.wait(timeout=max(
                    0.005, min(next_tick - _time.monotonic(), 0.25)))
                if self._stopping:
                    return
            g = self.graph
            if g._ended or g._cancel.cancelled:
                return
            now = _time.monotonic()
            if now >= next_tick:
                with self._cond:
                    clear = self._gap == 0 and not self._stopping
                pausing = (g._pause_ctl is not None
                           and g._pause_ctl.pausing)
                # a distributed follower never announces: its epochs
                # arrive off the wire with the leader's global ids
                if clear and not pausing and not self.follower:
                    try:
                        self.begin_epoch()
                    except Exception:  # pragma: no cover - never die
                        import traceback
                        traceback.print_exc()
                next_tick = now + self.interval_s
            try:
                self.drive()
            except Exception:  # pragma: no cover - keep the cadence
                import traceback
                traceback.print_exc()

    def drive(self) -> None:
        """Commit every ready pending epoch (oldest first), drop
        unreachable ones, refresh the stall gauge, publish."""
        while True:
            action = None
            with self._cond:
                if self._pending:
                    e = min(self._pending)
                    p = self._pending[e]
                    live_sinks = self._sinks - self._finished
                    live_sources = [s for s in self._sources
                                    if s not in self._finished]
                    if not live_sinks:
                        # stream ended past this epoch: the sinks'
                        # eos_flush released everything, nothing to
                        # commit (clean end is the implicit final
                        # commit)
                        del self._pending[e]
                        self._cond.notify_all()
                        continue
                    if p.acks >= live_sinks:
                        states = dict(self._final_states)
                        states.update(p.states)
                        action = ("commit", e, states, dict(p.offsets))
                        del self._pending[e]
                        self._committing = e
                    elif not live_sources and not p.injected:
                        # announced after every source finished: no
                        # barrier ever materialized
                        del self._pending[e]
                        self._cond.notify_all()
                        continue
            if action is None:
                break
            try:
                self._commit(action[1], action[2], action[3])
            finally:
                with self._cond:
                    self._committing = None
                    self._cond.notify_all()
        self._check_stall()
        self.publish()

    def _encode_states(self, states: Dict[str, object]):
        """Turn a collected state map into its manifest form: inline
        bytes pass through; ``KeyedCapture`` objects run through the
        per-replica delta encoders (durability/delta.py) and become
        ``{"keyed_chain": [...]}`` entries, with the epoch's fresh
        blobs staged in the returned ``blob_writes``."""
        from .delta import DeltaEncoder, KeyedCapture
        blob_writes: Dict[str, bytes] = {}
        enc: Dict[str, object] = {}
        for name, v in states.items():
            if isinstance(v, KeyedCapture):
                encoder = self._encoders.get(name)
                if encoder is None:
                    encoder = self._encoders[name] = DeltaEncoder(
                        self._chain_max)
                enc[name] = {"keyed_chain": encoder.encode(
                    v, blob_writes)}
            else:
                enc[name] = v
        return enc, blob_writes

    def _commit(self, epoch: int, states: Dict[str, bytes],
                offsets: Dict[str, float]) -> None:
        g = self.graph
        t0 = _time.perf_counter()
        states, blob_writes = self._encode_states(states)
        plan = getattr(g.config, "fault_plan", None)
        if plan is not None and epoch in getattr(plan, "torn_commit_epochs",
                                                 ()):
            # injected torn commit: a truncated manifest lands at the
            # FINAL path (simulating a non-atomic writer dying
            # mid-commit) and the "process" dies -- the next restart's
            # tolerant reader must fall back to the previous epoch
            path = self.store.write_torn(epoch, states, offsets)
            self.aborts += 1
            g.flight.record("epoch_abort", epoch=epoch,
                            reason="torn_commit", path=path)
            from ..resilience.errors import NodeFailureError
            g._cancel.cancel(
                NodeFailureError(
                    f"injected torn manifest commit at epoch {epoch}"),
                origin="epoch-coordinator")
            return
        try:
            path, nbytes = self.store.commit(
                epoch, states, offsets,
                meta={"graph": g.name, "committed_at": _time.time()},
                blob_writes=blob_writes)
        except OSError as e:
            # disk full (or any filesystem refusal) mid-commit: degrade,
            # do not die.  The last committed epoch stays the recovery
            # point, transactional sinks keep buffering until a later
            # commit succeeds, and the delta encoders reset so the next
            # epoch writes a fresh base chain -- their shadows may
            # reference blobs this commit never made durable.
            self.aborts += 1
            self._encoders.clear()
            g.flight.record("epoch_abort", epoch=epoch,
                            reason="disk_full", error=str(e),
                            committed=self.committed)
            return
        self.delta_bytes = nbytes
        g.flight.record("checkpoint_epoch", epoch=epoch, path=path,
                        replicas=len(states), bytes=nbytes)
        released = 0
        for logic in self._txn_sinks:
            try:
                released += logic.commit_epoch(epoch)
            except Exception:  # pragma: no cover - sink fn failure
                import traceback
                traceback.print_exc()
        self.last_commit_s = _time.perf_counter() - t0
        self._last_commit_t = _time.monotonic()
        self.last_manifest = {"epoch": epoch, "states": states,
                              "offsets": offsets}
        # publication order is load-bearing: checkpoint_now polls
        # `committed` and then reads `last_manifest`, so the manifest
        # must be visible first
        self.committed = epoch
        self.commits += 1
        self.stalled = False
        # sink progress rides the commit event so the non-stop property
        # is auditable offline: gets strictly increasing across commits
        # proves the graph kept flowing through every epoch
        sink_gets = 0
        for n in g._all_nodes():
            if not n.outlets and n.channel is not None:
                sink_gets += getattr(n.channel, "gets", 0)
        g.flight.record("epoch_commit", epoch=epoch,
                        commit_s=round(self.last_commit_s, 6),
                        effects=released, sink_gets=sink_gets,
                        offsets=offsets)

    def _check_stall(self) -> None:
        now = _time.monotonic()
        with self._cond:
            oldest = min(self._pending) if self._pending else None
            p = self._pending.get(oldest) if oldest is not None else None
        if p is None:
            self.stalled = False
            return
        if now - p.t0 > self.stall_s:
            self.stalled = True
            if not p.stall_reported:
                p.stall_reported = True
                self.graph.flight.record(
                    "epoch_stall", epoch=oldest,
                    age_s=round(now - p.t0, 3),
                    acks=sorted(p.acks), committed=self.committed)

    # -- rescale serialization (PipeGraph.rescale / quiesce) -----------
    def hold_epochs(self, timeout: float = 30.0) -> None:
        """Stop announcing epochs and wait until none is in flight.
        Refcounted (a rescale's inner quiesce nests).  The graph keeps
        processing while we wait -- in-flight barriers drain to the
        sinks and commit normally."""
        deadline = _time.monotonic() + timeout
        with self._cond:
            self._gap += 1
            while self._pending or self._committing is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    self._gap -= 1
                    raise RuntimeError(
                        "durability: in-flight epochs "
                        f"{sorted(self._pending)} failed to drain "
                        f"within {timeout}s (committed={self.committed})")
                self._cond.wait(min(remaining, 0.05))

    def release_epochs(self) -> None:
        with self._cond:
            self._gap = max(0, self._gap - 1)
            self._cond.notify_all()

    # -- supervised replica restart (durability/supervision.py) --------
    def abort_epochs(self, reason: str, timeout: float = 30.0) -> None:
        """Drop every in-flight epoch WITHOUT waiting for it to drain
        -- the supervisor's counterpart to ``hold_epochs``, for when a
        replica died mid-alignment and its barriers will never arrive
        (waiting would deadlock).  Only an in-progress manifest write
        is waited out (it is about to become the committed rewind
        point).  Announcing stays held until ``release_epochs``;
        stale barriers/acks for the dropped epochs no-op against the
        missing pending entries."""
        deadline = _time.monotonic() + timeout
        with self._cond:
            self._gap += 1
            while self._committing is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break  # commit is durable-or-not; do not deadlock
                self._cond.wait(min(remaining, 0.05))
            pending = sorted(self._pending)
            self._pending.clear()
            self._cond.notify_all()
        for e in pending:
            self.aborts += 1
            self.graph.flight.record("epoch_abort", epoch=e,
                                     reason=reason,
                                     committed=self.committed)

    def resolve_manifest_states(self, m: Optional[dict]
                                ) -> Dict[str, bytes]:
        """The ``states`` of a manifest-shaped dict as inline pickled
        bytes, whatever their stored form: inline bytes pass through,
        blob chains resolve from the store, raw ``KeyedCapture``
        objects (final states never committed yet) pack directly."""
        from .delta import KeyedCapture, pack_keyed
        out: Dict[str, bytes] = {}
        for name, v in ((m or {}).get("states", {}) or {}).items():
            if isinstance(v, KeyedCapture):
                out[name] = pack_keyed(v.entries)
            elif isinstance(v, dict) and "keyed_chain" in v:
                out[name] = self.store.resolve_states({name: v})[name]
            else:
                out[name] = v
        return out

    # -- on-demand epoch (PipeGraph.live_checkpoint) -------------------
    def checkpoint_now(self, timeout: float = 60.0
                       ) -> Tuple[int, Dict[str, bytes]]:
        """Force one epoch and wait for its commit -- the non-stop
        replacement for the quiesce-based live checkpoint.  Returns
        (epoch, pickled-state map).  Falls back to the final states
        when the stream ended before the barrier could materialize."""
        deadline = _time.monotonic() + timeout
        with self._cond:
            # serialize with rescales exactly like the cadence: a
            # forced barrier riding a half-rewired topology would
            # align against stale producer counts
            while self._gap > 0:
                if _time.monotonic() > deadline:
                    raise RuntimeError(
                        "durability: a rescale held the epoch plane "
                        f"for the whole {timeout}s checkpoint window")
                self._cond.wait(0.05)
            self.epoch_seq += 1
            target = self.epoch_seq
            self._pending[target] = _PendingEpoch(_time.monotonic())
            self._cond.notify_all()
        self.graph.flight.record("epoch_begin", epoch=target, forced=True)
        while True:
            with self._cond:
                if self.committed >= target:
                    return self.committed, self.resolve_manifest_states(
                        self.last_manifest)
                if target not in self._pending \
                        and target != self._committing:
                    # dropped (not mid-commit: drive() pops the pending
                    # entry BEFORE the manifest write, and mistaking
                    # that window for a drop would return empty state):
                    # the stream ended under the barrier -- the final
                    # states are the (complete) snapshot
                    return self.committed, self.resolve_manifest_states(
                        {"states": self._final_states})
                if _time.monotonic() > deadline:
                    raise RuntimeError(
                        f"durability: forced epoch {target} did not "
                        f"commit within {timeout}s")
                self._cond.wait(0.05)

    # -- publication / shutdown ----------------------------------------
    def publish(self) -> None:
        now = _time.monotonic()
        with self._cond:
            oldest = min(self._pending) if self._pending else None
            lag = (now - self._pending[oldest].t0) if oldest is not None \
                else 0.0
            block = {
                "Committed_epoch": self.committed,
                "Begun_epoch": self.epoch_seq,
                "Pending_epochs": len(self._pending),
                "Epoch_lag_s": round(lag, 3),
                "Last_commit_s": round(self.last_commit_s, 6),
                "Commits": self.commits,
                "Aborts": self.aborts,
                "Stalled": self.stalled,
                "Interval_s": self.interval_s,
                "Restored_from": self.restored_from,
                "Path": self.store.dir,
                "Delta": self.delta,
                "Last_commit_bytes": self.delta_bytes,
            }
            sup = getattr(self.graph, "_supervisor", None)
            if sup is not None:
                block["Replica_restarts"] = sup.heals
        self.graph.stats.set_durability(block)

    def stop(self, clean: bool = True) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self.is_alive():
            self.join(timeout=5.0)
        if clean:
            self._final_commit()
        if not clean:
            # a failed/cancelled run strands its in-flight epochs: the
            # restart recovers from the last COMMITTED one, so record
            # the aborts next to the failure for the post-mortem
            with self._cond:
                pending = sorted(self._pending)
                self._pending.clear()
            for e in pending:
                self.aborts += 1
                self.graph.flight.record("epoch_abort", epoch=e,
                                         reason="graph_failure",
                                         committed=self.committed)
        self.publish()

    def _final_commit(self) -> None:
        """Graph-level clean end (every replica joined without error):
        persist the final states as one last manifest, then release the
        sinks' remaining buffers.  Release happens HERE, not at each
        sink's own EOS -- one branch ending cleanly is not a commit
        point while another branch can still crash (its restart would
        regenerate whatever an eager flush released: duplicates)."""
        g = self.graph
        with self._cond:
            self._pending.clear()
            self.epoch_seq += 1
            epoch = self.epoch_seq
            states = dict(self._final_states)
        try:
            states, blob_writes = self._encode_states(states)
            path, nbytes = self.store.commit(
                epoch, states, {},
                meta={"graph": g.name, "final": True,
                      "committed_at": _time.time()},
                blob_writes=blob_writes)
            g.flight.record("checkpoint_epoch", epoch=epoch, path=path,
                            replicas=len(states), bytes=nbytes,
                            final=True)
            self.committed = epoch
            self.commits += 1
            self.last_manifest = {"epoch": epoch, "states": states,
                                  "offsets": {}}
        except OSError as e:
            # disk full at the final manifest: the run's OUTPUT is
            # complete either way (the finally below still releases the
            # sinks); only a later restart loses this last rewind point
            self.aborts += 1
            self._encoders.clear()
            g.flight.record("epoch_abort", epoch=epoch,
                            reason="disk_full", error=str(e),
                            committed=self.committed, final=True)
        finally:
            # the stream completed either way: the buffered effects ARE
            # the output (a failed manifest write only affects restarts
            # that will never need it)
            released = 0
            for logic in self._txn_sinks:
                try:
                    released += logic.final_release()
                except Exception:  # pragma: no cover - sink fn failure
                    import traceback
                    traceback.print_exc()
            g.flight.record("epoch_commit", epoch=epoch,
                            effects=released, final=True)
