"""Per-replica barrier mechanics: the epoch cut, the source injector
and the multi-producer aligner (docs/RESILIENCE.md "Exactly-once
epochs").

The protocol is the streaming adaptation of Chandy-Lamport snapshots
(Carbone et al., "Lightweight Asynchronous Snapshots for Distributed
Dataflows", the Flink aligned-barrier design): the coordinator
announces epoch ``e``; every source replica injects an
:class:`~windflow_tpu.runtime.queues.EpochBarrier` at a generation-step
boundary after capturing its offset; the barrier rides the channels as
an ordinary item; each consumer **aligns** -- input from producers that
already delivered barrier ``e`` is held back until every producer has
-- then takes the **epoch cut**: fence in-flight device batches
(``quiesce`` hook: async-dispatcher results land downstream *before*
the barrier), seal transactional sink buffers (``epoch_mark``),
snapshot per-segment state, and forward the barrier to every outlet
destination.  The graph is never globally quiesced: each replica pauses
only for its own cut while the rest keep flowing.

Accounting: barriers travel through ``Outlet.send_to``, so the audit
plane's per-edge delivery books count them symmetrically and every edge
stays balanced by construction; the graph-wide roll-up subtracts the
per-node ``epoch_barriers_in/out`` counters (audit/ledger.py).
"""
from __future__ import annotations

import pickle
from typing import Dict

from ..runtime.queues import EpochBarrier


def iter_named_logics(node):
    """(original-node-name, logic) pairs of one runtime node, seeing
    through fusion -- the same naming contract as
    ``graph.fuse.iter_logics`` / ``utils.checkpoint.graph_state``, so
    epoch-manifest states restore into any fusion level."""
    from ..runtime.node import FusedLogic
    if isinstance(node.logic, FusedLogic):
        for seg in node.logic.segments:
            yield seg.name, seg.logic
    else:
        yield node.name, node.logic


def capture_states(node) -> Dict[str, object]:
    """Per-replica state at the barrier point, keyed by pre-fusion
    node name.  Serialized IMMEDIATELY on the replica's own thread:
    several ``state_dict`` implementations alias live stores
    (AccumulatorLogic), and the stream keeps mutating them the moment
    the cut completes.

    Values are pickled ``state_dict`` bytes -- except under
    ``DurabilityConfig(delta=True)`` for logics with the full keyed
    contract, which capture as :class:`~windflow_tpu.durability.delta.
    KeyedCapture` (per-key pickled values) so the coordinator's delta
    encoder can diff them against the previous epoch's chain."""
    coord = getattr(node, "epoch_coord", None)
    delta_on = coord is not None and getattr(coord, "delta", False)
    out: Dict[str, object] = {}
    for name, logic in iter_named_logics(node):
        if delta_on:
            from .delta import KeyedCapture, keyed_capable
            if keyed_capable(logic):
                out[name] = KeyedCapture.capture(logic)
                continue
        getter = getattr(logic, "state_dict", None)
        st = getter() if getter is not None else None
        if st is not None:
            out[name] = pickle.dumps(st, protocol=pickle.HIGHEST_PROTOCOL)
    return out


def _fire_epoch_faults(node, epoch: int) -> None:
    """crash_at_epoch (resilience/faults.py): a seeded crash INSIDE the
    barrier window -- after alignment, before the cut -- deterministic
    on the epoch id, independent of stream timing."""
    from ..runtime.node import FusedLogic
    if node.faults is not None:
        node.faults.on_epoch(epoch)
    if isinstance(node.logic, FusedLogic):
        for seg in node.logic.segments:
            if seg.faults is not None:
                seg.faults.on_epoch(epoch)


def epoch_cut(node, epoch: int, coord) -> None:
    """The aligned cut on one replica: fault hook, device fence,
    transactional seal, state capture, barrier forward (or sink ack).
    Runs on the replica's own thread -- between items for consumers,
    at a generation-step boundary for sources -- so touching logic
    state is safe by the same contract as ``quiesce``."""
    _fire_epoch_faults(node, epoch)
    # fence: every in-flight device batch of THIS epoch lands (its
    # results emit downstream, pre-barrier) before the barrier passes
    # the async dispatcher -- otherwise a restored run would lose the
    # windows that were on the wire to the device at the cut.  The
    # fence emits through the node's OUTWARD path: on a fused node the
    # quiesce hook feeds downstream segments inline itself, so handing
    # it an inner-chain emit would loop the chain into itself
    q = getattr(node.logic, "quiesce", None)
    if q is not None:
        q(node._emit)
    for _name, logic in iter_named_logics(node):
        mark = getattr(logic, "epoch_mark", None)
        if mark is not None:
            mark(epoch)
    coord.add_snapshot(epoch, capture_states(node))
    if node.outlets:
        b = EpochBarrier(epoch)
        n = 0
        for o in node.outlets:
            for di in range(len(o.dests)):
                o.send_to(di, b)
                n += 1
        node.epoch_barriers_out += n
    else:
        coord.sink_ack(epoch, node.name)


def broadcast_final(node) -> None:
    """End-of-stream barrier: before a node closes its outlets it tells
    every downstream aligner that this producer will inject no further
    epochs (the aligner counts it as permanently arrived), so a
    finished branch can never stall another branch's alignment."""
    b = EpochBarrier(-1, final=True)
    for o in node.outlets:
        for di in range(len(o.dests)):
            o.send_to(di, b)
            node.epoch_barriers_out += 1


class EpochInjector:
    """Source-side barrier injection, polled at every generation-step
    boundary (SourceLoopLogic.eos_flush -- which is also the ingest
    transport poll loop).  Lock-free: reads the coordinator's monotone
    ``epoch_seq`` and catches up one epoch at a time, capturing the
    source offset for the manifest before each cut."""

    __slots__ = ("node", "coord", "last")

    def __init__(self, node, coord):
        self.node = node
        self.coord = coord
        self.last = coord.epoch_seq

    def maybe_inject(self) -> None:
        seq = self.coord.epoch_seq
        while self.last < seq:
            self.last += 1
            from ..audit.progress import source_frontier
            self.coord.source_offset(self.last, self.node.name,
                                     source_frontier(self.node))
            epoch_cut(self.node, self.last, self.coord)


class EpochAligner:
    """Multi-producer barrier alignment for one consumer node (KEYBY
    shuffles, merges, farm collectors).  Single-threaded: driven only
    by the owning node's consume loop, so no locking.

    While epoch ``e`` is aligning, items from producers that already
    delivered their ``e`` barrier are **held back** (the Flink
    alignment buffer) so the cut separates pre- from post-barrier input
    exactly; they replay in arrival order once the cut completes.
    ``final`` barriers mark a producer permanently arrived."""

    __slots__ = ("node", "coord", "n_producers", "waiting", "arrived",
                 "finished", "held", "_replay", "_draining")

    def __init__(self, node, coord, n_producers: int):
        from collections import deque
        self.node = node
        self.coord = coord
        self.n_producers = max(1, int(n_producers))
        self.waiting = None           # epoch currently aligning
        self.arrived = set()          # producer ids that delivered it
        self.finished = set()         # producers past their final barrier
        self.held = []                # [(cid, item)] parked during alignment
        self._replay = deque()        # holdback items being replayed
        self._draining = False

    @property
    def busy(self) -> bool:
        """True while an alignment is open or items are parked
        (including mid-replay) -- the drain detector and the frontier
        tracker must not call the node caught up then."""
        return (self.waiting is not None or bool(self.held)
                or bool(self._replay))

    def reset(self) -> None:
        """Abandon any open alignment and drop parked items (the
        replica supervisor's epoch abort: a crashed peer's barrier
        will never arrive, and held-back post-barrier input is
        regenerated by the source rewind).  ``finished`` producers and
        the producer count survive -- they are structural facts, not
        epoch state."""
        self.waiting = None
        self.arrived = set()
        self.held = []
        self._replay.clear()

    def offer(self, cid, item, process) -> bool:
        """Dispatch one channel item.  Returns True when the aligner
        consumed it (a barrier, or an item held back during alignment);
        False means the caller processes it normally."""
        if type(item) is not EpochBarrier:
            if self.waiting is not None and (cid in self.arrived
                                             or cid in self.finished):
                self.held.append((cid, item))
                return True
            return False
        self._on_barrier(cid, item, process)
        return True

    def _on_barrier(self, cid, b: EpochBarrier, process) -> None:
        self.node.epoch_barriers_in += 1
        if b.final:
            self.finished.add(cid)
            if self.waiting is not None:
                self._maybe_complete(process)
            return
        if self.waiting is None:
            self.waiting = b.epoch
            self.arrived = {cid}
        elif b.epoch == self.waiting:
            self.arrived.add(cid)
        else:
            # a future epoch's barrier from a producer already aligned
            # for the current one (per-producer FIFO guarantees its
            # current-epoch barrier came first): park it for replay
            self.held.append((cid, b))
            return
        self._maybe_complete(process)

    def _maybe_complete(self, process) -> None:
        if len(self.arrived | self.finished) < self.n_producers:
            return
        epoch = self.waiting
        self.waiting = None
        self.arrived = set()
        held, self.held = self.held, []
        epoch_cut(self.node, epoch, self.coord)
        # replay the alignment buffer in arrival order through the
        # _replay deque, which stays visible to `busy` the whole time
        # (the frontier tracker / drain detector must never see parked
        # items as caught up).  PREPENDING keeps per-producer FIFO when
        # a nested completion lands mid-drain: its re-held items must
        # run before the remaining (later-arrived) replay items.  Only
        # the outermost frame drains -- a parked next-epoch barrier
        # re-enters offer(), may complete the next alignment, and that
        # nested call just prepends.
        self._replay.extendleft(reversed(held))
        if self._draining:
            return
        self._draining = True
        try:
            while self._replay:
                hcid, hitem = self._replay.popleft()
                if not self.offer(hcid, hitem, process):
                    process(hcid, hitem)
        finally:
            self._draining = False
