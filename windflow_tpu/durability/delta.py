"""Incremental (delta) epoch snapshots (docs/RESILIENCE.md
"Delta snapshots").

The schema-1 manifest re-pickles every replica's full keyed state each
epoch -- O(total keys) commit cost no matter how few keys the epoch
touched.  With ``DurabilityConfig(delta=True)`` a keyed replica's
state is serialized as content-addressed **blobs** beside the manifest
and the manifest references a blob CHAIN instead of inlining bytes:

* the chain's first link is a **base** blob holding every key's
  pickled value;
* each later link is a **delta** blob holding only the keys that
  changed (``put``) or disappeared (``del``) since the previous link;
* after ``delta_chain_max`` links the encoder compacts the chain back
  to a fresh base blob, bounding replay length.

Blobs are content-addressed (file name = sha256 of the payload), so an
unchanged base is never rewritten -- consecutive manifests share it by
reference, and a commit under a 1%-dirty workload writes O(changed
keys) bytes.  Dirty detection is a per-key digest diff against the
encoder's shadow of the last committed chain (the blob-granular
analogue of the audit plane's keyed-state census deltas).

Readers walk the chain base-first, applying puts/dels; a missing or
corrupt blob raises, and the tolerant manifest scan
(``EpochStore.latest``) records an ``epoch_abort(blob_missing)``
flight event and falls back to the newest fully-loadable epoch.

Non-keyed state (source offsets, window engines without the keyed
contract) stays inline in the manifest exactly as at schema 1: it is
small, and inlining keeps the torn-blob failure domain to keyed
stores only.
"""
from __future__ import annotations

import hashlib
import os
import pickle
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

BLOB_MAGIC = "windflow-epoch-blob"

# resolved keyed manifest entries unpickle to this marker shape instead
# of a logic state_dict: {"__windflow_keyed_state__": True,
# "entries": {key: pickled_value_bytes}}.  ``load_into`` routes it to
# ``load_keyed_state`` so every restore path (epoch restore, live
# checkpoint, worker restart, supervision rewind) stays delta-agnostic.
KEYED_STATE_MARKER = "__windflow_keyed_state__"


@dataclass(frozen=True)
class BlobRef:
    """Pickle-friendly chain link: content digest + payload size."""

    digest: str
    nbytes: int
    base: bool = False


def _digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def pack_keyed(entries: Dict[Any, bytes]) -> bytes:
    """Serialize per-key pickled values as a marker payload whose
    unpickled form ``load_into`` recognizes."""
    return pickle.dumps({KEYED_STATE_MARKER: True, "entries": entries},
                        protocol=pickle.HIGHEST_PROTOCOL)


def is_keyed_payload(obj: Any) -> bool:
    return isinstance(obj, dict) and obj.get(KEYED_STATE_MARKER) is True


def unpack_keyed(obj: Dict[str, Any]) -> Dict[Any, Any]:
    """Marker payload -> {key: live value} (per-key unpickle)."""
    return {k: pickle.loads(v) for k, v in obj["entries"].items()}


def keyed_capable(logic) -> bool:
    """True iff the logic's class implements the FULL keyed contract
    (both ``keyed_state_dict`` and ``load_keyed_state`` overridden), so
    its state can round-trip through per-key blobs."""
    from ..runtime.node import NodeLogic
    kd = getattr(type(logic), "keyed_state_dict", None)
    lk = getattr(type(logic), "load_keyed_state", None)
    if kd is None or lk is None:
        return False
    return (kd is not getattr(NodeLogic, "keyed_state_dict", None)
            and lk is not getattr(NodeLogic, "load_keyed_state", None))


def load_into(logic, decoded: Any) -> None:
    """Load a decoded manifest/snapshot entry into a live logic,
    routing keyed marker payloads through ``load_keyed_state`` and
    everything else through ``load_state`` -- the single restore
    funnel shared by epoch restore, live checkpoints, distributed
    worker restarts and the replica supervisor."""
    if is_keyed_payload(decoded):
        logic.load_keyed_state(unpack_keyed(decoded))
    else:
        logic.load_state(decoded)


class KeyedCapture:
    """Replica-thread capture of a keyed logic's state as per-key
    pickled values.  Pickling per key (instead of one state_dict blob)
    happens on the replica thread -- values alias live stores, so they
    must be frozen before the coordinator thread diffs them."""

    __slots__ = ("entries",)

    def __init__(self, entries: Dict[Any, bytes]):
        self.entries = entries

    @classmethod
    def capture(cls, logic) -> "KeyedCapture":
        # tiered stores (state/tiers.py) serve warm/cold keys from the
        # pickled bytes they already hold -- unchanged cold keys digest
        # identically every epoch, so the chain references them with
        # zero new blob bytes ("cold tier by reference")
        fast = getattr(logic, "keyed_state_pickled", None)
        if fast is not None:
            got = fast()
            if got is not None:
                return cls(dict(got))
        return cls({k: pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
                    for k, v in logic.keyed_state_dict().items()})


class BlobStore:
    """Content-addressed blob files under ``<epochs>/blobs/``.

    Writes are atomic (durability/store.py) and skip-if-exists --
    content addressing makes rewrites byte-identical, so an existing
    file is already the payload.  Reads verify the digest, so a torn
    or bit-flipped blob surfaces as a RuntimeError instead of a bad
    unpickle deep inside restore."""

    def __init__(self, root: str):
        self.root = root

    def path(self, digest: str) -> str:
        return os.path.join(self.root, f"{digest}.blob")

    def write(self, digest: str, payload: bytes, fault_plan=None) -> str:
        from .store import atomic_write_bytes
        p = self.path(digest)
        if not os.path.exists(p):
            if fault_plan is not None \
                    and fault_plan.write_should_fail("blob"):
                import errno
                raise OSError(errno.ENOSPC,
                              "injected disk full (epoch blob)")
            os.makedirs(self.root, exist_ok=True)
            atomic_write_bytes(p, payload)
        return p

    def read(self, digest: str) -> bytes:
        p = self.path(digest)
        try:
            with open(p, "rb") as f:
                payload = f.read()
        except OSError as e:
            raise RuntimeError(
                f"epoch blob {digest[:12]}... missing or unreadable at "
                f"{p!r}: {e}") from e
        if _digest(payload) != digest:
            raise RuntimeError(
                f"epoch blob at {p!r} fails its content digest "
                "(torn or corrupt write)")
        return payload

    def digests_on_disk(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return [n[:-5] for n in names if n.endswith(".blob")]

    def unlink(self, digest: str) -> None:
        try:
            os.unlink(self.path(digest))
        except OSError:
            pass


def make_blob(base: bool, put: Dict[Any, bytes],
              dels: List[Any]) -> bytes:
    return pickle.dumps(
        {"magic": BLOB_MAGIC, "base": base, "put": put, "del": dels},
        protocol=pickle.HIGHEST_PROTOCOL)


def _load_blob(store: BlobStore, ref: BlobRef) -> Dict[str, Any]:
    payload = store.read(ref.digest)
    try:
        doc = pickle.loads(payload)
    except Exception as e:  # digest passed but unpickle failed
        raise RuntimeError(
            f"epoch blob {ref.digest[:12]}... unreadable: {e!r}") from e
    if not isinstance(doc, dict) or doc.get("magic") != BLOB_MAGIC:
        raise RuntimeError(
            f"file at {store.path(ref.digest)!r} is not a windflow "
            "epoch blob")
    return doc


def resolve_chain(store: BlobStore, chain: List[BlobRef]) -> Dict[Any, bytes]:
    """Walk a blob chain base-first, applying puts/dels; returns the
    merged {key: pickled_value_bytes}.  Raises RuntimeError on a
    missing/corrupt/ill-formed link (the tolerant manifest scan turns
    that into an ``epoch_abort(blob_missing)`` fallback)."""
    if not chain:
        return {}
    entries: Dict[Any, bytes] = {}
    for i, ref in enumerate(chain):
        doc = _load_blob(store, ref)
        if i == 0 and not doc.get("base"):
            raise RuntimeError(
                f"epoch blob chain starts with a delta blob "
                f"({ref.digest[:12]}...): base link missing")
        entries.update(doc.get("put", {}))
        for k in doc.get("del", ()):  # removed keys
            entries.pop(k, None)
    return entries


class DeltaEncoder:
    """Per-replica chain encoder living on the coordinator thread.

    Keeps a shadow of the last committed chain (per-key value digests
    for dirty detection, the pickled values themselves for
    compaction) and turns each epoch's :class:`KeyedCapture` into the
    blob writes + manifest chain for that epoch."""

    __slots__ = ("shadow", "entries", "chain", "chain_max")

    def __init__(self, chain_max: int = 8):
        self.shadow: Dict[Any, str] = {}     # key -> value digest
        self.entries: Dict[Any, bytes] = {}  # key -> pickled value
        self.chain: List[BlobRef] = []
        self.chain_max = max(1, int(chain_max))

    def encode(self, capture: KeyedCapture,
               blob_writes: Dict[str, bytes]) -> List[BlobRef]:
        """Diff ``capture`` against the shadow; stage the blob write
        for this epoch into ``blob_writes`` (digest -> payload) and
        return the manifest chain.  An epoch that touched nothing
        reuses the previous chain verbatim -- zero new bytes."""
        put: Dict[Any, bytes] = {}
        new_shadow: Dict[Any, str] = {}
        for k, vb in capture.entries.items():
            d = _digest(vb)
            new_shadow[k] = d
            if self.shadow.get(k) != d:
                put[k] = vb
        dels = [k for k in self.shadow if k not in capture.entries]
        self.shadow = new_shadow
        self.entries.update(put)
        for k in dels:
            self.entries.pop(k, None)
        if not self.chain:
            # first commit for this replica: full base
            payload = make_blob(True, dict(self.entries), [])
            ref = BlobRef(_digest(payload), len(payload), base=True)
            blob_writes[ref.digest] = payload
            self.chain = [ref]
        elif put or dels:
            if len(self.chain) >= self.chain_max:
                # compact: fresh base replaces the whole chain
                payload = make_blob(True, dict(self.entries), [])
                ref = BlobRef(_digest(payload), len(payload), base=True)
                blob_writes[ref.digest] = payload
                self.chain = [ref]
            else:
                payload = make_blob(False, put, dels)
                ref = BlobRef(_digest(payload), len(payload))
                blob_writes[ref.digest] = payload
                self.chain = self.chain + [ref]
        # else: nothing changed -- previous chain carries over
        return list(self.chain)


def chain_refs(states: Dict[str, Any]):
    """Yield every BlobRef referenced by a manifest ``states`` map
    (delta entries are ``{"keyed_chain": [BlobRef, ...]}``)."""
    for v in states.values():
        if isinstance(v, dict) and "keyed_chain" in v:
            for ref in v["keyed_chain"]:
                yield ref
