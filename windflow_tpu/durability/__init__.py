"""Durability plane: exactly-once pipelines through aligned epoch
barriers (docs/RESILIENCE.md "Exactly-once epochs").

Composes the machinery earlier planes proved -- fusion-invariant
state snapshots (utils/checkpoint), checkpointable source offsets
(ingest/operators), FaultPlan + recovery runners (resilience), the
audit plane's frontiers and delivery books -- into Flink-style aligned
incremental snapshots taken **without stopping the graph**, an
atomically-committed epoch manifest store, a transactional /
idempotent sink contract, and an epoch-aware restart runner.

Enable with ``RuntimeConfig.durability = DurabilityConfig(...)`` and,
for exactly-once sink output, ``SinkBuilder(fn).with_exactly_once()``.
``DurabilityConfig(delta=True)`` switches keyed replicas to
incremental blob-chain snapshots (delta.py); ``RuntimeConfig.
supervision = SupervisionConfig(...)`` arms in-place replica
self-healing for ``.with_restartable()`` operators (supervision.py).
"""
from ..core.basic import DurabilityConfig, SupervisionConfig
from ..runtime.queues import EpochBarrier
from .barrier import EpochAligner, EpochInjector, epoch_cut
from .coordinator import EpochCoordinator
from .delta import BlobRef, BlobStore, DeltaEncoder, KeyedCapture
from .recovery import restore_epoch, run_with_epochs
from .store import EpochStore, MANIFEST_SCHEMA, atomic_write_bytes
from .supervision import ReplicaSupervisor, SupervisedGroup
from .transaction import (EpochTaggedStore, IdempotentSinkLogic,
                          TransactionalSinkLogic)

__all__ = [
    "DurabilityConfig", "SupervisionConfig", "EpochBarrier",
    "EpochAligner", "EpochInjector", "EpochCoordinator", "EpochStore",
    "EpochTaggedStore", "IdempotentSinkLogic", "TransactionalSinkLogic",
    "MANIFEST_SCHEMA", "BlobRef", "BlobStore", "DeltaEncoder",
    "KeyedCapture", "ReplicaSupervisor", "SupervisedGroup",
    "atomic_write_bytes", "epoch_cut", "restore_epoch", "run_with_epochs",
]
