"""Epoch-aware recovery: restart from the last committed epoch
(docs/RESILIENCE.md "Exactly-once epochs").

``run_with_epochs`` is the durable sibling of
``utils.checkpoint.run_with_recovery``: each attempt rebuilds the graph
from the factory, restores every replica's state from the newest
loadable epoch manifest (sources rewind to the committed offsets --
their offset IS their snapshot state), and re-runs.  Combined with a
transactional/idempotent sink, the restart regenerates exactly the
effects the crashed attempt had not durably committed: end-to-end
exactly-once, verified online by the conservation ledger balancing in
the restarted run and offline by the kill-restart-verify chaos suite.
"""
from __future__ import annotations

import pickle
from typing import Any, Callable, List, Optional

from ..resilience.errors import NodeFailureError
from .store import EpochStore


def restore_epoch(graph, payload: dict, overrides=None) -> int:
    """Load a committed epoch manifest into an UNSTARTED graph;
    returns the number of replicas restored.

    Structure checking and state loading are shared with
    ``utils.checkpoint.restore_graph`` (``restore_states``): the
    manifest's stateful-replica names must equal this graph's (names
    are pre-fusion, so any OptLevel restores) -- a silent partial
    restore would misdistribute keyed state.  ``overrides``
    (operator-name -> new parallelism) lifts named replica groups out
    of that contract: their keyed state is merged and repartitioned
    through the elastic ``hash % n`` owner function instead
    (docs/RESILIENCE.md "Restore into a different parallelism")."""
    from ..utils.checkpoint import restore_states
    return restore_states(
        graph, payload["states"],
        f"epoch manifest (epoch {payload.get('epoch')})",
        decode=pickle.loads, overrides=overrides)


def run_with_epochs(graph_factory: Callable[[int], Any],
                    max_restarts: int = 3,
                    on_failure: Optional[Callable] = None,
                    on_restore: Optional[Callable] = None,
                    parallelism_overrides: Optional[dict] = None) -> Any:
    """Run ``graph_factory(attempt)`` to completion with epoch-aware
    restarts.  Every graph the factory builds must carry the SAME
    ``RuntimeConfig.durability`` (same manifest path).

    On a retryable failure (``NodeFailureError`` -- replica death,
    stall, injected torn commit) the latest loadable epoch manifest is
    restored into a freshly built graph: replica state reloads,
    sources rewind to the committed offsets, and uncommitted sink
    output is discarded with the dead graph.  ``on_restore(graph,
    epoch, payload)`` runs after a successful restore -- e.g. to
    ``truncate_above(epoch)`` an idempotent sink's store.
    ``on_failure(attempt, error, graph)`` observes each failed attempt;
    all failures attach to the finally raised error as
    ``attempt_history``.

    ``parallelism_overrides`` ({operator name: new replica count})
    declares that the factory now builds named operators at a DIFFERENT
    parallelism than the manifest was written with: their keyed state
    is repartitioned across the new replica set through the elastic
    ``hash % n`` contract instead of raising the structure-mismatch
    error.  Source offsets re-assign by name (sources are
    parallelism-1 under the durability plane, so their names -- and
    offsets -- survive any operator rescale unchanged).  The counts
    are advisory documentation of intent; the authoritative new
    parallelism is whatever the factory builds."""
    attempt = 0
    history: List[BaseException] = []
    while True:
        g = graph_factory(attempt)
        dcfg = getattr(g.config, "durability", None)
        if dcfg is None:
            raise ValueError(
                "run_with_epochs: the factory's graphs must set "
                "RuntimeConfig.durability (use run_with_recovery for "
                "quiescent-checkpoint restarts)")
        store = EpochStore(dcfg.path, dcfg.retained)
        epoch, payload = store.latest(flight=g.flight)
        if epoch is not None:
            n = restore_epoch(g, payload,
                              overrides=parallelism_overrides)
            g.flight.record("epoch_restore", epoch=epoch, replicas=n,
                            offsets=payload.get("offsets", {}),
                            attempt=attempt,
                            repartitioned=sorted(parallelism_overrides)
                            if parallelism_overrides else [])
            g._epoch_restored = epoch
            if on_restore is not None:
                on_restore(g, epoch, payload)
        try:
            g.run()
            return g
        except NodeFailureError as e:
            history.append(e)
            if on_failure is not None:
                on_failure(attempt, e, g)
            attempt += 1
            if attempt > max_restarts:
                e.attempt_history = history
                raise
