"""Multi-window error-budget burn-rate tracking
(docs/OBSERVABILITY.md "SLO plane").

The model is the SRE burn-rate alert: the graph declares objectives
(:class:`SloConfig`) and a *target* compliance fraction (default 0.99
-- at most 1% of observed stream time may violate any objective).  The
complement ``1 - target`` is the **error budget**.  Every diagnosis
tick the current gauges are judged good or bad per objective; the
**burn rate** over a window is::

    burn = (bad time fraction in the window) / (1 - target)

so ``burn == 1`` means the budget is being consumed exactly as fast as
the target permits, and ``burn == 1 / (1 - target)`` (100x at the
default target) means every observed second violates.

Two windows are kept, the classic fast+slow pair: the **fast** window
(1 min of stream time) reacts within seconds of an onset, the **slow**
window (1 hr equivalent) keeps one transient wobble from paging.  Both
scale by ``window_scale`` so replayed / accelerated streams (and
tests) evaluate in *stream* time rather than wall time.  A breach
opens only when the fast burn exceeds ``fast_burn`` AND the slow burn
exceeds ``slow_burn``, sustained ``BREACH_TICKS`` consecutive ticks
(the same debounce discipline as the anomaly bands); it closes after
``CLEAR_TICKS`` compliant ticks.  Episodes surface as
``FlightRecorder("slo_breach")`` / ``"slo_recovered"`` events, the
``Slo`` stats block, and the ``windflow_slo_*`` metric families.

Evaluation windows early in a run (or right after onset) hold fewer
samples than the nominal span; the burn is computed over the samples
that exist (min 2), which is what makes a sustained violation
detectable within a few ticks of onset instead of a full window later.

Everything here is pure bookkeeping over gauge reads -- the tracker
never touches the item path, so results with the plane on are bitwise
identical to off (bench ``13_slo_overhead`` asserts it).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# consecutive burning ticks before an episode opens (debounce)
BREACH_TICKS = 2
# consecutive compliant ticks before it closes
CLEAR_TICKS = 3
# samples kept (prunes also by slow-window age; 4096 ~ 1 hr at 1 Hz)
MAX_SAMPLES = 4096
# a window needs at least this many samples to produce a burn rate
MIN_SAMPLES = 2


@dataclass(frozen=True)
class SloConfig:
    """Per-graph service-level objectives (``RuntimeConfig.slo`` /
    ``PipeGraph.with_slo``).  At least one objective must be set.

    * ``p99_ms``             -- traced end-to-end p99 budget (needs
                                ``tracing`` with a sampling period);
    * ``min_throughput_rps`` -- sink results/s floor (the history
                                plane's ``throughput_rps`` unit);
    * ``max_frontier_lag_s`` -- frontier-lag ceiling (audit plane).
    """

    p99_ms: Optional[float] = None
    min_throughput_rps: Optional[float] = None
    max_frontier_lag_s: Optional[float] = None
    # objective compliance fraction; 1 - target is the error budget
    target: float = 0.99
    # nominal window spans, scaled by window_scale into stream time
    fast_window_s: float = 60.0
    slow_window_s: float = 3600.0
    window_scale: float = 1.0
    # burn-rate thresholds: breach needs fast AND slow to concur
    fast_burn: float = 10.0
    slow_burn: float = 1.0
    # ticks ignored at graph start (gauges settle: first throughput
    # delta, first traced closures)
    warmup_ticks: int = 3

    def __post_init__(self):
        if (self.p99_ms is None and self.min_throughput_rps is None
                and self.max_frontier_lag_s is None):
            raise ValueError(
                "SloConfig needs at least one objective (p99_ms, "
                "min_throughput_rps or max_frontier_lag_s)")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), "
                             f"got {self.target}")
        for name in ("fast_window_s", "slow_window_s", "window_scale"):
            if getattr(self, name) <= 0:
                raise ValueError(f"SloConfig.{name} must be positive")

    def objectives(self) -> Dict[str, float]:
        return {k: getattr(self, k)
                for k in ("p99_ms", "min_throughput_rps",
                          "max_frontier_lag_s")
                if getattr(self, k) is not None}


def evaluate_objectives(cfg: SloConfig, gauges: Dict[str, float],
                        flow_seen: bool = True) -> List[str]:
    """Names of the objectives the current gauge row violates.  An
    objective whose signal is absent does not count either way: no
    traced closures yet for the p99, and -- via ``flow_seen`` -- no
    first result yet for the throughput floor (a cold start spending
    seconds in a device compile is not an outage; once flow HAS been
    seen, a zero-throughput tick is a genuine violation)."""
    bad: List[str] = []
    if cfg.p99_ms is not None:
        p99_us = float(gauges.get("e2e_p99_us") or 0.0)
        if p99_us > 0 and p99_us / 1e3 > cfg.p99_ms:
            bad.append("e2e_p99")
    if cfg.min_throughput_rps is not None and flow_seen:
        if float(gauges.get("throughput_rps") or 0.0) \
                < cfg.min_throughput_rps:
            bad.append("throughput")
    if cfg.max_frontier_lag_s is not None:
        if float(gauges.get("frontier_lag_ms") or 0.0) / 1e3 \
                > cfg.max_frontier_lag_s:
            bad.append("frontier_lag")
    return bad


class SloTracker:
    """Burn-rate state over the diagnosis tick cadence.  ``update``
    returns a flight-event dict when an episode opens or closes."""

    def __init__(self, cfg: SloConfig):
        self.cfg = cfg
        self.fast_s = cfg.fast_window_s * cfg.window_scale
        self.slow_s = cfg.slow_window_s * cfg.window_scale
        self.budget = 1.0 - cfg.target
        self._samples: deque = deque(maxlen=MAX_SAMPLES)  # (t, bad)
        self.ticks = 0
        self.bad_ticks = 0
        self.breached = False
        self.breaches_total = 0
        self.since: Optional[float] = None
        self._breach_run = 0
        self._clear_run = 0
        self._violating: List[str] = []
        self._burn_fast = 0.0
        self._burn_slow = 0.0
        self._budget_burned = 0.0
        self._values: Dict[str, float] = {}
        self._flow_seen = False

    # -- burn-rate math (pure; unit-tested against hand-computed
    # windows in tests/test_slo.py) -----------------------------------
    def _window(self, now: float, span: float) -> Tuple[int, int, float]:
        """(bad, total, observed_span_s) of the samples within
        ``span`` seconds of ``now``."""
        lo = now - span
        bad = total = 0
        oldest = now
        for t, b in self._samples:
            if t < lo:
                continue
            total += 1
            if b:
                bad += 1
            if t < oldest:
                oldest = t
        return bad, total, max(0.0, now - oldest)

    def burn_rate(self, now: float, span: float) -> float:
        """Bad-time fraction over the window, normalized by the error
        budget.  0.0 until the window holds ``MIN_SAMPLES`` samples."""
        bad, total, _ = self._window(now, span)
        if total < MIN_SAMPLES:
            return 0.0
        return (bad / total) / self.budget

    def budget_burned(self, now: float) -> float:
        """Fraction of the slow window's error budget already consumed
        (can exceed 1.0: the budget is overdrawn)."""
        bad, total, observed = self._window(now, self.slow_s)
        if total < MIN_SAMPLES or observed <= 0.0:
            return 0.0
        bad_time = (bad / total) * min(observed, self.slow_s)
        return bad_time / (self.budget * self.slow_s)

    # -- tick ----------------------------------------------------------
    def update(self, now: float,
               gauges: Dict[str, float]) -> Optional[dict]:
        self.ticks += 1
        # remember flow BEFORE the warmup early-return: a pipeline
        # that bursts during warmup and then wedges must be judged
        # against the throughput floor from the first post-warmup tick
        if float(gauges.get("throughput_rps") or 0.0) > 0.0:
            self._flow_seen = True
        if self.ticks <= self.cfg.warmup_ticks:
            return None
        violating = evaluate_objectives(self.cfg, gauges,
                                        self._flow_seen)
        self._violating = violating
        # latest judged values ride the block so the verdict can cite
        # them even in a merged view (which carries no History block)
        self._values = {
            "e2e_p99_ms": round(
                float(gauges.get("e2e_p99_us") or 0.0) / 1e3, 3),
            "throughput_rps": round(
                float(gauges.get("throughput_rps") or 0.0), 1),
            "frontier_lag_ms": round(
                float(gauges.get("frontier_lag_ms") or 0.0), 1),
        }
        bad = bool(violating)
        if bad:
            self.bad_ticks += 1
        # prune by slow-window age so the deque never serves stale time
        lo = now - self.slow_s
        while self._samples and self._samples[0][0] < lo:
            self._samples.popleft()
        self._samples.append((now, bad))
        self._burn_fast = round(self.burn_rate(now, self.fast_s), 3)
        self._burn_slow = round(self.burn_rate(now, self.slow_s), 3)
        self._budget_burned = round(self.budget_burned(now), 4)
        burning = (self._burn_fast >= self.cfg.fast_burn
                   and self._burn_slow >= self.cfg.slow_burn)
        event = None
        if burning:
            self._clear_run = 0
            self._breach_run += 1
            if not self.breached and self._breach_run >= BREACH_TICKS:
                self.breached = True
                self.breaches_total += 1
                self.since = now
                event = {"event": "slo_breach",
                         "violating": list(violating),
                         "burn_fast": self._burn_fast,
                         "burn_slow": self._burn_slow,
                         "budget_burned": self._budget_burned}
        else:
            self._breach_run = 0
            if self.breached:
                self._clear_run += 1
                if self._clear_run >= CLEAR_TICKS:
                    self.breached = False
                    event = {"event": "slo_recovered",
                             "burn_fast": self._burn_fast,
                             "budget_burned": self._budget_burned}
        return event

    def block(self) -> dict:
        """The stats-JSON ``Slo`` block (every field optional to
        readers, like every block in the report)."""
        return {
            "Objectives": self.cfg.objectives(),
            "Target": self.cfg.target,
            "Windows": {"fast_s": round(self.fast_s, 3),
                        "slow_s": round(self.slow_s, 3)},
            "Ticks": self.ticks,
            "Bad_ticks": self.bad_ticks,
            "Burn_rate_fast": self._burn_fast,
            "Burn_rate_slow": self._burn_slow,
            "Budget_burned": self._budget_burned,
            "Breached": self.breached,
            "Breaches_total": self.breaches_total,
            "Violating": list(self._violating),
            "Values": dict(self._values),
            "Since": round(self.since, 3) if self.since else None,
        }


def merge_slo(blocks: List[dict]) -> Optional[dict]:
    """Fold per-worker ``Slo`` blocks into the cluster view: worst
    news wins (any breach breaches the merged view; burn rates and the
    burned budget take the max; episode counts sum).  Tolerant of
    heterogeneous/missing fields like every stats reader."""
    blocks = [b for b in blocks if isinstance(b, dict)]
    if not blocks:
        return None
    first = blocks[0]
    violating: List[str] = []
    for b in blocks:
        for v in b.get("Violating") or ():
            if v not in violating:
                violating.append(v)
    sinces = [b.get("Since") for b in blocks
              if b.get("Breached") and b.get("Since")]
    values: Dict[str, float] = {}
    for b in blocks:
        for k, v in (b.get("Values") or {}).items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            # element-wise worst: latency/lag take the max, the
            # throughput floor the min
            if k == "throughput_rps":
                values[k] = min(values.get(k, v), v)
            else:
                values[k] = max(values.get(k, v), v)
    return {
        "Objectives": first.get("Objectives"),
        "Target": first.get("Target"),
        "Windows": first.get("Windows"),
        "Ticks": max(int(b.get("Ticks", 0) or 0) for b in blocks),
        "Bad_ticks": sum(int(b.get("Bad_ticks", 0) or 0)
                         for b in blocks),
        "Burn_rate_fast": max(float(b.get("Burn_rate_fast", 0) or 0)
                              for b in blocks),
        "Burn_rate_slow": max(float(b.get("Burn_rate_slow", 0) or 0)
                              for b in blocks),
        "Budget_burned": max(float(b.get("Budget_burned", 0) or 0)
                             for b in blocks),
        "Breached": any(b.get("Breached") for b in blocks),
        "Breaches_total": sum(int(b.get("Breaches_total", 0) or 0)
                              for b in blocks),
        "Violating": violating,
        "Values": values,
        "Since": min(sinces) if sinces else None,
        "Workers": len(blocks),
    }
