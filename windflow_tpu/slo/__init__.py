"""SLO plane: declared objectives, continuously evaluated
(docs/OBSERVABILITY.md "SLO plane").

A graph declares what "healthy" means -- an end-to-end p99 budget, a
throughput floor, a frontier-lag ceiling -- and the runtime holds
itself to it on the existing diagnosis tick with multi-window
error-budget burn-rate accounting.  Breaches open ``slo_breach``
flight episodes, surface as the ``Slo`` stats block, the
``windflow_slo_*`` metric families and a worst-news-first doctor
verdict line, and (in a distributed run) fold into the coordinator's
live merged cluster view.
"""
from .plane import SloConfig, SloTracker

__all__ = ["SloConfig", "SloTracker"]
