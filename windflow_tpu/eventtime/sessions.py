"""Gap-based session windows, merged on overlap, watermark-closed.

A session for key k is a maximal run of tuples where consecutive
event-times are at most ``gap`` apart.  Sessions are DATA-DEFINED
windows: a new tuple either extends a live session (``start - gap <=
ts <= last + gap``), bridges several (they merge into one), or opens a
fresh one.  A session closes -- fires its aggregate and leaves state --
when the merged watermark passes ``last_event + gap + lateness``: no
future tuple can extend it any more (every future ts >= watermark >
last + gap).  A tuple that can neither join a live session nor open a
closable-in-the-future one (``wm >= ts + gap + lateness`` already) is
late and quarantined loudly (docs/EVENTTIME.md).

State shape per key: ``[[start, last, rows], ...]`` sorted by start --
plain lists so sessions pickle for epochs, repartition at rescale and
demote into the tiered store unchanged.
"""
from __future__ import annotations

from typing import Callable

from ..core.basic import OrderingMode, Pattern, RoutingMode
from ..core.tuples import BasicRecord
from ..operators.base import Operator, StageSpec
from ..runtime.emitters import StandardEmitter
from ..runtime.node import EOSMarker
from .base import EventTimeLogic, iter_rows

__all__ = ["SessionWindowLogic", "SessionWindow"]


class SessionWindowLogic(EventTimeLogic):
    node_name = "session_window"

    def __init__(self, agg: Callable, gap: float, lateness: float = 0.0):
        super().__init__(lateness)
        self.agg = agg
        self.gap = float(gap)
        self._open = 0  # gauge: live sessions across keys

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        gap = self.gap
        for key, tid, ts, value in iter_rows(item):
            sess = self.state.get(key)
            if sess is None:
                sess = self.state[key] = []
            joined = [s for s in sess if s[0] - gap <= ts <= s[1] + gap]
            if not joined:
                if self.wm >= ts + gap + self.lateness:
                    self._late(key, tid, ts, value)
                    continue
                sess.append([ts, ts, [(ts, tid, value)]])
                sess.sort(key=lambda s: s[0])
                self._open += 1
            else:
                base = joined[0]
                base[2].append((ts, tid, value))
                base[0] = min(base[0], ts)
                base[1] = max(base[1], ts)
                for other in joined[1:]:  # ts bridged them: merge
                    base[2].extend(other[2])
                    base[0] = min(base[0], other[0])
                    base[1] = max(base[1], other[1])
                    sess.remove(other)
                    self._open -= 1
        if self.stats is not None:
            self.stats.sessions_open = self._open

    # the open-session gauge rebuilds from restored/repartitioned state
    def load_state(self, st):
        super().load_state(st)
        self._open = sum(len(v) for v in st["state"].values())

    def load_keyed_state(self, kv):
        super().load_keyed_state(kv)
        self._open = sum(len(v) for v in kv.values())

    def on_watermark(self, wm, emit):
        if wm.ts > self.wm:
            self.wm = wm.ts
        self._close(self.wm, emit)

    def eos_flush(self, emit):
        self._close(float("inf"), emit)

    def _close(self, wm_ts, emit):
        horizon = self.gap + self.lateness
        fired = []
        for key in list(self.state.keys()):
            sess = self.state.get(key)
            live = []
            for s in sess:
                if s[1] + horizon <= wm_ts:
                    fired.append((s[0], key, s))
                else:
                    live.append(s)
            if live:
                self.state[key] = live
            else:
                del self.state[key]
        self._open -= len(fired)
        if self.stats is not None:
            self.stats.sessions_open = self._open
        fired.sort(key=lambda f: (f[0], f[1]))
        for start, key, (_, last, rows) in fired:
            rows.sort(key=lambda r: (r[0], r[1]))
            emit(BasicRecord(key, len(rows), start,
                             self.agg([r[2] for r in rows])))


class SessionWindow(Operator):
    """Keyed session-window operator: per-key gap sessions, merging on
    overlap, closing at watermark passage.  The fired record carries
    ``ts = session start`` and ``id = session tuple count``."""

    def __init__(self, agg: Callable, gap: float, lateness: float = 0.0,
                 parallelism: int = 1, name: str = "session_window"):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.ACCUMULATOR)
        self.agg = agg
        self.gap = gap
        self.lateness = lateness

    def _make_logic(self, i, n=None):
        return SessionWindowLogic(self.agg, self.gap, self.lateness)

    def stages(self):
        reps = [self._make_logic(i) for i in range(self.parallelism)]
        return [StageSpec(self.name, reps, StandardEmitter(keyed=True),
                          self.routing, ordering_mode=OrderingMode.TS)]

    def elastic_logic_factory(self):
        return self._make_logic
