"""Watermark-triggered event-time windows (tumbling and sliding).

Unlike the device window engines (operators/tpu/) which fire on tuple
ARRIVAL order, an event-time window [s, s+size) fires exactly when the
merged low-watermark passes ``s + size + allowed_lateness`` -- the
out-of-order-safe trigger (docs/EVENTTIME.md).  Determinism contract:
the replica buffers ``(ts, id, value)`` rows per (key, window), sorts
them at fire time and applies the aggregation to the sorted value
list, so results are bitwise identical to the numpy oracle no matter
how arrival order was shuffled.  Fired windows emit in (win_start,
key) order as :class:`~windflow_tpu.core.tuples.BasicRecord` with
``ts = win_start`` and ``id = win_start // slide``.

A tuple whose LAST containing window already fired is late: it is
quarantined through the loud lateness policy
(:meth:`~windflow_tpu.eventtime.base.EventTimeLogic._late`), never
silently dropped.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

from ..core.basic import OrderingMode, Pattern, RoutingMode
from ..core.tuples import BasicRecord
from ..operators.base import Operator, StageSpec
from ..runtime.emitters import StandardEmitter
from ..runtime.node import EOSMarker
from .base import EventTimeLogic, iter_rows

__all__ = ["EventTimeWindowLogic", "EventTimeWindow"]


class EventTimeWindowLogic(EventTimeLogic):
    """Replica logic: per-key aligned windows, watermark-fired.

    State shape (the keyed contract's unit of repartition):
    ``{key: {win_start: [(ts, id, value), ...]}}``.
    """

    node_name = "event_window"

    def __init__(self, agg: Callable, size: float, slide: float = None,
                 lateness: float = 0.0):
        super().__init__(lateness)
        self.agg = agg
        self.size = float(size)
        self.slide = float(slide) if slide else float(size)

    # window index range containing ts: n*slide <= ts < n*slide + size
    def _win_range(self, ts: float):
        n_hi = math.floor(ts / self.slide)
        n_lo = math.floor((ts - self.size) / self.slide) + 1
        return n_lo, n_hi

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        horizon = self.size + self.lateness
        for key, tid, ts, value in iter_rows(item):
            n_lo, n_hi = self._win_range(ts)
            if self.wm >= n_hi * self.slide + horizon:
                self._late(key, tid, ts, value)  # every window fired
                continue
            wins = self.state.get(key)
            if wins is None:
                wins = self.state[key] = {}
            for n in range(n_lo, n_hi + 1):
                s = n * self.slide
                if self.wm < s + horizon:  # unfired windows only
                    wins.setdefault(s, []).append((ts, tid, value))

    def on_watermark(self, wm, emit):
        if wm.ts > self.wm:
            self.wm = wm.ts
        self._fire(self.wm, emit)

    def eos_flush(self, emit):
        # safety net for graphs whose sources never seal with
        # Watermark(inf): end of stream fires everything still open
        self._fire(float("inf"), emit)

    def _fire(self, wm_ts, emit):
        horizon = self.size + self.lateness
        fired = []
        for key in list(self.state.keys()):
            wins = self.state.get(key)
            for s in [s for s in wins if s + horizon <= wm_ts]:
                fired.append((s, key, wins.pop(s)))
            if not wins:
                del self.state[key]
        fired.sort(key=lambda f: (f[0], f[1]))
        for s, key, rows in fired:
            rows.sort(key=lambda r: (r[0], r[1]))
            emit(BasicRecord(key, int(s // self.slide), s,
                             self.agg([r[2] for r in rows])))


class EventTimeWindow(Operator):
    """Keyed event-time window operator: ``agg(sorted_values)`` per
    (key, window), fired by watermark passage.

    ``EventTimeWindow(sum, size=10)`` tumbles; a ``slide < size``
    overlaps.  Composes with elastic rescale (keyed repartition),
    exactly-once epochs and the tiered keyed store through the
    EventTimeLogic contract."""

    def __init__(self, agg: Callable, size: float, slide: float = None,
                 lateness: float = 0.0, parallelism: int = 1,
                 name: str = "event_window"):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.ACCUMULATOR)
        self.agg = agg
        self.size = size
        self.slide = slide
        self.lateness = lateness

    def _make_logic(self, i, n=None):
        return EventTimeWindowLogic(self.agg, self.size, self.slide,
                                    self.lateness)

    def stages(self):
        reps = [self._make_logic(i) for i in range(self.parallelism)]
        return [StageSpec(self.name, reps, StandardEmitter(keyed=True),
                          self.routing, ordering_mode=OrderingMode.TS)]

    def elastic_logic_factory(self):
        return self._make_logic
