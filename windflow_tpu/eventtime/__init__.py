"""Event-time relational plane: watermark-triggered windows, session
windows and two-input stream joins (docs/EVENTTIME.md).

Built on the generic watermark transport in runtime/node.py (per-edge
broadcast, per-node min-merge across producers, ledger-balanced like
epoch barriers) and the keyed-state contract shared with
AccumulatorLogic, so every operator here composes with exactly-once
epochs (durability/), the tiered keyed store (state/) and runtime
rescale (elastic/) out of the box.
"""
from ..runtime.queues import Watermark
from .base import EventTimeLogic, iter_rows
from .frontend import StreamQuery, query
from .joins import (LEFT, RIGHT, IntervalJoin, IntervalJoinLogic, Sided,
                    WindowJoin, WindowJoinLogic, side_tagger, tag_side)
from .sessions import SessionWindow, SessionWindowLogic
from .watermarks import WatermarkedSource, watermarked
from .windows import EventTimeWindow, EventTimeWindowLogic

__all__ = [
    "Watermark", "WatermarkedSource", "watermarked",
    "EventTimeLogic", "iter_rows",
    "EventTimeWindow", "EventTimeWindowLogic",
    "SessionWindow", "SessionWindowLogic",
    "LEFT", "RIGHT", "Sided", "side_tagger", "tag_side",
    "IntervalJoin", "IntervalJoinLogic",
    "WindowJoin", "WindowJoinLogic",
    "StreamQuery", "query",
]
