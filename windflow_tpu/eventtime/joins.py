"""Two-input event-time stream joins: interval and windowed.

windflow graphs are single-input DAGs at the channel level, so a
binary join is expressed with the merge algebra: each input pipe tags
its records with a side (:func:`side_tagger` -> :class:`Sided`), the
pipes ``merge()``, and the join operator consumes the merged stream --
its replica channel then has every tail of both inputs as producers,
which is exactly what the runtime's per-producer watermark min-merge
needs: the join's event-time clock is ``min(left WM, right WM)`` by
construction, and the join node participates in epoch barrier
alignment like any multi-producer node.

* :class:`IntervalJoin` -- match L and R rows of one key when
  ``lower <= ts_r - ts_l <= upper``.  Probing is incremental on
  arrival; the watermark EVICTS a buffered left row once
  ``ts_l + upper + lateness < WM`` (no future right row can match it)
  and a right row once ``ts_r - lower + lateness < WM``.  Infinite
  bounds disable eviction on that side (a full history join, NexMark
  Q3).
* :class:`WindowJoin` -- per-(key, window) two-sided buffers; the
  cross product fires when the watermark passes ``win_end +
  lateness``, in deterministic (win_start, key, ts_l, ts_r) order.

An arrival whose own eviction/fire horizon has already passed is late
and quarantined loudly (docs/EVENTTIME.md).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Optional

from ..core.basic import OrderingMode, Pattern, RoutingMode
from ..core.tuples import BasicRecord, TupleBatch
from ..operators.base import Operator, StageSpec
from ..operators.basic_ops import FlatMap
from ..runtime.emitters import StandardEmitter
from ..runtime.node import EOSMarker
from .base import EventTimeLogic

__all__ = ["LEFT", "RIGHT", "Sided", "side_tagger", "tag_side",
           "IntervalJoinLogic", "IntervalJoin",
           "WindowJoinLogic", "WindowJoin"]

LEFT = 0
RIGHT = 1


class Sided:
    """A record tagged with its join side.  Carries the standard
    control-field contract so KEYBY emitters, ordering collectors and
    the audit plane treat it like any record."""

    __slots__ = ("side", "key", "id", "ts", "value", "trace")

    def __init__(self, side: int, key: Any, tid: int, ts: float,
                 value: Any):
        self.side = side
        self.key = key
        self.id = tid
        self.ts = ts
        self.value = value

    def get_control_fields(self):
        return (self.key, self.id, self.ts)

    def set_control_fields(self, key, tid, ts):
        self.key = key
        self.id = tid
        self.ts = ts

    def __repr__(self):
        side = "L" if self.side == LEFT else "R"
        return (f"Sided({side}, key={self.key}, id={self.id}, "
                f"ts={self.ts}, value={self.value})")


def side_tagger(side: int, key_of: Callable = None,
                key_col: str = None, value_col: str = "value"):
    """FlatMap body tagging one join input: expands records or
    TupleBatch rows into :class:`Sided` with an optional re-key --
    ``key_of(record)`` on the record plane, column ``key_col`` on the
    batch plane (joins key both sides on the JOIN key, which is rarely
    both inputs' native key)."""

    def tag(item, shipper):
        if isinstance(item, TupleBatch):
            keys = item[key_col] if key_col else item.key
            vals = item.cols.get(value_col)
            tid, ts = item.id, item.ts
            for i in range(len(item)):
                shipper.push(Sided(
                    side, int(keys[i]), int(tid[i]), float(ts[i]),
                    None if vals is None else vals[i]))
        else:
            k, tid, ts = item.get_control_fields()
            if key_of is not None:
                k = key_of(item)
            shipper.push(Sided(side, k, tid, float(ts),
                               getattr(item, "value", None)))
    return tag


def tag_side(side: int, key_of: Callable = None, key_col: str = None,
             value_col: str = "value", parallelism: int = 1,
             name: str = None) -> FlatMap:
    """The :func:`side_tagger` body packaged as a FlatMap operator:
    ``pipe.chain(tag_side(LEFT, key_col="seller"))``."""
    return FlatMap(side_tagger(side, key_of, key_col, value_col),
                   parallelism=parallelism,
                   name=name or ("tag_left" if side == LEFT
                                 else "tag_right"))


class _JoinLogicBase(EventTimeLogic):
    """Shared: pair construction + join-state gauge."""

    def __init__(self, join_fn: Optional[Callable],
                 lateness: float = 0.0):
        super().__init__(lateness)
        self.join_fn = join_fn or (lambda l, r: (l, r))

    def _gauge(self):
        if self.stats is not None:
            self.stats.join_state_keys = len(self.state)


class IntervalJoinLogic(_JoinLogicBase):
    """State per key: ``{"L": [(ts, id, value)...], "R": [...]}``."""

    node_name = "interval_join"

    def __init__(self, lower: float, upper: float,
                 join_fn: Callable = None, lateness: float = 0.0):
        super().__init__(join_fn, lateness)
        self.lower = float(lower)
        self.upper = float(upper)

    def _evictable(self, side: int, ts: float, wm: float) -> bool:
        if side == LEFT:
            return ts + self.upper + self.lateness < wm
        return ts - self.lower + self.lateness < wm

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        side = item.side
        key, tid, ts = item.get_control_fields()
        ts = float(ts)
        if self._evictable(side, ts, self.wm):
            self._late(key, tid, ts, item.value)
            return
        st = self.state.get(key)
        if st is None:
            st = self.state[key] = {"L": [], "R": []}
        mine, other = ("L", "R") if side == LEFT else ("R", "L")
        st[mine].append((ts, tid, item.value))
        for ts2, tid2, val2 in st[other]:
            d = (ts2 - ts) if side == LEFT else (ts - ts2)
            if self.lower <= d <= self.upper:
                lv, rv = ((item.value, val2) if side == LEFT
                          else (val2, item.value))
                emit(BasicRecord(key, tid, max(ts, ts2),
                                 self.join_fn(lv, rv)))
        self._gauge()

    def on_watermark(self, wm, emit):
        if wm.ts > self.wm:
            self.wm = wm.ts
        w = self.wm
        for key in list(self.state.keys()):
            st = self.state.get(key)
            st["L"] = [r for r in st["L"]
                       if not self._evictable(LEFT, r[0], w)]
            st["R"] = [r for r in st["R"]
                       if not self._evictable(RIGHT, r[0], w)]
            if not st["L"] and not st["R"]:
                del self.state[key]
        self._gauge()


class WindowJoinLogic(_JoinLogicBase):
    """State per key: ``{win_start: [L_rows, R_rows]}``."""

    node_name = "window_join"

    def __init__(self, size: float, slide: float = None,
                 join_fn: Callable = None, lateness: float = 0.0):
        super().__init__(join_fn, lateness)
        self.size = float(size)
        self.slide = float(slide) if slide else float(size)

    def svc(self, item, channel_id, emit):
        if isinstance(item, EOSMarker):
            return
        side = item.side
        key, tid, ts = item.get_control_fields()
        ts = float(ts)
        horizon = self.size + self.lateness
        n_hi = math.floor(ts / self.slide)
        n_lo = math.floor((ts - self.size) / self.slide) + 1
        if self.wm >= n_hi * self.slide + horizon:
            self._late(key, tid, ts, item.value)
            return
        wins = self.state.get(key)
        if wins is None:
            wins = self.state[key] = {}
        for n in range(n_lo, n_hi + 1):
            s = n * self.slide
            if self.wm < s + horizon:
                wins.setdefault(s, [[], []])[side].append(
                    (ts, tid, item.value))
        self._gauge()

    def on_watermark(self, wm, emit):
        if wm.ts > self.wm:
            self.wm = wm.ts
        self._fire(self.wm, emit)

    def eos_flush(self, emit):
        self._fire(float("inf"), emit)

    def _fire(self, wm_ts, emit):
        horizon = self.size + self.lateness
        fired = []
        for key in list(self.state.keys()):
            wins = self.state.get(key)
            for s in [s for s in wins if s + horizon <= wm_ts]:
                fired.append((s, key, wins.pop(s)))
            if not wins:
                del self.state[key]
        self._gauge()
        fired.sort(key=lambda f: (f[0], f[1]))
        for s, key, (left, right) in fired:
            left.sort(key=lambda r: (r[0], r[1]))
            right.sort(key=lambda r: (r[0], r[1]))
            for ts_l, tid_l, lv in left:
                for ts_r, _tid_r, rv in right:
                    emit(BasicRecord(key, tid_l, s,
                                     self.join_fn(lv, rv)))


class _JoinOp(Operator):
    def __init__(self, name, parallelism):
        super().__init__(name, parallelism, RoutingMode.KEYBY,
                         Pattern.ACCUMULATOR)

    def _make_logic(self, i, n=None):
        raise NotImplementedError

    def stages(self):
        reps = [self._make_logic(i) for i in range(self.parallelism)]
        return [StageSpec(self.name, reps, StandardEmitter(keyed=True),
                          self.routing, ordering_mode=OrderingMode.TS)]

    def elastic_logic_factory(self):
        return self._make_logic


class IntervalJoin(_JoinOp):
    """Keyed interval join over a merged side-tagged stream: emit
    ``join_fn(l, r)`` when ``lower <= ts_r - ts_l <= upper``.  Use
    ``-inf/inf`` bounds for a full-history incremental join."""

    def __init__(self, lower: float, upper: float,
                 join_fn: Callable = None, lateness: float = 0.0,
                 parallelism: int = 1, name: str = "interval_join"):
        super().__init__(name, parallelism)
        self.lower = lower
        self.upper = upper
        self.join_fn = join_fn
        self.lateness = lateness

    def _make_logic(self, i, n=None):
        return IntervalJoinLogic(self.lower, self.upper, self.join_fn,
                                 self.lateness)


class WindowJoin(_JoinOp):
    """Keyed tumbling/sliding window join over a merged side-tagged
    stream: the per-window cross product of both sides fires at
    watermark passage."""

    def __init__(self, size: float, slide: float = None,
                 join_fn: Callable = None, lateness: float = 0.0,
                 parallelism: int = 1, name: str = "window_join"):
        super().__init__(name, parallelism)
        self.size = size
        self.slide = slide
        self.join_fn = join_fn
        self.lateness = lateness

    def _make_logic(self, i, n=None):
        return WindowJoinLogic(self.size, self.slide, self.join_fn,
                               self.lateness)
