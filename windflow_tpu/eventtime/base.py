"""Shared plumbing of the event-time relational plane.

Every operator in :mod:`windflow_tpu.eventtime` (watermark-triggered
windows, session windows, stream joins) is a keyed stateful logic fed
by the generic watermark transport in :mod:`windflow_tpu.runtime.node`:
the runtime min-merges per-producer ``Watermark`` items and hands every
*advanced* merged value to the logic's ``on_watermark(wm, emit)`` hook
before forwarding it downstream.  What the operators share lives here:

* :class:`EventTimeLogic` -- the keyed-state contract (checkpoint,
  tiered store, elastic repartition, census) lifted verbatim from
  ``AccumulatorLogic`` so event-time state composes with exactly-once
  epochs (durability/), the tiered store (state/) and runtime rescale
  (elastic/) without any special-casing, plus the **loud lateness
  policy**: a tuple arriving behind the allowed-lateness horizon is
  never silently dropped -- it lands in ``graph.dead_letters`` with a
  :class:`~windflow_tpu.runtime.ordering.LateTupleDropped` reason, a
  ``late_data`` flight event and the ``Late_tuples`` gauge.
* :func:`iter_rows` -- plane-agnostic row iteration (records or
  columnar ``TupleBatch``), so event-time operators sit downstream of
  either the record or the batch plane.

See docs/EVENTTIME.md for the semantics contract.
"""
from __future__ import annotations

from ..core.tuples import TupleBatch
from ..runtime.node import NodeLogic
from ..runtime.ordering import LateTupleDropped


def iter_rows(item):
    """Yield ``(key, tid, ts, value)`` rows from a record or a
    TupleBatch (ts as float -- event time is a real-valued axis)."""
    if isinstance(item, TupleBatch):
        key, tid, ts = item.key, item.id, item.ts
        val = item.cols.get("value")
        for i in range(len(item)):
            yield (int(key[i]), int(tid[i]), float(ts[i]),
                   None if val is None else float(val[i]))
    else:
        k, t, s = item.get_control_fields()
        yield (k, t, float(s), getattr(item, "value", None))


class EventTimeLogic(NodeLogic):
    """Base replica logic for the event-time plane: watermark scalar,
    allowed-lateness accounting and the full keyed-state contract."""

    # dead-letter binding marker (graph/pipegraph.py binds the graph
    # store + node name at start on any logic carrying this flag)
    uses_dead_letters = True
    dead_letters = None
    node_name = "eventtime"

    def __init__(self, lateness: float = 0.0):
        self.lateness = float(lateness)
        # last merged watermark observed by THIS replica; part of the
        # checkpointed state so a restored replica keeps detecting late
        # replays of windows it already fired (docs/EVENTTIME.md)
        self.wm = float("-inf")
        self.state: dict = {}

    # -- lateness policy ----------------------------------------------
    def _late(self, key, tid, ts, value) -> None:
        """A tuple behind the lateness horizon: account it loudly."""
        if self.stats is not None:
            self.stats.late_tuples += 1
        dl = self.dead_letters
        if dl is not None:
            dl.add(self.node_name, (key, tid, ts, value),
                   LateTupleDropped(
                       f"event-time ts {ts} behind watermark {self.wm} "
                       f"(allowed lateness {self.lateness})"))
        fl = self.flight
        if fl is not None:
            fl.record("late_data", node=self.node_name, n=1,
                      watermark=self.wm, ts=ts)

    # -- checkpoint hooks (durability/; utils/checkpoint.py) ----------
    def state_dict(self):
        st = self.state
        if hasattr(st, "materialize"):     # tiered store: inline copy
            st = st.materialize()
        return {"state": st, "wm": self.wm}

    def load_state(self, st):
        if hasattr(self.state, "replace_all"):
            self.state.replace_all(st["state"])
        else:
            self.state = st["state"]
        self.wm = st.get("wm", float("-inf"))

    # -- tiered keyed state (state/; docs/RESILIENCE.md) --------------
    def enable_tiered_state(self, store):
        store.replace_all(self.state)
        self.state = store

    def bind_hot_sketch(self, hot_keys_fn):
        if hasattr(self.state, "bind_hot_sketch"):
            self.state.bind_hot_sketch(hot_keys_fn)

    def state_tier_of(self, key):
        if hasattr(self.state, "tier_of"):
            return self.state.tier_of(key)
        return "hot" if key in self.state else None

    def keyed_state_pickled(self):
        if hasattr(self.state, "keyed_state_pickled"):
            return self.state.keyed_state_pickled()
        return None

    # -- keyed-state hooks (elastic/rescale.py) -----------------------
    def keyed_state_dict(self):
        st = self.state
        if hasattr(st, "materialize"):
            return st.materialize()
        return dict(st)

    def load_keyed_state(self, kv):
        if hasattr(self.state, "replace_all"):
            self.state.replace_all(kv)
        else:
            self.state = dict(kv)

    # -- audit-plane census (audit/census.py) -------------------------
    def keyed_state_census(self):
        state = self.state
        if hasattr(state, "census"):       # tiered: per-tier gauges
            return state.census()
        n = len(state)
        if n == 0:
            return (0, 0)
        import sys
        try:
            per = sys.getsizeof(next(iter(state.values()))) + 64
        except (RuntimeError, StopIteration):
            per = 64  # resized under us: count-only estimate
        return (n, n * per)
