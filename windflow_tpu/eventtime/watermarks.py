"""Watermark generation: the in-band event-time trigger.

A :class:`~windflow_tpu.runtime.queues.Watermark` is an ordinary
channel item carrying a promise -- "every future tuple on this stream
has event-time >= ts".  The runtime transports it generically
(broadcast over every emitter, per-node min-merge across producers,
ledger-balanced like epoch barriers); this module is where watermarks
are BORN: :func:`watermarked` wraps any shipper-style source body so it
punctuates its own output with periodic watermarks derived from the
maximum event-time it has shipped, and seals the stream with
``Watermark(inf)`` at end-of-stream so every downstream merge drains.

``watermark_of(source)`` (audit/progress.py) reads the wrapper's
current promise for dashboards and tests.

``skew`` may be the string ``"auto"``: the out-of-order bound is then
LEARNED from the observed lateness of the stream itself (the same
bounded-EWMA shape as the K-slack collector's adaptive K,
runtime/ordering.py) instead of being promised up front.
"""
from __future__ import annotations

from typing import Any, Callable, Union

from ..core.tuples import TupleBatch
from ..runtime.queues import Watermark

__all__ = ["Watermark", "WatermarkedSource", "watermarked"]

# bounded-EWMA constants for skew="auto" (mirroring KSlackLogic's K
# adaptation): the learned bound relaxes instantly to any observed
# lateness above it (never promise what the stream already broke) and
# tightens slowly below it, so one well-ordered stretch does not erase
# the memory of a bursty one
_SKEW_ALPHA = 0.25


class _TsShipper:
    """Shipper proxy tracking the max event-time of pushed items, plus
    the worst observed lateness (how far a pushed ts trailed the
    running max) for the adaptive-skew estimator."""

    __slots__ = ("_inner", "max_ts", "pushed", "max_late")

    def __init__(self, inner, prev_max: float = float("-inf")):
        self._inner = inner
        self.max_ts = prev_max
        self.pushed = 0
        self.max_late = 0.0

    def push(self, item: Any) -> None:
        ts = None
        late = None
        if isinstance(item, TupleBatch):
            if len(item):
                ts = float(item.ts.max())
                # batch lateness: the oldest ts in the batch against
                # the newest seen so far (the columnar analogue of
                # KSlackLogic's per-batch ts.min() sample)
                late = max(self.max_ts, ts) - float(item.ts.min())
        else:
            try:
                ts = float(item.get_control_fields()[2])
            except (AttributeError, TypeError):
                pass  # ts-less control item
            if ts is not None:
                late = self.max_ts - ts
        if ts is not None and ts > self.max_ts:
            self.max_ts = ts
        if late is not None and late > self.max_late:
            self.max_late = late
        self.pushed += 1
        self._inner.push(item)

    def num_delivered(self) -> int:
        return self.pushed


class WatermarkedSource:
    """Source-body wrapper that punctuates its stream with watermarks.

    ``fn(shipper) -> bool`` is the wrapped shipper-style source body
    (SourceBuilder convention: push 0..N records, return False at end
    of stream).  Every ``every`` shipped tuples the wrapper emits
    ``Watermark(max_shipped_ts - skew)``; ``skew`` is the out-of-order
    bound the source promises (a tuple may trail the newest one by at
    most ``skew`` time units).  At end of stream it emits
    ``Watermark(inf)`` so downstream merges drain every open window.

    ``skew="auto"`` learns the bound instead: every generation step
    measures how far pushed tuples trailed the running max event-time,
    and the bound follows a bounded EWMA of that lateness -- jumping
    straight UP to any observed lateness above it (a promise already
    violated is worthless) and decaying DOWN slowly.  Each meaningful
    adjustment is recorded loudly as a ``skew_adapted`` flight event
    (telemetry/recorder.py) so an operator can see the source revising
    its disorder estimate.

    One instance drives ONE source replica -- the wrapper is stateful
    (shipped-count, max-ts, current promise), so watermarked sources
    run with parallelism 1 or one distinct instance per replica.

    Checkpoint contract (durability/): the wrapper's own counters ride
    ``state_dict`` next to the wrapped body's (when it has one), so an
    exactly-once restore resumes the watermark clock consistently with
    the replayed offset.
    """

    # PipeGraph.start binds the graph's flight recorder + node name to
    # any source body advertising _wants_flight (the builder call chain
    # never sees the graph)
    _wants_flight = True
    flight = None
    source_name = "watermarked"

    def __init__(self, fn: Callable, every: int = 64,
                 skew: Union[float, str] = 0.0):
        self.fn = fn
        self.every = int(every)
        self.auto_skew = skew == "auto"
        self.skew = 0.0 if self.auto_skew else float(skew)
        self._max_ts = float("-inf")
        self._since = 0
        self._wm = float("-inf")
        self._done = False

    @property
    def current_watermark(self) -> float:
        """The newest promise this source has emitted
        (``watermark_of`` reads this)."""
        return self._wm

    def _adapt_skew(self, observed: float) -> None:
        old = self.skew
        if observed > old:
            new = observed          # violated bound: jump to cover it
        else:
            new = old + _SKEW_ALPHA * (observed - old)  # decay slowly
        if new == old:
            return
        self.skew = new
        # loud only on meaningful moves: >=10% relative (or any jump
        # from zero), so the steady-state decay trickle stays quiet
        if self.flight is not None and (
                old == 0.0 or abs(new - old) >= 0.1 * old):
            self.flight.record("skew_adapted", source=self.source_name,
                               old=round(old, 6), new=round(new, 6),
                               observed=round(observed, 6))

    def __call__(self, shipper) -> bool:
        if self._done:
            return False
        proxy = _TsShipper(shipper, prev_max=self._max_ts)
        alive = self.fn(proxy)
        if proxy.max_ts > self._max_ts:
            self._max_ts = proxy.max_ts
        if self.auto_skew and proxy.pushed:
            self._adapt_skew(proxy.max_late)
        if not alive:
            self._done = True
            self._wm = float("inf")
            shipper.push(Watermark(float("inf")))
            return False
        self._since += proxy.pushed
        if self._since >= self.every and self._max_ts > float("-inf"):
            self._since = 0
            wm = self._max_ts - self.skew
            if wm > self._wm:
                self._wm = wm
                shipper.push(Watermark(wm))
        return True

    # -- checkpoint hooks: delegate to the wrapped body and stack the
    # watermark clock on top (durability/barrier.capture_states probes
    # the SOURCE LOGIC's state_dict, which closes over the callable;
    # SourceBuilder users get this through _WmSourceLogic in tests or
    # their own SourceLoopLogic subclass) -----------------------------
    def state_dict(self):
        inner = getattr(self.fn, "state_dict", None)
        return {
            "inner": inner() if inner is not None else None,
            "max_ts": self._max_ts, "since": self._since,
            "wm": self._wm, "done": self._done,
            "skew": self.skew, "auto_skew": self.auto_skew,
        }

    def load_state(self, st):
        if st.get("inner") is not None:
            self.fn.load_state(st["inner"])
        self._max_ts = st["max_ts"]
        self._since = st["since"]
        self._wm = st["wm"]
        self._done = st["done"]
        # pre-adaptive snapshots lack the skew keys: keep the
        # constructor's bound
        self.skew = st.get("skew", self.skew)
        self.auto_skew = st.get("auto_skew", self.auto_skew)


def watermarked(fn: Callable, every: int = 64,
                skew: Union[float, str] = 0.0) -> WatermarkedSource:
    """Wrap a shipper-style source body so it emits watermarks:
    ``SourceBuilder(watermarked(body, every=32)).build()`` --
    ``skew="auto"`` learns the out-of-order bound from observed
    lateness instead of promising a static one."""
    return WatermarkedSource(fn, every=every, skew=skew)
