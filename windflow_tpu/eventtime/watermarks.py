"""Watermark generation: the in-band event-time trigger.

A :class:`~windflow_tpu.runtime.queues.Watermark` is an ordinary
channel item carrying a promise -- "every future tuple on this stream
has event-time >= ts".  The runtime transports it generically
(broadcast over every emitter, per-node min-merge across producers,
ledger-balanced like epoch barriers); this module is where watermarks
are BORN: :func:`watermarked` wraps any shipper-style source body so it
punctuates its own output with periodic watermarks derived from the
maximum event-time it has shipped, and seals the stream with
``Watermark(inf)`` at end-of-stream so every downstream merge drains.

``watermark_of(source)`` (audit/progress.py) reads the wrapper's
current promise for dashboards and tests.
"""
from __future__ import annotations

from typing import Any, Callable

from ..core.tuples import TupleBatch
from ..runtime.queues import Watermark

__all__ = ["Watermark", "WatermarkedSource", "watermarked"]


class _TsShipper:
    """Shipper proxy tracking the max event-time of pushed items."""

    __slots__ = ("_inner", "max_ts", "pushed")

    def __init__(self, inner):
        self._inner = inner
        self.max_ts = float("-inf")
        self.pushed = 0

    def push(self, item: Any) -> None:
        ts = None
        if isinstance(item, TupleBatch):
            if len(item):
                ts = float(item.ts.max())
        else:
            try:
                ts = float(item.get_control_fields()[2])
            except (AttributeError, TypeError):
                pass  # ts-less control item
        if ts is not None and ts > self.max_ts:
            self.max_ts = ts
        self.pushed += 1
        self._inner.push(item)

    def num_delivered(self) -> int:
        return self.pushed


class WatermarkedSource:
    """Source-body wrapper that punctuates its stream with watermarks.

    ``fn(shipper) -> bool`` is the wrapped shipper-style source body
    (SourceBuilder convention: push 0..N records, return False at end
    of stream).  Every ``every`` shipped tuples the wrapper emits
    ``Watermark(max_shipped_ts - skew)``; ``skew`` is the out-of-order
    bound the source promises (a tuple may trail the newest one by at
    most ``skew`` time units).  At end of stream it emits
    ``Watermark(inf)`` so downstream merges drain every open window.

    One instance drives ONE source replica -- the wrapper is stateful
    (shipped-count, max-ts, current promise), so watermarked sources
    run with parallelism 1 or one distinct instance per replica.

    Checkpoint contract (durability/): the wrapper's own counters ride
    ``state_dict`` next to the wrapped body's (when it has one), so an
    exactly-once restore resumes the watermark clock consistently with
    the replayed offset.
    """

    def __init__(self, fn: Callable, every: int = 64, skew: float = 0.0):
        self.fn = fn
        self.every = int(every)
        self.skew = float(skew)
        self._max_ts = float("-inf")
        self._since = 0
        self._wm = float("-inf")
        self._done = False

    @property
    def current_watermark(self) -> float:
        """The newest promise this source has emitted
        (``watermark_of`` reads this)."""
        return self._wm

    def __call__(self, shipper) -> bool:
        if self._done:
            return False
        proxy = _TsShipper(shipper)
        alive = self.fn(proxy)
        if proxy.max_ts > self._max_ts:
            self._max_ts = proxy.max_ts
        if not alive:
            self._done = True
            self._wm = float("inf")
            shipper.push(Watermark(float("inf")))
            return False
        self._since += proxy.pushed
        if self._since >= self.every and self._max_ts > float("-inf"):
            self._since = 0
            wm = self._max_ts - self.skew
            if wm > self._wm:
                self._wm = wm
                shipper.push(Watermark(wm))
        return True

    # -- checkpoint hooks: delegate to the wrapped body and stack the
    # watermark clock on top (durability/barrier.capture_states probes
    # the SOURCE LOGIC's state_dict, which closes over the callable;
    # SourceBuilder users get this through _WmSourceLogic in tests or
    # their own SourceLoopLogic subclass) -----------------------------
    def state_dict(self):
        inner = getattr(self.fn, "state_dict", None)
        return {
            "inner": inner() if inner is not None else None,
            "max_ts": self._max_ts, "since": self._since,
            "wm": self._wm, "done": self._done,
        }

    def load_state(self, st):
        if st.get("inner") is not None:
            self.fn.load_state(st["inner"])
        self._max_ts = st["max_ts"]
        self._since = st["since"]
        self._wm = st["wm"]
        self._done = st["done"]


def watermarked(fn: Callable, every: int = 64,
                skew: float = 0.0) -> WatermarkedSource:
    """Wrap a shipper-style source body so it emits watermarks:
    ``SourceBuilder(watermarked(body, every=32)).build()``."""
    return WatermarkedSource(fn, every=every, skew=skew)
