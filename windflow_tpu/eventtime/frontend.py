"""Declarative frontend: select / where / window / join combinators.

A thin relational veneer over the MultiPipe algebra so event-time
queries read like the NexMark prose (docs/EVENTTIME.md "Declarative
frontend").  Each combinator appends the corresponding operator to the
wrapped pipe and returns the query, so pipelines compose left to
right::

    q = wf.query(g.add_source(src))
    (q.where(lambda t: t.value > 0)
      .select(lambda t: setattr(t, "value", t.value * RATE))
      .window(sum, size=10)
      .sink(collect))

Joins take a second query and compile the merge + side-tagging
plumbing of :mod:`windflow_tpu.eventtime.joins` automatically.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..operators.basic_ops import Filter, Map, Sink
from .joins import (LEFT, RIGHT, IntervalJoin, WindowJoin, tag_side)
from .sessions import SessionWindow
from .windows import EventTimeWindow

__all__ = ["StreamQuery", "query"]


class StreamQuery:
    """A MultiPipe wrapped with relational combinators."""

    def __init__(self, pipe):
        self.pipe = pipe

    # -- stateless relational ops ------------------------------------
    def where(self, pred: Callable, parallelism: int = 1,
              name: str = "where") -> "StreamQuery":
        self.pipe.chain(Filter(pred, parallelism=parallelism, name=name))
        return self

    def select(self, fn: Callable, parallelism: int = 1,
               name: str = "select") -> "StreamQuery":
        self.pipe.chain(Map(fn, parallelism=parallelism, name=name))
        return self

    # -- event-time windows ------------------------------------------
    def window(self, agg: Callable, size: float, slide: float = None,
               lateness: float = 0.0, parallelism: int = 1,
               name: str = "window") -> "StreamQuery":
        self.pipe.add(EventTimeWindow(agg, size, slide, lateness,
                                      parallelism, name))
        return self

    def session(self, agg: Callable, gap: float, lateness: float = 0.0,
                parallelism: int = 1,
                name: str = "session") -> "StreamQuery":
        self.pipe.add(SessionWindow(agg, gap, lateness, parallelism,
                                    name))
        return self

    # -- two-input joins ---------------------------------------------
    def join(self, other: "StreamQuery", *,
             size: float = None, slide: float = None,
             lower: float = None, upper: float = None,
             join_fn: Callable = None, lateness: float = 0.0,
             parallelism: int = 1, key_of: Callable = None,
             other_key_of: Callable = None, key_col: str = None,
             other_key_col: str = None,
             name: str = "join") -> "StreamQuery":
        """Windowed join (``size=``) or interval join (``lower=`` /
        ``upper=``) of this query (LEFT) with ``other`` (RIGHT),
        re-keying either side on the join key via ``key_of`` (record
        plane) or ``key_col`` (batch plane)."""
        windowed = size is not None
        if windowed == (lower is not None or upper is not None):
            raise ValueError(
                "join() needs exactly one of size= (window join) or "
                "lower=/upper= (interval join)")
        self.pipe.chain(tag_side(LEFT, key_of=key_of, key_col=key_col,
                                 name=f"{name}_tag_left"))
        other.pipe.chain(tag_side(RIGHT, key_of=other_key_of,
                                  key_col=other_key_col,
                                  name=f"{name}_tag_right"))
        merged = self.pipe.merge(other.pipe)
        if windowed:
            merged.add(WindowJoin(size, slide, join_fn, lateness,
                                  parallelism, name))
        else:
            merged.add(IntervalJoin(
                float("-inf") if lower is None else lower,
                float("inf") if upper is None else upper,
                join_fn, lateness, parallelism, name))
        return StreamQuery(merged)

    # -- terminal ------------------------------------------------------
    def sink(self, fn: Callable, parallelism: int = 1,
             name: str = "sink") -> "StreamQuery":
        self.pipe.add_sink(Sink(fn, parallelism=parallelism, name=name))
        return self


def query(pipe) -> StreamQuery:
    """Wrap a sourced MultiPipe in the declarative combinators."""
    return StreamQuery(pipe)
