"""Keyed-state census + hot-key skew sketch
(docs/OBSERVABILITY.md "Keyed-state census").

Two independent skew views:

* **State census** -- each replica whose logic implements
  ``keyed_state_census()`` (AccumulatorLogic's fold store, the device
  window engines' per-key window state) reports ``(key_count,
  bytes_estimate)`` as a lock-free gauge read; rows land in the stats
  JSON ``Skew.Census`` table.
* **Hot-key sketch** -- a space-saving top-K sketch (Metwally et al.,
  the classic bounded heavy-hitters structure) attached to every KEYBY
  ``StandardEmitter``.  The batch plane offers one sampled
  ``np.unique`` per S batches (default 1-in-8), the record plane one
  sampled key per 16 items, so the hot path pays a counter test.  The
  top-1 share is the **skew signal** the elastic plane reads: a 0.9
  share means scaling out cannot help -- one replica owns the hot key
  no matter the parallelism.
"""
from __future__ import annotations

from typing import Dict, List, Optional

# sampling strides (the sketch estimates shares, not exact counts)
BATCH_SAMPLE = 8
SCALAR_SAMPLE = 16


class SpaceSavingSketch:
    """Bounded top-K heavy hitters.  Single-writer (the emitting
    thread); the auditor snapshots ``counts`` via ``dict()`` (atomic
    under the GIL)."""

    __slots__ = ("k", "counts", "errs", "total", "_batches", "_items")

    def __init__(self, k: int = 16):
        self.k = max(1, int(k))
        self.counts: Dict = {}
        self.errs: Dict = {}
        self.total = 0
        self._batches = 0
        self._items = 0

    # -- hot-path offers ----------------------------------------------
    def offer_batch(self, keys) -> None:
        """Columnar KEYBY path: sampled per-batch key histogram."""
        self._batches += 1
        if self._batches % BATCH_SAMPLE:
            return
        import numpy as np
        u, c = np.unique(keys, return_counts=True)
        for key, cnt in zip(u.tolist(), c.tolist()):
            self._offer(key, cnt * BATCH_SAMPLE)

    def offer(self, key) -> None:
        """Record KEYBY path: sampled 1-in-N scalar offer."""
        self._items += 1
        if self._items % SCALAR_SAMPLE:
            return
        self._offer(key, SCALAR_SAMPLE)

    def _offer(self, key, w: int) -> None:
        self.total += w
        counts = self.counts
        cur = counts.get(key)
        if cur is not None:
            counts[key] = cur + w
            return
        if len(counts) < self.k:
            counts[key] = w
            self.errs[key] = 0
            return
        # space-saving eviction: replace the current minimum, carrying
        # its count as the newcomer's overestimation error
        victim = min(counts, key=counts.get)
        floor = counts.pop(victim)
        self.errs.pop(victim, None)
        counts[key] = floor + w
        self.errs[key] = floor

    # -- reads ---------------------------------------------------------
    def top(self, n: Optional[int] = None) -> List[list]:
        counts = dict(self.counts)
        errs = dict(self.errs)
        rows = sorted(counts.items(), key=lambda kv: -kv[1])
        if n is not None:
            rows = rows[:n]
        return [[k, c, errs.get(k, 0)] for k, c in rows]

    def top_share(self) -> float:
        """Estimated share of the hottest key in the observed stream."""
        if not self.counts or not self.total:
            return 0.0
        key, cnt = max(self.counts.items(), key=lambda kv: kv[1])
        cnt -= self.errs.get(key, 0)  # conservative: strip overcount
        return max(0.0, min(1.0, cnt / self.total))


def take_census(nodes) -> List[dict]:
    """Per-replica keyed-state rows from the ``keyed_state_census``
    hooks (fused nodes report per segment under original names).  A
    hook may return ``(keys, bytes)`` or -- tiered stores
    (state/tiers.py) -- ``(keys, bytes, extras)`` where ``extras``
    carries per-tier splits and spill/promotion/shed counters that
    land verbatim on the row."""
    from ..runtime.node import FusedLogic
    rows: List[dict] = []

    def probe(logic, name):
        fn = getattr(logic, "keyed_state_census", None)
        if fn is None:
            return
        try:
            got = fn()
        except (RuntimeError, TypeError):
            return
        if got is None:
            return
        keys, nbytes = got[0], got[1]
        row = {"replica": name, "keys": int(keys),
               "bytes_est": int(nbytes)}
        if len(got) > 2 and isinstance(got[2], dict):
            row.update(got[2])
        rows.append(row)

    for n in nodes:
        if isinstance(n.logic, FusedLogic):
            for seg in n.logic.segments:
                probe(seg.logic, seg.name)
        else:
            probe(n.logic, n.name)
    return rows
