"""Audit plane: online flow-conservation ledger, progress/frontier
tracking, and keyed-state skew census (docs/OBSERVABILITY.md).

The telemetry plane (PR 7) lets an operator see how *fast* the runtime
is; this package lets the runtime observe its own *correctness* while
it runs.  Three pillars, one :class:`GraphAuditor` thread per graph
(``RuntimeConfig.audit``, on by default):

* **Flow-conservation ledger** (ledger.py) -- every channel edge keeps
  two independent delivery books (producer intent at the Outlet layer
  vs the channel's own put/get counters, both planes + CreditedChannel
  proxies), folded with admission sheds, dead letters, in-flight
  device batches and elastic-rescale migrations; a periodic graph-wide
  pass (and an exact closure check at ``wait_end``) proves per-edge
  ``sent == delivered == enqueued == dequeued + depth``.  Violations
  land in the FlightRecorder (``conservation_violation``), the stats
  JSON ``Conservation`` block and ``/metrics``.
* **Progress/frontier tracking** (progress.py) -- per-source monotone
  frontiers (replay offset / synth index / emitted position)
  propagated topologically as min-over-inputs low-watermarks through
  operators, fused segments and KEYBY shuffles; per-operator
  ``Frontier`` / ``Frontier_lag_ms`` gauges and a stalled-frontier
  detector (``frontier_stall`` flight events) -- the groundwork
  event-time triggering (ROADMAP item 4) will stand on.
* **Keyed-state census** (census.py) -- per-replica key counts + byte
  estimates from the ``keyed_state_census`` hooks, plus a space-saving
  top-K hot-key sketch on the KEYBY emitters, rendered as a ``Skew``
  block and exposed to the elastic controller as a skew signal.
"""
from .auditor import GraphAuditor
from .census import SpaceSavingSketch
from .ledger import EdgeCell, FlowLedger
from .progress import FrontierTracker

__all__ = [
    "GraphAuditor",
    "EdgeCell", "FlowLedger",
    "FrontierTracker",
    "SpaceSavingSketch",
]
