"""Flow-conservation ledger: per-edge delivery books and the checks
that prove them (docs/OBSERVABILITY.md "Audit plane").

The runtime already counts per-channel ``puts``/``gets``/``depth`` on
both channel planes (runtime/queues.py:71-74, runtime/native.py:206-210,
forwarded by the CreditedChannel proxies).  This module promotes those
counters into a two-book ledger per edge:

* the **producer book** lives in :class:`EdgeCell` objects attached to
  every Outlet destination: ``sent`` is incremented immediately before
  the channel ``put`` (the intent), ``delivered`` immediately after it
  returns, and ``inflight`` is True in between.  Cells are written only
  by the node's single emitting thread, so plain int adds suffice and
  ``sent - delivered`` is exactly the one item currently mid-put (or a
  bulk run mid-``put_many``) -- anything more is a lost delivery.
* the **channel book** is the channel's own ``puts`` counter plus the
  consumer side (``gets`` + residual ``depth``).

The per-edge conservation equation the auditor proves online (and
exactly at ``wait_end``)::

    sum(sent) == sum(delivered) == puts == gets + depth      (per edge)

which composes graph-wide into the ledger identity::

    sources_emitted == sinks_consumed + dead_letters + sheds + in_flight

for the transport plane (operator-level expansion/absorption -- maps,
filters, window folds -- happens *inside* nodes, between edges, and is
accounted by the per-node ``taken``/``done``/shed/dead-letter
counters).

False-positive discipline: every online rule is gated on the
``inflight`` flags, so a producer legitimately blocked mid-put (full
channel, exhausted credits, a descheduled thread) is never reported;
an injected ``drop_put``/``dup_put`` fault (resilience/faults.py)
diverges the two books permanently and is flagged on the first audit
pass that observes the edge quiet (in practice: within one interval).
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional

# per-edge rows kept in the stats-JSON Conservation block
MAX_EDGE_ROWS = 64
# violations kept in the block (the full list stays on the auditor)
MAX_VIOLATION_ROWS = 32


class EdgeCell:
    """Producer-side delivery books for one (outlet, destination) pair.
    Single-writer (the owning node's emitting thread); read lock-free
    by the auditor."""

    __slots__ = ("sent", "delivered", "inflight")

    def __init__(self):
        self.sent = 0
        self.delivered = 0
        self.inflight = False


def unwrap(ch):
    """The raw channel under a CreditedChannel proxy (the ledger keys
    edges by the physical channel; producers may hold the proxy while
    the consumer holds the same proxy object, or vice versa)."""
    return getattr(ch, "inner", ch)


class _Edge:
    """One audit pass's view of a channel edge."""

    __slots__ = ("key", "channel", "consumer", "cells")

    def __init__(self, key, channel, consumer):
        self.key = key
        self.channel = channel
        self.consumer = consumer          # RtNode or None (untracked)
        self.cells = []                   # (producer RtNode, EdgeCell)


def _op_of(node_name: str) -> str:
    """Operator name of a replica node name ('pipe0/map.1' -> 'pipe0/map')."""
    head, _, tail = node_name.rpartition(".")
    return head if head and tail.isdigit() else node_name


class FlowLedger:
    """Owns cell attachment, the per-pass topology snapshot and the
    conservation checks.  One per GraphAuditor."""

    def __init__(self, graph):
        self.graph = graph
        # channel-key -> (delivered, sent, producers) folded from
        # retired elastic replicas (their cells leave the topology when
        # the rescale removes the node, but the channel's cumulative
        # puts keep their history)
        self.retired: Dict[int, List[int]] = {}
        # deliveries a SOURCE node made into channels that later left
        # the topology (scale-down trims the upstream fan-out): the
        # graph-wide Sources_emitted roll-up must keep counting them
        self.retired_source_sent = 0
        # report-once state: (id(cell)|edge key, kind) -> count reported
        self._reported: Dict[tuple, int] = {}

    # -- attachment ----------------------------------------------------
    def attach_node(self, node) -> None:
        """Give every outlet destination of ``node`` a fresh EdgeCell.
        (Put-fault binding is the runtime's job --
        ``RtNode.bind_outlet_faults`` -- so an injected drop_put /
        dup_put fires with or without the ledger books.)"""
        for o in node.outlets:
            if o.audit_cells is None:
                o.audit_cells = [EdgeCell() for _ in o.dests]
            elif len(o.audit_cells) != len(o.dests):
                # defensive: align after an unmirrored dests mutation
                while len(o.audit_cells) < len(o.dests):
                    o.audit_cells.append(EdgeCell())
                del o.audit_cells[len(o.dests):]

    def fold_trimmed(self, outlet, cells) -> None:
        """Scale-down trims ``outlet.dests[new_n:]``: the trimmed
        edges vanish with their (drained) channels, but a source's
        deliveries into them stay part of Sources_emitted."""
        for n in self.graph._all_nodes():
            if outlet in n.outlets:
                if n.channel is None:
                    self.retired_source_sent += sum(c.sent
                                                    for c in cells)
                return

    def fold_retired(self, node) -> None:
        """Fold a retiring replica's delivery books into the per-channel
        retired ledger before the rescale drops the node from the
        topology -- without this, every scale-down would leave
        ``puts > sum(delivered)`` on the downstream edges forever (a
        false duplication)."""
        for o in node.outlets:
            cells = o.audit_cells
            if cells is None:
                continue
            for (ch, _pid), cell in zip(o.dests, cells):
                raw = unwrap(ch)
                # the 4th slot PINS the channel object: entries are
                # keyed by id(), and a freed channel's address could
                # otherwise be reused by a later rescale's fresh
                # channel, which would inherit the dead books
                acc = self.retired.setdefault(id(raw), [0, 0, 0, raw])
                acc[0] += cell.delivered
                acc[1] += cell.sent
                acc[2] += 1

    # -- topology snapshot ---------------------------------------------
    def edges(self, nodes=None) -> List[_Edge]:
        graph = self.graph
        if nodes is None:
            nodes = graph._all_nodes()
        owner = {}
        for n in nodes:
            if n.channel is not None:
                owner[id(unwrap(n.channel))] = n
        table: Dict[int, _Edge] = {}
        for n in nodes:
            for o in n.outlets:
                cells = o.audit_cells
                if cells is None:
                    continue
                for di, (ch, _pid) in enumerate(o.dests):
                    if di >= len(cells):
                        continue  # mid-rescale append; next pass sees it
                    k = id(unwrap(ch))
                    e = table.get(k)
                    if e is None:
                        e = table[k] = _Edge(k, ch, owner.get(k))
                    e.cells.append((n, cells[di]))
        return list(table.values())

    # -- checks --------------------------------------------------------
    def _edge_name(self, edge: _Edge) -> str:
        if edge.consumer is not None:
            return edge.consumer.name
        # distributed plane: a wire sender names its edge after the
        # remote consumer it feeds (distributed/transport.py)
        name = getattr(edge.channel, "edge_name", None)
        if name is not None:
            return name
        return f"channel@{edge.key:x}"

    def _report(self, key: tuple, count: int, make) -> Optional[dict]:
        """Report-once-per-level: a violation is (re-)emitted only when
        its count grows past what was already reported."""
        prev = self._reported.get(key, 0)
        if count <= prev:
            return None
        self._reported[key] = count
        v = make(count)
        v["at"] = round(_time.time(), 6)
        return v

    def check_pass(self, edges: List[_Edge]) -> List[dict]:
        """One online conservation pass; returns NEW violations."""
        out: List[dict] = []
        for edge in edges:
            ch = edge.channel
            name = self._edge_name(edge)
            # channel book FIRST (an enqueue between the two reads can
            # only make P stale-low, never inflate the dup gap)
            puts = getattr(ch, "puts", 0)
            delivered = sent = 0
            any_inflight = False
            for prod, cell in edge.cells:
                # read order is load-bearing: sent, THEN inflight, THEN
                # delivered.  The producer's cycle is inflight=True ->
                # sent++ -> put -> delivered++ -> inflight=False, so an
                # inflight==False read proves every cycle counted in
                # the earlier `sent` read has its delivered increment
                # visible to the LATER `delivered` read -- the gap can
                # only understate, never invent, a drop.  (Reading
                # delivered first would let a full producer cycle slip
                # between the reads and mint a permanent false
                # positive.)
                s = cell.sent
                infl = cell.inflight
                d = cell.delivered
                delivered += d
                sent += s
                any_inflight = any_inflight or infl
                gap = s - d
                if gap > 0 and not infl:
                    # the emitting thread is not mid-put, so the gap is
                    # not in transit: those deliveries were dropped
                    v = self._report(
                        (id(cell), "lost"), gap,
                        lambda c, _p=prod.name: {
                            "kind": "lost_delivery", "edge": name,
                            "producer": _p, "count": c})
                    if v is not None:
                        out.append(v)
            r = self.retired.get(edge.key)
            if r is not None:
                delivered += r[0]
                sent += r[1]
            n_prod = getattr(ch, "n_producers", None)
            covered = (n_prod is not None
                       and len(edge.cells) + (r[2] if r else 0) == n_prod)
            extra = puts - delivered
            if covered and extra > 0 and not any_inflight:
                v = self._report(
                    (edge.key, "extra"), extra,
                    lambda c: {"kind": "extra_delivery", "edge": name,
                               "count": c})
                if v is not None:
                    out.append(v)
        return out

    def final_check(self, edges: List[_Edge]) -> List[dict]:
        """Exact closure at a cleanly-ended graph: every thread joined,
        nothing in flight -- the books must balance to the tuple."""
        out: List[dict] = []
        for edge in edges:
            ch = edge.channel
            name = self._edge_name(edge)
            puts = getattr(ch, "puts", 0)
            gets = getattr(ch, "gets", 0)
            try:
                depth = ch.qsize()
            except (OSError, RuntimeError):
                depth = 0
            delivered = sent = 0
            for prod, cell in edge.cells:
                delivered += cell.delivered
                sent += cell.sent
                gap = cell.sent - cell.delivered
                if gap > 0:
                    v = self._report(
                        (id(cell), "lost"), gap,
                        lambda c, _p=prod.name: {
                            "kind": "lost_delivery", "edge": name,
                            "producer": _p, "count": c, "final": True})
                    if v is not None:
                        out.append(v)
            r = self.retired.get(edge.key)
            if r is not None:
                delivered += r[0]
                sent += r[1]
            n_prod = getattr(ch, "n_producers", None)
            covered = (n_prod is not None
                       and len(edge.cells) + (r[2] if r else 0) == n_prod)
            if covered and puts != delivered:
                kind = ("extra_delivery" if puts > delivered
                        else "channel_mismatch")
                v = self._report(
                    (edge.key, "extra"), abs(puts - delivered),
                    lambda c, _k=kind: {"kind": _k, "edge": name,
                                        "count": c, "final": True})
                if v is not None:
                    out.append(v)
            if depth != 0:
                v = self._report(
                    (edge.key, "residual"), depth,
                    lambda c: {"kind": "residual_items", "edge": name,
                               "count": c, "final": True})
                if v is not None:
                    out.append(v)
            elif gets + depth != puts:
                v = self._report(
                    (edge.key, "consumer"), abs(puts - gets - depth),
                    lambda c: {"kind": "consumer_loss", "edge": name,
                               "count": c, "final": True})
                if v is not None:
                    out.append(v)
        return out

    # -- reporting -----------------------------------------------------
    def conservation_block(self, edges: List[_Edge], nodes,
                           violations: List[dict], passes: int,
                           final: bool) -> dict:
        """The stats-JSON ``Conservation`` block: per-edge rows + the
        graph-wide ledger identity inputs."""
        graph = self.graph
        # rows are built for EVERY edge (the balance summary must not
        # depend on serialization truncation); only the first
        # MAX_EDGE_ROWS ship in the JSON
        rows = []
        for edge in edges:
            ch = edge.channel
            puts = getattr(ch, "puts", 0)
            gets = getattr(ch, "gets", 0)
            depth = getattr(ch, "depth", 0)
            delivered = sum(c.delivered for _n, c in edge.cells)
            sent = sum(c.sent for _n, c in edge.cells)
            r = self.retired.get(edge.key)
            if r is not None:
                delivered += r[0]
                sent += r[1]
            rows.append({
                "edge": self._edge_name(edge),
                "producers": len(edge.cells),
                "sent": sent, "delivered": delivered,
                "enqueued": puts, "dequeued": gets, "depth": depth,
                "balanced": (sent == delivered == puts
                             == gets + depth),
            })
        sources_emitted = self.retired_source_sent
        sinks_consumed = 0
        processing = 0
        device_batches = 0
        for n in nodes:
            if n.channel is None:
                for o in n.outlets:
                    if o.audit_cells:
                        sources_emitted += sum(c.sent
                                               for c in o.audit_cells)
                # durability plane: epoch barriers ride the same outlet
                # send path (so per-edge books balance by construction)
                # but are control items, not stream tuples -- the
                # graph-wide identity subtracts them on both ends
                sources_emitted -= getattr(n, "epoch_barriers_out", 0)
                # event-time plane: watermarks ride the same outlet
                # send path as barriers and get the same subtraction
                sources_emitted -= getattr(n, "watermarks_out", 0)
            elif not n.outlets:
                sinks_consumed += getattr(n.channel, "gets", 0)
                sinks_consumed -= getattr(n, "epoch_barriers_in", 0)
                sinks_consumed -= getattr(n, "watermarks_in", 0)
            processing += max(0, n.taken - n.done)
            probe = getattr(n.logic, "audit_in_flight", None)
            if probe is not None:
                try:
                    device_batches += int(probe().get("device_batches", 0))
                except (RuntimeError, TypeError, ValueError):
                    pass
        depth_total = sum(row["depth"] for row in rows)
        return {
            "Violations_total": len(violations),
            "Violations": violations[-MAX_VIOLATION_ROWS:],
            "Edges": rows[:MAX_EDGE_ROWS],
            "Edges_total": len(edges),
            "Edges_balanced": all(row["balanced"] for row in rows),
            "Sources_emitted": sources_emitted,
            "Sinks_consumed": sinks_consumed,
            "In_flight": {"channels": depth_total,
                          "processing": processing,
                          "device_batches": device_batches},
            "Shed_tuples": sum(
                r.tuples_shed
                for rs in list(graph.stats.records.values())
                for r in rs),
            "Dead_letters": graph.dead_letters.count(),
            "Audit_passes": passes,
            "Final_check": final,
        }
