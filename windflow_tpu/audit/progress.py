"""Progress/frontier tracking: Naiad-style low-watermarks over the
wired graph, computed passively from counters the runtime already
keeps (docs/OBSERVABILITY.md "Progress tracking").

Each source replica publishes a monotone **frontier** -- its transport
position (``NodeLogic.progress_frontier``: replay offset, socket raw
tuples, synth index) or, generically, the items it has shipped into
its outlet channels (the ledger's intent book, so no extra hot-path
counter exists).  Operators inherit the min over their inputs as a
**low-watermark**, but only advance it at instants where they are
provably caught up (empty inbound channel and between items:
``depth == 0 and taken == done``); otherwise the watermark holds and
its age becomes ``Frontier_lag_ms``.  Fused nodes are one consumer
(segments share the node's watermark); KEYBY shuffles are ordinary
multi-producer edges, so min-over-inputs covers them naturally.

The **stalled-frontier detector** flags an operator whose watermark
has not advanced for ``RuntimeConfig.frontier_stall_s`` while work is
pending (backlog or upstream ahead) and its own completion counter is
frozen -- the "could advance but does not" condition, distinct from
mere load (a busy-but-progressing operator re-stamps ``done`` every
pass and is never flagged).  Stalls are recorded once per episode as
``frontier_stall`` flight-recorder events and feed the watchdog's
stall report.
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional

from .ledger import unwrap


def source_frontier(node) -> float:
    """The monotone position of a source node: the logic's own
    ``progress_frontier`` hook when it defines one (seen through
    fusion/chaining wrappers), else the ledger intent book."""
    from ..runtime.node import ChainedLogic, FusedLogic
    logic = node.logic
    while True:
        if isinstance(logic, FusedLogic):
            logic = logic.segments[0].logic
        elif isinstance(logic, ChainedLogic):
            logic = logic.a
        else:
            break
    probe = getattr(logic, "progress_frontier", None)
    if probe is not None:
        try:
            v = probe()
        except (RuntimeError, TypeError):
            v = None
        if v is not None:
            return float(v)
    total = 0
    for o in node.outlets:
        cells = o.audit_cells
        if cells:
            total += sum(c.sent for c in cells)
    return float(total)


def watermark_of(source) -> float:
    """The current EVENT-TIME promise of a source (eventtime/;
    docs/EVENTTIME.md) -- distinct from the transport frontier above,
    which counts items, not event time.

    Accepts a :class:`~windflow_tpu.eventtime.watermarks.
    WatermarkedSource` (or anything exposing ``current_watermark``),
    a running RtNode (its last min-merged outbound watermark), or any
    node as a fallback through :func:`source_frontier`.  Returns
    ``-inf`` before the first promise."""
    wm = getattr(source, "current_watermark", None)
    if wm is not None:
        return float(wm)
    out = getattr(source, "_wm_out_ts", None)
    if out is not None and out > float("-inf"):
        return float(out)
    if hasattr(source, "outlets"):
        return source_frontier(source)
    return float("-inf")


class _Progress:
    __slots__ = ("wm", "wm_t", "last_done", "stall_reported")

    def __init__(self, now: float):
        self.wm = 0.0
        self.wm_t = now
        self.last_done = -1
        self.stall_reported = False


class FrontierTracker:
    """Per-graph watermark state across audit passes."""

    def __init__(self, stall_s: float):
        self.stall_s = stall_s
        self._state: Dict[str, _Progress] = {}
        # latest per-node view: name -> {frontier, lag_ms, stalled}
        self.frontiers: Dict[str, dict] = {}

    def update(self, nodes, now: Optional[float] = None) -> List[dict]:
        """One propagation pass; returns NEW stall events."""
        if now is None:
            now = _time.monotonic()
        # producer adjacency over the live topology (rebuilt per pass:
        # elastic rescales rewire channels at runtime)
        owner = {}
        for n in nodes:
            if n.channel is not None:
                owner[id(unwrap(n.channel))] = n
        producers: Dict[int, List] = {id(n): [] for n in nodes}
        indeg: Dict[int, int] = {id(n): 0 for n in nodes}
        consumers_of: Dict[int, List] = {id(n): [] for n in nodes}
        for n in nodes:
            seen = set()
            for o in n.outlets:
                for ch, _pid in o.dests:
                    c = owner.get(id(unwrap(ch)))
                    if c is None or id(c) in seen or c is n:
                        continue
                    seen.add(id(c))
                    producers[id(c)].append(n)
                    consumers_of[id(n)].append(c)
                    indeg[id(c)] += 1
        # Kahn topological order (the wired graph is a DAG)
        order = [n for n in nodes if indeg[id(n)] == 0]
        qi = 0
        while qi < len(order):
            n = order[qi]
            qi += 1
            for c in consumers_of[id(n)]:
                indeg[id(c)] -= 1
                if indeg[id(c)] == 0:
                    order.append(c)
        stalls: List[dict] = []
        wms: Dict[int, float] = {}
        for n in order:
            st = self._state.get(n.name)
            if st is None:
                st = self._state[n.name] = _Progress(now)
            ups = producers[id(n)]
            if n.channel is None and not ups:
                wm = source_frontier(n)
                if wm > st.wm:
                    st.wm = wm
                    st.wm_t = now
                    st.stall_reported = False
                pending = False
            else:
                cand = min((wms.get(id(p), 0.0) for p in ups),
                           default=st.wm)
                depth = getattr(n.channel, "depth", 0) \
                    if n.channel is not None else 0
                # durability plane: items parked in a barrier aligner's
                # holdback buffer are unprocessed input even though
                # they were dequeued (depth 0) and never taken
                aligner = getattr(n, "epochs", None)
                caught_up = depth == 0 and n.taken == n.done \
                    and (aligner is None or not aligner.busy)
                if caught_up and cand > st.wm:
                    st.wm = cand
                    st.wm_t = now
                    st.stall_reported = False
                pending = (not caught_up) or cand > st.wm
            wms[id(n)] = st.wm
            lag_ms = (now - st.wm_t) * 1e3 if pending else 0.0
            done = n.done
            if (pending and not st.stall_reported
                    and now - st.wm_t > self.stall_s
                    and done == st.last_done and n.is_alive()):
                st.stall_reported = True
                stalls.append({"node": n.name,
                               "frontier": round(st.wm, 1),
                               "lag_ms": round(lag_ms, 1)})
            st.last_done = done
            self.frontiers[n.name] = {
                "frontier": st.wm,
                "lag_ms": lag_ms,
                "stalled": st.stall_reported,
            }
            # gauge export: the replica's stats record (fused nodes
            # attribute to their first segment, like refresh_gauges)
            rec = n.stats
            if rec is None:
                segs = getattr(n.logic, "segments", None)
                if segs:
                    rec = segs[0].stats
            if rec is not None:
                rec.frontier = st.wm
                rec.frontier_lag_ms = lag_ms
        return stalls
