"""GraphAuditor: the audit-plane thread (docs/OBSERVABILITY.md).

One per started PipeGraph when ``RuntimeConfig.audit`` is on (the
default).  Every ``audit_interval_s`` it snapshots the live topology
(rebuilt per pass, so elastic rescales are transparent) and runs the
three pillars -- flow-conservation ledger, frontier propagation,
keyed-state/skew census -- then publishes:

* violations -> ``conservation_violation`` flight-recorder events +
  the auditor's ``violations`` list,
* frontier stalls -> ``frontier_stall`` flight events + per-replica
  ``Frontier``/``Frontier_lag_ms`` gauges,
* the ``Conservation`` and ``Skew`` stats-JSON blocks
  (GraphStats.set_audit), scraped onward by ``/metrics``,
* ``op_skew`` (top-key share per KEYBY-fed operator) for the elastic
  signal plane.

``final_check()`` runs at ``wait_end`` on cleanly-ended graphs: with
every replica joined, the books must balance exactly -- the ledger
identity ``sources_emitted == sinks_consumed + dead_letters + sheds +
in_flight`` holds with ``in_flight == 0``.
"""
from __future__ import annotations

import threading
from typing import Dict, List

from .census import SpaceSavingSketch, take_census
from .ledger import FlowLedger, _op_of
from .progress import FrontierTracker

MAX_VIOLATIONS = 256


class GraphAuditor(threading.Thread):
    def __init__(self, graph):
        super().__init__(name=f"windflow-auditor-{graph.name}",
                         daemon=True)
        self.graph = graph
        cfg = graph.config
        self.interval_s = max(0.02, float(cfg.audit_interval_s))
        self.topk = int(cfg.audit_topk)
        self.ledger = FlowLedger(graph)
        self.tracker = FrontierTracker(float(cfg.frontier_stall_s))
        self._stop_evt = threading.Event()
        self.violations: List[dict] = []
        self.passes = 0
        self.final_done = False
        # (consumer-op name, sketch) per KEYBY emitter
        self._sketches: List[tuple] = []
        self.op_skew: Dict[str, dict] = {}
        self.census_rows: List[dict] = []
        # op -> {str(key): tier name} for the sketch's hot keys, probed
        # from the owning logics' state_tier_of each skew refresh
        self.key_tiers: Dict[str, Dict[str, str]] = {}

    # -- wiring (PipeGraph.start / elastic rescale) --------------------
    def attach(self) -> None:
        """Attach delivery books, put-fault state and hot-key sketches
        to every wired node.  Must run after fusion/ingest wiring and
        fault binding, before any replica thread starts."""
        for n in self.graph._all_nodes():
            self.attach_node(n)

    def attach_node(self, node) -> None:
        self.ledger.attach_node(node)
        self._attach_sketches(node)
        self._bind_hot_keys(node)

    def _attach_sketches(self, node) -> None:
        from .ledger import unwrap
        owner = None
        for o in node.outlets:
            em = o.emitter
            if not getattr(em, "keyed", False):
                continue
            if getattr(em, "key_sketch", None) is not None:
                continue  # already attached + registered (idempotent)
            em.key_sketch = SpaceSavingSketch(self.topk)
            if owner is None:
                owner = {}
                for c in self.graph._all_nodes():
                    if c.channel is not None:
                        owner[id(unwrap(c.channel))] = c
            dest_op = None
            for ch, _pid in o.dests:
                c = owner.get(id(unwrap(ch)))
                if c is not None:
                    dest_op = _op_of(c.name)
                    break
            self._sketches.append((dest_op or node.name, em.key_sketch))

    def _bind_hot_keys(self, node) -> None:
        """Hand the hot-key sketch to this node's keyed stores (tiered
        state, state/tiers.py): the merged top-K of the sketches
        feeding the node's operator becomes the store's pinned-hot key
        set, so the keys the audit plane currently names hot are never
        demoted off the fast tier."""
        from ..runtime.node import FusedLogic

        def bind(logic, name):
            fn = getattr(logic, "bind_hot_sketch", None)
            if fn is None:
                return
            op = _op_of(name)

            def hot_keys(op=op):
                keys = set()
                for o, sk in self._sketches:
                    if o == op:
                        keys.update(sk.counts)
                return keys
            fn(hot_keys)

        if isinstance(node.logic, FusedLogic):
            for seg in node.logic.segments:
                bind(seg.logic, seg.name)
        else:
            bind(node.logic, node.name)

    def fold_retired(self, node) -> None:
        """Elastic scale-down accounting (called by rescale before the
        retired replica leaves the topology): delivery books fold into
        the retired ledger, and the replica's sketches are dropped --
        a frozen sketch would misstate the live share forever (and the
        registry would otherwise grow without bound across rescale
        cycles)."""
        self.ledger.fold_retired(node)
        dead = {id(sk) for sk in
                (getattr(o.emitter, "key_sketch", None)
                 for o in node.outlets) if sk is not None}
        if dead:
            self._sketches = [(op, sk) for op, sk in self._sketches
                              if id(sk) not in dead]

    # -- audit passes --------------------------------------------------
    def run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            g = self.graph
            if g._ended or g._cancel.cancelled:
                return
            pause = g._pause_ctl
            if pause is not None and pause.pausing:
                continue  # checkpoint/rescale barrier: books are moving
            try:
                self.audit_once()
            except Exception:  # pragma: no cover - never kill the graph
                import traceback
                traceback.print_exc()

    def audit_once(self) -> None:
        """One full pass: ledger, frontiers, census, publication."""
        g = self.graph
        nodes = g._all_nodes()
        edges = self.ledger.edges(nodes)
        fresh = self.ledger.check_pass(edges)
        self._record_violations(fresh)
        stalls = self.tracker.update(nodes)
        for s in stalls:
            g.flight.record("frontier_stall", **s)
        self.passes += 1
        self._refresh_skew(nodes)
        self._publish(edges, nodes)
        # diagnosis plane (diagnosis/): audit passes keep the history /
        # anomaly / bottleneck surfaces live even for untraced graphs
        # (no monitor thread); rate-limited to diagnosis_interval_s
        diag = getattr(g, "diagnosis", None)
        if diag is not None:
            diag.maybe_tick()

    def _record_violations(self, fresh: List[dict]) -> None:
        g = self.graph
        for v in fresh:
            if len(self.violations) < MAX_VIOLATIONS:
                self.violations.append(v)
            fields = {("violation" if k == "kind" else k): val
                      for k, val in v.items() if k != "at"}
            g.flight.record("conservation_violation", **fields)

    def _merged_sketches(self) -> Dict[str, dict]:
        """Merge per-emitter sketches per consumer operator: a KEYBY
        edge with N upstream replicas has N sketches, and every
        surface (Skew block, /metrics, elastic signal) must see ONE
        row per operator -- duplicate samples with identical labels
        are rejected by strict OpenMetrics parsers."""
        by_op: Dict[str, dict] = {}
        for op, sk in self._sketches:
            agg = by_op.setdefault(op, {"counts": {}, "errs": {},
                                        "observed": 0})
            agg["observed"] += sk.total
            for key, cnt, err in sk.top():
                agg["counts"][key] = agg["counts"].get(key, 0) + cnt
                agg["errs"][key] = agg["errs"].get(key, 0) + err
        return by_op

    def _refresh_skew(self, nodes) -> None:
        self.census_rows = take_census(nodes)
        merged = self._merged_sketches()
        skew: Dict[str, dict] = {}
        for op, agg in merged.items():
            if not agg["observed"] or not agg["counts"]:
                continue
            key, cnt = max(agg["counts"].items(), key=lambda kv: kv[1])
            cnt -= agg["errs"].get(key, 0)  # strip the overcount bound
            share = max(0.0, min(1.0, cnt / agg["observed"]))
            skew[op] = {"share": round(share, 4), "key": key,
                        "observed": agg["observed"]}
        self.op_skew = skew
        self.key_tiers = self._probe_tiers(nodes, merged)

    def _probe_tiers(self, nodes, merged: Dict[str, dict]
                     ) -> Dict[str, Dict[str, str]]:
        """Which tier each sketch-reported hot key lives in, probed
        from the owning logics' ``state_tier_of`` (gauge-grade, like
        the census): tiered stores answer hot/warm/cold, the
        device-resident engines answer "device"."""
        from ..runtime.node import FusedLogic
        out: Dict[str, Dict[str, str]] = {}

        def probe(logic, name):
            fn = getattr(logic, "state_tier_of", None)
            if fn is None:
                return
            op = _op_of(name)
            agg = merged.get(op)
            if agg is None:
                return
            tiers = out.setdefault(op, {})
            for k in agg["counts"]:
                sk = str(k)
                if sk in tiers:
                    continue  # another replica already owns it
                try:
                    t = fn(k)
                except Exception:
                    t = None
                if t is not None:
                    tiers[sk] = t

        for n in nodes:
            if isinstance(n.logic, FusedLogic):
                for seg in n.logic.segments:
                    probe(seg.logic, seg.name)
            else:
                probe(n.logic, n.name)
        return out

    def skew_of(self, op_name: str) -> float:
        """Top-key share signal for the elastic plane (0.0 = unknown)."""
        info = self.op_skew.get(op_name)
        return info["share"] if info else 0.0

    def _skew_block(self) -> dict:
        hot = []
        for op, agg in self._merged_sketches().items():
            if not agg["observed"] or not agg["counts"]:
                continue
            rows = sorted(agg["counts"].items(),
                          key=lambda kv: -kv[1])[:8]
            top = [[k, c, agg["errs"].get(k, 0)] for k, c in rows]
            info = self.op_skew.get(op)
            share = info["share"] if info else 0.0
            entry = {"operator": op, "share": share,
                     "observed": agg["observed"], "top": top}
            tiers = self.key_tiers.get(op)
            if tiers:
                entry["tiers"] = {str(k): tiers[str(k)] for k, _c in rows
                                  if str(k) in tiers}
            hot.append(entry)
        return {"Census": self.census_rows, "Hot_keys": hot}

    def _publish(self, edges, nodes) -> None:
        g = self.graph
        cons = self.ledger.conservation_block(
            edges, nodes, self.violations, self.passes, self.final_done)
        g.stats.set_audit(cons, self._skew_block())

    # -- shutdown ------------------------------------------------------
    def final_check(self) -> List[dict]:
        """Exact ledger closure after every replica joined (clean end).
        Returns the violations found (also recorded + published)."""
        g = self.graph
        nodes = g._all_nodes()
        edges = self.ledger.edges(nodes)
        fresh = self.ledger.final_check(edges)
        self._record_violations(fresh)
        self.final_done = True
        # settle the frontier gauges: every replica is joined and
        # drained, so watermarks converge to the source frontiers and
        # lag reads zero on a healthy run
        self.tracker.update(nodes)
        self._refresh_skew(nodes)
        self._publish(edges, nodes)
        return fresh

    def stop(self) -> None:
        self._stop_evt.set()
        if self.is_alive():
            self.join(timeout=5.0)
