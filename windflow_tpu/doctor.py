"""The doctor CLI: render the diagnosis report away from the code
(docs/OBSERVABILITY.md "Diagnosis plane").

    python -m windflow_tpu.doctor http://127.0.0.1:20208
    python -m windflow_tpu.doctor log/
    python -m windflow_tpu.doctor log/1234_app_stats.json
    python -m windflow_tpu.doctor log/ --json
    python -m windflow_tpu.doctor --watch http://127.0.0.1:41234

* **URL** -- a live dashboard HTTP server (monitoring/dashboard.py):
  fetches ``/apps`` and renders one report per registered app (the
  server-side ``/explain`` endpoint returns the same reports as JSON).
* **--watch URL** -- live CLUSTER mode (docs/OBSERVABILITY.md "Live
  cluster view"): polls the ``/cluster`` endpoint (the coordinator's
  ClusterObserver, or a dashboard HTTP server) every ``--interval``
  seconds and refreshes the MERGED doctor verdict in place -- a
  bottleneck on a remote worker is named mid-run with zero stats
  files read.  ``--once`` renders a single refresh (CI smoke).
* **directory** -- an offline dump dir: picks the newest stats-JSON
  dump (the monitor's ``*_stats.json`` snapshot fallback or
  ``PipeGraph._dump_logs``'s ``<pid>_<graph>.json``) and, when a
  matching ``*_flight.jsonl`` post-mortem dump sits next to it, folds
  its events in.
* **file** -- one stats-JSON dump.

The loader is schema-tolerant by contract: every block is optional
(``Schema_version`` is informational), so dumps from older runtimes
still render -- with the bottleneck walk and attribution recomputed
from ``Operators``/``Trace_records`` when no precomputed ``Diagnosis``
block exists.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

from .diagnosis.report import build_report, render_text


def _load_flight_jsonl(path: str) -> List[dict]:
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail line of a crash dump
    except OSError:
        pass
    return events


def _newest(paths: List[str]) -> Optional[str]:
    best, best_mt = None, -1.0
    for p in paths:
        try:
            mt = os.path.getmtime(p)
        except OSError:
            continue
        if mt > best_mt:
            best, best_mt = p, mt
    return best


def _find_dump(d: str) -> Tuple[Optional[str], Optional[str]]:
    """Newest stats-JSON dump in ``d`` plus its sibling flight JSONL
    (matched by the ``<pid>_<graph>`` prefix when possible, else the
    newest one)."""
    stats_paths, flight_paths = [], []
    try:
        names = os.listdir(d)
    except OSError:
        return None, None
    for n in names:
        p = os.path.join(d, n)
        if n.endswith("_flight.jsonl"):
            flight_paths.append(p)
        elif n.endswith(".json") and not n.endswith("_runtime.json"):
            stats_paths.append(p)
    stats = _newest(stats_paths)
    if stats is None:
        return None, None
    base = os.path.basename(stats)
    prefix = base[:-len("_stats.json")] if base.endswith("_stats.json") \
        else base[:-len(".json")]
    sib = os.path.join(d, prefix + "_flight.jsonl")
    flight = sib if sib in flight_paths else _newest(flight_paths)
    return stats, flight


def load_stats(target: str) -> List[Tuple[str, dict, Optional[list]]]:
    """Resolve ``target`` (file or directory) into
    ``[(label, stats_dict, flight_events_or_None)]``.  Tolerant: a
    malformed or partial dump raises ValueError with the path named."""
    if os.path.isdir(target):
        stats_path, flight_path = _find_dump(target)
        if stats_path is None:
            raise ValueError(f"no stats-JSON dump under {target!r}")
    else:
        stats_path, flight_path = target, None
        guess = target[:-len(".json")] if target.endswith(".json") else target
        if guess.endswith("_stats"):
            guess = guess[:-len("_stats")]
        cand = guess + "_flight.jsonl"
        if os.path.exists(cand):
            flight_path = cand
    try:
        with open(stats_path) as f:
            stats = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"unreadable stats dump {stats_path!r}: {e}")
    if not isinstance(stats, dict):
        raise ValueError(f"{stats_path!r} is not a stats-JSON object")
    flight = _load_flight_jsonl(flight_path) if flight_path else None
    return [(stats_path, stats, flight)]


def fetch_reports(url: str) -> List[Tuple[str, dict, Optional[list]]]:
    """Pull ``/apps`` from a live dashboard HTTP server and return one
    (label, stats, flight) triple per app that has reported."""
    import urllib.request
    base = url.rstrip("/")
    with urllib.request.urlopen(base + "/apps", timeout=5) as r:
        apps = json.loads(r.read().decode())
    out = []
    for aid in sorted(apps, key=str):
        app = apps[aid]
        if not isinstance(app, dict):
            continue
        rep = app.get("report")
        if rep:
            out.append((f"app {aid}", rep, rep.get("Flight")))
    if not out:
        raise ValueError(f"no reporting apps at {base}/apps")
    return out


def fetch_cluster(url: str) -> Tuple[dict, dict]:
    """Pull one ``/cluster`` snapshot: ``(merged_stats, meta)``.  The
    report is re-derived locally from the merged stats (the tolerant-
    loading contract applies to the live endpoint too)."""
    import urllib.request
    with urllib.request.urlopen(url, timeout=5) as r:
        doc = json.loads(r.read().decode())
    merged = doc.get("merged") or {}
    meta = {"workers": doc.get("workers"), "pushes": doc.get("pushes"),
            "now": doc.get("now")}
    return merged, meta


def _watch_url(target: str) -> str:
    base = target if target.startswith(("http://", "https://")) \
        else "http://" + target
    base = base.rstrip("/")
    return base if base.endswith("/cluster") else base + "/cluster"


def watch(target: str, interval_s: float = 2.0, once: bool = False,
          as_json: bool = False) -> int:
    """The ``--watch`` loop: poll the merged cluster view and refresh
    the verdict in place (clears the screen on a tty; plain appends
    otherwise, so piping to a file keeps every refresh)."""
    import time
    url = _watch_url(target)
    seen_any = False
    while True:
        try:
            merged, meta = fetch_cluster(url)
        except (OSError, ValueError) as e:
            if once and not seen_any:
                print(f"doctor: cannot reach {url}: {e}",
                      file=sys.stderr)
                return 2
            merged, meta = None, None
        out: List[str] = []
        if merged:
            seen_any = True
            rep = build_report(merged, merged.get("Flight"))
            rep["Source"] = url
            if as_json:
                out.append(json.dumps(rep, indent=1))
            else:
                n_workers = len((meta or {}).get("workers") or {})
                out.append(f"-- live cluster view {url} "
                           f"({n_workers} worker(s), "
                           f"{(meta or {}).get('pushes', 0)} pushes) --")
                out.append(render_text(rep))
        else:
            out.append(f"-- waiting for worker pushes at {url} --")
        if sys.stdout.isatty() and not as_json:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        print("\n".join(out), flush=True)
        if once:
            return 0
        try:
            time.sleep(max(0.1, interval_s))
        except KeyboardInterrupt:
            return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m windflow_tpu.doctor",
        description="Render the diagnosis report of a live dashboard "
                    "endpoint or an offline stats/flight dump.")
    ap.add_argument("targets", nargs="+",
                    help="dashboard URL (http://host:port), a dump "
                         "directory, or stats-JSON file(s); several "
                         "files with --merge fold into one report")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured report as JSON instead "
                         "of text")
    ap.add_argument("--merge", action="store_true",
                    help="merge multiple per-worker stats dumps of one "
                         "distributed run into ONE graph view "
                         "(distributed/observe.py) before reporting")
    ap.add_argument("--watch", action="store_true",
                    help="live cluster mode: poll the target's "
                         "/cluster endpoint and refresh the merged "
                         "verdict in place")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between --watch refreshes")
    ap.add_argument("--once", action="store_true",
                    help="with --watch: render a single refresh and "
                         "exit (smoke tests)")
    args = ap.parse_args(argv)
    if args.watch:
        if len(args.targets) != 1:
            print("doctor: --watch takes exactly one URL",
                  file=sys.stderr)
            return 2
        return watch(args.targets[0], args.interval, args.once,
                     args.json)
    try:
        urls = [t for t in args.targets
                if t.startswith(("http://", "https://"))]
        if urls and (args.merge or len(args.targets) > 1):
            raise ValueError(
                "dashboard URLs take a single target without --merge "
                "(the server already aggregates its apps); offline "
                "merging works on stats-JSON files/directories")
        if args.merge:
            from .distributed.observe import merge_stats
            loaded = []
            for t in args.targets:
                loaded.extend(load_stats(t))
            merged = merge_stats([s for _l, s, _f in loaded])
            triples = [("merged:" + ",".join(l for l, _s, _f in loaded),
                        merged, merged.get("Flight"))]
        elif len(args.targets) > 1:
            triples = []
            for t in args.targets:
                triples.extend(load_stats(t))
        elif urls:
            triples = fetch_reports(args.targets[0])
        else:
            triples = load_stats(args.targets[0])
    except (ValueError, OSError) as e:
        print(f"doctor: {e}", file=sys.stderr)
        return 2
    reports = []
    for label, stats, flight in triples:
        rep = build_report(stats, flight)
        rep["Source"] = label
        reports.append(rep)
    if args.json:
        print(json.dumps(reports if len(reports) > 1 else reports[0],
                         indent=1))
    else:
        for i, rep in enumerate(reports):
            if i:
                print()
            print(f"[{rep['Source']}]")
            print(render_text(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
