"""Fluent builders: the user-facing construction API.

Re-design of reference ``wf/builders.hpp`` (13 CPU builders, :49-2357).
Method surface kept: withName / withParallelism / withCBWindows /
withTBWindows(len, slide[, delay]) / withClosingFunction /
withInitialValue / withOptLevel / build.  Both snake_case and the
reference's camelCase spellings are provided so users of the reference
can port code mechanically.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from ..core.basic import OptLevel, WinType
from ..core.tuples import BasicRecord
from ..operators.basic_ops import (Accumulator, Filter, FlatMap, Map, Sink,
                                   Source)
from ..operators.win_seq import WinSeq


def _alias_camel(cls):
    """Attach camelCase aliases for every with_/build method, including
    ones inherited from mixins (the window-parameter surface lives on a
    shared base, so walk the MRO, nearest definition winning).  Also
    wraps ``build`` so builder-level operator attributes shared by every
    operator kind (the error policy) land on the built descriptor
    without each build() re-implementing the copy."""
    build = cls.__dict__.get("build")
    if build is not None and not getattr(build, "_wf_wrapped", False):
        import functools

        @functools.wraps(build)
        def build_wrapper(self, *a, **kw):
            op = build(self, *a, **kw)
            policy = getattr(self, "error_policy", "fail")
            if policy != "fail":
                op.error_policy = policy
            pin = getattr(self, "worker_pin", None)
            if pin is not None:
                op.worker = pin
            spec = getattr(self, "elasticity", None)
            if spec is not None:
                if op.parallelism > spec.max_replicas:
                    raise ValueError(
                        f"operator {op.name!r}: with_parallelism"
                        f"({op.parallelism}) exceeds with_elasticity "
                        f"max_replicas={spec.max_replicas}")
                # starting parallelism is the declared one raised into
                # the elastic interval (with_parallelism left at 1 under
                # with_elasticity(2, 8) means "start at the minimum")
                op.elasticity = spec
                op.parallelism = max(op.parallelism, spec.min_replicas)
            if getattr(self, "restartable", False):
                op.restartable = True
            return op

        build_wrapper._wf_wrapped = True
        cls.build = build_wrapper
    targets = {}
    for klass in cls.__mro__:
        for name, fn in vars(klass).items():
            if name not in targets and (name.startswith("with_")
                                        or name in ("build_ptr",)):
                targets[name] = fn
    for name, fn in targets.items():
        parts = name.split("_")
        camel = parts[0] + "".join(p.upper() if p in ("cb", "tb", "tpu")
                                   else p.capitalize()
                                   for p in parts[1:])
        setattr(cls, camel, fn)
    return cls


class _BuilderBase:
    _default_name = "op"

    def __init__(self, fn):
        self.fn = fn
        self.name = self._default_name
        self.parallelism = 1
        self.closing_func = None
        self.error_policy = "fail"
        self.elasticity = None
        self.worker_pin = None
        self.restartable = False

    def with_name(self, name: str):
        self.name = name
        return self

    def with_parallelism(self, n: int):
        self.parallelism = n
        return self

    def with_closing_function(self, fn: Callable):
        self.closing_func = fn
        return self

    def with_error_policy(self, policy: str):
        """Per-tuple svc failure handling for this operator:
        ``'fail'`` (default -- the replica dies and the graph cancels),
        ``'skip'`` (drop the offending tuple, count it) or
        ``'dead_letter'`` (skip + quarantine the tuple with node name
        and traceback in ``graph.dead_letters``).  See
        docs/RESILIENCE.md."""
        from ..resilience.policies import validate_policy
        self.error_policy = validate_policy(policy)
        return self

    def with_worker(self, worker: int):
        """Pin this operator to worker ``worker`` of a distributed run
        (docs/DISTRIBUTED.md): the partition planner places its whole
        co-located group there, and an edge between two differently-
        pinned operators becomes a cut (carried by the shuffle
        transport) even when it is a FORWARD edge.  Ignored outside
        ``RuntimeConfig.distributed`` runs."""
        worker = int(worker)
        if worker < 0:
            raise ValueError("with_worker: worker ids are >= 0")
        self.worker_pin = worker
        return self

    def with_elasticity(self, min_replicas: int, max_replicas: int,
                        target_util: float = 0.75):
        """Declare this operator elastically scalable at runtime
        (docs/ELASTIC.md): the elastic controller (or manual
        ``PipeGraph.rescale``) adjusts its replica count inside
        ``[min_replicas, max_replicas]``, steering toward
        ``target_util`` busy fraction per replica.  Keys repartition by
        the same ``hash % parallelism`` contract the KEYBY emitter
        uses; per-key state (Accumulator) migrates across the rescale.
        Supported for single-stage Filter/Map/FlatMap/Accumulator
        operators in Mode.DEFAULT graphs."""
        from ..core.basic import ElasticSpec
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                "with_elasticity: need 1 <= min_replicas <= max_replicas")
        if not 0.0 < target_util <= 1.0:
            raise ValueError(
                "with_elasticity: target_util must be in (0, 1]")
        self.elasticity = ElasticSpec(min_replicas, max_replicas,
                                      target_util)
        return self

    def with_restartable(self):
        """Mark this operator's replicas individually restartable under
        supervision (docs/RESILIENCE.md "Supervised replica restart"):
        with ``RuntimeConfig.supervision`` set (which requires the
        durability plane), a crash in one of its replicas is healed in
        place -- the supervisor quiesces, rebuilds the replica from
        the last committed epoch's state slice and resumes -- instead
        of failing the whole graph.  Needs a fresh-replica factory
        (the same contract as elasticity: single-stage Filter / Map /
        FlatMap / Accumulator operators); without supervision
        configured the mark is inert."""
        self.restartable = True
        return self

    def build_ptr(self):
        return self.build()


class _WinBuilderBase(_BuilderBase):
    """Shared window-spec surface (builders.hpp:851-858 and peers)."""

    def __init__(self, fn):
        super().__init__(fn)
        self.win_len = None
        self.slide_len = None
        self.win_type = None
        self.triggering_delay = 0
        self.opt_level = OptLevel.LEVEL0
        self.result_factory = BasicRecord
        self.incremental = False

    def with_cb_windows(self, win_len: int, slide_len: int):
        self.win_type = WinType.CB
        self.win_len = win_len
        self.slide_len = slide_len
        return self

    def with_tb_windows(self, win_len_us: int, slide_len_us: int,
                        triggering_delay_us: int = 0):
        self.win_type = WinType.TB
        self.win_len = win_len_us
        self.slide_len = slide_len_us
        self.triggering_delay = triggering_delay_us
        return self

    def with_opt_level(self, level: OptLevel):
        self.opt_level = OptLevel(level)
        return self

    def with_result_type(self, factory: Callable[[], Any]):
        self.result_factory = factory
        return self

    def with_incremental(self, incremental: bool = True):
        """Select the incremental (winupdate) query style; the reference
        dispatches on the callable's C++ signature (meta.hpp), Python
        cannot, so it is explicit here."""
        self.incremental = incremental
        return self

    def _check_windows(self):
        if self.win_type is None:
            raise ValueError(
                f"{type(self).__name__}: call with_cb_windows or "
                "with_tb_windows before build()")


@_alias_camel
class SourceBuilder(_BuilderBase):
    """Builds the classic shipper-style :class:`Source` from a callable,
    or -- via the ``from_socket`` / ``from_replay`` / ``from_async``
    constructors -- an ingest-plane source (docs/INGEST.md) with
    credit-based backpressure, an adaptive microbatch controller and
    optional admission control."""

    _default_name = "source"

    def __init__(self, fn=None):
        super().__init__(fn)
        self._ingest_kind = None
        self._ingest_args: dict = {}
        self.credits = None           # None = RuntimeConfig.ingest_credits
        self.admission = None
        self.latency_target_ms = None
        self.initial_batch = None
        self.trace_sample = None      # None = RuntimeConfig.trace_sample

    # -- ingest-plane constructors (windflow_tpu/ingest/) ---------------
    @classmethod
    def from_socket(cls, host: str, port: int,
                    connect_timeout_s: float = 10.0) -> "SourceBuilder":
        """Non-blocking framed-TCP source (ingest.codec protocol); each
        replica opens one client connection."""
        b = cls(None)
        b._ingest_kind = "socket"
        b._ingest_args = dict(host=host, port=port,
                              connect_timeout_s=connect_timeout_s)
        b.name = "socket_source"
        return b

    @classmethod
    def from_replay(cls, trace, speedup: Optional[float] = 1.0,
                    ts_unit_s: float = 1e-6, chunk: Optional[int] = 65536,
                    seed: int = 0) -> "SourceBuilder":
        """Timestamp-faithful replay of a recorded trace (TupleBatch,
        dict of columns, or .npz path) at ``speedup`` x real time
        (None = as fast as possible); deterministic under ``seed``."""
        b = cls(None)
        b._ingest_kind = "replay"
        b._ingest_args = dict(trace=trace, speedup=speedup,
                              ts_unit_s=ts_unit_s, chunk=chunk, seed=seed)
        b.name = "replay"
        return b

    @classmethod
    def from_async(cls, factory) -> "SourceBuilder":
        """Async-generator source: ``factory()`` is called per replica
        and must return an async generator yielding TupleBatch items or
        records."""
        b = cls(None)
        b._ingest_kind = "async"
        b._ingest_args = dict(factory=factory)
        b.name = "async_source"
        return b

    # -- ingest-plane knobs ---------------------------------------------
    def with_credits(self, budget: int) -> "SourceBuilder":
        """Per-replica credit budget: tuples outstanding in outlet
        channels before the transport stops reading."""
        self.credits = budget
        return self

    def with_admission(self, policy: str, max_wait_ms: float = 0.0,
                       seed: int = 0) -> "SourceBuilder":
        """Overload policy ('drop_newest' | 'drop_oldest' | 'sample'):
        shed instead of blocking once an arrival has waited
        ``max_wait_ms`` for stage space; shed tuples are quarantined in
        ``graph.dead_letters`` (docs/INGEST.md)."""
        from ..ingest.admission import AdmissionConfig
        self.admission = AdmissionConfig(policy, max_wait_ms, seed)
        return self

    def with_latency_target(self, target_ms: float) -> "SourceBuilder":
        """Per-source latency budget override for the microbatch
        controller (defaults to RuntimeConfig.latency_target_ms)."""
        self.latency_target_ms = target_ms
        return self

    def with_microbatch(self, initial_batch: int) -> "SourceBuilder":
        """Initial coalesced batch size; the AIMD controller adapts
        from here (this replaces the static RuntimeConfig.microbatch
        knob for ingest-fed runs)."""
        self.initial_batch = initial_batch
        return self

    def with_tracing(self, sample_rate: int) -> "SourceBuilder":
        """Per-source end-to-end latency-tracing period
        (docs/OBSERVABILITY.md): every ``sample_rate``-th emitted item
        starts a trace context that rides to the sinks and lands in the
        per-operator residency and graph e2e histograms.  Overrides
        ``RuntimeConfig.trace_sample`` for this source; 0 opts this
        source out of sampling.  Active only under
        ``RuntimeConfig.tracing``."""
        sample_rate = int(sample_rate)
        if sample_rate < 0:
            raise ValueError("with_tracing: sample_rate must be >= 0")
        self.trace_sample = sample_rate
        return self

    def with_error_policy(self, policy: str):
        """Sources reject non-default policies loudly: a generation
        loop has no per-tuple svc boundary, so 'skip'/'dead_letter'
        would validate here and then be silently ignored at runtime."""
        from ..resilience.policies import validate_policy
        if validate_policy(policy) != "fail":
            raise ValueError(
                "sources always fail hard: error policies apply to "
                "per-tuple svc processing (docs/RESILIENCE.md)")
        return self

    def with_elasticity(self, *a, **kw):
        """Sources cannot rescale at runtime: rescaling a generation
        loop would need offset repartitioning across replicas, which
        only the source callable could define (docs/ELASTIC.md)."""
        raise ValueError("sources are not elastically scalable")

    def build(self):
        if self._ingest_kind is None:
            if self.fn is None:
                raise ValueError(
                    "SourceBuilder needs a generation function, or use "
                    "from_socket/from_replay/from_async (docs/INGEST.md)")
            op = Source(self.fn, self.parallelism, self.name,
                        self.closing_func)
            op.trace_sample = self.trace_sample
            return op
        from ..ingest.sources import (AsyncGeneratorSource, ReplaySource,
                                      SocketSource)
        kw = dict(parallelism=self.parallelism, name=self.name,
                  credits=self.credits, admission=self.admission,
                  latency_target_ms=self.latency_target_ms,
                  initial_batch=self.initial_batch,
                  closing_func=self.closing_func)
        if self._ingest_kind == "socket":
            op = SocketSource(**self._ingest_args, **kw)
        elif self._ingest_kind == "replay":
            op = ReplaySource(**self._ingest_args, **kw)
        else:
            op = AsyncGeneratorSource(**self._ingest_args, **kw)
        op.trace_sample = self.trace_sample
        return op


@_alias_camel
class FilterBuilder(_BuilderBase):
    _default_name = "filter"

    def __init__(self, fn):
        super().__init__(fn)
        self.keyed = False

    def with_key_by(self):
        self.keyed = True
        return self

    def build(self) -> Filter:
        return Filter(self.fn, self.parallelism, self.name,
                      self.closing_func, self.keyed)


@_alias_camel
class MapBuilder(_BuilderBase):
    _default_name = "map"

    def __init__(self, fn):
        super().__init__(fn)
        self.keyed = False

    def with_key_by(self):
        self.keyed = True
        return self

    def build(self) -> Map:
        return Map(self.fn, self.parallelism, self.name, self.closing_func,
                   self.keyed)


@_alias_camel
class FlatMapBuilder(_BuilderBase):
    _default_name = "flatmap"

    def __init__(self, fn):
        super().__init__(fn)
        self.keyed = False

    def with_key_by(self):
        self.keyed = True
        return self

    def build(self) -> FlatMap:
        return FlatMap(self.fn, self.parallelism, self.name,
                       self.closing_func, self.keyed)


@_alias_camel
class AccumulatorBuilder(_BuilderBase):
    _default_name = "accumulator"

    def __init__(self, fn):
        super().__init__(fn)
        self.init_value = None

    def with_initial_value(self, value: Any):
        self.init_value = value
        return self

    def build(self) -> Accumulator:
        if self.init_value is None:
            self.init_value = BasicRecord()
        return Accumulator(self.fn, self.init_value, self.parallelism,
                           self.name, self.closing_func)


@_alias_camel
class SinkBuilder(_BuilderBase):
    _default_name = "sink"

    def __init__(self, fn):
        super().__init__(fn)
        self.exactly_once = None

    def with_exactly_once(self, mode: str = "transactional"):
        """Exactly-once sink contract under the durability plane
        (``RuntimeConfig.durability``; docs/RESILIENCE.md):

        * ``'transactional'`` -- effects buffer per epoch; the aligned
          barrier seals the buffer and the coordinator releases it only
          after the epoch's manifest committed durably.  A crash
          discards unreleased effects; the restart regenerates exactly
          them.
        * ``'idempotent'`` -- effects apply immediately through an
          epoch-keyed writer (``write(epoch, item)``, e.g.
          ``windflow_tpu.durability.EpochTaggedStore``); recovery
          truncates the writer above the restored epoch.  The contract
          for side channels keyed by epoch id (the stats / dead-letter
          surfaces)."""
        if mode not in ("transactional", "idempotent"):
            raise ValueError(
                "with_exactly_once: mode must be 'transactional' or "
                f"'idempotent', not {mode!r}")
        self.exactly_once = mode
        return self

    def build(self) -> Sink:
        return Sink(self.fn, self.parallelism, self.name,
                    self.closing_func, exactly_once=self.exactly_once)


@_alias_camel
class WinSeqBuilder(_WinBuilderBase):
    _default_name = "win_seq"

    def build(self) -> WinSeq:
        self._check_windows()
        return WinSeq(self.fn, self.win_len, self.slide_len, self.win_type,
                      self.triggering_delay, self.incremental, self.name,
                      self.result_factory, self.closing_func)


from ..operators.win_farm import WinFarm
from ..operators.key_farm import KeyFarm
from ..operators.pane_farm import PaneFarm
from ..operators.win_mapreduce import WinMapReduce
from ..operators.win_seqffat import KeyFFAT, WinSeqFFAT


@_alias_camel
class WinFarmBuilder(_WinBuilderBase):
    """builders.hpp:1127 -- window-parallel farm."""

    _default_name = "win_farm"

    def __init__(self, fn):
        super().__init__(fn)
        self.ordered = True

    def with_ordered(self, ordered: bool = True):
        self.ordered = ordered
        return self

    def build(self):
        from ..operators.nesting import NestedWinFarm
        from ..operators.pane_farm import PaneFarm
        from ..operators.win_mapreduce import WinMapReduce
        if isinstance(self.fn, (PaneFarm, WinMapReduce)):
            # nesting constructor (win_farm.hpp:259-378): replicate the
            # inner complex operator; windowing comes from the inner op
            return NestedWinFarm(self.fn, self.parallelism, self.name,
                                 self.ordered, self.opt_level)
        self._check_windows()
        return WinFarm(self.fn, self.win_len, self.slide_len, self.win_type,
                       self.parallelism, self.triggering_delay,
                       self.incremental, self.name, self.result_factory,
                       self.closing_func, self.ordered, self.opt_level)


@_alias_camel
class KeyFarmBuilder(_WinBuilderBase):
    """builders.hpp:1350 -- key-partitioned farm."""

    _default_name = "key_farm"

    def build(self):
        from ..operators.nesting import NestedKeyFarm
        from ..operators.pane_farm import PaneFarm
        from ..operators.win_mapreduce import WinMapReduce
        if isinstance(self.fn, (PaneFarm, WinMapReduce)):
            # nesting constructor (key_farm.hpp:254-...)
            return NestedKeyFarm(self.fn, self.parallelism, self.name,
                                 self.opt_level)
        self._check_windows()
        return KeyFarm(self.fn, self.win_len, self.slide_len, self.win_type,
                       self.parallelism, self.triggering_delay,
                       self.incremental, self.name, self.result_factory,
                       self.closing_func, self.opt_level)


class _TwoStageWinBuilder(_WinBuilderBase):
    """Shared by PaneFarm (PLQ/WLQ) and WinMapReduce (MAP/REDUCE)."""

    def __init__(self, fn1, fn2):
        super().__init__(fn1)
        self.fn2 = fn2
        self.par1 = 1
        self.par2 = 1
        self.incremental2 = False
        self.ordered = True

    def with_ordered(self, ordered: bool = True):
        self.ordered = ordered
        return self


@_alias_camel
class PaneFarmBuilder(_TwoStageWinBuilder):
    """builders.hpp:1762 -- pane decomposition (PLQ + WLQ)."""

    _default_name = "pane_farm"

    def with_parallelism(self, plq: int, wlq: int = None):
        self.par1 = plq
        self.par2 = wlq if wlq is not None else plq
        return self

    withParallelism = with_parallelism

    def with_plq_incremental(self, inc: bool = True):
        self.incremental = inc
        return self

    def with_wlq_incremental(self, inc: bool = True):
        self.incremental2 = inc
        return self

    def build(self) -> PaneFarm:
        self._check_windows()
        return PaneFarm(self.fn, self.fn2, self.win_len, self.slide_len,
                        self.win_type, self.par1, self.par2,
                        self.triggering_delay, self.incremental,
                        self.incremental2, self.name, self.result_factory,
                        self.closing_func, self.ordered, self.opt_level)


@_alias_camel
class WinMapReduceBuilder(_TwoStageWinBuilder):
    """builders.hpp:1982 -- intra-window map + reduce."""

    _default_name = "win_mr"

    def __init__(self, map_fn, reduce_fn):
        super().__init__(map_fn, reduce_fn)
        self.par1 = 2

    def with_parallelism(self, map_par: int, reduce_par: int = 1):
        self.par1 = map_par
        self.par2 = reduce_par
        return self

    withParallelism = with_parallelism

    def with_map_incremental(self, inc: bool = True):
        self.incremental = inc
        return self

    def with_reduce_incremental(self, inc: bool = True):
        self.incremental2 = inc
        return self

    def build(self) -> WinMapReduce:
        self._check_windows()
        return WinMapReduce(self.fn, self.fn2, self.win_len, self.slide_len,
                            self.win_type, self.par1, self.par2,
                            self.triggering_delay, self.incremental,
                            self.incremental2, self.name,
                            self.result_factory, self.closing_func,
                            self.ordered, self.opt_level)


class _FFATBuilderBase(_WinBuilderBase):
    def __init__(self, lift_fn, combine_fn):
        super().__init__(lift_fn)
        self.combine_fn = combine_fn


@_alias_camel
class WinSeqFFATBuilder(_FFATBuilderBase):
    """builders.hpp:957 -- sequential FlatFAT engine (lift + combine)."""

    _default_name = "win_seqffat"

    def build(self) -> WinSeqFFAT:
        self._check_windows()
        return WinSeqFFAT(self.fn, self.combine_fn, self.win_len,
                          self.slide_len, self.win_type,
                          self.triggering_delay, self.name,
                          self.result_factory, self.closing_func)


@_alias_camel
class KeyFFATBuilder(_FFATBuilderBase):
    """builders.hpp:1576 -- key-parallel FlatFAT farm (lift + combine)."""

    _default_name = "key_ffat"

    def build(self) -> KeyFFAT:
        self._check_windows()
        return KeyFFAT(self.fn, self.combine_fn, self.win_len,
                       self.slide_len, self.win_type, self.parallelism,
                       self.triggering_delay, self.name,
                       self.result_factory, self.closing_func)
