"""Device-operator builders: the TPU twin of ``wf/builders_gpu.hpp``.

The reference's GPU builders add ``withBatch(batch_len)`` and
``withGPUConfiguration(gpu_id, n_thread_block)`` (builders_gpu.hpp:120,
:133); these builders keep ``withBatch`` and replace the CUDA knobs with
``withTPUConfiguration(device_index)`` -- block shaping is the XLA
compiler's job, not the user's.  Per the BASELINE north star, every
builder also exposes ``withTPU()`` as a no-op marker so reference-style
code reads naturally.
"""
from __future__ import annotations

from typing import Any, Callable

from ..operators.tpu.farms_tpu import (KeyFarmTPU, KeyFFATTPU, PaneFarmTPU,
                                       WinFarmTPU, WinMapReduceTPU,
                                       WinSeqFFATTPU)
from ..core.basic import WinType
from ..operators.tpu.win_seq_tpu import (DEFAULT_BATCH_LEN,
    DEFAULT_INFLIGHT_DEPTH, DEFAULT_MAX_BATCH_DELAY_MS,
    DEFAULT_MAX_BUFFER_ELEMS, WinSeqTPU)
from .builders import _WinBuilderBase, _alias_camel


class _TPUBuilderMixin:
    max_buffer_elems = DEFAULT_MAX_BUFFER_ELEMS
    inflight_depth = DEFAULT_INFLIGHT_DEPTH
    max_batch_delay_ms = DEFAULT_MAX_BATCH_DELAY_MS
    placement = "device"
    adaptive_batch = False
    resident = None

    def with_batch(self, batch_len: int):
        self.batch_len = batch_len
        return self

    def with_resident(self, on=True):
        """Resident pane-partial state (docs/PLANNER.md "Resident
        state"): per-key window carry stays device-resident across
        launches and only new partials ship.  True forces the resident
        lane (rejecting ineligible shapes loudly), False opts out;
        the default (None) lets the placement planner promote
        eligible device-lane engines automatically."""
        self.resident = on
        return self

    withResident = with_resident

    def with_placement(self, placement: str):
        """Engine lane: 'device' (XLA launches -- the default, status
        quo), 'host' (numpy host engine: no transport, no launch
        floor), or 'auto' (the cost-based placement planner resolves
        the lane at PipeGraph.start from the measured RTT floor, the
        calibrated host rate and this operator's bytes/launch --
        graph/planner.py; docs/PLANNER.md)."""
        from ..operators.tpu.win_seq_tpu import PLACEMENTS
        if placement not in PLACEMENTS:
            raise ValueError(
                f"placement must be one of {PLACEMENTS}: {placement!r}")
        self.placement = placement
        return self

    withPlacement = with_placement

    def with_adaptive_batch(self, on: bool = True):
        """x2 / /2 device-batch resize driven by observed launch
        latency vs the measured RTT floor (the adaptation loop of
        win_seq_gpu.hpp:574-592; docs/PLANNER.md)."""
        self.adaptive_batch = on
        return self

    withAdaptiveBatch = with_adaptive_batch

    def _check_placement_supported(self):
        """Builders whose operators cannot change lanes (FFAT trees,
        device MAP/REDUCE composites) reject non-default placement
        loudly instead of ignoring it."""
        if self.placement != "device" or self.adaptive_batch \
                or self.resident is not None:
            raise ValueError(
                f"{type(self).__name__} is device-pinned: "
                "with_placement/with_adaptive_batch/with_resident are "
                "not supported on this operator family (the FFAT "
                "family's resident mode is with_rebuild(False))")

    def with_max_buffer(self, elems: int):
        """Host staging-buffer capacity (elements) for the device
        engine replicas; larger buffers flush less often on the hot
        ingest path."""
        self.max_buffer_elems = elems
        return self

    def with_tpu_configuration(self, device_index: int = 0):
        self.device_index = device_index
        return self

    def with_tpu(self):
        return self

    def with_value_of(self, value_of: Callable[[Any], float]):
        """Host-side extractor tuple -> float fed to the device batch
        (the staging-format hook; defaults to ``t.value``)."""
        self.value_of = value_of
        return self

    def with_batch_output(self, on: bool = True):
        """Emit results as columnar TupleBatches (hot path)."""
        self.emit_batches = on
        return self

    def with_inflight(self, depth: int):
        """Device launches kept in flight before the oldest is flushed
        (the waitAndFlush pipeline depth, win_seq_gpu.hpp:267-297).
        Nested farms (a farm builder wrapping a PaneFarmTPU /
        WinMapReduceTPU) take their depth from the INNER operator's
        builder; this knob applies to non-nested builds."""
        self.inflight_depth = depth
        return self

    def with_max_batch_delay(self, ms: float):
        """Partial-batch launch trigger: ready windows launch at most
        this long after the previous launch (the latency half of the
        adaptive batch resize, win_seq_gpu.hpp:574-592)."""
        self.max_batch_delay_ms = ms
        return self


class _KeyShardedMixin:
    """Knobs that only make sense on key-sharded device farms."""

    def with_coalesce(self, on: bool = True):
        """Lower same-device replicas to one engine handling all keys
        per launch (default on -- see KeyFarmTPU).  Off keeps the
        literal N-replica farm.  Nested farms (KeyFarm over
        PaneFarmTPU/WinMapReduceTPU) ignore this: their replication IS
        the requested composite structure."""
        self.coalesce = on
        return self



@_alias_camel
class WinSeqTPUBuilder(_WinBuilderBase, _TPUBuilderMixin):
    """builders_gpu.hpp:50 analogue."""

    _default_name = "win_seq_tpu"

    def __init__(self, win_kind):
        super().__init__(win_kind)
        self.batch_len = DEFAULT_BATCH_LEN
        self.value_of = None
        self.device_index = 0
        self.emit_batches = False

    def build(self) -> WinSeqTPU:
        self._check_windows()
        return WinSeqTPU(self.fn, self.win_len, self.slide_len,
                         self.win_type, self.batch_len,
                         self.triggering_delay, self.name,
                         self.result_factory, self.value_of,
                         self.closing_func, self.emit_batches,
                         max_buffer_elems=self.max_buffer_elems,
                         inflight_depth=self.inflight_depth,
                         max_batch_delay_ms=self.max_batch_delay_ms,
                         placement=self.placement,
                         adaptive_batch=self.adaptive_batch,
                         resident=self.resident)


@_alias_camel
class WinFarmTPUBuilder(_WinBuilderBase, _TPUBuilderMixin):
    """builders_gpu.hpp:426 analogue."""

    _default_name = "win_farm_tpu"

    def __init__(self, win_kind):
        super().__init__(win_kind)
        self.batch_len = DEFAULT_BATCH_LEN
        self.value_of = None
        self.device_index = 0
        self.ordered = True

    def with_ordered(self, ordered: bool = True):
        self.ordered = ordered
        return self

    def build(self):
        from ..operators.nesting import NestedWinFarm
        if isinstance(self.fn, (PaneFarmTPU, WinMapReduceTPU)):
            # device nesting ctor (win_farm_gpu.hpp:73-76): replicate
            # the inner device operator; windowing comes from the inner
            self._check_placement_supported()
            return NestedWinFarm(self.fn, self.parallelism, self.name,
                                 self.ordered, self.opt_level)
        self._check_windows()
        return WinFarmTPU(self.fn, self.win_len, self.slide_len,
                          self.win_type, self.parallelism, self.batch_len,
                          self.triggering_delay, self.name,
                          self.result_factory, self.value_of, self.ordered,
                          self.opt_level,
                          max_buffer_elems=self.max_buffer_elems,
                          inflight_depth=self.inflight_depth,
                          max_batch_delay_ms=self.max_batch_delay_ms,
                          placement=self.placement,
                          adaptive_batch=self.adaptive_batch)


@_alias_camel
class KeyFarmTPUBuilder(_WinBuilderBase, _TPUBuilderMixin,
                        _KeyShardedMixin):
    """builders_gpu.hpp:713 analogue."""

    _default_name = "key_farm_tpu"

    def __init__(self, win_kind):
        super().__init__(win_kind)
        self.batch_len = DEFAULT_BATCH_LEN
        self.value_of = None
        self.device_index = 0
        self.emit_batches = False
        self.coalesce = True

    def build(self):
        from ..operators.nesting import NestedKeyFarm
        if isinstance(self.fn, (PaneFarmTPU, WinMapReduceTPU)):
            # device nesting ctor (key_farm_gpu.hpp:254-...)
            self._check_placement_supported()
            return NestedKeyFarm(self.fn, self.parallelism, self.name,
                                 self.opt_level)
        self._check_windows()
        return KeyFarmTPU(self.fn, self.win_len, self.slide_len,
                          self.win_type, self.parallelism, self.batch_len,
                          self.triggering_delay, self.name,
                          self.result_factory, self.value_of,
                          emit_batches=self.emit_batches,
                          max_buffer_elems=self.max_buffer_elems,
                          coalesce=self.coalesce,
                          inflight_depth=self.inflight_depth,
                          max_batch_delay_ms=self.max_batch_delay_ms,
                          placement=self.placement,
                          adaptive_batch=self.adaptive_batch)


@_alias_camel
class PaneFarmTPUBuilder(_WinBuilderBase, _TPUBuilderMixin):
    """builders_gpu.hpp:1217 analogue: exactly one of PLQ/WLQ on device."""

    _default_name = "pane_farm_tpu"

    def __init__(self, plq, wlq, plq_on_tpu: bool = True):
        super().__init__(plq)
        self.wlq = wlq
        self.plq_on_tpu = plq_on_tpu
        self.par1 = 1
        self.par2 = 1
        self.batch_len = DEFAULT_BATCH_LEN
        self.value_of = None
        self.device_index = 0
        self.ordered = True
        self.emit_batches = False

    def with_parallelism(self, plq: int, wlq: int = None):
        self.par1 = plq
        self.par2 = wlq if wlq is not None else plq
        return self

    withParallelism = with_parallelism

    def build(self) -> PaneFarmTPU:
        self._check_windows()
        return PaneFarmTPU(self.fn, self.wlq, self.win_len, self.slide_len,
                           self.win_type, self.par1, self.par2,
                           self.plq_on_tpu, not self.plq_on_tpu,
                           self.batch_len, self.triggering_delay, self.name,
                           self.result_factory, self.value_of, self.ordered,
                           self.opt_level,
                           max_buffer_elems=self.max_buffer_elems,
                           inflight_depth=self.inflight_depth,
                           max_batch_delay_ms=self.max_batch_delay_ms,
                           emit_batches=self.emit_batches,
                           placement=self.placement,
                           adaptive_batch=self.adaptive_batch)


@_alias_camel
class WinMapReduceTPUBuilder(_WinBuilderBase, _TPUBuilderMixin):
    """builders_gpu.hpp:1482 analogue: exactly one of MAP/REDUCE on device."""

    _default_name = "win_mr_tpu"

    def __init__(self, map_stage, reduce_stage, map_on_tpu: bool = True):
        super().__init__(map_stage)
        self.reduce_stage = reduce_stage
        self.map_on_tpu = map_on_tpu
        self.par1 = 2
        self.par2 = 1
        self.batch_len = DEFAULT_BATCH_LEN
        self.value_of = None
        self.device_index = 0
        self.ordered = True

    def with_parallelism(self, map_par: int, reduce_par: int = 1):
        self.par1 = map_par
        self.par2 = reduce_par
        return self

    withParallelism = with_parallelism

    def build(self) -> WinMapReduceTPU:
        self._check_windows()
        self._check_placement_supported()
        return WinMapReduceTPU(self.fn, self.reduce_stage, self.win_len,
                               self.slide_len, self.win_type, self.par1,
                               self.par2, self.map_on_tpu, self.batch_len,
                               self.triggering_delay, self.name,
                               self.result_factory, self.value_of,
                               self.ordered,
                               max_buffer_elems=self.max_buffer_elems,
                               inflight_depth=self.inflight_depth,
                               max_batch_delay_ms=self.max_batch_delay_ms)


@_alias_camel
class WinSeqFFATTPUBuilder(_WinBuilderBase, _TPUBuilderMixin):
    """builders_gpu.hpp:232 analogue (lift + combine)."""

    _default_name = "win_seqffat_tpu"

    _BUILTIN_COMBINES = {"sum": (None, 0.0), "max": (None, float("-inf")),
                         "min": (None, float("inf"))}

    def __init__(self, lift, combine):
        super().__init__(lift)
        self.combine = combine
        self.batch_len = DEFAULT_BATCH_LEN
        self.device_index = 0
        # None = auto (docs/PLANNER.md "Resident state"): CB windows
        # default onto the RESIDENT lane (rebuild=False) -- per-key
        # forests stay in HBM across launches and only new leaves
        # ship; TB windows default to rebuild (the resident ring's
        # eviction proof needs per-key in-order timestamps, which an
        # arbitrary TB stream does not guarantee)
        self.rebuild = None

    def with_rebuild(self, rebuild: bool):
        """rebuild=True: the tree is rebuilt from the staged flat
        buffer every device launch.  rebuild=False: the per-key forest
        stays resident in HBM and is incrementally updated (the
        Win_SeqFFAT_GPU ``rebuild`` flag, win_seqffat_gpu.hpp:150) --
        the DEFAULT for CB windows.  CB windows ride the arrival-order
        leaf ring; TB windows need per-key in-order timestamps (ring
        eviction is keyed on the timestamp proof), so out-of-order TB
        streams must keep rebuild=True (the TB default; rebuild=False
        opts an in-order TB stream in)."""
        self.rebuild = rebuild
        return self

    withRebuild = with_rebuild

    def _resident_combine(self):
        if isinstance(self.combine, tuple) and len(self.combine) == 2:
            return self.combine
        if isinstance(self.combine, str) \
                and self.combine in self._BUILTIN_COMBINES:
            import jax.numpy as jnp
            fn = {"sum": jnp.add, "max": jnp.maximum,
                  "min": jnp.minimum}[self.combine]
            return fn, self._BUILTIN_COMBINES[self.combine][1]
        raise ValueError(
            "resident (rebuild=False) mode needs a (jax_fn, neutral) "
            "combine or one of sum/max/min")

    def build(self):
        self._check_windows()
        self._check_placement_supported()
        rebuild = self.rebuild
        if rebuild is None:
            # auto: CB engines default onto the resident lane when the
            # combine has a resident form; TB (ordering not guaranteed)
            # and exotic combines keep the rebuild path
            try:
                self._resident_combine()
                rebuild = self.win_type != WinType.CB
            except ValueError:
                rebuild = True
        if not rebuild:
            from ..operators.tpu.ffat_resident import WinSeqFFATResident
            fn, neutral = self._resident_combine()
            return WinSeqFFATResident(self.fn, fn, neutral, self.win_len,
                                      self.slide_len, self.win_type,
                                      self.name, self.result_factory)
        return WinSeqFFATTPU(self.fn, self.combine, self.win_len,
                             self.slide_len, self.win_type, self.batch_len,
                             self.triggering_delay, self.name,
                             self.result_factory,
                             max_buffer_elems=self.max_buffer_elems,
                             inflight_depth=self.inflight_depth,
                             max_batch_delay_ms=self.max_batch_delay_ms)


@_alias_camel
class KeyFFATTPUBuilder(_WinBuilderBase, _TPUBuilderMixin,
                        _KeyShardedMixin):
    """builders_gpu.hpp:1003 analogue (lift + combine, key-sharded)."""

    _default_name = "key_ffat_tpu"

    def __init__(self, lift, combine):
        super().__init__(lift)
        self.combine = combine
        self.batch_len = DEFAULT_BATCH_LEN
        self.device_index = 0
        self.coalesce = True

    def build(self) -> KeyFFATTPU:
        self._check_windows()
        self._check_placement_supported()
        return KeyFFATTPU(self.fn, self.combine, self.win_len,
                          self.slide_len, self.win_type, self.parallelism,
                          self.batch_len, self.triggering_delay, self.name,
                          self.result_factory,
                          max_buffer_elems=self.max_buffer_elems,
                          coalesce=self.coalesce,
                          inflight_depth=self.inflight_depth,
                          max_batch_delay_ms=self.max_batch_delay_ms)
