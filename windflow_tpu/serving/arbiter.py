"""The SLO-driven cross-tenant arbiter (docs/SERVING.md).

One control loop per :class:`~windflow_tpu.serving.server.Server`,
riding the same ~1 Hz cadence as the diagnosis tick: every interval it
*reads* each tenant's already-computed SLO tracker state (burn rates,
open breach, violating objectives -- slo/plane.py judges them on the
diagnosis tick) and bottleneck scores, and only under contention does
it *actuate* -- scale a donor tenant's elastic operator down and/or
move part of the donor's credit allocation to the breaching victim.
It adds zero hot-path work: everything it reads is a gauge some other
plane already maintains, and when nothing is breached it takes no
action at all (bench ``14_multitenant_contention`` asserts results
with the arbiter on are bitwise identical to off when uncontended).

Policy (:func:`plan_arbitration`, pure and unit-tested):

* a **victim** is a RUNNING tenant whose declared SLO is in an open
  breach episode, sustained ``breach_ticks`` consecutive arbiter ticks
  on top of the tracker's own debounce (the anomaly-band hysteresis
  discipline -- one tracker blip never triggers an arbitration);
* a **donor** is a RUNNING, non-breached, ``donor=True`` tenant of
  priority <= the victim's (never squeeze a more-important tenant for
  a less-important one), outside its per-donor cooldown, with
  something left to give: an elastic operator above ``min_replicas``
  or credits above its ``min_credits`` floor;
* victims are served worst-first (highest priority, then weight);
  donors are squeezed cheapest-first (lowest priority, then weight);
* one decision per tick (gentle convergence), each opening a per-donor
  ``cooldown_s`` window;
* every decision is recorded as an ``arbitration`` flight event
  carrying ``{victim, donor, action, evidence}`` in the server ring
  AND both tenants' graph rings, so ``doctor`` on either side explains
  it;
* **restitution**: once a victim's episode closes and stays closed
  ``clear_ticks`` consecutive ticks, the donations it drove are
  reversed newest-first (donor scaled back up, credits returned), each
  reversal an ``arbitration`` event with ``action: restore ...``.
"""
from __future__ import annotations

import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .tenant import TenantState


@dataclass
class ArbiterConfig:
    """Server-level arbiter tuning (``Server(arbiter=...)``)."""

    enabled: bool = True
    # decision cadence; matches the diagnosis tick it reads from
    interval_s: float = 1.0
    # victim must be breached this many CONSECUTIVE arbiter ticks
    # (on top of the SLO tracker's own 2-tick debounce)
    breach_ticks: int = 2
    # victim must be clear this many consecutive ticks before its
    # donations are returned (hysteresis against flapping)
    clear_ticks: int = 3
    # no further squeeze of the same donor for this long after one
    cooldown_s: float = 5.0
    # fraction of the donor's spare credits (above its floor) moved
    # per credit action
    credit_step_frac: float = 0.5
    # drain budget handed to PipeGraph.rescale per action
    rescale_timeout_s: float = 60.0


@dataclass
class TenantView:
    """One tenant's arbitration-relevant state at a tick -- a pure
    value so the planner is testable without a server."""

    name: str
    running: bool = True
    priority: int = 0
    weight: float = 1.0
    donor: bool = True
    # SLO tracker state (None when the tenant declared no objectives)
    breached: Optional[bool] = None
    burn_fast: float = 0.0
    budget_burned: float = 0.0
    violating: Tuple[str, ...] = ()
    values: dict = field(default_factory=dict)
    # actuation surface
    credits: int = 0
    min_credits: int = 1
    # (operator_key, parallelism, min_replicas, max_replicas)
    elastic: List[Tuple[str, int, int, int]] = field(default_factory=list)
    # diagnosis root-cause walk: the donor's own bottleneck score
    # (recorded as evidence -- a donor that is itself saturated gets
    # named in the decision, helping post-mortems)
    bottleneck: float = 0.0
    # device-lease rows from the worker's DeviceLeaseRegistry
    # ({"Operator", "Chip", "Contended", "Resident", …}); empty when
    # the server schedules no device lanes
    device_ops: List[dict] = field(default_factory=list)
    # the live TenantHandle this view was taken from (ignored by the
    # pure planners; the arbiter actuates through it so an evict +
    # same-name resubmit after the snapshot can never be squeezed as
    # if it were the tenant the view described)
    handle: object = field(default=None, compare=False)


@dataclass
class Donation:
    """Ledger entry for one applied squeeze, so restitution can
    reverse it exactly.  ``victim_departed`` marks entries whose
    victim name was evicted and RE-SUBMITTED as an unrelated tenant:
    the new namesake must neither hold the restitution hostage nor
    be debited for credits it never received."""

    victim: str
    donor: str
    operator: Optional[str] = None
    old_parallelism: int = 0
    new_parallelism: int = 0
    credits_moved: int = 0
    victim_departed: bool = False


def _spare_credits(v: TenantView, frac: float) -> int:
    """The documented step: ``frac`` of the credits above the donor's
    floor (min 1 so a tiny spare still converges), never more than the
    spare itself."""
    spare = max(0, v.credits - v.min_credits)
    if spare <= 0:
        return 0
    return max(1, min(spare, int(spare * frac)))


def _scalable_op(v: TenantView) -> Optional[Tuple[str, int, int]]:
    """(operator, parallelism, new_parallelism) of the donor operator
    with the most headroom above its floor, or None."""
    best = None
    for op, par, lo, _hi in v.elastic:
        if par > lo and (best is None or par - lo > best[1] - best[2]):
            best = (op, par, lo)
    if best is None:
        return None
    op, par, lo = best
    new = max(lo, par - max(1, par // 2))
    return op, par, new


def _contended_demotion(victim: TenantView,
                        donor: TenantView) -> Optional[dict]:
    """The device rung of the escalation ladder: when the victim holds
    a lease on a CONTENDED chip and the donor holds a demotable
    (non-resident) lease on the same chip, flipping the donor's lane
    device->host frees the chip for the breaching tenant -- the
    targeted fix, tried before any rescale/credit squeeze."""
    victim_chips = {r.get("Chip") for r in victim.device_ops
                    if r.get("Contended")}
    if not victim_chips:
        return None
    for r in donor.device_ops:
        if r.get("Chip") in victim_chips and not r.get("Resident"):
            return {"type": "device", "operator": r["Operator"],
                    "chip": r.get("Chip"), "to": "host"}
    return None


def plan_arbitration(views: List[TenantView], cfg: ArbiterConfig,
                     breach_runs: Dict[str, int],
                     cooldowns: Dict[str, float],
                     now: float) -> Optional[dict]:
    """One decision (or None): the worst sustained victim paired with
    the cheapest eligible donor, with the concrete actions to apply.
    Pure -- all runtime state comes in as arguments."""
    victims = [v for v in views
               if v.running and v.breached
               and breach_runs.get(v.name, 0) >= cfg.breach_ticks]
    if not victims:
        return None
    victims.sort(key=lambda v: (-v.priority, -v.weight, v.name))
    for victim in victims:
        donors = [d for d in views
                  if d.running and d.donor and d.name != victim.name
                  and not d.breached
                  and d.priority <= victim.priority
                  and now >= cooldowns.get(d.name, 0.0)]
        donors.sort(key=lambda d: (d.priority, d.weight, d.name))
        # rung 1 of the ladder: a chip-targeted device demotion.  When
        # the victim's chip is contended, squeezing an unrelated
        # donor's credits cannot clear the contention -- sweep for a
        # co-lessee first (cheapest donor order still applies).
        for donor in donors:
            demote = _contended_demotion(victim, donor)
            if demote is None:
                continue
            return {
                "victim": victim.name,
                "donor": donor.name,
                "actions": [demote],
                "evidence": {
                    "violating": list(victim.violating),
                    "burn_fast": victim.burn_fast,
                    "budget_burned": victim.budget_burned,
                    "values": dict(victim.values),
                    "victim_priority": victim.priority,
                    "donor_priority": donor.priority,
                    "donor_weight": donor.weight,
                    "donor_bottleneck": round(donor.bottleneck, 3),
                    "chip": demote["chip"],
                    "contended": True,
                },
            }
        # rungs 2+3: elastic down-scale, then credit transfer
        for donor in donors:
            actions = []
            rescale = _scalable_op(donor)
            if rescale is not None:
                op, par, new = rescale
                actions.append({"type": "rescale", "operator": op,
                                "old": par, "new": new})
            moved = _spare_credits(donor, cfg.credit_step_frac)
            if moved > 0:
                actions.append({"type": "credits", "moved": moved,
                                "donor_credits": donor.credits,
                                "victim_credits": victim.credits})
            if not actions:
                continue  # this donor has nothing left; try the next
            return {
                "victim": victim.name,
                "donor": donor.name,
                "actions": actions,
                "evidence": {
                    "violating": list(victim.violating),
                    "burn_fast": victim.burn_fast,
                    "budget_burned": victim.budget_burned,
                    "values": dict(victim.values),
                    "victim_priority": victim.priority,
                    "donor_priority": donor.priority,
                    "donor_weight": donor.weight,
                    "donor_bottleneck": round(donor.bottleneck, 3),
                },
            }
    return None


def plan_restitution(views: List[TenantView], cfg: ArbiterConfig,
                     donations: List[Donation],
                     clear_runs: Dict[str, int]) -> Optional[Donation]:
    """The newest donation whose victim has stayed clear (un-breached,
    still running) for ``clear_ticks`` consecutive ticks -- or whose
    victim is gone entirely (no point holding a squeeze for a tenant
    that ended).  Returned one at a time, newest-first, mirroring the
    gentle one-action-per-tick application."""
    by_name = {v.name: v for v in views}
    for d in reversed(donations):
        v = None if d.victim_departed else by_name.get(d.victim)
        if v is None or not v.running:
            return d
        if not v.breached and clear_runs.get(d.victim, 0) \
                >= cfg.clear_ticks:
            return d
    return None


def describe_actions(actions: List[dict], donor: str,
                     victim: str, restore: bool = False) -> str:
    """Human phrasing of a decision's actions -- the ``action`` string
    in the flight event and the doctor line."""
    parts = []
    for a in actions:
        if a["type"] == "rescale":
            arrow = f"{a['old']}→{a['new']}"
            verb = "restored" if restore else "scaled"
            parts.append(f"{verb} {a['operator']}@{donor} {arrow}")
        elif a["type"] == "credits":
            if restore:
                parts.append(f"returned {a['moved']} credits to {donor}")
            else:
                parts.append(f"granted {a['moved']} credits to {victim}")
        elif a["type"] == "device":
            parts.append(f"demoted {a['operator']}@{donor} "
                         f"device→host on contended {a['chip']}")
    return ", ".join(parts) if parts else "no-op"


def describe_evidence(ev: dict) -> str:
    """One evidence phrase for the doctor line, e.g.
    ``throughput 12.0 < floor rps, budget 45% burned``."""
    if not ev:
        return ""
    parts = []
    vals = ev.get("values") or {}
    for name in ev.get("violating") or ():
        if name == "e2e_p99" and vals.get("e2e_p99_ms") is not None:
            parts.append(f"p99 {vals['e2e_p99_ms']:g} ms over budget")
        elif name == "throughput" \
                and vals.get("throughput_rps") is not None:
            parts.append(
                f"throughput {vals['throughput_rps']:g} rps under floor")
        elif name == "frontier_lag" \
                and vals.get("frontier_lag_ms") is not None:
            parts.append(
                f"frontier lag {vals['frontier_lag_ms']:g} ms over cap")
        else:
            parts.append(name)
    if ev.get("budget_burned"):
        parts.append(f"{ev['budget_burned'] * 100:.0f}% budget burned")
    return ", ".join(parts)


class CrossTenantArbiter(threading.Thread):
    """Owns the cadence and the hysteresis/cooldown state; reads
    tenant views from the server and applies planned decisions through
    it.  ``tick()`` is callable directly (tests drive it without the
    thread)."""

    def __init__(self, server, cfg: Optional[ArbiterConfig] = None):
        super().__init__(name="windflow-tenant-arbiter", daemon=True)
        self.server = server
        self.cfg = cfg or ArbiterConfig()
        self._stop_evt = threading.Event()
        # orders tick() (arbiter thread) against forget() (a submit
        # thread re-using a tenant name): ledger/hysteresis mutations
        # only -- never held across an apply (rescales drain for
        # seconds)
        self._state_lock = threading.Lock()
        self._breach_runs: Dict[str, int] = {}
        self._clear_runs: Dict[str, int] = {}
        self._cooldowns: Dict[str, float] = {}
        self.donations: List[Donation] = []
        # recent applied decisions, BOUNDED like every other
        # observability ring in this repo; decisions_total keeps the
        # lifetime count for the stats surface
        self.decisions: deque = deque(maxlen=256)
        self.decisions_total = 0

    # -- cadence -------------------------------------------------------
    def run(self) -> None:
        while not self._stop_evt.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover -- the arbiter must
                import traceback  # never take the server down
                traceback.print_exc()

    def stop(self) -> None:
        self._stop_evt.set()
        if self.is_alive():
            self.join(timeout=10.0)

    def forget(self, name: str) -> None:
        """Drop all hysteresis state for ``name`` -- called by the
        server when a tenant name is (re)submitted, so a fresh tenant
        can never inherit a departed namesake's breach run or
        cooldown (eviction + resubmit inside one tick would otherwise
        dodge the absent-name sweep in ``_advance_runs``).  The
        donation ledger is scrubbed too: a departed DONOR's squeezes
        die with it (the new namesake never donated and must not be
        'restored'), and entries owed by a departed VICTIM are marked
        so restitution fires instead of resolving against the new
        namesake's lease."""
        with self._state_lock:
            self._breach_runs.pop(name, None)
            self._clear_runs.pop(name, None)
            self._cooldowns.pop(name, None)
            self.donations = [d for d in self.donations
                              if d.donor != name]
            for d in self.donations:
                if d.victim == name:
                    d.victim_departed = True

    # -- one decision cycle --------------------------------------------
    def _advance_runs(self, views: List[TenantView]) -> None:
        seen = set()
        for v in views:
            seen.add(v.name)
            if v.breached:
                self._breach_runs[v.name] = \
                    self._breach_runs.get(v.name, 0) + 1
                self._clear_runs[v.name] = 0
            else:
                self._breach_runs[v.name] = 0
                self._clear_runs[v.name] = \
                    self._clear_runs.get(v.name, 0) + 1
        for name in list(self._breach_runs):
            if name not in seen:
                self._breach_runs.pop(name, None)
                self._clear_runs.pop(name, None)
        for name in list(self._cooldowns):
            # prune with the same sweep: a long-lived server cycling
            # tenant names must not grow this dict without bound, and
            # a re-submitted name must not inherit a departed
            # namesake's residual cooldown
            if name not in seen:
                self._cooldowns.pop(name, None)

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        now = _time.monotonic() if now is None else now
        views = self.server.tenant_views()
        with self._state_lock:
            self._advance_runs(views)
            decision = plan_arbitration(views, self.cfg,
                                        self._breach_runs,
                                        self._cooldowns, now)
        if decision is not None:
            by_name = {v.name: v for v in views}
            donor_view = by_name.get(decision["donor"])
            donor_handle = donor_view.handle \
                if donor_view is not None else None
            victim_view = by_name.get(decision["victim"])
            applied = self.server.apply_arbitration(
                decision,
                victim=victim_view.handle
                if victim_view is not None else None,
                donor=donor_handle)
            if applied:
                with self._state_lock:
                    # a forget() during the (possibly seconds-long)
                    # apply means the donor name now belongs to an
                    # unrelated tenant: no cooldown, no ledger entry
                    # -- the squeeze died with the evicted graph
                    same = donor_handle is None or \
                        self.server.get(decision["donor"]) \
                        is donor_handle
                    if same:
                        self._cooldowns[decision["donor"]] = \
                            now + self.cfg.cooldown_s
                        for a in decision["actions"]:
                            if a.get("applied") is False:
                                continue
                            if a["type"] == "device":
                                # device demotions are ONE-WAY: a
                                # restitution that promoted the lane
                                # back host->device would re-contend
                                # the chip the moment the victim
                                # recovers (flap by construction).
                                # Re-promotion is an operator decision
                                # via replace_lane(op, "device").
                                continue
                            self.donations.append(Donation(
                                victim=decision["victim"],
                                donor=decision["donor"],
                                operator=a.get("operator")
                                if a["type"] == "rescale" else None,
                                old_parallelism=a.get("old", 0),
                                new_parallelism=a.get("new", 0),
                                credits_moved=a.get("moved", 0)
                                if a["type"] == "credits" else 0))
                self.decisions.append(decision)
                self.decisions_total += 1
            return decision
        # nothing to squeeze: consider giving something back.  A
        # ledger entry is dropped only once FULLY restored (the apply
        # mutates it down -- a partial give-back keeps its remainder)
        # or once its donor is gone (nothing left to restore to); a
        # failed restore (e.g. a rescale drain timeout, no cap room)
        # stays ledgered and is skipped over THIS tick so one stuck
        # entry cannot starve an older restorable donation forever.
        # At most one actuation per tick, like the squeeze path.
        skipped: set = set()
        while True:
            with self._state_lock:
                pool = [x for x in self.donations
                        if id(x) not in skipped]
            d = plan_restitution(views, self.cfg, pool,
                                 self._clear_runs)
            if d is None:
                return None
            with self._state_lock:
                # forget() may have scrubbed it between the snapshot
                # and now (tenant name re-submitted): applying would
                # resolve against an unrelated namesake
                if not any(x is d for x in self.donations):
                    skipped.add(id(d))
                    continue
            applied = self.server.apply_restitution(d)
            donor = self.server.get(d.donor)
            fully = d.operator is None and d.credits_moved <= 0
            if fully or donor is None \
                    or donor.state != TenantState.RUNNING:
                with self._state_lock:
                    self.donations = [x for x in self.donations
                                      if x is not d]
            if applied:
                return None
            skipped.add(id(d))
