"""Multi-tenant serving plane: one shared runtime hosting many
PipeGraphs (docs/SERVING.md).

The :class:`Server` turns the library into an operable runtime:

* **dynamic submission/teardown** -- ``submit(name, build_fn,
  tenant=TenantSpec(...))`` constructs a fresh PipeGraph, lets the
  caller's ``build_fn`` populate it, starts it, and registers it
  against the server's shared monitoring/dashboard plane; the returned
  :class:`TenantHandle` watches the graph to a terminal state
  (``COMPLETED`` / ``STOPPED`` / ``FAILED``).  ``handle.stop()`` /
  ``Server.evict(name)`` tear a tenant down with full resource
  reclamation: replica/monitor/auditor threads joined by the graph's
  own ``wait_end``, dashboard sockets closed, ColumnPool arenas
  drained, credit reservation returned to the cap.  One tenant's crash
  surfaces as a FAILED handle while every other tenant keeps flowing
  -- isolation is per-graph by construction (own channels, own
  CancelToken, own DeadLetterStore, own buffer pool).
* **per-tenant budgets + admission control** -- every tenant reserves
  its ``TenantSpec.credits`` under the server's global ``capacity``
  cap at submit (strictly: an over-cap submit raises
  :class:`~windflow_tpu.serving.tenant.AdmissionError`), and the
  reservation is partitioned across the tenant's ingest credit gates,
  so a tenant over budget blocks or sheds at ITS OWN ingest boundary
  into ITS OWN ledger-visible dead letters.
* **the cross-tenant arbiter** -- see serving/arbiter.py; the server
  supplies :meth:`tenant_views` and applies decisions
  (:meth:`apply_arbitration` / :meth:`apply_restitution`), recording
  every decision as an ``arbitration`` flight event in its own ring
  and both affected tenants' graph rings.
* **per-tenant observability** -- each graph's stats JSON carries a
  ``Tenant`` block, the dashboard serves a registered-apps index and a
  ``/tenants`` view, ``/metrics`` grows ``windflow_tenant_*``
  families, and ``doctor`` explains every arbitration.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Callable, Dict, List, Optional

from .arbiter import (ArbiterConfig, CrossTenantArbiter, Donation,
                      TenantView, describe_actions, describe_evidence)
from .tenant import AdmissionError, TenantSpec, TenantState


def process_census() -> dict:
    """Thread + file-descriptor census of this process -- the
    lifecycle-leak regression surface (tests assert repeated
    submit/evict cycles return to the baseline census)."""
    threads = sorted(t.name for t in threading.enumerate())
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:  # non-procfs platform: thread census only
        fds = -1
    return {"threads": len(threads), "names": threads, "fds": fds}


class TenantHandle:
    """One submitted tenant: the graph, its live resource lease, and a
    watcher thread driving the handle to a terminal state."""

    def __init__(self, server: "Server", name: str, spec: TenantSpec,
                 graph):
        self.server = server
        self.name = name
        self.spec = spec
        self.graph = graph
        self.state = TenantState.RUNNING
        self.error: Optional[BaseException] = None
        self.credits = spec.credits     # live allocation (arbiter moves it)
        self.arbitrations = 0
        self._ingest: List = []          # IngestSourceLogic instances
        self._stop_requested = False
        self._done = threading.Event()
        self._watcher = threading.Thread(
            target=self._watch, name=f"windflow-tenant-{name}",
            daemon=True)

    # -- lifecycle -----------------------------------------------------
    def _watch(self) -> None:
        error: Optional[BaseException] = None
        try:
            self.graph.wait_end()
            state = TenantState.STOPPED if self._stop_requested \
                else TenantState.COMPLETED
        except BaseException as exc:
            # cancellation we asked for is not a failure -- but a
            # GENUINE replica error racing our stop() must still
            # surface as FAILED (a pure-cancel NodeFailureError
            # carries no (name, error) pairs; one from a real crash
            # does, whether or not a stop was also in flight)
            genuine = bool(getattr(exc, "errors", None))
            if self._stop_requested and not genuine:
                state = TenantState.STOPPED
            else:
                state, error = TenantState.FAILED, exc
        self.state, self.error = state, error
        self.server._on_tenant_end(self)
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until the tenant reaches a terminal state (or the
        timeout passes); returns the current state either way."""
        self._done.wait(timeout)
        return self.state

    def done(self) -> bool:
        return self._done.is_set()

    def stop(self, timeout: float = 30.0) -> str:
        """Cancel the graph and wait for teardown: replica + plane
        threads joined by ``wait_end``, then arenas drained.  A tenant
        already terminal just reclaims.  Returns the terminal state."""
        if not self._done.is_set():
            self._stop_requested = True
            self.graph.cancel()
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"tenant {self.name!r} did not tear down in {timeout}s")
        self._reclaim()
        return self.state

    def _reclaim(self) -> None:
        pool = getattr(self.graph, "buffer_pool", None)
        if pool is not None:
            pool.drain()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"<TenantHandle {self.name} {self.state} "
                f"credits={self.credits}>")


class Server:
    """Shared-runtime control plane hosting many tenant PipeGraphs
    under one global credit capacity cap, one monitoring/dashboard
    plane and one cross-tenant arbiter."""

    def __init__(self, capacity: int = 1 << 20, *,
                 name: str = "windflow-server",
                 arbiter=None, dashboard: bool = True,
                 http_port: Optional[int] = None,
                 fair_share: bool = False,
                 devices=None,
                 worker_id: Optional[int] = None):
        if capacity < 1:
            raise ValueError("Server capacity must be >= 1")
        self.name = name
        self.capacity = capacity
        self._granted = 0
        self._lock = threading.RLock()
        self._tenants: Dict[str, TenantHandle] = {}
        self._closed = False
        # global-scheduler plane (windflow_tpu/scheduler/): both knobs
        # default OFF so a plain Server behaves exactly as before --
        # the FleetServer's workers turn them on.
        self.worker_id = worker_id
        self.shares = None
        if fair_share:
            from ..scheduler.leases import FairShareRegistry
            self.shares = FairShareRegistry()
        self.devices = None
        if devices is not None:
            from ..scheduler.devices import DeviceLeaseRegistry
            self.devices = DeviceLeaseRegistry(lanes=devices) \
                if isinstance(devices, int) else devices
        from ..telemetry import FlightRecorder
        self.flight = FlightRecorder(512)
        # shared monitoring plane: every tenant's MonitoringThread
        # registers here (ephemeral port -- many servers coexist)
        self.dash = None
        self.httpd = None
        if not dashboard and http_port is not None:
            raise ValueError("http_port needs the dashboard plane "
                             "(Server(dashboard=False) has nothing "
                             "to serve)")
        if dashboard:
            from ..monitoring.dashboard import DashboardServer, serve_http
            self.dash = DashboardServer(port=0)
            self.dash.start()
            if http_port is not None:
                self.httpd = serve_http(self.dash, http_port,
                                        server=self)
        # the arbiter: ArbiterConfig | None (defaults) | False (off)
        if arbiter is False:
            acfg = None
        elif arbiter is None or arbiter is True:
            acfg = ArbiterConfig()
        else:
            acfg = arbiter
        self.arbiter = None
        if acfg is not None and acfg.enabled:
            self.arbiter = CrossTenantArbiter(self, acfg)
            self.arbiter.start()

    # -- submission / teardown -----------------------------------------
    def submit(self, name: str, build_fn: Callable,
               tenant: Optional[TenantSpec] = None,
               config=None) -> TenantHandle:
        """Construct, start and register one tenant graph.

        ``build_fn(graph)`` populates the fresh PipeGraph (sources,
        operators, sinks); ``tenant`` declares its budget/standing;
        ``config`` seeds the RuntimeConfig (cloned -- the server owns
        the tracing/dashboard/credit fields it needs)."""
        spec = tenant or TenantSpec()
        with self._lock:
            if self._closed:
                raise RuntimeError("Server is closed")
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already submitted "
                                 "(evict it first)")
            if self._granted + spec.credits > self.capacity:
                raise AdmissionError(
                    f"tenant {name!r} wants {spec.credits} credits but "
                    f"only {self.capacity - self._granted} of "
                    f"{self.capacity} remain under the global cap")
            self._granted += spec.credits
        handle: Optional[TenantHandle] = None
        try:
            handle = self._build_and_start(name, spec, build_fn, config)
        except BaseException:
            with self._lock:
                self._granted -= spec.credits
            raise
        with self._lock:
            # re-check BOTH refusal conditions at registration: a
            # close() that raced the build has already evicted its
            # registry snapshot (registering now would leak a running
            # graph nobody manages), and a concurrent same-name
            # submit may have won the registration -- overwriting it
            # would orphan the winner's graph the same way
            closed = self._closed
            duplicate = not closed and name in self._tenants
            if not closed and not duplicate:
                self._tenants[name] = handle
        if closed or duplicate:
            with self._lock:
                self._granted -= spec.credits
            handle.graph.cancel()

            def _unwind():
                try:
                    handle.graph.wait_end()
                except BaseException:
                    pass  # cancellation unwind; the refusal is the story
                handle._reclaim()

            # bounded like every other teardown here: a wedged loser
            # graph must not hang the refusing submit() forever
            t = threading.Thread(target=_unwind, daemon=True,
                                 name=f"windflow-submit-unwind-{name}")
            t.start()
            t.join(30.0)
            if closed:
                raise RuntimeError("Server is closed")
            raise ValueError(f"tenant {name!r} already submitted "
                             "(evict it first)")
        if self.arbiter is not None:
            # a re-submitted name starts with clean hysteresis state
            self.arbiter.forget(name)
        self.flight.record("tenant_submit", tenant=name,
                           credits=spec.credits, priority=spec.priority)
        handle._watcher.start()
        return handle

    def _build_and_start(self, name: str, spec: TenantSpec,
                         build_fn: Callable, config) -> TenantHandle:
        from ..core.basic import Mode, RuntimeConfig
        from ..graph.pipegraph import PipeGraph
        cfg = dataclasses.replace(config) if config is not None \
            else RuntimeConfig()
        # the serving plane is an OPERATED runtime: monitoring is on,
        # reporting to the server's shared dashboard (results stay
        # bitwise identical -- tracing sampling never alters the
        # item path, bench 8/13/14 assert it)
        cfg.tracing = True
        if self.dash is not None:
            cfg.dashboard_machine = "127.0.0.1"
            cfg.dashboard_port = self.dash.port
        # the tenant's credit allocation seeds every non-explicit
        # ingest gate; the per-gate split is rebalanced after start
        cfg.ingest_credits = spec.credits
        if spec.slo is not None:
            from ..slo import SloConfig
            cfg.slo = SloConfig(**spec.slo) \
                if isinstance(spec.slo, dict) else spec.slo
        if self.shares is not None:
            # the tenant's consume loops share cores by weighted
            # credit, not by the OS scheduler (scheduler/leases.py)
            cfg.sched_lease = self.shares.lease(name, spec.weight)
        g = PipeGraph(name, Mode.DEFAULT, cfg)
        if self.devices is not None:
            # the planner consults the worker's device-lease registry
            # before resolving 'device' (graph/planner.py)
            g.device_leases = self.devices
            g.tenant_name = name
            g.tenant_priority = spec.priority
        if spec.pool_buffers is not None and g.buffer_pool is not None:
            from ..core.tuples import ColumnPool
            g.buffer_pool = ColumnPool(max_per_bucket=spec.pool_buffers)
        handle = TenantHandle(self, name, spec, g)
        self._set_tenant_block(handle)
        build_fn(g)
        try:
            g.start()
        except BaseException:
            # a partially-started graph must not strand threads: poison
            # whatever came up, then surface the original error
            try:
                g.cancel()
            except Exception:
                pass
            raise
        self._collect_ingest(handle)
        self._set_scheduler_block(handle)   # after plan: leases exist
        return handle

    # -- resource plumbing ---------------------------------------------
    def _collect_ingest(self, handle: TenantHandle) -> None:
        """Find the tenant's ingest credit gates (source heads are
        never fused) and split its allocation across them."""
        from ..ingest.sources import IngestSourceLogic
        logics = [n.logic for n in handle.graph._all_nodes()
                  if isinstance(n.logic, IngestSourceLogic)]
        handle._ingest = logics
        if logics:
            self._apply_credit_split(handle)

    def _apply_credit_split(self, handle: TenantHandle) -> None:
        """Partition the live lease EXACTLY across the tenant's ingest
        gates (remainder to the first gates -- an even-only split
        would silently shave up to n-1 credits off the lease).  A
        CreditGate cannot hold less than one credit, so a lease below
        the gate count is effectively one credit per gate -- the only
        corner where the gates sum above the lease, and one the
        arbiter cannot create (its clamp floors at
        ``TenantSpec.min_credits``)."""
        gates = handle._ingest
        if not gates:
            return
        base, rem = divmod(max(handle.credits, len(gates)), len(gates))
        for i, logic in enumerate(gates):
            logic.gate.resize(base + (1 if i < rem else 0))

    def _transfer_credits(self, src: TenantHandle, dst: TenantHandle,
                          moved: int) -> int:
        """Zero-sum lease move between two RUNNING tenants.  The state
        check and both lease writes happen under the server lock --
        the same lock the watcher's end-of-tenant release takes and
        the watcher sets the terminal state BEFORE calling it, so a
        tenant terminating mid-move can never strand credits outside
        the ``_granted`` cap accounting.  Returns the amount actually
        moved (clamped against the live lease and ``src``'s floor)."""
        with self._lock:
            if src.state != TenantState.RUNNING \
                    or dst.state != TenantState.RUNNING:
                return 0
            moved = min(moved, src.credits - src.spec.min_credits)
            if moved <= 0:
                return 0
            src.credits -= moved
            dst.credits += moved
        for h in (src, dst):
            if h._ingest:
                self._apply_credit_split(h)
            self._set_tenant_block(h)
        return moved

    def _set_tenant_block(self, handle: TenantHandle) -> None:
        handle.graph.stats.set_tenant({
            "Name": handle.name,
            "State": handle.state,
            "Credits": handle.credits,
            "Arbitrations": handle.arbitrations,
            **handle.spec.block(),
        })

    def _set_scheduler_block(self, handle: TenantHandle) -> None:
        """The per-graph ``Scheduler`` stats block (schema v11): only
        published when some scheduler feature is on -- a plain
        Server's tenants keep the block None."""
        if self.shares is None and self.devices is None \
                and self.worker_id is None:
            return
        blk = {"Tenant": handle.name, "Worker": self.worker_id,
               "Fair_share": self.shares is not None}
        if self.shares is not None:
            blk["Weight"] = handle.spec.weight
        if self.devices is not None:
            blk["Device_leases"] = self.devices.tenant_rows(handle.name)
        handle.graph.stats.set_scheduler(blk)

    def _on_tenant_end(self, handle: TenantHandle) -> None:
        """Watcher callback at the tenant's terminal state: return its
        credit reservation to the cap and publish the final block."""
        with self._lock:
            self._granted -= handle.credits
        if self.shares is not None:
            # drop the lease so survivors' fair-share floor no longer
            # counts this tenant (activity expiry would also age it
            # out, but release is immediate and exact)
            self.shares.release(handle.name)
        if self.devices is not None:
            self.devices.release(handle.name)
        self._set_scheduler_block(handle)
        self._set_tenant_block(handle)
        self.flight.record("tenant_end", tenant=handle.name,
                           state=handle.state,
                           error=repr(handle.error)
                           if handle.error is not None else None)

    def evict(self, name: str, timeout: float = 30.0) -> TenantHandle:
        """Tear a tenant down (stop if still running) and drop it from
        the registry; its name becomes submittable again."""
        with self._lock:
            handle = self._tenants.get(name)
            if handle is None:
                raise KeyError(f"no tenant {name!r}")
        handle.stop(timeout)
        with self._lock:
            self._tenants.pop(name, None)
        return handle

    def tenants(self) -> Dict[str, TenantHandle]:
        with self._lock:
            return dict(self._tenants)

    def get(self, name: str) -> Optional[TenantHandle]:
        with self._lock:
            return self._tenants.get(name)

    @property
    def granted(self) -> int:
        with self._lock:
            return self._granted

    # -- arbiter surface -----------------------------------------------
    def tenant_views(self) -> List[TenantView]:
        """Gauge-grade snapshot of every registered tenant for the
        arbiter's planner: SLO tracker state, elastic headroom, credit
        lease.  Reads only state other planes already maintain."""
        views = []
        for handle in self.tenants().values():
            g = handle.graph
            tracker = getattr(g.diagnosis, "slo", None) \
                if g.diagnosis is not None else None
            breached = None
            burn_fast = budget = 0.0
            violating: tuple = ()
            values: dict = {}
            if tracker is not None:
                blk = tracker.block()
                breached = bool(blk.get("Breached"))
                burn_fast = float(blk.get("Burn_rate_fast") or 0.0)
                budget = float(blk.get("Budget_burned") or 0.0)
                violating = tuple(blk.get("Violating") or ())
                values = dict(blk.get("Values") or {})
            elastic = []
            for key, eh in getattr(g, "elastic", {}).items():
                elastic.append((key, eh.parallelism,
                                eh.spec.min_replicas,
                                eh.spec.max_replicas))
            scores = getattr(g.diagnosis, "_scores", None) or {} \
                if g.diagnosis is not None else {}
            views.append(TenantView(
                name=handle.name,
                running=handle.state == TenantState.RUNNING,
                priority=handle.spec.priority,
                weight=handle.spec.weight,
                donor=handle.spec.donor,
                breached=breached,
                burn_fast=burn_fast,
                budget_burned=budget,
                violating=violating,
                values=values,
                credits=handle.credits,
                min_credits=handle.spec.min_credits,
                elastic=elastic,
                bottleneck=max(scores.values(), default=0.0),
                device_ops=self.devices.tenant_rows(handle.name)
                if self.devices is not None else [],
                handle=handle,
            ))
        return views

    def _record_arbitration(self, victim, donor: TenantHandle,
                            action: str, evidence: dict,
                            actions: List[dict]) -> None:
        """``victim`` is a TenantHandle, or just its name when a
        restitution fires after the victim left -- the donor-side
        actuation must STILL be explained (every actuation is an
        arbitration flight event, ARCHITECTURE decision 16)."""
        victim_handle = victim if isinstance(victim, TenantHandle) \
            else None
        victim_name = victim.name if victim_handle is not None \
            else victim
        fields = dict(victim=victim_name, donor=donor.name,
                      action=action, evidence=evidence,
                      detail=describe_evidence(evidence),
                      actions=actions)
        self.flight.record("arbitration", **fields)
        donor.graph.flight.record("arbitration", **fields)
        donor.arbitrations += 1
        self._set_tenant_block(donor)
        if victim_handle is not None:
            victim_handle.graph.flight.record("arbitration", **fields)
            victim_handle.arbitrations += 1
            self._set_tenant_block(victim_handle)

    def apply_arbitration(self, decision: dict, victim=None,
                          donor=None) -> bool:
        """Apply one planned decision; returns True when at least one
        action took effect (the arbiter then opens the donor's
        cooldown and ledgers the donation).  ``victim``/``donor``
        accept the HANDLES the decision's views were taken from, so an
        evict + same-name resubmit after the snapshot actuates the
        departed handle (whose terminal state refuses below), never
        an unrelated namesake."""
        victim = victim if victim is not None \
            else self.get(decision["victim"])
        donor = donor if donor is not None \
            else self.get(decision["donor"])
        # both sides must still be RUNNING: the view was a snapshot,
        # and squeezing a donor (a possibly seconds-long rescale
        # drain) for a victim that just died is pure waste
        if victim is None or donor is None \
                or donor.state != TenantState.RUNNING \
                or victim.state != TenantState.RUNNING:
            return False
        cfg = self.arbiter.cfg if self.arbiter is not None \
            else ArbiterConfig()
        applied_any = False
        for a in decision["actions"]:
            if a["type"] == "rescale":
                try:
                    donor.graph.rescale(
                        a["operator"], a["new"],
                        trigger=f"arbiter:donate->{victim.name}",
                        timeout=cfg.rescale_timeout_s)
                    a["applied"] = True
                    applied_any = True
                except Exception as exc:
                    a["applied"] = False
                    a["error"] = repr(exc)
            elif a["type"] == "credits":
                # _transfer_credits re-clamps against the LIVE lease
                # under the server lock and refuses if either side
                # reached a terminal state (a released lease granted
                # anyway would corrupt the cap accounting)
                moved = self._transfer_credits(donor, victim,
                                               a["moved"])
                if moved > 0:
                    a["moved"] = moved
                    a["applied"] = True
                    applied_any = True
                else:
                    a["applied"] = False
            elif a["type"] == "device":
                # the contended-chip rung: flip the donor's lane
                # device->host through the quiesce path (zero lost
                # tuples) and release its chip lease so the victim
                # stops sharing the device
                try:
                    donor.graph.replace_lane(
                        a["operator"], "host",
                        trigger=f"arbiter:device->host"
                                f" for {victim.name}",
                        timeout=cfg.rescale_timeout_s,
                        evidence=decision.get("evidence") or None)
                    if self.devices is not None:
                        self.devices.release(donor.name,
                                             a["operator"])
                        self._set_scheduler_block(donor)
                        self._set_scheduler_block(victim)
                    a["applied"] = True
                    applied_any = True
                except Exception as exc:
                    a["applied"] = False
                    a["error"] = repr(exc)
        if applied_any:
            applied = [a for a in decision["actions"]
                       if a.get("applied")]
            self._record_arbitration(
                victim, donor,
                describe_actions(applied, donor.name, victim.name),
                decision.get("evidence") or {}, decision["actions"])
        return applied_any

    def apply_restitution(self, d: Donation) -> bool:
        """Reverse one ledgered donation (victim recovered or left).
        Mutates ``d`` to reflect what actually came back -- a restored
        rescale clears ``d.operator``, returned credits subtract from
        ``d.credits_moved`` -- so a PARTIAL restore (victim's floor or
        the cap clamped the give-back) stays ledgered for its
        remainder instead of silently forfeiting the donor's lease."""
        donor = self.get(d.donor)
        if donor is None or donor.state != TenantState.RUNNING:
            return False
        # a departed victim's name may have been re-submitted by an
        # UNRELATED tenant: never resolve the donation against it
        victim = None if d.victim_departed else self.get(d.victim)
        cfg = self.arbiter.cfg if self.arbiter is not None \
            else ArbiterConfig()
        actions: List[dict] = []
        if d.operator is not None and d.old_parallelism:
            eh = donor.graph.elastic.get(d.operator)
            cur = eh.parallelism if eh is not None else None
            if cur is None or cur >= d.old_parallelism:
                # already at/above the restore target (a manual or
                # elastic-controller rescale intervened): moot
                d.operator = None
            elif cur != d.new_parallelism:
                # a NEWER squeeze on this operator is still applied
                # below this one: restoring d.old_parallelism now
                # would silently undo it mid-breach.  Donations on one
                # operator unwind strictly LIFO -- leave this entry
                # for the tick after the newer one restores.
                pass
            else:
                try:
                    donor.graph.rescale(
                        d.operator, d.old_parallelism,
                        trigger=f"arbiter:restore<-{d.victim}",
                        timeout=cfg.rescale_timeout_s)
                    actions.append({"type": "rescale",
                                    "operator": d.operator,
                                    "old": d.new_parallelism,
                                    "new": d.old_parallelism,
                                    "applied": True})
                    d.operator = None   # restored; nothing left
                except Exception as exc:
                    actions.append({"type": "rescale",
                                    "operator": d.operator,
                                    "applied": False,
                                    "error": repr(exc)})
        if d.credits_moved > 0:
            if victim is not None \
                    and victim.state == TenantState.RUNNING:
                give_back = self._transfer_credits(victim, donor,
                                                   d.credits_moved)
            else:
                # a gone victim's lease was already released to the
                # cap; re-reserve for the donor only what the cap
                # still holds -- atomically with the donor's own
                # possible termination
                with self._lock:
                    if donor.state != TenantState.RUNNING:
                        give_back = 0
                    else:
                        give_back = min(d.credits_moved,
                                        self.capacity - self._granted)
                        if give_back > 0:
                            self._granted += give_back
                            donor.credits += give_back
                if give_back > 0:
                    if donor._ingest:
                        self._apply_credit_split(donor)
                    self._set_tenant_block(donor)
            if give_back > 0:
                d.credits_moved -= give_back
                actions.append({"type": "credits",
                                "moved": give_back,
                                "applied": True})
        applied = [a for a in actions if a.get("applied")]
        if applied:
            # record even when the victim already left: the donor-side
            # actuation must still be explained by doctor
            self._record_arbitration(
                victim if victim is not None else d.victim, donor,
                describe_actions(applied, d.donor, d.victim,
                                 restore=True),
                {}, actions)
        return bool(applied)

    # -- observability -------------------------------------------------
    def scheduler_block(self) -> Optional[dict]:
        """Worker-level ``Scheduler`` block (None when the scheduler
        plane is off): capacity envelope, per-tenant placements, fair
        -share leases with their accumulated waits, device leases.
        Fleet workers push this to the ClusterObserver so the policy
        re-reads live load, and ``merge_stats`` folds it fleet-wide."""
        if self.shares is None and self.devices is None \
                and self.worker_id is None:
            return None
        placements = []
        for handle in self.tenants().values():
            placements.append({
                "Tenant": handle.name,
                "Worker": self.worker_id,
                "State": handle.state,
                "Credits": handle.credits,
                "Priority": handle.spec.priority,
                "Weight": handle.spec.weight,
                "Devices": handle.spec.devices,
            })
        blk = {
            "Worker": self.worker_id,
            "Capacity": self.capacity,
            "Granted": self.granted,
            "Fair_share": self.shares is not None,
            "Placements": placements,
        }
        if self.shares is not None:
            blk.update(self.shares.block())
        if self.devices is not None:
            blk["Devices"] = self.devices.block()
        return blk

    def stats(self) -> dict:
        """The server-level ``Tenants`` block: one row per registered
        tenant with its standing, lease, state, last SLO judgement and
        arbitration count."""
        rows = []
        for handle in self.tenants().values():
            g = handle.graph
            with g.stats.lock:
                slo = g.stats.slo
            rows.append({
                "Name": handle.name,
                "State": handle.state,
                "Priority": handle.spec.priority,
                "Weight": handle.spec.weight,
                "Donor": handle.spec.donor,
                "Credits": handle.credits,
                "Arbitrations": handle.arbitrations,
                "Slo": slo,
                "Error": repr(handle.error)
                if handle.error is not None else None,
            })
        out = {
            "Server": self.name,
            "Capacity": self.capacity,
            "Granted": self.granted,
            "Tenant_count": len(rows),
            "Arbitration_decisions":
                self.arbiter.decisions_total
                if self.arbiter is not None else 0,
            "Tenants": rows,
        }
        sched = self.scheduler_block()
        if sched is not None:
            out["Scheduler"] = sched
        return out

    def stats_json(self) -> str:
        return json.dumps(self.stats())

    def explain(self, name: str) -> dict:
        """The tenant's doctor report (arbitration events included via
        its graph's flight ring)."""
        handle = self.get(name)
        if handle is None:
            raise KeyError(f"no tenant {name!r}")
        g = handle.graph
        if not g._ended:
            return g.explain()
        from ..diagnosis.report import build_report
        stats = json.loads(g.stats.to_json(
            g.get_num_dropped_tuples(), g.dead_letters.count()))
        return build_report(stats, g.flight.snapshot())

    # -- shutdown ------------------------------------------------------
    def close(self, timeout: float = 30.0) -> None:
        """Stop the arbiter, tear down every tenant, and close the
        shared dashboard/HTTP plane.  Idempotent.

        Cancellation is broadcast FIRST and the joins share ONE
        deadline (the DistRuntime.stop discipline: K wedged tenants
        cannot stack K x timeout).  A tenant that still refuses to
        tear down is surfaced with a warning and left registered --
        its watcher still releases the credit reservation whenever it
        finally unwinds, and its monitor falls back to stats-JSON
        snapshots once the dashboard is gone."""
        import time as _time
        import warnings as _warnings
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.arbiter is not None:
            self.arbiter.stop()
        handles = self.tenants()
        for h in handles.values():
            if not h._done.is_set():
                h._stop_requested = True
                h.graph.cancel()
        deadline = _time.monotonic() + timeout
        stuck = []
        for name, h in handles.items():
            remaining = max(0.1, deadline - _time.monotonic())
            if h._done.wait(remaining):
                h._reclaim()
                with self._lock:
                    self._tenants.pop(name, None)
            else:
                stuck.append(name)
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None
        if self.dash is not None:
            self.dash.stop()
            self.dash = None
        if stuck:
            _warnings.warn(
                f"Server.close: tenants did not tear down within "
                f"{timeout}s: {stuck} (threads abandoned as stuck; "
                f"their reservations release if they ever unwind)",
                RuntimeWarning, stacklevel=2)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
