"""Tenant identity and resource contract (docs/SERVING.md).

A :class:`TenantSpec` is everything the serving plane needs to know
about one hosted pipeline that is not derivable from its graph: how
many ingest credits it may hold under the server's global capacity cap
(admission control happens against that cap at ``submit``), how it
ranks against its neighbours when the cross-tenant arbiter has to take
resources from someone (``priority`` strictly, then ``weight``), what
service-level objectives it declares (ridden by the existing SLO
plane, slo/plane.py), and how far the arbiter may squeeze it when it
is the donor.

Isolation that needs no spec field because it is per-graph by
construction: every tenant's PipeGraph owns its own
:class:`~windflow_tpu.resilience.policies.DeadLetterStore` (admission
shedding under the tenant's own budget quarantines into the tenant's
own ledger-visible dead letters, never a neighbour's) and its own
:class:`~windflow_tpu.core.tuples.ColumnPool` arena (bounded per
tenant via ``pool_buffers``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

# default credit allocation a spec-less tenant reserves under the cap
DEFAULT_TENANT_CREDITS = 1 << 14


class TenantState:
    """Lifecycle of a submitted tenant (string constants, not an enum:
    they travel through stats JSON)."""

    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"   # clean end (sources exhausted)
    STOPPED = "STOPPED"       # handle.stop() / Server.evict()
    FAILED = "FAILED"         # a replica error ended the graph

    TERMINAL = (COMPLETED, STOPPED, FAILED)


class AdmissionError(RuntimeError):
    """submit() rejected: the tenant's declared resources do not fit
    under the server's global capacity cap.  Admission is strict by
    design -- over-committing the cap would let one tenant's burst
    shed into a neighbour's latency instead of its own dead letters."""


@dataclass(frozen=True)
class TenantSpec:
    """Per-tenant resource budget + arbitration standing.

    * ``credits``       -- ingest-credit allocation reserved under the
                           server's global cap; split across the
                           tenant's credit gates after start.
    * ``priority``      -- arbiter ordering, higher = protected longer;
                           a donor is never squeezed for a victim of
                           strictly lower priority.
    * ``weight``        -- tie-break inside one priority class: the
                           lowest-weight eligible donor donates first.
    * ``donor``         -- False exempts the tenant from donating
                           entirely (it can still be a victim).
    * ``slo``           -- :class:`~windflow_tpu.slo.SloConfig` or a
                           kwargs dict for ``PipeGraph.with_slo``; the
                           arbiter only ever defends tenants that
                           declared objectives.
    * ``min_credits``   -- floor below which the arbiter never shrinks
                           this tenant's credit allocation.
    * ``pool_buffers``  -- per-(dtype, bucket) ColumnPool arena bound
                           (``max_per_bucket``); None keeps the library
                           default.
    * ``devices``       -- declared device-lane demand, an input to the
                           fleet scheduler's placement policy (the
                           planner still resolves actual lanes; this
                           only steers which WORKER hosts the tenant so
                           device-hungry tenants spread before they
                           contend).
    """

    credits: int = DEFAULT_TENANT_CREDITS
    priority: int = 0
    weight: float = 1.0
    donor: bool = True
    slo: Any = None
    min_credits: int = 256
    pool_buffers: Optional[int] = None
    devices: int = 0

    def __post_init__(self):
        if self.credits < 1:
            raise ValueError("TenantSpec.credits must be >= 1")
        if self.weight <= 0:
            raise ValueError("TenantSpec.weight must be positive")
        if not 1 <= self.min_credits <= self.credits:
            raise ValueError(
                "TenantSpec.min_credits must be in [1, credits]")
        if self.pool_buffers is not None and self.pool_buffers < 1:
            raise ValueError("TenantSpec.pool_buffers must be >= 1")
        if self.devices < 0:
            raise ValueError("TenantSpec.devices must be >= 0")

    def block(self) -> dict:
        """The static half of the stats-JSON ``Tenant`` block (the
        server adds the live fields: state, granted credits,
        arbitration count)."""
        return {
            "Priority": self.priority,
            "Weight": self.weight,
            "Donor": self.donor,
            "Min_credits": self.min_credits,
            "Devices": self.devices,
        }
