"""Multi-tenant serving plane (docs/SERVING.md).

Many PipeGraphs in one shared runtime process: dynamic graph
submission/teardown (:class:`Server` / :class:`TenantHandle`),
per-tenant credit budgets + admission control under a global capacity
cap (:class:`TenantSpec` / :class:`AdmissionError`), and the
SLO-driven cross-tenant arbiter (:class:`CrossTenantArbiter` /
:class:`ArbiterConfig`) that scales a donor tenant down to restore a
breaching victim's SLO -- every decision an ``arbitration`` flight
event the doctor explains.
"""
from .arbiter import (ArbiterConfig, CrossTenantArbiter, Donation,
                      TenantView, plan_arbitration, plan_restitution)
from .server import Server, TenantHandle, process_census
from .tenant import AdmissionError, TenantSpec, TenantState

__all__ = [
    "AdmissionError", "ArbiterConfig", "CrossTenantArbiter",
    "Donation", "Server", "TenantHandle", "TenantSpec", "TenantState",
    "TenantView", "plan_arbitration", "plan_restitution",
    "process_census",
]
