"""Shared helpers for the examples: size/backends knobs and a counting
sink, so each walkthrough stays focused on the feature it shows."""
import os
import threading


def maybe_force_host():
    """Honour WINDFLOW_FORCE_HOST=1 BEFORE anything touches jax (env
    var JAX_PLATFORMS alone does not beat an installed PJRT plugin)."""
    if os.environ.get("WINDFLOW_FORCE_HOST") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")


def scale(n: int) -> int:
    """Stream length, shrunk under the smoke test."""
    return max(1000, n // 100) if os.environ.get(
        "WINDFLOW_EXAMPLES_SMALL") == "1" else n


class CountingSink:
    """Thread-safe sink callback: counts results and sums .value."""

    def __init__(self):
        self.lock = threading.Lock()
        self.count = 0
        self.total = 0.0

    def __call__(self, rec):
        if rec is None:
            return
        with self.lock:
            try:
                n = len(rec)            # columnar TupleBatch
                self.count += n
                self.total += float(rec["value"].sum())
            except TypeError:
                self.count += 1
                self.total += rec.value
