"""Device-batched window aggregation: WinSeqTPU on the columnar plane.

The columnar fast path: a BatchSource produces TupleBatches (struct of
numpy arrays), WinSeqTPU folds them into per-key pane accumulators at
ingest and launches batched window reductions on the device (the
Win_Seq_GPU re-design -- win_seq_gpu.hpp:391-645 -- as XLA programs).
With no reachable accelerator the same graph runs on the host XLA
backend unchanged.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, maybe_force_host, scale  # noqa: E402

maybe_force_host()

import numpy as np  # noqa: E402

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import Mode  # noqa: E402
from windflow_tpu.core.tuples import TupleBatch  # noqa: E402
from windflow_tpu.operators.basic_ops import Sink  # noqa: E402
from windflow_tpu.operators.batch_ops import BatchSource  # noqa: E402

WIN, SLIDE = 512, 256


def main():
    n, n_keys, batch = scale(2_000_000), 16, 16_384
    state = {"sent": 0}
    arange = np.arange(batch, dtype=np.int64)

    def source(ctx):
        i = state["sent"]
        if i >= n:
            return None
        m = min(batch, n - i)
        ids = (arange[:m] + i) // n_keys
        state["sent"] = i + m
        return TupleBatch({"key": (arange[:m] + i) % n_keys, "id": ids,
                           "ts": ids, "value": np.ones(m, np.float32)})

    sink = CountingSink()
    op = wf.WinSeqTPUBuilder("sum").withTBWindows(WIN, SLIDE) \
        .withBatch(1024).withBatchOutput().build()
    g = wf.PipeGraph("device", Mode.DEFAULT)
    g.add_source(BatchSource(source)).add(op).add_sink(Sink(sink))
    g.run()
    # every full window sums WIN ones
    full = sink.count * WIN
    print(f"[03] {n} tuples -> {sink.count} device-computed windows, "
          f"sum {sink.total:,.0f} (<= {full:,} = count*win; EOS windows "
          f"are partial)")
    return sink


if __name__ == "__main__":
    main()
