"""The Yahoo Streaming Benchmark model (the flagship application).

Ad events stream through filter (views only) -> static join
(ad -> campaign) -> per-campaign windowed counts on the device plane
(`models/yahoo.py`, BASELINE config #5).
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, maybe_force_host, scale  # noqa: E402

maybe_force_host()

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import Mode  # noqa: E402
from windflow_tpu.models.yahoo import build_pipeline  # noqa: E402


def main():
    n = scale(1_000_000)
    sink = CountingSink()
    g = wf.PipeGraph("yahoo", Mode.DEFAULT)
    build_pipeline(g, n, batch_size=max(1024, n // 16),
                   device_batch=1024, sink=sink,
                   win_len=1 << 14, slide_len=1 << 14)
    g.run()
    print(f"[06] Yahoo benchmark: {n} ad events -> {sink.count} "
          f"per-campaign window counts, {sink.total:,.0f} views total")
    return sink


if __name__ == "__main__":
    main()
