"""Basic operators and the fluent builder API.

Builds the canonical chain Source -> Filter -> FlatMap -> Map ->
Accumulator -> Sink (the reference's `mp_tests` pipeline prefix plus a
keyed rolling fold), using both spellings of the builder surface
(snake_case and the reference's camelCase).
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, scale  # noqa: E402

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import BasicRecord, Mode  # noqa: E402


def main() -> CountingSink:
    n, n_keys = scale(200_000), 8
    state = {}

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= n:
            return False
        shipper.push(BasicRecord(i % n_keys, i // n_keys, i, float(i)))
        state["i"] = i + 1
        return True

    def odd_values_only(t):           # Filter: in-place predicate
        return int(t.value) % 2 == 1

    def duplicate(t, shipper):        # FlatMap: one-to-many via Shipper
        shipper.push(t)
        shipper.push(BasicRecord(t.key, t.id, t.ts, t.value / 1000.0))

    def clamp(t):                     # Map: in-place transform
        t.value = min(t.value, 1e6)

    def rolling_sum(t, acc):          # Accumulator: keyed fold
        acc.value += t.value

    sink = CountingSink()
    g = wf.PipeGraph("basic", Mode.DEFAULT)
    g.add_source(wf.SourceBuilder(src).withName("events").build()) \
        .chain(wf.FilterBuilder(odd_values_only).build()) \
        .add(wf.FlatMapBuilder(duplicate).with_parallelism(2).build()) \
        .chain(wf.MapBuilder(clamp).build()) \
        .add(wf.AccumulatorBuilder(rolling_sum)
             .withInitialValue(BasicRecord(0, 0, 0, 0.0)).build()) \
        .add_sink(wf.SinkBuilder(sink).build())
    g.run()
    print(f"[01] {n} events -> {sink.count} rolling-fold updates, "
          f"final running total {sink.total:,.1f}")
    return sink


if __name__ == "__main__":
    main()
