"""Tracing + the live dashboard.

Enable ``RuntimeConfig(tracing=True)`` and the graph reports per-replica
statistics once a second over the framed TCP protocol
(monitoring.hpp:232-313 equivalent).  This example hosts the bundled
dashboard server in-process and leaves it up briefly so you can open
the HTML front-end while the graph runs:

    http://127.0.0.1:20208/        (the web UI)
    http://127.0.0.1:20208/apps    (raw JSON snapshot)
"""
import json
import os
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, scale  # noqa: E402

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import BasicRecord, Mode, RuntimeConfig  # noqa: E402
from windflow_tpu.monitoring.dashboard import (DashboardServer,  # noqa: E402
                                               serve_http)


def main():
    n = scale(3_000_000)
    dash = DashboardServer(port=0)
    dash.start()
    httpd = serve_http(dash, port=0)
    port = httpd.server_address[1]
    print(f"[07] dashboard up: http://127.0.0.1:{port}/ "
          f"(ingest on :{dash.port})")

    log_dir = Path(os.environ.get("WINDFLOW_LOG_DIR", "/tmp/windflow_logs"))
    log_dir.mkdir(parents=True, exist_ok=True)
    cfg = RuntimeConfig(tracing=True, log_dir=str(log_dir),
                        dashboard_port=dash.port)
    state = {}

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= n:
            return False
        shipper.push(BasicRecord(i % 8, i // 8, i, float(i)))
        state["i"] = i + 1
        return True

    def window_sum(gwid, it, result):
        result.value = sum(t.value for t in it)

    sink = CountingSink()
    g = wf.PipeGraph("traced-demo", Mode.DEFAULT, cfg)
    g.add_source(wf.SourceBuilder(src).build()) \
        .add(wf.MapBuilder(lambda t: None).withParallelism(2).build()) \
        .add(wf.KeyFarmBuilder(window_sum).withCBWindows(256, 128)
             .withParallelism(2).build()) \
        .add_sink(wf.SinkBuilder(sink).build())
    g.run()

    # report frames are parsed by the dashboard's connection thread;
    # poll briefly instead of racing it (and tolerate tracing having
    # been disabled if the 2 s register handshake timed out)
    app = None
    deadline = time.time() + 5
    while time.time() < deadline:
        snap = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/apps", timeout=5))
        if snap:
            (app,) = snap.values()
            if app["report"] is not None:
                break
        time.sleep(0.1)
    if app is None or app["report"] is None:
        print(f"[07] graph done: {sink.count} windows; dashboard "
              f"received no report (register handshake timed out?)")
    else:
        ops = app["report"]["Operators"]
        print(f"[07] graph done: {sink.count} windows; dashboard "
              f"captured {len(ops)} operators, diagram "
              f"{len(app['diagram'])} bytes")
    if os.environ.get("WINDFLOW_EXAMPLES_SMALL") != "1":
        print("[07] leaving the dashboard up for 15 s -- open the URL "
              "above to see the final report")
        time.sleep(15)
    httpd.shutdown()
    httpd.server_close()
    dash.stop()
    return sink


if __name__ == "__main__":
    main()
