"""The headline lane: fused synthesis + ingest via SynthChunk.

A declared SyntheticSource with ``chunked=True`` ships tiny SynthChunk
descriptors instead of materialized columns; the device window stage's
C++ engine generates and folds each chunk in one pass (no host arrays
at all -- the columnar twin of the record plane's set_synth lowering).
Everything else in the graph is unchanged, and any non-chunk-aware
consumer transparently receives materialized batches.

This is the benchmark's headline configuration; on the bench box it
sustains >170M tuples/s end to end on one host core + one chip.
"""
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, maybe_force_host, scale  # noqa: E402

maybe_force_host()

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import Mode  # noqa: E402
from windflow_tpu.operators.basic_ops import Sink  # noqa: E402
from windflow_tpu.operators.synth import SyntheticSource  # noqa: E402

WIN, SLIDE, N_KEYS = 4096, 2048, 64


def run(n, chunked):
    sink = CountingSink()
    op = wf.WinSeqTPUBuilder("sum").withTBWindows(WIN, SLIDE) \
        .withBatch(4096).withBatchOutput().withInflight(8).build()
    g = wf.PipeGraph("chunked" if chunked else "materialized",
                     Mode.DEFAULT)
    g.add_source(SyntheticSource(n, N_KEYS, batch=1 << 20,
                                 chunked=chunked)) \
        .add(op).add_sink(Sink(sink))
    t0 = time.perf_counter()
    g.run()
    return time.perf_counter() - t0, sink


def main():
    n = scale(16_000_000)
    run(max(1000, n // 10), chunked=False)  # warm-up: backend init +
    #                                         XLA compile must not bias
    #                                         the first timed run
    dt_mat, s_mat = run(n, chunked=False)
    dt_chk, s_chk = run(n, chunked=True)
    assert s_chk.count == s_mat.count and s_chk.total == s_mat.total, \
        "the two feeds must compute identical windows"
    print(f"[08] {n:,} tuples, {s_chk.count} windows -- materialized "
          f"feed {n / dt_mat / 1e6:.1f}M tuples/s, chunked synthesis "
          f"{n / dt_chk / 1e6:.1f}M tuples/s (identical results)")
    return s_chk


if __name__ == "__main__":
    main()
