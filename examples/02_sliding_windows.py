"""Sliding-window aggregation: Key_Farm vs the incremental Key_FFAT.

Both operators compute the same keyed sliding-window sums; Key_Farm
runs the whole-window function over an Iterable of archived tuples,
Key_FFAT folds each tuple into a FlatFAT aggregation tree as it
arrives (lift + associative combine -- Tangwongsan et al., VLDB'15).
The totals must agree exactly.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, scale  # noqa: E402

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import BasicRecord, Mode  # noqa: E402

WIN, SLIDE = 100, 25


def make_source(n, n_keys):
    state = {}

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= n:
            return False
        shipper.push(BasicRecord(i % n_keys, i // n_keys, i // n_keys,
                                 float(i)))
        state["i"] = i + 1
        return True

    return src


def window_sum(gwid, iterable, result):
    result.value = sum(t.value for t in iterable)


def main():
    n, n_keys = scale(100_000), 8

    sink_kf = CountingSink()
    g1 = wf.PipeGraph("kf", Mode.DEFAULT)
    g1.add_source(wf.SourceBuilder(make_source(n, n_keys)).build()) \
        .add(wf.KeyFarmBuilder(window_sum).withTBWindows(WIN, SLIDE)
             .withParallelism(4).build()) \
        .add_sink(wf.SinkBuilder(sink_kf).build())
    g1.run()

    sink_ffat = CountingSink()
    g2 = wf.PipeGraph("kff", Mode.DEFAULT)
    g2.add_source(wf.SourceBuilder(make_source(n, n_keys)).build()) \
        .add(wf.KeyFFATBuilder(
            lambda t, r: setattr(r, "value", t.value),        # lift
            lambda a, b, o: setattr(o, "value", a.value + b.value))  # comb
            .withTBWindows(WIN, SLIDE).withParallelism(4).build()) \
        .add_sink(wf.SinkBuilder(sink_ffat).build())
    g2.run()

    assert sink_kf.total == sink_ffat.total, (sink_kf.total,
                                              sink_ffat.total)
    print(f"[02] {sink_kf.count} windows; Key_Farm and Key_FFAT agree: "
          f"total {sink_kf.total:,.1f}")
    return sink_kf


if __name__ == "__main__":
    main()
