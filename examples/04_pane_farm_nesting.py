"""Two-stage window parallelism: Pane_Farm, Win_MapReduce, and the
complex nesting WF(PF).

* Pane_Farm splits each window into non-overlapping panes
  (pane = gcd(win, slide)); the PLQ stage aggregates panes, the WLQ
  stage combines panes into windows (Li et al., SIGMOD'05).
* Win_MapReduce stripes each window's tuples over MAP workers and
  merges partials in REDUCE.
* A Pane_Farm can itself be replicated inside a Win_Farm: copy i owns
  every R-th window (private slide = slide * R -- which must stay
  below the window length, or construction is rejected).
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, scale  # noqa: E402

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import BasicRecord, Mode  # noqa: E402

WIN, SLIDE = 60, 6


def make_source(n, n_keys):
    state = {}

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= n:
            return False
        shipper.push(BasicRecord(i % n_keys, i // n_keys, i // n_keys,
                                 float(i % 97)))
        state["i"] = i + 1
        return True

    return src


def agg(gwid, iterable, result):
    result.value = sum(t.value for t in iterable)


def run(name, op, n, n_keys):
    sink = CountingSink()
    g = wf.PipeGraph(name, Mode.DETERMINISTIC)
    g.add_source(wf.SourceBuilder(make_source(n, n_keys)).build()) \
        .add(op).add_sink(wf.SinkBuilder(sink).build())
    g.run()
    return sink


def main():
    n, n_keys = scale(60_000), 6

    pf = wf.PaneFarmBuilder(agg, agg).withTBWindows(WIN, SLIDE) \
        .withParallelism(2, 2).build()
    s1 = run("pf", pf, n, n_keys)

    wmr = wf.WinMapReduceBuilder(agg, agg).withTBWindows(WIN, SLIDE) \
        .withParallelism(3, 1).build()
    s2 = run("wmr", wmr, n, n_keys)

    inner = wf.PaneFarmBuilder(agg, agg).withTBWindows(WIN, SLIDE) \
        .withParallelism(2, 1).build()
    wf_pf = wf.WinFarmBuilder(inner).withParallelism(4).build()
    s3 = run("wf_pf", wf_pf, n, n_keys)

    assert s1.total == s2.total == s3.total, (s1.total, s2.total, s3.total)
    print(f"[04] Pane_Farm, Win_MapReduce and WF(Pane_Farm x4) agree: "
          f"{s1.count} windows, total {s1.total:,.1f}")
    return s1


if __name__ == "__main__":
    main()
