"""Graph algebra: split a MultiPipe into branches, process them
differently, and merge branches back together.

The splitting function returns a branch index (or several, to
broadcast); ``select(i)`` continues building branch i; ``merge`` joins
MultiPipes into one (the reference's execute_Split / execute_Merge,
pipegraph.hpp:289-503).
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from examples._common import CountingSink, scale  # noqa: E402

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.core import BasicRecord, Mode  # noqa: E402


def main():
    n = scale(50_000)
    state = {}

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= n:
            return False
        shipper.push(BasicRecord(i % 4, i // 4, i, float(i)))
        state["i"] = i + 1
        return True

    def negate(t):
        t.value = -t.value

    sink = CountingSink()
    g = wf.PipeGraph("algebra", Mode.DEFAULT)
    pipe = g.add_source(wf.SourceBuilder(src).build())
    pipe.split(lambda t: int(t.value) % 2, 2)   # evens -> 0, odds -> 1
    evens = pipe.select(0).add(wf.MapBuilder(negate).build())
    odds = pipe.select(1)
    merged = evens.merge(odds)                  # back into one stream
    merged.add_sink(wf.SinkBuilder(sink).build())
    g.run()

    expect = sum(-v if v % 2 == 0 else v for v in range(n))
    assert sink.total == expect, (sink.total, expect)
    print(f"[05] split -> negate evens -> merge: {sink.count} records, "
          f"total {sink.total:,.0f}")
    return sink


if __name__ == "__main__":
    main()
