"""Tracing subsystem tests: stats records, JSON aggregation, dashboard
TCP protocol (type 0/1/2 frames against a fake dashboard), log dump.
Mirrors tests/miscellanea/test_tracing.cpp (SURVEY.md §4).
"""
import json
import socket
import struct
import threading

import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, Mode, RuntimeConfig, WinType
from windflow_tpu.monitoring.stats import GraphStats, StatsRecord


class FakeDashboard(threading.Thread):
    """Accepts one app: reads registration, acks an id, collects report
    frames until deregistration (reverse of monitoring.hpp:232-313)."""

    def __init__(self):
        super().__init__(daemon=True)
        self.server = socket.create_server(("127.0.0.1", 0))
        self.port = self.server.getsockname()[1]
        self.diagram = None
        self.reports = []
        self.deregistered = False

    def _recv_exact(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    def run(self):
        conn, _ = self.server.accept()
        with conn:
            mtype, length = struct.unpack("<ii", self._recv_exact(conn, 8))
            assert mtype == 0
            self.diagram = self._recv_exact(conn, length).decode()
            conn.sendall(struct.pack("<i", 42))  # app id
            while True:
                try:
                    header = self._recv_exact(conn, 12)
                except ConnectionError:
                    return
                mtype, app_id, length = struct.unpack("<iii", header)
                assert app_id == 42
                if mtype == 2:
                    self.deregistered = True
                    return
                self.reports.append(
                    json.loads(self._recv_exact(conn, length)))


def small_graph(config):
    g = wf.PipeGraph("traced", Mode.DEFAULT, config)
    state = {}

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= 50:
            return False
        shipper.push(BasicRecord(i % 2, i // 2, i, float(i)))
        state["i"] = i + 1
        return True

    def ident(t):
        pass

    g.add_source(wf.SourceBuilder(src).build()) \
        .add(wf.MapBuilder(ident).with_parallelism(2).build()) \
        .add_sink(wf.SinkBuilder(lambda r: None).build())
    return g


def test_stats_record_json_shape():
    gs = GraphStats("app")
    r = gs.register("pipe0/map", "0")
    r.inputs_received = 10
    r.outputs_sent = 10
    out = json.loads(gs.to_json(dropped_tuples=3))
    assert out["PipeGraph_name"] == "app"
    assert out["Dropped_tuples"] == 3
    assert out["Operators"][0]["Replicas"][0]["Inputs_received"] == 10
    assert out["Memory_usage_KB"] > 0


def test_tracing_counts_inputs(tmp_path):
    cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path))
    # no dashboard: monitor fails to connect, tracing still counts + dumps
    g = small_graph(cfg)
    g.run()
    data = json.loads(g.stats.to_json())
    by_name = {o["Operator_name"]: o for o in data["Operators"]}
    map_op = next(v for k, v in by_name.items() if "map" in k)
    total_in = sum(r["Inputs_received"] for r in map_op["Replicas"])
    assert total_in == 50
    # log dump happened (pipegraph.hpp:683-709 analogue)
    files = list(tmp_path.iterdir())
    assert any(f.suffix == ".json" for f in files)
    assert any(f.suffix == ".dot" for f in files)


def test_dashboard_protocol(tmp_path):
    dash = FakeDashboard()
    dash.start()
    cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path),
                        dashboard_port=dash.port)
    g = small_graph(cfg)
    g.run()
    dash.join(timeout=5)
    assert dash.diagram is not None and "digraph" in dash.diagram
    assert dash.deregistered
    assert dash.reports, "at least one 1 Hz report"
    assert dash.reports[-1]["PipeGraph_name"] == "traced"


def test_device_metrics_reported(tmp_path):
    """Device launches / staged bytes appear in the per-replica stats
    under tracing (the H2D/D2H counters of stats_record.hpp:77-79)."""
    import numpy as np
    from windflow_tpu.core.tuples import TupleBatch
    from windflow_tpu.operators.batch_ops import BatchSource
    from windflow_tpu.operators.tpu.win_seq_tpu import WinSeqTPU

    cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path))
    g = wf.PipeGraph("devstats", Mode.DEFAULT, cfg)
    n = 20_000
    keys = np.arange(n, dtype=np.int64) % 4
    ids = np.arange(n, dtype=np.int64) // 4
    it = iter([TupleBatch({"key": keys[i:i + 4096], "id": ids[i:i + 4096],
                           "ts": ids[i:i + 4096],
                           "value": np.ones(len(keys[i:i + 4096]))})
               for i in range(0, n, 4096)])
    op = WinSeqTPU("sum", 128, 64, WinType.TB, batch_len=64,
                   emit_batches=True)
    g.add_source(BatchSource(lambda ctx: next(it, None))).add(op) \
        .add_sink(wf.SinkBuilder(lambda x: None).build())
    g.run()
    data = json.loads(g.stats.to_json())
    win = next(o for o in data["Operators"]
               if "win_seq_tpu" in o["Operator_name"])
    rep = win["Replicas"][0]
    assert rep["Device_launches"] > 0
    assert rep["Bytes_to_device"] > 0
    assert rep["Bytes_from_device"] > 0


def test_runtime_queue_stats_dump(tmp_path):
    """trace_runtime dumps raw channel stats (puts/gets/high-watermark),
    the -DTRACE_FASTFLOW analogue (pipegraph.hpp:711-733)."""
    cfg = RuntimeConfig(trace_runtime=True, log_dir=str(tmp_path))
    g = small_graph(cfg)
    g.run()
    f = next(p for p in tmp_path.iterdir() if p.name.endswith("_runtime.json"))
    data = json.loads(f.read_text())
    assert data["channels"], "no channel rows dumped"
    by_node = {r["node"]: r for r in data["channels"]}
    consumed = [r for r in data["channels"] if r["gets"] > 0]
    assert consumed, by_node
    for r in consumed:
        assert r["puts"] >= r["gets"]
        assert r["residual"] == 0
        assert r["high_watermark"] >= 1


def test_dashboard_http_webui(tmp_path):
    """serve_http serves the self-contained HTML front-end at / and the
    JSON snapshot at /apps (the reference's React dashboard analogue)."""
    import urllib.request

    from windflow_tpu.monitoring.dashboard import (DashboardServer,
                                                   serve_http)

    dash = DashboardServer(port=0)
    dash.start()
    httpd = serve_http(dash, port=0)
    http_port = httpd.server_address[1]
    try:
        cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path),
                            dashboard_port=dash.port)
        g = small_graph(cfg)
        g.run()

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}{path}", timeout=5) as r:
                return r.headers["Content-Type"], r.read().decode()

        ctype, html = get("/")
        assert ctype.startswith("text/html")
        # the page is self-contained: topology parser, sparkline, table
        for marker in ("parseDot", "sparkline", "Device_launches",
                       "/apps"):
            assert marker in html, marker
        # the type-2 deregister frame is applied by the dashboard's
        # connection thread; poll until it lands rather than racing it
        import time
        deadline = time.time() + 5
        while True:
            ctype, body = get("/apps")
            assert ctype.startswith("application/json")
            apps = json.loads(body)
            assert apps, "traced graph did not register"
            (app,) = apps.values()
            if not app["active"] or time.time() > deadline:
                break
            time.sleep(0.05)
        assert "digraph" in app["diagram"]
        assert app["report"]["PipeGraph_name"] == "traced"
        assert not app["active"], "graph deregistered at wait_end"
    finally:
        httpd.shutdown()
        httpd.server_close()
        dash.stop()
