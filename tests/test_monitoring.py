"""Tracing subsystem tests: stats records, JSON aggregation, dashboard
TCP protocol (type 0/1/2 frames against a fake dashboard), log dump.
Mirrors tests/miscellanea/test_tracing.cpp (SURVEY.md §4).
"""
import json
import socket
import struct
import threading


import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, Mode, RuntimeConfig, WinType
from windflow_tpu.monitoring.stats import GraphStats


class FakeDashboard(threading.Thread):
    """Accepts one app: reads registration, acks an id, collects report
    frames until deregistration (reverse of monitoring.hpp:232-313)."""

    def __init__(self):
        super().__init__(daemon=True)
        self.server = socket.create_server(("127.0.0.1", 0))
        self.port = self.server.getsockname()[1]
        self.diagram = None
        self.reports = []
        self.deregistered = False

    def _recv_exact(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    def run(self):
        conn, _ = self.server.accept()
        with conn:
            mtype, length = struct.unpack("<ii", self._recv_exact(conn, 8))
            assert mtype == 0
            self.diagram = self._recv_exact(conn, length).decode()
            conn.sendall(struct.pack("<i", 42))  # app id
            while True:
                try:
                    header = self._recv_exact(conn, 12)
                except ConnectionError:
                    return
                mtype, app_id, length = struct.unpack("<iii", header)
                assert app_id == 42
                if mtype == 2:
                    self.deregistered = True
                    return
                self.reports.append(
                    json.loads(self._recv_exact(conn, length)))


def small_graph(config):
    g = wf.PipeGraph("traced", Mode.DEFAULT, config)
    state = {}

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= 50:
            return False
        shipper.push(BasicRecord(i % 2, i // 2, i, float(i)))
        state["i"] = i + 1
        return True

    def ident(t):
        pass

    g.add_source(wf.SourceBuilder(src).build()) \
        .add(wf.MapBuilder(ident).with_parallelism(2).build()) \
        .add_sink(wf.SinkBuilder(lambda r: None).build())
    return g


def test_stats_record_json_shape():
    gs = GraphStats("app")
    r = gs.register("pipe0/map", "0")
    r.inputs_received = 10
    r.outputs_sent = 10
    out = json.loads(gs.to_json(dropped_tuples=3))
    assert out["PipeGraph_name"] == "app"
    assert out["Dropped_tuples"] == 3
    assert out["Operators"][0]["Replicas"][0]["Inputs_received"] == 10
    assert out["Memory_usage_KB"] > 0


def test_tracing_counts_inputs(tmp_path):
    cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path))
    # no dashboard: monitor fails to connect, tracing still counts + dumps
    g = small_graph(cfg)
    g.run()
    data = json.loads(g.stats.to_json())
    by_name = {o["Operator_name"]: o for o in data["Operators"]}
    map_op = next(v for k, v in by_name.items() if "map" in k)
    total_in = sum(r["Inputs_received"] for r in map_op["Replicas"])
    assert total_in == 50
    # log dump happened (pipegraph.hpp:683-709 analogue)
    files = list(tmp_path.iterdir())
    assert any(f.suffix == ".json" for f in files)
    assert any(f.suffix == ".dot" for f in files)
    # rendered diagram artifact (the reference dumps a PDF/SVG over the
    # wire, pipegraph.hpp:683-709): well-formed XML with one box per
    # operator in the chain
    import xml.etree.ElementTree as ET
    svg = next(f for f in files if f.suffix == ".svg")
    root = ET.fromstring(svg.read_text())
    ns = "{http://www.w3.org/2000/svg}"
    boxes = root.findall(f"{ns}rect")
    texts = [t.text for t in root.findall(f"{ns}text")]
    assert len(boxes) >= 3  # source + map + sink at minimum
    assert any("map" in (t or "") for t in texts)


def test_dashboard_protocol(tmp_path):
    dash = FakeDashboard()
    dash.start()
    cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path),
                        dashboard_port=dash.port)
    g = small_graph(cfg)
    g.run()
    dash.join(timeout=5)
    assert dash.diagram is not None
    assert dash.diagram.lstrip().startswith("<svg")
    assert dash.deregistered
    assert dash.reports, "at least one 1 Hz report"
    assert dash.reports[-1]["PipeGraph_name"] == "traced"


def test_device_metrics_reported(tmp_path):
    """Device launches / staged bytes appear in the per-replica stats
    under tracing (the H2D/D2H counters of stats_record.hpp:77-79)."""
    import numpy as np
    from windflow_tpu.core.tuples import TupleBatch
    from windflow_tpu.operators.batch_ops import BatchSource
    from windflow_tpu.operators.tpu.win_seq_tpu import WinSeqTPU

    cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path))
    g = wf.PipeGraph("devstats", Mode.DEFAULT, cfg)
    n = 20_000
    keys = np.arange(n, dtype=np.int64) % 4
    ids = np.arange(n, dtype=np.int64) // 4
    it = iter([TupleBatch({"key": keys[i:i + 4096], "id": ids[i:i + 4096],
                           "ts": ids[i:i + 4096],
                           "value": np.ones(len(keys[i:i + 4096]))})
               for i in range(0, n, 4096)])
    op = WinSeqTPU("sum", 128, 64, WinType.TB, batch_len=64,
                   emit_batches=True)
    g.add_source(BatchSource(lambda ctx: next(it, None))).add(op) \
        .add_sink(wf.SinkBuilder(lambda x: None).build())
    g.run()
    data = json.loads(g.stats.to_json())
    win = next(o for o in data["Operators"]
               if "win_seq_tpu" in o["Operator_name"])
    rep = win["Replicas"][0]
    assert rep["Device_launches"] > 0
    assert rep["Bytes_to_device"] > 0
    assert rep["Bytes_from_device"] > 0


def test_runtime_queue_stats_dump(tmp_path):
    """trace_runtime dumps raw channel stats (puts/gets/high-watermark),
    the -DTRACE_FASTFLOW analogue (pipegraph.hpp:711-733)."""
    cfg = RuntimeConfig(trace_runtime=True, log_dir=str(tmp_path))
    g = small_graph(cfg)
    g.run()
    f = next(p for p in tmp_path.iterdir() if p.name.endswith("_runtime.json"))
    data = json.loads(f.read_text())
    assert data["channels"], "no channel rows dumped"
    by_node = {r["node"]: r for r in data["channels"]}
    consumed = [r for r in data["channels"] if r["gets"] > 0]
    assert consumed, by_node
    for r in consumed:
        assert r["puts"] >= r["gets"]
        assert r["residual"] == 0
        assert r["high_watermark"] >= 1


def test_dashboard_http_webui(tmp_path):
    """serve_http serves the self-contained HTML front-end at / and the
    JSON snapshot at /apps (the reference's React dashboard analogue)."""
    import urllib.request

    from windflow_tpu.monitoring.dashboard import (DashboardServer,
                                                   serve_http)

    dash = DashboardServer(port=0)
    dash.start()
    httpd = serve_http(dash, port=0)
    http_port = httpd.server_address[1]
    try:
        cfg = RuntimeConfig(tracing=True, log_dir=str(tmp_path),
                            dashboard_port=dash.port)
        g = small_graph(cfg)
        g.run()

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}{path}", timeout=5) as r:
                return r.headers["Content-Type"], r.read().decode()

        ctype, html = get("/")
        assert ctype.startswith("text/html")
        # the page is self-contained: topology parser, sparkline, table
        for marker in ("parseDot", "sparkline", "Device_launches",
                       "/apps"):
            assert marker in html, marker
        # the type-2 deregister frame is applied by the dashboard's
        # connection thread; poll until it lands rather than racing it
        import time
        deadline = time.time() + 5
        while True:
            ctype, body = get("/apps")
            assert ctype.startswith("application/json")
            apps = json.loads(body)
            assert apps, "traced graph did not register"
            (app,) = apps.values()
            if not app["active"] or time.time() > deadline:
                break
            time.sleep(0.05)
        assert app["diagram"].lstrip().startswith("<svg")
        assert app["report"]["PipeGraph_name"] == "traced"
        assert not app["active"], "graph deregistered at wait_end"
    finally:
        httpd.shutdown()
        httpd.server_close()
        dash.stop()


def test_webui_script_structure():
    """No JS engine exists in this environment, so structurally lint the
    dashboard page's embedded script: balanced brackets outside
    strings/templates/regex-free zones, terminated string literals, and
    resolved Python-level escapes. Catches the realistic breakages
    (unbalanced template literals, bad escaping) that the marker-grep
    test cannot."""
    import re

    from windflow_tpu.monitoring.webui import HTML_PAGE

    m = re.search(r"<script>\n(.*?)</script>", HTML_PAGE, re.S)
    assert m, "no script block"
    src = m.group(1)
    # Python-level escapes must have resolved: the page is a plain
    # string, so a literal backslash-backslash means a \\ reached JS
    legit = ("\\\\n", "\\\\s", "\\\\w", "\\\\[",
             # parseDot label regex: escaped backslash in a character
             # class, escaped-any, and the unescape replace pattern
             "\\\\]", "\\\\.", "\\\\(")
    stripped = src
    for esc in legit:
        stripped = stripped.replace(esc, "")
    assert "\\\\" not in stripped, \
        "unresolved double backslash outside regex"
    stack = []
    pairs = {")": "(", "]": "[", "}": "{"}
    i, n, state = 0, len(src), None  # state: None | '"' | "'" | "`"
    while i < n:
        c = src[i]
        if state is None:
            if c == "/" and i + 1 < n and src[i + 1] == "/":
                i = src.find("\n", i)
                i = n if i < 0 else i
                continue
            if c == "/" and i + 1 < n and src[i + 1] == "*":
                end = src.find("*/", i + 2)
                assert end >= 0, f"unterminated block comment at {i}"
                i = end + 2
                continue
            if c == "/":
                # regex literal iff it can't be division: previous
                # non-space token is an operator/open-bracket/keyword
                j = i - 1
                while j >= 0 and src[j] in " \t\n":
                    j -= 1
                word = re.search(r"[A-Za-z$_]+$", src[:j + 1])
                if (j < 0 or src[j] in "(,=:[!&|?{;"
                        or (src[j] == ">" and j > 0 and src[j-1] == "=")
                        or (word and word.group(0) in (
                            "return", "typeof", "case", "in", "of",
                            "new", "delete", "void", "instanceof"))):
                    in_class = False
                    i += 1
                    while i < n:
                        if src[i] == "\\":
                            i += 2
                            continue
                        if src[i] == "[":
                            in_class = True
                        elif src[i] == "]":
                            in_class = False
                        elif src[i] == "/" and not in_class:
                            break
                        i += 1
                    i += 1
                    continue
            if c == "}" and stack and stack[-1][0] == "${":
                stack.pop()          # end of template interpolation
                state = "`"
            elif c in "\"'`":
                state = c
            elif c in "([{":
                stack.append((c, i))
            elif c in ")]}":
                assert stack and stack[-1][0] == pairs[c], \
                    f"unbalanced {c!r} at offset {i}: {src[max(0,i-40):i+5]!r}"
                stack.pop()
        else:
            if c == "\\":
                i += 2
                continue
            assert not (c == "\n" and state in "\"'"), \
                f"unterminated {state} string literal before offset {i}"
            if state == "`" and c == "$" and i + 1 < n and src[i+1] == "{":
                # template interpolation: recurse-lite via the stack
                stack.append(("${", i))
                state = None
                i += 2
                continue
            if c == state:
                state = None
        i += 1
    assert state is None, f"unterminated {state} literal"
    assert not stack, f"unclosed {stack[-3:]}"
