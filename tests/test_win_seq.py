"""End-to-end Win_Seq tests: CB and TB windows, NIC and incremental."""
import threading

import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, Mode, WinType


def ordered_source(n_keys, per_key):
    """Generates, per key, ids 0..per_key-1 with ts = id (in order)."""
    state = {}

    def fn(shipper, ctx):
        i = state.setdefault("i", 0)
        total = n_keys * per_key
        if i >= total:
            return False
        key = i % n_keys
        tid = i // n_keys
        shipper.push(BasicRecord(key, tid, tid, float(tid)))
        state["i"] = i + 1
        return True

    return fn


class Collector:
    def __init__(self):
        self.lock = threading.Lock()
        self.results = []

    def __call__(self, rec):
        if rec is not None:
            with self.lock:
                self.results.append((rec.key, rec.id, rec.ts, rec.value))


def sum_win(gwid, iterable, result):
    result.value = sum(t.value for t in iterable)


def sum_update(gwid, t, result):
    result.value += t.value


def naive_windows(per_key, win, slide, flush=True):
    """Expected (gwid -> sum) for one key with ids/ts/value = 0..per_key-1.
    Sliding windows [g*slide, g*slide+win); EOS flushes partial windows
    that were opened."""
    out = {}
    g = 0
    while True:
        lo = g * slide
        if lo >= per_key:  # windows open when a tuple with id >= lo arrives
            break
        vals = [v for v in range(per_key) if lo <= v < lo + win]
        if vals or flush:
            out[g] = float(sum(vals))
        g += 1
    return out


@pytest.mark.parametrize("win_type", [WinType.CB, WinType.TB])
@pytest.mark.parametrize("incremental", [False, True])
@pytest.mark.parametrize("win,slide", [(5, 5), (6, 2), (2, 5)])
def test_win_seq_exact(win_type, incremental, win, slide):
    n_keys, per_key = 3, 40
    coll = Collector()
    g = wf.PipeGraph("ws", Mode.DEFAULT)
    b = wf.WinSeqBuilder(sum_update if incremental else sum_win) \
        .with_incremental(incremental)
    b = (b.with_cb_windows(win, slide) if win_type == WinType.CB
         else b.with_tb_windows(win, slide))
    g.add_source(wf.SourceBuilder(ordered_source(n_keys, per_key)).build()) \
        .add(b.build()) \
        .add_sink(wf.SinkBuilder(coll).build())
    g.run()

    expect = naive_windows(per_key, win, slide)
    got = {}
    for key, gwid, ts, val in coll.results:
        got.setdefault(key, {})[gwid] = val
    assert set(got.keys()) == set(range(n_keys))
    for key in got:
        if win >= slide:
            assert got[key] == expect, (key, win, slide)
        else:
            # hopping windows: compare only the windows whose extent was
            # reached by the stream
            for gwid, v in got[key].items():
                assert expect.get(gwid) == v


def test_win_seq_result_control_fields_tb():
    coll = Collector()
    g = wf.PipeGraph("ws", Mode.DEFAULT)
    g.add_source(wf.SourceBuilder(ordered_source(1, 20)).build()) \
        .add(wf.WinSeqBuilder(sum_win).with_tb_windows(4, 4).build()) \
        .add_sink(wf.SinkBuilder(coll).build())
    g.run()
    for key, gwid, ts, val in coll.results:
        assert ts == gwid * 4 + 4 - 1  # TB result ts = window end


def test_win_seq_deterministic_mode_parallel_prefix():
    """Window op behind a parallel (FORWARD) map stage in DETERMINISTIC
    mode: ordering collector restores per-key id order."""
    n_keys, per_key = 4, 30
    totals = []
    for map_par in (1, 3):
        coll = Collector()
        g = wf.PipeGraph("ws", Mode.DETERMINISTIC)

        def ident(t):
            pass

        g.add_source(wf.SourceBuilder(ordered_source(n_keys, per_key)).build()) \
            .add(wf.MapBuilder(ident).with_parallelism(map_par).build()) \
            .add(wf.WinSeqBuilder(sum_win).with_cb_windows(5, 5).build()) \
            .add_sink(wf.SinkBuilder(coll).build())
        g.run()
        totals.append(sum(r[3] for r in coll.results))
    assert totals[0] == totals[1] == n_keys * sum(range(per_key))


def test_large_first_id_anchors_all_engines():
    """A first tuple with an epoch-scale id/ts anchors window creation
    at its first containing window on EVERY plane (native engine parity
    for the Python record plane, the columnar TPU plane, and both
    resident-FFAT rebuild modes): no ~id/slide empty leading windows,
    identical window sets across engines."""
    import threading
    import windflow_tpu as wf
    from windflow_tpu.core import Mode, WinType
    from windflow_tpu.core.tuples import BasicRecord

    OFF, N, WINL, SL = 100_000, 40, 8, 8

    def src():
        state = {"i": 0}

        def fn(shipper, ctx):
            i = state["i"]
            if i >= N:
                return False
            shipper.push(BasicRecord(0, OFF + i, OFF + i, 1.0))
            state["i"] = i + 1
            return True

        return fn

    def run(op):
        got = {}
        lock = threading.Lock()

        def sink(rec):
            if rec is not None:
                with lock:
                    got[rec.get_control_fields()[1]] = rec.value

        g = wf.PipeGraph("anchor", Mode.DEFAULT)
        g.add_source(wf.SourceBuilder(src()).build()) \
            .add(op).add_sink(wf.SinkBuilder(sink).build())
        g.run()
        return got

    ops = {
        "win_seq": wf.WinSeqBuilder(
            lambda gwid, it, res: setattr(
                res, "value", sum(t.value for t in it))) \
            .with_tb_windows(WINL, SL).build(),
        "win_seq_tpu": wf.WinSeqTPUBuilder("sum")
            .with_tb_windows(WINL, SL).build(),
        "ffat_rebuild": wf.WinSeqFFATTPUBuilder(lambda t: t.value, "sum")
            .with_tb_windows(WINL, SL).build(),
        "ffat_resident": wf.WinSeqFFATTPUBuilder(lambda t: t.value, "sum")
            .with_tb_windows(WINL, SL).with_rebuild(False).build(),
    }
    results = {name: run(op) for name, op in ops.items()}
    w0 = OFF // SL  # anchor window (tumbling; first ts on a boundary)
    expect = {w0 + j: 8.0 for j in range(N // SL)}
    for name, got in results.items():
        assert got == expect, (name, min(got, default=None), len(got))
