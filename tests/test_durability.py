"""Durability-plane tests (docs/RESILIENCE.md "Exactly-once epochs"):
aligned epoch barriers riding the channel planes, atomic manifest
commits, the transactional/idempotent sink contract, epoch-aware
restarts, and the kill-restart-verify chaos proofs -- results bitwise
equal to an uninterrupted run with zero duplicate or lost sink effects
and the conservation ledger balanced across the restart."""
import collections
import json
import os
import pickle
import time

import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, DurabilityConfig
from windflow_tpu.core.basic import Pattern, RoutingMode
from windflow_tpu.durability import (EpochStore, EpochTaggedStore,
                                     run_with_epochs)
from windflow_tpu.operators.base import Operator, StageSpec
from windflow_tpu.resilience import FaultPlan
from windflow_tpu.runtime.emitters import StandardEmitter
from windflow_tpu.runtime.node import SourceLoopLogic


# ---------------------------------------------------------------------------
# helpers: an offset-checkpointable record source (exactly-once needs
# sources that rewind -- the same contract ReplaySource/SyntheticSource
# implement) and deterministic oracles
# ---------------------------------------------------------------------------

N_KEYS = 4


def _val(i: int) -> float:
    return float(i % 7)


class _CkptSourceLogic(SourceLoopLogic):
    def __init__(self, n, pace_every=128, pace_s=0.001):
        self.i = 0
        self.n = n
        self.pace_every = pace_every
        self.pace_s = pace_s
        super().__init__(self._step)

    def _step(self, emit):
        i = self.i
        if i >= self.n:
            return False
        if self.pace_every and i % self.pace_every == 0:
            time.sleep(self.pace_s)
        emit(BasicRecord(i % N_KEYS, i // N_KEYS, i, _val(i)))
        self.i = i + 1
        return True

    def state_dict(self):
        return {"i": self.i}

    def load_state(self, st):
        self.i = st["i"]

    def progress_frontier(self):
        return self.i


class CkptSource(Operator):
    """Offset-checkpointable paced source for the chaos suites."""

    def __init__(self, n, name="ckpt_source", pace_every=128,
                 pace_s=0.001):
        super().__init__(name, 1, RoutingMode.NONE, Pattern.SOURCE)
        self.n = n
        self.pace_every = pace_every
        self.pace_s = pace_s

    def stages(self):
        logic = _CkptSourceLogic(self.n, self.pace_every, self.pace_s)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing)]


def _acc_oracle(n):
    """Per-key (id, rolling sum) sequences of the accumulator pipeline."""
    out = collections.defaultdict(list)
    sums = collections.defaultdict(float)
    for i in range(n):
        k = i % N_KEYS
        sums[k] += _val(i)
        out[k].append((i // N_KEYS, sums[k]))
    return out


def _per_key(effects):
    got = collections.defaultdict(list)
    for k, tid, v in effects:
        got[k].append((tid, v))
    return got


def _acc_graph(n, tmp, effects, fault_plan=None, interval=0.03,
               pace_every=64, pace_s=0.004, acc_par=2, elastic=None,
               delta=False):
    """source -> keyed map (par 2: multi-producer KEYBY alignment) ->
    keyed accumulator -> transactional sink."""
    def acc(t, a):
        a.value += t.value

    def sink(r):
        if r is not None:
            effects.append((r.key, r.id, r.value))

    cfg = wf.RuntimeConfig(
        durability=DurabilityConfig(epoch_interval_s=interval,
                                    path=os.path.join(tmp, "epochs"),
                                    delta=delta),
        fault_plan=fault_plan)
    g = wf.PipeGraph("dur_acc", wf.Mode.DEFAULT, config=cfg)
    accb = wf.AccumulatorBuilder(acc) \
        .with_initial_value(BasicRecord(value=0.0)) \
        .with_parallelism(acc_par)
    if elastic is not None:
        accb = wf.AccumulatorBuilder(acc) \
            .with_initial_value(BasicRecord(value=0.0)) \
            .with_elasticity(*elastic)
    g.add_source(CkptSource(n, pace_every=pace_every, pace_s=pace_s)) \
        .add(wf.MapBuilder(lambda t: None).with_key_by()
             .with_parallelism(2).build()) \
        .add(accb.build()) \
        .add_sink(wf.SinkBuilder(sink).with_exactly_once().build())
    return g


def _assert_exactly_once(effects, n, graph):
    """Zero duplicate/lost effects, per-key sequences equal the
    uninterrupted oracle, ledger balanced in the (final) run."""
    assert len(effects) == n, (len(effects), n)
    assert len(set(effects)) == len(effects), "duplicate sink effects"
    oracle = _acc_oracle(n)
    got = _per_key(effects)
    assert set(got) == set(oracle)
    for k in oracle:
        assert got[k] == oracle[k], (k, got[k][:4], oracle[k][:4])
    cons = json.loads(graph.stats.to_json())["Conservation"]
    assert cons["Violations_total"] == 0, cons["Violations"]
    assert cons["Edges_balanced"], cons
    # barriers are subtracted from the graph-wide roll-up: the ledger
    # identity holds in stream tuples across the restart
    assert cons["Sources_emitted"] == cons["Sinks_consumed"] \
        + cons["Dead_letters"] + cons["Shed_tuples"], cons


# ---------------------------------------------------------------------------
# manifest store (crash-safe commits, tolerant reads)
# ---------------------------------------------------------------------------

def test_epoch_store_atomic_commit_and_retention(tmp_path):
    store = EpochStore(str(tmp_path / "ep"), retained=2)
    for e in (1, 2, 3):
        path, nbytes = store.commit(e, {"n": pickle.dumps({"x": e})},
                                    {"src": e * 10})
        assert os.path.exists(path) and nbytes > 0
        assert not os.path.exists(path + ".tmp")  # temp renamed away
    # retention keeps only the newest 2
    names = sorted(os.listdir(str(tmp_path / "ep")))
    assert names == ["epoch-000000000002.ckpt", "epoch-000000000003.ckpt"]
    e, payload = store.latest()
    assert e == 3 and payload["offsets"] == {"src": 30}


def test_epoch_store_skips_truncated_manifest(tmp_path):
    """A truncated newest manifest (the crash save_graph used to allow)
    falls back to the previous epoch with a flight event instead of an
    unpickling crash."""
    from windflow_tpu.telemetry import FlightRecorder
    store = EpochStore(str(tmp_path / "ep"), retained=4)
    store.commit(1, {"n": pickle.dumps({"x": 1})}, {})
    store.commit(2, {"n": pickle.dumps({"x": 2})}, {})
    p2 = store.manifest_path(2)
    blob = open(p2, "rb").read()
    with open(p2, "wb") as f:
        f.write(blob[:len(blob) // 2])   # torn mid-write
    flight = FlightRecorder(64)
    e, payload = store.latest(flight=flight)
    assert e == 1 and pickle.loads(payload["states"]["n"]) == {"x": 1}
    evs = [ev for ev in flight.snapshot() if ev["kind"] == "epoch_abort"]
    assert evs and evs[0]["reason"] == "manifest_corrupt"
    assert evs[0]["epoch"] == 2


def test_epoch_store_rejects_foreign_and_newer_schema(tmp_path):
    store = EpochStore(str(tmp_path / "ep"))
    with open(store.manifest_path(1), "wb") as f:
        pickle.dump({"magic": "something-else"}, f)
    with pytest.raises(RuntimeError, match="not a windflow epoch"):
        store.load(1)
    with open(store.manifest_path(2), "wb") as f:
        pickle.dump({"magic": "windflow-epoch-manifest", "schema": 99,
                     "states": {}}, f)
    with pytest.raises(RuntimeError, match="newer than this runtime"):
        store.load(2)


# ---------------------------------------------------------------------------
# snapshot header satellite (utils/checkpoint.py)
# ---------------------------------------------------------------------------

def test_snapshot_header_and_actionable_errors(tmp_path):
    from windflow_tpu.utils.checkpoint import (read_snapshot,
                                               write_snapshot)
    path = str(tmp_path / "s.pkl")
    write_snapshot(path, {"a": {"x": 1}}, epoch=7)
    payload = pickle.load(open(path, "rb"))
    assert payload["magic"] == "windflow-graph-state"
    assert payload["epoch"] == 7
    assert read_snapshot(path) == {"a": {"x": 1}}
    assert not os.path.exists(path + ".tmp")
    # truncation -> actionable error, not an UnpicklingError traceback
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])
    with pytest.raises(RuntimeError, match="truncated or corrupt"):
        read_snapshot(path)
    # foreign magic -> actionable
    with open(path, "wb") as f:
        pickle.dump({"magic": "other-tool"}, f)
    with pytest.raises(RuntimeError, match="not a windflow graph"):
        read_snapshot(path)
    # newer schema -> actionable
    with open(path, "wb") as f:
        pickle.dump({"magic": "windflow-graph-state", "schema": 99,
                     "states": {}}, f)
    with pytest.raises(RuntimeError, match="newer than this runtime"):
        read_snapshot(path)
    # legacy header-less state maps still load (tolerant contract)
    with open(path, "wb") as f:
        pickle.dump({"node": {"x": 2}}, f)
    assert read_snapshot(path) == {"node": {"x": 2}}


def test_restore_graph_rejects_truncated_snapshot(tmp_path):
    """End to end through restore_graph: a torn snapshot names the file
    and loads nothing (the pre-atomic failure mode)."""
    from windflow_tpu.utils.checkpoint import restore_graph, save_graph

    def build():
        def acc(t, a):
            a.value += t.value
        state = {"i": 0}

        def src(shipper, ctx):
            if state["i"] >= 10:
                return False
            shipper.push(BasicRecord(0, state["i"], state["i"], 1.0))
            state["i"] += 1
            return True
        g = wf.PipeGraph("hdr")
        g.add_source(wf.SourceBuilder(src).build()) \
            .add(wf.AccumulatorBuilder(acc)
                 .with_initial_value(BasicRecord(value=0.0)).build()) \
            .add_sink(wf.SinkBuilder(lambda r: None).build())
        return g

    g1 = build()
    g1.run()
    path = str(tmp_path / "g.pkl")
    save_graph(g1, path)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) - 8])
    with pytest.raises(RuntimeError, match="truncated or corrupt"):
        restore_graph(build(), path)


# ---------------------------------------------------------------------------
# fault-plan epoch actions
# ---------------------------------------------------------------------------

def test_faultplan_epoch_actions_bind_and_fire():
    from windflow_tpu.resilience import InjectedFailure
    plan = FaultPlan(seed=1).crash_at_epoch("acc", 3).torn_commit(5)
    assert plan.torn_commit_epochs == {5}
    nf = plan.for_node("pipe0/acc.0")
    assert nf is not None
    nf.on_epoch(2)  # no-op
    with pytest.raises(InjectedFailure, match="epoch 3"):
        nf.on_epoch(3)
    assert plan.for_node("pipe0/other.0") is None
    with pytest.raises(ValueError):
        plan.crash_at_epoch("x", 0)
    with pytest.raises(ValueError):
        plan.torn_commit(0)


# ---------------------------------------------------------------------------
# barrier aligner unit semantics
# ---------------------------------------------------------------------------

def test_aligner_holds_back_post_barrier_items():
    """Items from producers already past barrier e are parked until the
    alignment completes, then replay in arrival order -- the cut
    separates pre- from post-barrier input exactly."""
    from windflow_tpu.durability.barrier import EpochAligner
    from windflow_tpu.runtime.queues import EpochBarrier

    class _Coord:
        def __init__(self):
            self.snaps = []
            self.acks = []

        def add_snapshot(self, epoch, states):
            self.snaps.append(epoch)

        def sink_ack(self, epoch, name):
            self.acks.append((epoch, name))

    class _Node:
        name = "sink.0"
        outlets = ()
        faults = None
        epoch_barriers_in = 0
        epoch_barriers_out = 0

        class logic:  # stateless, no quiesce/epoch_mark hooks
            pass

        def _emit(self, item):
            raise AssertionError("no emissions expected")

    node = _Node()
    coord = _Coord()
    al = EpochAligner(node, coord, n_producers=2)
    seen = []

    def process(cid, item):
        seen.append((cid, item))

    assert not al.offer(0, "a0", process)     # plain item passes through
    process(0, "a0")
    assert al.offer(0, EpochBarrier(1), process)   # producer 0 aligned
    assert al.busy
    assert al.offer(0, "a1", process)         # held back (0 is aligned)
    assert not al.offer(1, "b0", process)     # producer 1 not yet aligned
    process(1, "b0")
    assert al.offer(1, EpochBarrier(1), process)   # completes the cut
    assert not al.busy
    assert coord.acks == [(1, "sink.0")]
    assert seen == [(0, "a0"), (1, "b0"), (0, "a1")]  # holdback replayed
    assert node.epoch_barriers_in == 2


def test_aligner_final_barrier_unblocks_alignment():
    """A finished producer (final barrier) counts as permanently
    arrived: a finished branch can never stall another's alignment."""
    from windflow_tpu.durability.barrier import EpochAligner
    from windflow_tpu.runtime.queues import EpochBarrier

    class _Coord:
        def __init__(self):
            self.acks = []

        def add_snapshot(self, epoch, states):
            pass

        def sink_ack(self, epoch, name):
            self.acks.append(epoch)

    class _Node:
        name = "sink.0"
        outlets = ()
        faults = None
        epoch_barriers_in = 0
        epoch_barriers_out = 0

        class logic:
            pass

        def _emit(self, item):
            pass

    coord = _Coord()
    al = EpochAligner(_Node(), coord, n_producers=2)
    al.offer(0, EpochBarrier(-1, final=True), lambda c, i: None)
    al.offer(1, EpochBarrier(1), lambda c, i: None)   # completes at once
    al.offer(1, EpochBarrier(2), lambda c, i: None)
    assert coord.acks == [1, 2]


# ---------------------------------------------------------------------------
# end-to-end: clean run, exactly-once sinks, metrics/doctor surfaces
# ---------------------------------------------------------------------------

def test_durable_pipeline_clean_run_exactly_once(tmp_path):
    N = 3000
    effects = []
    g = _acc_graph(N, str(tmp_path), effects, interval=0.04,
                   pace_every=128, pace_s=0.002)
    g.run()
    _assert_exactly_once(effects, N, g)
    dur = g.durability
    # >= 2: at least one mid-stream commit plus the graph-level final
    # commit at the clean end (which releases the sink buffers)
    assert dur.commits >= 2 and dur.committed >= 2
    kinds = collections.Counter(e["kind"] for e in g.flight.snapshot())
    assert kinds["epoch_begin"] >= dur.commits - 1  # final has no begin
    assert kinds["epoch_commit"] == dur.commits
    assert kinds["checkpoint_epoch"] == dur.commits
    finals = [e for e in g.flight.snapshot()
              if e["kind"] == "epoch_commit" and e.get("final")]
    assert len(finals) == 1 and finals[0]["effects"] > 0
    # every epoch event carries its epoch id
    for ev in g.flight.snapshot():
        if ev["kind"] in ("epoch_begin", "epoch_commit",
                          "checkpoint_epoch"):
            assert isinstance(ev.get("epoch"), int)
    # manifests on disk + stats/metrics surfaces
    stats = json.loads(g.stats.to_json())
    block = stats["Durability"]
    assert block["Committed_epoch"] == dur.committed
    assert not block["Stalled"]
    from windflow_tpu.telemetry.metrics import render_openmetrics
    text = render_openmetrics({"1": {"report": stats, "active": False}})
    assert "windflow_epoch{" in text
    assert "windflow_epoch_lag_seconds{" in text
    assert "windflow_epoch_commit_seconds{" in text
    # doctor folds the block into the report
    from windflow_tpu.diagnosis.report import build_report, render_text
    rep = build_report(stats)
    assert rep["Durability"]["Committed_epoch"] == dur.committed
    assert "epochs: committed=" in render_text(rep)


def test_doctor_names_stalled_epochs():
    from windflow_tpu.diagnosis.report import build_report, render_text
    stats = {"PipeGraph_name": "g", "Durability": {
        "Committed_epoch": 4, "Epoch_lag_s": 12.5, "Last_commit_s": 0.01,
        "Commits": 4, "Aborts": 0, "Stalled": True}}
    rep = build_report(stats)
    assert "epochs STALLED" in rep["Verdict"]
    assert "committed 4" in rep["Verdict"]
    assert "stalled=True" in render_text(rep)


def test_sink_progress_during_epochs(tmp_path):
    """The non-stop property: the graph keeps emitting THROUGH epochs
    -- sink consumption strictly increases between consecutive commits
    (no graph-wide quiesce on the barrier path)."""
    N = 6000
    effects = []
    g = _acc_graph(N, str(tmp_path), effects, interval=0.05,
                   pace_every=32, pace_s=0.004)
    g.run()
    commits = [e for e in g.flight.snapshot()
               if e["kind"] == "epoch_commit" and "sink_gets" in e]
    assert len(commits) >= 3, commits
    gets = [c["sink_gets"] for c in commits]
    for a, b in zip(gets, gets[1:]):
        assert b > a, ("sink made no progress between commits -- "
                       "the barrier path quiesced the graph", gets)
    _assert_exactly_once(effects, N, g)


def test_live_checkpoint_is_non_stop_under_durability(tmp_path):
    """live_checkpoint with the plane on forces one epoch (no source
    pause) and writes a restore_graph-compatible snapshot."""
    from windflow_tpu.utils.checkpoint import read_snapshot
    N = 20000
    effects = []
    g = _acc_graph(N, str(tmp_path), effects, interval=0.5,
                   pace_every=16, pace_s=0.002)
    g.start()
    deadline = time.monotonic() + 30
    while not effects and time.monotonic() < deadline:
        time.sleep(0.002)
    pre = len(effects)
    path = str(tmp_path / "live.pkl")
    n = g.live_checkpoint(path, timeout=30)
    assert n >= 1
    states = read_snapshot(path)
    assert "pipe0/ckpt_source" in states
    src_off = states["pipe0/ckpt_source"]["i"]
    assert 0 < src_off <= N
    g.wait_end()
    assert len(effects) > pre
    _assert_exactly_once(effects, N, g)
    evs = [e for e in g.flight.snapshot()
           if e["kind"] == "checkpoint_epoch" and e.get("non_stop")]
    assert evs and evs[0]["path"] == path


# ---------------------------------------------------------------------------
# kill-restart-verify chaos: mid-stream crash, barrier-window crash,
# fused-segment crash, torn commit
# ---------------------------------------------------------------------------

def _chaos(tmp_path, plan_for_attempt, n=4000, max_restarts=2):
    effects = []
    attempts = []

    def factory(attempt):
        attempts.append(attempt)
        return _acc_graph(n, str(tmp_path), effects,
                          fault_plan=plan_for_attempt(attempt))

    g = run_with_epochs(factory, max_restarts=max_restarts)
    return g, effects, attempts


def test_chaos_crash_midstream_restarts_exactly_once(tmp_path):
    N = 4000
    g, effects, attempts = _chaos(
        tmp_path,
        lambda a: (FaultPlan(seed=3)
                   .crash_replica("accumulator", at_tuple=1200)
                   if a == 0 else None),
        n=N)
    assert attempts == [0, 1]
    # the paced stream guarantees committed epochs before tuple 1200:
    # the restart resumed from one, not from scratch
    assert getattr(g, "_epoch_restored", None) is not None
    assert g._epoch_restored >= 1
    restores = [e for e in g.flight.snapshot()
                if e["kind"] == "epoch_restore"]
    assert restores and restores[0]["epoch"] == g._epoch_restored
    _assert_exactly_once(effects, N, g)
    # epoch numbering continued across the restart (a reset could let a
    # second failure rewind past already-released effects)
    assert g.durability.committed > g._epoch_restored


def test_chaos_crash_inside_barrier_window(tmp_path):
    """crash_at_epoch: the replica dies mid-cut (aligned, pre-snapshot)
    -- the epoch never commits, the restart resumes from the previous
    one, results stay exactly-once."""
    N = 4000
    g, effects, attempts = _chaos(
        tmp_path,
        lambda a: (FaultPlan(seed=5).crash_at_epoch("accumulator", 2)
                   if a == 0 else None),
        n=N)
    assert attempts == [0, 1]
    assert getattr(g, "_epoch_restored", None) == 1
    _assert_exactly_once(effects, N, g)


def test_chaos_torn_commit_falls_back_previous_epoch(tmp_path):
    """torn_commit: epoch 2's manifest lands truncated at the final
    path and the graph dies; the restart's tolerant reader records the
    damage and falls back to epoch 1."""
    N = 4000
    g, effects, attempts = _chaos(
        tmp_path,
        lambda a: FaultPlan(seed=7).torn_commit(2) if a == 0 else None,
        n=N)
    assert attempts == [0, 1]
    assert getattr(g, "_epoch_restored", None) == 1
    aborts = [e for e in g.flight.snapshot()
              if e["kind"] == "epoch_abort"
              and e.get("reason") == "manifest_corrupt"]
    assert aborts and aborts[0]["epoch"] == 2
    _assert_exactly_once(effects, N, g)
    # the continued numbering moved PAST the torn epoch (re-committing
    # 2 over the damage), and the newest manifest on disk loads clean
    e, payload = EpochStore(
        os.path.join(str(tmp_path), "epochs")).latest()
    assert e is not None and e >= 2 and payload["epoch"] == e


def test_chaos_crash_inside_fused_segment_with_device_engine(tmp_path):
    """The fully-fused lane: source + maps + WinSeqTPU + transactional
    sink fused into one replica; the crash fires on a fused-AWAY
    operator's fault clock; barriers cross the fused segments and the
    async device dispatcher (epoch fence drains in-flight launches).
    Window results after restart equal the uninterrupted run."""
    N, WIN, SLIDE = 6000, 16, 8

    def run(plan_path, fault):
        wins = {}
        counts = collections.Counter()

        def sink(r):
            if r is None:
                return
            wins[(r.key, r.id)] = r.value
            counts[(r.key, r.id)] += 1
        effects_graph = []

        def factory(attempt):
            plan = fault if attempt == 0 else None
            cfg = wf.RuntimeConfig(durability=DurabilityConfig(
                epoch_interval_s=0.03, path=plan_path),
                fault_plan=plan)
            g = wf.PipeGraph("dur_win", wf.Mode.DEFAULT, config=cfg)
            op = wf.WinSeqTPUBuilder("sum") \
                .with_tb_windows(WIN, SLIDE).build()
            g.add_source(CkptSource(N, pace_every=64, pace_s=0.003)) \
                .add(wf.MapBuilder(lambda t: None).build()) \
                .add(op) \
                .add_sink(wf.SinkBuilder(sink).with_exactly_once()
                          .build())
            effects_graph.append(g)
            return g

        g = run_with_epochs(factory, max_restarts=2)
        return g, wins, counts

    # uninterrupted reference (own manifest dir)
    _gr, ref, ref_counts = run(str(tmp_path / "ref"), None)
    assert ref and max(ref_counts.values()) == 1
    # crash on the fused-away map's tuple clock, mid-stream
    plan = FaultPlan(seed=11).crash_replica("map", at_tuple=2500)
    g, wins, counts = run(str(tmp_path / "chaos"), plan)
    assert getattr(g, "_epoch_restored", None) is not None
    assert max(counts.values()) == 1, "duplicate window results"
    assert wins == ref  # bitwise: float sums over identical series


def test_branch_eos_then_crash_releases_no_duplicates(tmp_path):
    """A split graph where one branch ends cleanly BEFORE the other
    branch crashes: the finished branch's sink must not have released
    uncommitted-epoch effects at its own EOS (the restart regenerates
    them -- duplicates).  Release is deferred to the coordinator's
    graph-level final commit."""
    N = 3000
    fast, slow = [], []

    def factory(attempt):
        # the slow branch dies on its LAST tuple -- deterministically
        # after the fast branch's sink reached EOS (it lags ~0.2 ms per
        # tuple behind)
        plan = (FaultPlan(seed=17).crash_replica("slowmap", at_tuple=N)
                if attempt == 0 else None)

        def slow_fn(t):
            time.sleep(0.0002)

        cfg = wf.RuntimeConfig(
            durability=DurabilityConfig(
                epoch_interval_s=0.04,
                path=os.path.join(str(tmp_path), "epochs")),
            fault_plan=plan)
        g = wf.PipeGraph("dur_split", wf.Mode.DEFAULT, config=cfg)
        mp = g.add_source(CkptSource(N, pace_every=64, pace_s=0.002))
        mp = mp.split(lambda t: (0, 1), 2)
        mp.select(0).add_sink(
            wf.SinkBuilder(lambda r: fast.append((r.key, r.id, r.value))
                           if r is not None else None)
            .with_exactly_once().build())
        mp.select(1) \
            .add(wf.MapBuilder(slow_fn).with_name("slowmap").build()) \
            .add_sink(
                wf.SinkBuilder(lambda r: slow.append((r.key, r.id,
                                                      r.value))
                               if r is not None else None)
                .with_exactly_once().build())
        return g

    g = run_with_epochs(factory, max_restarts=2)
    assert getattr(g, "_epoch_restored", None) is not None
    for name, effects in (("fast", fast), ("slow", slow)):
        assert len(effects) == N, (name, len(effects), N)
        assert len(set(effects)) == N, f"{name} branch duplicated effects"


def test_chaos_exhausted_restarts_reraise(tmp_path):
    from windflow_tpu.graph.pipegraph import NodeFailureError
    with pytest.raises(NodeFailureError) as ei:
        _chaos(tmp_path,
               lambda a: FaultPlan(seed=9).crash_replica(
                   "accumulator", at_tuple=100),
               n=2000, max_restarts=1)
    assert len(ei.value.attempt_history) == 2


# ---------------------------------------------------------------------------
# idempotent-by-epoch-id sink variant
# ---------------------------------------------------------------------------

def test_idempotent_sink_with_truncate_on_restore(tmp_path):
    """The idempotent contract: effects apply immediately tagged with
    their epoch; the crashed attempt's uncommitted tail is truncated on
    restore and replayed identically."""
    N = 4000
    store = EpochTaggedStore()

    def factory(attempt):
        plan = (FaultPlan(seed=13).crash_replica("accumulator",
                                                 at_tuple=1200)
                if attempt == 0 else None)

        def acc(t, a):
            a.value += t.value
        cfg = wf.RuntimeConfig(
            durability=DurabilityConfig(
                epoch_interval_s=0.03,
                path=os.path.join(str(tmp_path), "epochs")),
            fault_plan=plan)
        g = wf.PipeGraph("dur_idem", wf.Mode.DEFAULT, config=cfg)
        g.add_source(CkptSource(N, pace_every=64, pace_s=0.004)) \
            .add(wf.MapBuilder(lambda t: None).with_key_by()
                 .with_parallelism(2).build()) \
            .add(wf.AccumulatorBuilder(acc)
                 .with_initial_value(BasicRecord(value=0.0))
                 .with_parallelism(2).build()) \
            .add_sink(wf.SinkBuilder(store)
                      .with_exactly_once("idempotent").build())
        return g

    g = run_with_epochs(
        factory, max_restarts=2,
        on_restore=lambda g_, e, payload: store.truncate_above(e))
    assert getattr(g, "_epoch_restored", None) is not None
    effects = [(r.key, r.id, r.value) for r in store.items()]
    assert len(effects) == N and len(set(effects)) == N
    got, oracle = _per_key(effects), _acc_oracle(N)
    for k in oracle:
        assert sorted(got[k]) == oracle[k]
    # epochs tag monotonically across the restart
    assert store.epochs() == sorted(store.epochs())


def test_idempotent_sink_rejects_plain_callable():
    with pytest.raises(TypeError, match="epoch-keyed writer"):
        g = wf.PipeGraph("bad")
        g.add_source(CkptSource(10)).add_sink(
            wf.SinkBuilder(lambda r: None)
            .with_exactly_once("idempotent").build())
        g.start()


def test_with_exactly_once_validates_mode():
    with pytest.raises(ValueError, match="transactional"):
        wf.SinkBuilder(lambda r: None).with_exactly_once("bogus")


# ---------------------------------------------------------------------------
# epoch x elastic interaction
# ---------------------------------------------------------------------------

def test_epochs_serialize_with_scripted_rescale(tmp_path):
    """A scripted rescale lands between two epochs and a barrier
    cadence keeps firing around it: commits continue on both sides,
    the rewired channel set aligns (new producer counts), and the
    per-key sequences equal the uninterrupted run."""
    N = 12000
    effects = []
    g = _acc_graph(N, str(tmp_path), effects, interval=0.03,
                   pace_every=32, pace_s=0.003, elastic=(1, 3))
    g.start()
    deadline = time.monotonic() + 30
    while not effects and time.monotonic() < deadline:
        time.sleep(0.002)
    before = g.durability.committed
    ev = g.rescale("accumulator", 2)
    assert ev is not None and ev.new_parallelism == 2
    # a barrier arriving during/after the rescale still aligns and
    # commits (the gap released with refreshed producer counts)
    deadline = time.monotonic() + 30
    while g.durability.committed <= before \
            and time.monotonic() < deadline \
            and any(n.is_alive() for n in g._all_nodes()):
        time.sleep(0.005)
    g.wait_end()
    assert g.durability.committed > before, \
        "no epoch committed after the rescale"
    _assert_exactly_once(effects, N, g)
    kinds = [e["kind"] for e in g.flight.snapshot()]
    assert "rescale" in kinds


def test_quiesce_holds_epochs(tmp_path):
    """The legacy quiesce barrier serializes with the epoch plane: it
    drains in-flight epochs first, and no epoch begins while paused."""
    N = 20000
    effects = []
    g = _acc_graph(N, str(tmp_path), effects, interval=0.03,
                   pace_every=32, pace_s=0.002)
    g.start()
    deadline = time.monotonic() + 30
    while g.durability.committed < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    g.quiesce()
    try:
        with g.durability._cond:
            assert not g.durability._pending  # drained, none in flight
        seq = g.durability.epoch_seq
        time.sleep(0.12)                      # > several intervals
        assert g.durability.epoch_seq == seq  # cadence held
    finally:
        g.resume()
    g.wait_end()
    _assert_exactly_once(effects, N, g)
