"""Device-resident keyed window state + online re-planning
(docs/PLANNER.md "Resident state & online re-planning").

* the fused scatter+query forest program (one launch per chunk,
  donated carry) matches the sequential update/query pair;
* the WinSeqTPULogic resident pane carry produces results BITWISE
  identical to the rebuild lane while shipping a fraction of its
  bytes, with the resident footprint on a separate gauge;
* the FFAT resident lane ships >= 10x fewer bytes/launch than the
  rebuild lane on a sliding-window config;
* resident engines stay checkpoint-, rescale- (keyed_state_dict
  repartition) and epoch-compatible, including a mid-run lane flip
  between two epochs recovering exactly-once;
* the online re-planner flips a lane mid-run with zero lost tuples,
  records a ``replacement`` flight event and the doctor explains it.

Runs on the JAX CPU backend (cpu-fallback XLA); the same programs
compile for TPU unchanged.  Green on both channel planes (the
WINDFLOW_NATIVE=0 CI job).
"""
import collections
import threading
import time

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, Mode, WinType
from windflow_tpu.core.basic import Pattern, RoutingMode, RuntimeConfig
from windflow_tpu.core.tuples import TupleBatch
from windflow_tpu.operators.base import Operator, StageSpec
from windflow_tpu.operators.basic_ops import Sink
from windflow_tpu.operators.batch_ops import BatchSource
from windflow_tpu.operators.tpu.ffat_resident import (
    WinSeqFFATResident, WinSeqFFATResidentLogic)
from windflow_tpu.operators.tpu.win_seq_tpu import (WinSeqTPU,
                                                    WinSeqTPULogic)
from windflow_tpu.runtime.emitters import StandardEmitter
from windflow_tpu.runtime.node import SourceLoopLogic

N_KEYS = 3


@pytest.fixture(autouse=True)
def _pin_cost_model(monkeypatch, tmp_path):
    """Deterministic cost-model inputs: tiny RTT floor, pinned host
    rate, no compute calibration, and the calibration CACHE redirected
    to a tmp file so tests never write the per-box one."""
    from windflow_tpu.graph import planner
    monkeypatch.setenv("WINDFLOW_RTT_FLOOR_MS", "0.001")
    monkeypatch.setenv("WINDFLOW_HOST_RATE_TPS", "20000000")
    monkeypatch.setenv("WINDFLOW_DEVICE_COMPUTE_MS", "0")
    monkeypatch.setattr(planner, "_DEV_CALIB_PATH",
                        str(tmp_path / "device_calibration.json"))
    monkeypatch.setattr(planner, "_device_compute_ms", None)
    yield


def _int_batch(lo, hi, n_keys=N_KEYS):
    idx = np.arange(lo, hi)
    return TupleBatch({"key": idx % n_keys, "id": idx // n_keys,
                       "ts": idx // n_keys,
                       "value": (idx % 7).astype(np.float64)})


def _run_logic(lg, n, chunk=500, n_keys=N_KEYS):
    out = []
    for c in range(0, n, chunk):
        lg.svc(_int_batch(c, min(c + chunk, n), n_keys), 0, out.append)
    lg.eos_flush(out.append)
    flat = {}
    for r in out:
        if isinstance(r, TupleBatch):
            for i in range(len(r)):
                flat[(int(r.key[i]), int(r.id[i]))] = \
                    (float(r["value"][i]), int(r.ts[i]))
        else:
            flat[(r.key, r.id)] = (r.value, r.ts)
    return flat


# ---------------------------------------------------------------------------
# fused forest program
# ---------------------------------------------------------------------------

class TestFusedForest:
    def test_fused_matches_sequential(self):
        import jax.numpy as jnp
        from windflow_tpu.ops.flatfat_jax import BatchedFlatFAT
        rng = np.random.default_rng(0)
        a = BatchedFlatFAT(jnp.add, 0.0, 4, 32)
        b = BatchedFlatFAT(jnp.add, 0.0, 4, 32)
        for step in range(6):
            keys = rng.integers(0, 4, 12)
            ids = np.arange(step * 12, step * 12 + 12)
            vals = rng.integers(0, 100, 12).astype(np.float32)
            qk = np.arange(4)
            qs = np.full(4, max(0, step * 12 - 10))
            qe = np.full(4, step * 12 + 6)
            a.update(keys, ids, vals)
            r1 = a.query(qk, qs, qe)
            r2 = b.update_query(keys, ids, vals, qk, qs, qe)
            assert np.array_equal(r1, r2)

    def test_fused_ring_wrap_keeps_time_order(self):
        import jax.numpy as jnp
        from windflow_tpu.ops.flatfat_jax import BatchedFlatFAT
        # non-commutative combine: order proves the wrap pieces fold
        # oldest -> newest
        comb = lambda x, y: x * 0.5 + y  # noqa: E731
        f = BatchedFlatFAT(comb, 0.0, 2, 8)
        g = BatchedFlatFAT(comb, 0.0, 2, 8)
        vals = np.arange(1, 25, dtype=np.float32)
        for i in range(0, 24, 4):
            ids = np.arange(i, i + 4)
            f.update(np.zeros(4, int), ids, vals[i:i + 4])
            lo = max(0, i + 4 - 8)
            r1 = f.query([0], [lo], [i + 4])
            r2 = g.update_query(np.zeros(4, int), ids, vals[i:i + 4],
                                [0], [lo], [i + 4])
            assert np.array_equal(r1, r2)

    def test_state_bytes_gauge(self):
        import jax.numpy as jnp
        from windflow_tpu.ops.flatfat_jax import BatchedFlatFAT
        f = BatchedFlatFAT(jnp.add, 0.0, 4, 64)
        assert f.state_bytes == 4 * 2 * 64 * 4  # K x 2n x f32


# ---------------------------------------------------------------------------
# WinSeqTPULogic resident pane carry
# ---------------------------------------------------------------------------

def _win_logic(resident, kind="sum", win=256, slide=32,
               win_type=WinType.CB, batch_len=16):
    # value_of defeats the native engine on BOTH lanes so the Python
    # staging path (the one the resident carry extends) is compared
    return WinSeqTPULogic(kind, win, slide, win_type,
                          batch_len=batch_len, async_dispatch=False,
                          resident=resident,
                          value_of=lambda t: t.value)


class TestResidentPaneCarry:
    @pytest.mark.parametrize("kind", ["sum", "count", "max"])
    def test_cb_bitwise_vs_rebuild(self, kind):
        a = _run_logic(_win_logic(False, kind), 6000)
        b = _run_logic(_win_logic(True, kind), 6000)
        assert a and a == b

    def test_tb_bitwise_vs_rebuild(self):
        a = _run_logic(_win_logic(False, "sum", win_type=WinType.TB),
                       6000)
        b = _run_logic(_win_logic(True, "sum", win_type=WinType.TB),
                       6000)
        assert a and a == b

    def test_resident_ships_fraction_of_rebuild_bytes(self):
        from windflow_tpu.monitoring.stats import StatsRecord
        shipped = {}
        for resident in (False, True):
            lg = _win_logic(resident, "sum", win=4096, slide=64,
                            batch_len=8)
            lg.stats = StatsRecord()
            _run_logic(lg, 40_000)
            assert lg.stats.num_launches > 4
            shipped[resident] = (lg.stats.bytes_to_device
                                 / lg.stats.num_launches)
            if resident:
                # the separate footprint gauge: state lives on device,
                # not in the per-launch traffic
                assert lg.stats.device_state_bytes > 0
                assert lg.device_resident_bytes() \
                    == lg.stats.device_state_bytes
        assert shipped[True] < shipped[False] / 3, shipped

    def test_checkpoint_restore_continues_identically(self):
        ref = _run_logic(_win_logic(True), 8000)
        a = _win_logic(True)
        out = []
        for c in range(0, 4000, 500):
            a.svc(_int_batch(c, c + 500), 0, out.append)
        a.quiesce(out.append)  # snapshot contract: nothing in flight
        blob = a.state_dict()
        b = _win_logic(True)
        b.load_state(blob)
        for c in range(4000, 8000, 500):
            b.svc(_int_batch(c, c + 500), 0, out.append)
        b.eos_flush(out.append)
        got = {(r.key, r.id): (r.value, r.ts) for r in out}
        assert got == ref

    def test_lane_flip_drops_then_recovers_residency(self):
        lg = _win_logic(True)
        out = []
        lg.svc(_int_batch(0, 2000), 0, out.append)
        assert lg._resident is not None
        lg.apply_placement("host")
        assert lg._resident is None
        lg.apply_placement("device")
        assert lg.maybe_enable_resident()
        lg.svc(_int_batch(2000, 6000), 0, out.append)
        lg.eos_flush(out.append)
        got = {(r.key, r.id): (r.value, r.ts) for r in out}
        assert got == _run_logic(_win_logic(False), 6000)

    def test_many_keys_grow_forest_empty_swap(self):
        """Key count past the initial forest capacity swaps in a
        bigger EMPTY forest (never a tree copy: queued launches still
        scatter into the old object) and re-ships dirty partials --
        results stay identical to the rebuild lane."""
        a = _run_logic(_win_logic(False, win=64, slide=32), 20_000,
                       n_keys=40)
        lg = _win_logic(True, win=64, slide=32)
        b = _run_logic(lg, 20_000, n_keys=40)
        assert lg._resident.forest.n_keys >= 40
        assert a and a == b

    def test_forced_resident_rejects_ineligible_shapes(self):
        with pytest.raises(ValueError, match="resident"):
            _win_logic(True, "mean")          # no monoid pair form
        with pytest.raises(ValueError, match="resident"):
            _win_logic(True, "sum", win=24, slide=6)  # pane < 16

    def test_planner_promotes_eligible_device_engines(self):
        for opt_out, expect in ((False, True), (True, False)):
            rows = []
            g = wf.PipeGraph("resident_promo", wf.Mode.DEFAULT)
            op = WinSeqTPU("sum", 256, 32, WinType.CB, batch_len=32,
                           placement="device",
                           value_of=lambda t: t.value,
                           resident=(False if opt_out else None))
            g.add_source(BatchSource(_counted_batches(20_000, 2000))) \
                .add(op).add_sink(Sink(rows.append))
            g.run()
            entry = next(p for p in g.placements
                         if p["operator"].endswith("win_seq_tpu.0"))
            assert entry.get("resident", False) is expect
            assert rows


def _counted_batches(n, sb, n_keys=N_KEYS, pace_s=0.0):
    state = {"i": 0}

    def fn():
        i = state["i"]
        if i * sb >= n:
            return None
        state["i"] = i + 1
        if pace_s:
            time.sleep(pace_s)
        return _int_batch(i * sb, min((i + 1) * sb, n), n_keys)

    return fn


# ---------------------------------------------------------------------------
# FFAT resident lane: bytes/launch + fused launches + mirror bound
# ---------------------------------------------------------------------------

def oracle(per_key, win, slide, agg=sum):
    out = {}
    g = 0
    while g * slide < per_key:
        vals = [float(v % 7) for v in range(per_key)
                if g * slide <= v < g * slide + win]
        out[g] = float(agg(vals)) if vals else 0.0
        g += 1
    return out


class TestResidentFFAT:
    def _resident(self, win=512, slide=16, tb=False):
        import jax.numpy as jnp
        return WinSeqFFATResidentLogic(
            lambda t: t.value, jnp.add, 0.0, win, slide,
            win_type=WinType.TB if tb else WinType.CB)

    def test_bytes_per_launch_10x_below_rebuild(self):
        """The acceptance ratio: on a sliding-window config the
        resident lane ships >= 10x fewer bytes per launch than the
        rebuild lane (which re-stages the window carry every launch),
        with identical results."""
        from windflow_tpu.monitoring.stats import StatsRecord
        import jax.numpy as jnp
        win, slide, n = 512, 16, 30_000
        rebuild = WinSeqTPULogic(("ffat", jnp.add, 0.0), win, slide,
                                 WinType.CB, batch_len=64,
                                 async_dispatch=False,
                                 value_of=lambda t: t.value)
        rebuild.stats = StatsRecord()
        a = _run_logic(rebuild, n)
        resident = self._resident(win, slide)
        resident.stats = StatsRecord()
        b = _run_logic(resident, n)
        # identical fired windows, bitwise (integer-valued f32 sums)
        assert a and {k: v[0] for k, v in a.items()} \
            == {k: v[0] for k, v in b.items()}
        per_rebuild = (rebuild.stats.bytes_to_device
                       + rebuild.stats.bytes_from_device) \
            / rebuild.stats.num_launches
        per_resident = (resident.stats.bytes_to_device
                        + resident.stats.bytes_from_device) \
            / resident.stats.num_launches
        assert per_rebuild >= 10 * per_resident, \
            (per_rebuild, per_resident)
        assert resident.stats.device_state_bytes > 0

    def test_one_fused_launch_per_chunk(self):
        lg = self._resident(64, 16)
        out = []
        before = lg.launched_batches
        # one chunk that both scatters AND fires windows: exactly ONE
        # fused launch, not an update launch plus a query launch
        lg.svc(_int_batch(0, 300, 1), 0, out.append)
        assert out  # windows fired
        assert lg.launched_batches == before + 1

    def test_tb_mirror_stays_bounded(self):
        """Satellite fix: the TB eviction proof resumes at the running
        cursor and the mirror is sliced there -- a long in-order
        stream keeps the host mirror O(live span), not O(history)."""
        lg = self._resident(64, 16, tb=True)
        out = []
        n, per_chunk = 40_000, 1000
        for c in range(0, n, per_chunk):
            idx = np.arange(c, c + per_chunk)
            lg.svc(TupleBatch({"key": np.zeros(per_chunk, np.int64),
                               "id": idx, "ts": idx,
                               "value": (idx % 7).astype(np.float64)}),
                   0, out.append)
        st = lg.keys[0]
        # live span = win + headroom-ish; the mirror must not have
        # accumulated the 40k-tuple history
        assert len(st.ts_vals) < 8192, len(st.ts_vals)
        assert st.ts_base > 30_000  # evicted at the proof
        lg.eos_flush(out.append)
        got = {r.get_control_fields()[1]: r.value for r in out}
        expect = oracle(n, 64, 16)
        assert got.keys() == expect.keys()
        for w in (0, 100, len(expect) - 1):
            assert got[w] == expect[w]

    def test_keyed_state_partitions_across_replicas(self):
        """The elastic contract: keyed_state_dict() splits by
        hash%n and load_keyed_state() rebuilds per-owner forests --
        a 1->2 repartition mid-stream matches the fixed run."""
        from windflow_tpu.elastic.rescale import (merge_keyed_states,
                                                  owner_of,
                                                  partition_keyed_state)
        n, n_keys = 12_000, 4
        ref = {}
        full = self._resident(128, 32)
        out = []
        for c in range(0, n, 600):
            full.svc(_int_batch(c, c + 600, n_keys), 0, out.append)
        full.eos_flush(out.append)
        ref = {(r.key, r.id): r.value for r in out}

        a = self._resident(128, 32)
        out = []
        for c in range(0, n // 2, 600):
            a.svc(_int_batch(c, c + 600, n_keys), 0, out.append)
        merged = a.keyed_state_dict()
        assert set(merged) == set(range(n_keys))
        parts = partition_keyed_state(merged, 2)
        reps = [self._resident(128, 32), self._resident(128, 32)]
        for part, rep in zip(parts, reps):
            rep.load_keyed_state(part)
        for c in range(n // 2, n, 600):
            batch = _int_batch(c, c + 600, n_keys)
            keys = batch.key
            for owner in (0, 1):
                mask = np.array([owner_of(int(k), 2) == owner
                                 for k in keys])
                if mask.any():
                    reps[owner].svc(batch.take(np.nonzero(mask)[0]),
                                    0, out.append)
        for rep in reps:
            rep.eos_flush(out.append)
        got = {(r.key, r.id): r.value for r in out}
        assert got == ref
        # and the merge invariant holds on the split replicas
        class _N:  # noqa: N801 - minimal RtNode stand-in
            def __init__(self, logic):
                self.logic = logic
                self.name = "ffat"
        merged2, stateful = merge_keyed_states([_N(r) for r in reps])
        assert stateful and set(merged2) == set(range(n_keys))


# ---------------------------------------------------------------------------
# online re-planning
# ---------------------------------------------------------------------------

class TestReplanDecision:
    def test_device_lane_measured_slow_flips_host(self):
        from windflow_tpu.graph.replanner import replan_decision
        v = replan_decision("device", measured_ms_per_launch=2.5,
                            tuples_per_launch=2048,
                            bytes_per_launch=1200, rtt_ms=0.01,
                            host_tps=20e6)
        assert v["placement"] == "host"
        assert v["measured_ms"] == 2.5
        assert v["device_compute_ms"] > 2.0

    def test_device_lane_measured_fast_stays(self):
        from windflow_tpu.graph.replanner import replan_decision
        v = replan_decision("device", measured_ms_per_launch=0.02,
                            tuples_per_launch=65536,
                            bytes_per_launch=1200, rtt_ms=0.01,
                            host_tps=20e6)
        assert v["placement"] == "device"

    def test_host_lane_wins_chip_back_with_cheap_calibration(self):
        from windflow_tpu.graph.replanner import replan_decision
        v = replan_decision("host", measured_ms_per_launch=None,
                            tuples_per_launch=65536,
                            bytes_per_launch=1200, rtt_ms=0.01,
                            host_tps=20e6, calibrated_compute_ms=0.01)
        assert v["placement"] == "device"
        v = replan_decision("host", measured_ms_per_launch=None,
                            tuples_per_launch=65536,
                            bytes_per_launch=1200, rtt_ms=0.01,
                            host_tps=20e6, calibrated_compute_ms=50.0)
        assert v["placement"] == "host"


def _window_count(n, n_keys, win, slide):
    per_key = n // n_keys
    c = 0
    while c * slide < per_key:
        c += 1
    return c * n_keys


class TestReplanFlip:
    def test_scripted_load_shift_flips_lane_zero_loss(self):
        """The acceptance scenario: auto resolves 'device' from the
        tiny pinned RTT floor, the measured cpu-fallback launch walls
        contradict the projection, and the re-planner flips the lane
        mid-run -- zero lost/duplicated windows (ledger balanced
        across the flip), values equal to the integer oracle on both
        sides of the flip, flip visible as a ``replacement`` flight
        event and explained by doctor.  The paced stream keeps
        flowing until the flip lands (bounded), so the proof is
        robust to a loaded box."""
        win, slide, sb, cap = 1024, 32, 1500, 800
        cfg = RuntimeConfig(mode=Mode.DEFAULT, replan=True,
                            replan_ticks=2, diagnosis_interval_s=0.15,
                            audit_interval_s=0.1)
        g = wf.PipeGraph("replan_flip", wf.Mode.DEFAULT, cfg)
        rows = []
        op = WinSeqTPU("sum", win, slide, WinType.CB, batch_len=64,
                       inflight_depth=1, placement="auto",
                       value_of=lambda t: t.value)
        state = {"i": 0, "tail": 0}

        def batch():
            i = state["i"]
            flipped = any(e["kind"] == "replacement"
                          for e in g.flight.snapshot())
            if flipped:
                state["tail"] += 1
            if i >= cap * sb or state["tail"] > 25:
                return None  # flip landed (plus a post-flip tail)
            state["i"] = i + sb
            time.sleep(0.004)
            return _int_batch(i, i + sb)

        g.add_source(BatchSource(batch)).add(op).add_sink(
            Sink(rows.append))
        g.run()
        n = state["i"]
        got = {}
        for r in rows:
            if r is None:  # EOS sentinel
                continue
            got[(r.key, r.id)] = got.get((r.key, r.id), []) + [r.value]
        entry = next(p for p in g.placements
                     if "win_seq_tpu" in p["operator"])
        assert entry["placement"] == "host" and entry.get("replanned")
        flips = [e for e in g.flight.snapshot()
                 if e["kind"] == "replacement"]
        assert flips and flips[0]["old"] == "device" \
            and flips[0]["new"] == "host"
        assert flips[0]["evidence"]["measured_ms"] > 0
        # zero lost / duplicated windows across the flip, values ==
        # the integer oracle on BOTH sides (host f64 and device f32
        # sums agree exactly on these magnitudes)
        assert all(len(v) == 1 for v in got.values())
        assert len(got) == _window_count(n, N_KEYS, win, slide)
        per_key = n // N_KEYS
        for key in range(N_KEYS):
            for w in (0, per_key // (2 * slide),
                      (per_key - 1) // slide):
                ids = range(w * slide, min(w * slide + win, per_key))
                want = float(sum((i * N_KEYS + key) % 7 for i in ids))
                assert got[(key, w)][0] == want, (key, w)
        # ledger balanced: a violation would have been flagged
        assert not [e for e in g.flight.snapshot()
                    if e["kind"] == "conservation_violation"]
        # doctor explains the flip
        rep = g.explain()
        assert rep["Replacements"] and \
            rep["Replacements"][0]["operator"] == flips[0]["operator"]
        from windflow_tpu.diagnosis.report import render_text
        txt = render_text(rep)
        assert "lane replacements (online re-planning):" in txt
        assert "device -> host" in txt


# ---------------------------------------------------------------------------
# durability: resident engines across epochs, crashes and lane flips
# ---------------------------------------------------------------------------

class _CkptSourceLogic(SourceLoopLogic):
    def __init__(self, n, pace_every=128, pace_s=0.001):
        self.i = 0
        self.n = n
        self.pace_every = pace_every
        self.pace_s = pace_s
        super().__init__(self._step)

    def _step(self, emit):
        i = self.i
        if i >= self.n:
            return False
        if self.pace_every and i % self.pace_every == 0:
            time.sleep(self.pace_s)
        emit(BasicRecord(i % N_KEYS, i // N_KEYS, i // N_KEYS,
                         float(i % 7)))
        self.i = i + 1
        return True

    def state_dict(self):
        return {"i": self.i}

    def load_state(self, st):
        self.i = st["i"]

    def progress_frontier(self):
        return self.i


class CkptSource(Operator):
    def __init__(self, n, name="ckpt_source", pace_every=128,
                 pace_s=0.001):
        super().__init__(name, 1, RoutingMode.NONE, Pattern.SOURCE)
        self.n = n
        self.pace_every = pace_every
        self.pace_s = pace_s

    def stages(self):
        logic = _CkptSourceLogic(self.n, self.pace_every, self.pace_s)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing)]


class TestResidentDurability:
    def _ffat_run(self, path, n, fault=None):
        from windflow_tpu.core import DurabilityConfig
        from windflow_tpu.durability import run_with_epochs
        from windflow_tpu.resilience.faults import FaultPlan
        wins = {}
        counts = collections.Counter()

        def sink(r):
            if r is None:
                return
            wins[(r.key, r.id)] = r.value
            counts[(r.key, r.id)] += 1

        graphs = []

        def factory(attempt):
            plan = fault if attempt == 0 else None
            cfg = wf.RuntimeConfig(
                durability=DurabilityConfig(epoch_interval_s=0.05,
                                            path=path),
                fault_plan=plan)
            g = wf.PipeGraph("dur_resident", wf.Mode.DEFAULT,
                             config=cfg)
            op = wf.WinSeqFFATTPUBuilder(lambda t: t.value, "sum") \
                .with_cb_windows(96, 16).build()
            assert isinstance(op, WinSeqFFATResident)  # default lane
            g.add_source(CkptSource(n, pace_every=64, pace_s=0.002)) \
                .add(op) \
                .add_sink(wf.SinkBuilder(sink).with_exactly_once()
                          .build())
            graphs.append(g)
            return g

        g = run_with_epochs(factory, max_restarts=2)
        return g, wins, counts

    def test_crash_restart_verify_resident_ffat(self, tmp_path):
        """Kill-restart-verify with the device-resident (cpu-fallback
        XLA) FFAT engine: epoch snapshots carry the resident forest,
        the restored run is bitwise equal to an uninterrupted one."""
        from windflow_tpu.resilience.faults import FaultPlan
        N = 5000
        _g, ref, ref_counts = self._ffat_run(str(tmp_path / "ref"), N)
        assert ref and max(ref_counts.values()) == 1
        # the builder names the op win_seqffat_tpu (the resident logic
        # rides the same builder); the crash clock binds per fused
        # segment, so the substring must match the SEGMENT name
        plan = FaultPlan(seed=9).crash_replica("win_seqffat_tpu",
                                               at_tuple=2500)
        g, wins, counts = self._ffat_run(str(tmp_path / "chaos"), N,
                                         fault=plan)
        assert getattr(g, "_epoch_restored", None) is not None
        assert max(counts.values()) == 1, "duplicate windows"
        assert wins == ref

    def test_lane_flip_between_epochs_exactly_once(self, tmp_path):
        """A scripted mid-run device->host lane flip lands between two
        epochs (replace_lane holds the epoch cadence like a rescale);
        a crash after the flip restarts from a committed epoch and the
        resolved results equal the uninterrupted no-flip run."""
        from windflow_tpu.core import DurabilityConfig
        from windflow_tpu.durability import run_with_epochs
        from windflow_tpu.resilience.faults import FaultPlan
        N, WIN, SLIDE = 6000, 64, 32

        def run(path, flip, fault):
            wins = {}
            counts = collections.Counter()

            def sink(r):
                if r is None:
                    return
                wins[(r.key, r.id)] = r.value
                counts[(r.key, r.id)] += 1

            flips = []

            def factory(attempt):
                plan = fault if attempt == 0 else None
                cfg = wf.RuntimeConfig(
                    durability=DurabilityConfig(epoch_interval_s=0.05,
                                                path=path),
                    fault_plan=plan)
                g = wf.PipeGraph("dur_flip", wf.Mode.DEFAULT,
                                 config=cfg)
                op = WinSeqTPU("sum", WIN, SLIDE, WinType.CB,
                               batch_len=32, placement="device",
                               value_of=lambda t: t.value)
                g.add_source(CkptSource(N, pace_every=32,
                                        pace_s=0.004)) \
                    .add(op) \
                    .add_sink(wf.SinkBuilder(sink).with_exactly_once()
                              .build())
                if flip and attempt == 0:
                    def flipper():
                        time.sleep(0.3)
                        try:
                            ev = g.replace_lane(
                                "pipe0/win_seq_tpu.0", "host",
                                trigger="script")
                            flips.append(ev)
                        except Exception:
                            pass  # graph already dead (late crash)
                    threading.Thread(target=flipper,
                                     daemon=True).start()
                return g

            g = run_with_epochs(factory, max_restarts=2)
            return g, wins, counts, flips

        _gr, ref, rc, _ = run(str(tmp_path / "ref"), False, None)
        assert ref and max(rc.values()) == 1
        # crash the ENGINE's tuple clock (a source's clock never ticks:
        # it consumes nothing), late enough to land after the flip
        plan = FaultPlan(seed=13).crash_replica("win_seq_tpu",
                                                at_tuple=5200)
        g, wins, counts, flips = run(str(tmp_path / "chaos"), True,
                                     plan)
        assert flips and flips[0] is not None  # the flip happened
        assert getattr(g, "_epoch_restored", None) is not None
        assert max(counts.values()) == 1, "duplicate windows"
        assert wins == ref
