"""Graph compile pass (graph/fuse.py): LEVEL0 vs LEVEL2 equivalence.

Every test runs a representative graph at OptLevel.LEVEL0 (fusion off)
and LEVEL2 (the default) and asserts identical outputs, dead-letter
counts and stats totals -- the acceptance contract of the compile pass:
fusion may only remove channel hops, never change results.
"""
import threading

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.core.basic import (OptLevel, Pattern, RoutingMode,
                                     RuntimeConfig)
from windflow_tpu.core.tuples import ColumnPool, TupleBatch
from windflow_tpu.graph.fuse import find_logic, iter_logics
from windflow_tpu.graph.pipegraph import NodeFailureError
from windflow_tpu.operators.base import Operator, StageSpec
from windflow_tpu.operators.basic_ops import Sink
from windflow_tpu.operators.batch_ops import BatchMap, BatchSource
from windflow_tpu.operators.tpu.win_seq_tpu import WinSeqTPU
from windflow_tpu.resilience.faults import FaultPlan, InjectedFailure
from windflow_tpu.runtime.emitters import StandardEmitter
from windflow_tpu.runtime.node import SourceLoopLogic


def record_source(n, n_keys=3):
    state = {"i": 0}

    def fn(shipper):
        i = state["i"]
        if i >= n:
            return False
        shipper.push(wf.BasicRecord(i % n_keys, i // n_keys, i // n_keys,
                                    float(i)))
        state["i"] = i + 1
        return True

    return fn


def batch_source(n, n_keys=8, batch=1024, vmod=97):
    state = {"i": 0}

    def fn(ctx):
        i = state["i"]
        if i >= n:
            return None
        m = min(batch, n - i)
        idx = i + np.arange(m)
        state["i"] = i + m
        return TupleBatch({"key": idx % n_keys, "id": idx // n_keys,
                           "ts": idx // n_keys,
                           "value": (idx % vmod).astype(np.float64)})

    return fn


class CollectSink:
    def __init__(self):
        self.lock = threading.Lock()
        self.items = []

    def __call__(self, item):
        if item is None:
            return
        with self.lock:
            if isinstance(item, TupleBatch):
                for j in range(len(item)):
                    self.items.append((int(item.key[j]), int(item.id[j]),
                                       float(item["value"][j])))
            else:
                self.items.append((item.key, item.id, item.value))

    def sorted(self):
        return sorted(self.items)


def cfg_for(level, **kw):
    return RuntimeConfig(opt_level=level, **kw)


# ---------------------------------------------------------------------------
# result equivalence
# ---------------------------------------------------------------------------

def test_record_chain_equivalence_and_thread_collapse():
    results, threads = {}, {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        g = wf.PipeGraph("chain", wf.Mode.DEFAULT, config=cfg_for(lvl))
        g.add_source(wf.SourceBuilder(record_source(300)).build()) \
            .add(wf.MapBuilder(lambda t: wf.BasicRecord(
                t.key, t.id, t.ts, t.value * 2.0)).build()) \
            .add(wf.FilterBuilder(lambda t: t.value % 4 == 0).build()) \
            .add_sink(wf.SinkBuilder(sink).build())
        g.run()
        results[lvl] = sink.sorted()
        threads[lvl] = g.thread_count()
    assert results[OptLevel.LEVEL0] == results[OptLevel.LEVEL2]
    assert threads[OptLevel.LEVEL2] == 1  # whole chain in one replica
    assert threads[OptLevel.LEVEL0] == 4


def test_flatmap_chain_equivalence():
    def dup(t, shipper):
        shipper.push(t)
        shipper.push(wf.BasicRecord(t.key, t.id, t.ts, -t.value))

    results = {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        g = wf.PipeGraph("fm", wf.Mode.DEFAULT, config=cfg_for(lvl))
        g.add_source(wf.SourceBuilder(record_source(120)).build()) \
            .add(wf.FlatMapBuilder(dup).build()) \
            .add_sink(wf.SinkBuilder(sink).build())
        g.run()
        results[lvl] = sink.sorted()
    assert results[OptLevel.LEVEL0] == results[OptLevel.LEVEL2]
    assert len(results[OptLevel.LEVEL0]) == 240


def test_parallel_forward_stage_pattern_fuses():
    """n:n FORWARD fusion: same-parallelism map stage pairs off with
    its upstream tails; the output multiset is unchanged."""
    results, threads = {}, {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        g = wf.PipeGraph("par", wf.Mode.DEFAULT, config=cfg_for(lvl))
        g.add_source(wf.SourceBuilder(record_source(400)).build()) \
            .add(wf.MapBuilder(lambda t: wf.BasicRecord(
                t.key, t.id, t.ts, t.value + 1.0))
                 .with_parallelism(2).build()) \
            .add(wf.MapBuilder(lambda t: wf.BasicRecord(
                t.key, t.id, t.ts, t.value * 3.0))
                 .with_parallelism(2).build()) \
            .add_sink(wf.SinkBuilder(sink).build())
        g.run()
        results[lvl] = sink.sorted()
        threads[lvl] = g.thread_count()
    assert results[OptLevel.LEVEL0] == results[OptLevel.LEVEL2]
    # the two 2-replica map stages fused pairwise (4 nodes -> 2)
    assert threads[OptLevel.LEVEL2] < threads[OptLevel.LEVEL0]


@pytest.mark.parametrize("force_python", [False, True])
def test_keyed_window_equivalence(force_python):
    """Keyed TB window sums must be bitwise identical across levels,
    on both the native C++ engine and the pure-Python path."""
    results = {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        g = wf.PipeGraph("win", wf.Mode.DEFAULT, config=cfg_for(lvl))
        op = WinSeqTPU("sum", 64, 32, wf.WinType.TB, batch_len=128,
                       emit_batches=True)
        g.add_source(BatchSource(batch_source(50_000))) \
            .add(BatchMap(lambda b: b.with_cols(value=b["value"] * 0.5))) \
            .add(op).add_sink(Sink(sink))
        if force_python:
            for _name, logic in iter_logics(g):
                if hasattr(logic, "_native"):
                    logic._native = None
        g.run()
        results[lvl] = sink.sorted()
    assert results[OptLevel.LEVEL0] == results[OptLevel.LEVEL2]
    assert results[OptLevel.LEVEL0], "no windows emitted"


@pytest.mark.parametrize("query", ["q5", "q7"])
def test_nexmark_equivalence(query):
    from windflow_tpu.models.nexmark import (build_q5_hot_items,
                                             build_q7_highest_bid)
    results = {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        g = wf.PipeGraph(f"nex_{query}", wf.Mode.DEFAULT,
                         config=cfg_for(lvl))
        if query == "q5":
            build_q5_hot_items(g, 60_000, 1 << 12, 1 << 11, sink,
                               batch_size=4096, device_batch=512)
        else:
            build_q7_highest_bid(g, 60_000, 1 << 12, sink,
                                 batch_size=4096, device_batch=512)
        g.run()
        results[lvl] = sink.sorted()
    assert results[OptLevel.LEVEL0] == results[OptLevel.LEVEL2]
    assert results[OptLevel.LEVEL0], "no windows emitted"


# ---------------------------------------------------------------------------
# containment contracts inside fused segments
# ---------------------------------------------------------------------------

def dl_graph(lvl):
    sink = CollectSink()

    def bad(t):
        if t.id % 5 == 2:
            raise ValueError("poison")
        return t

    g = wf.PipeGraph("dl", wf.Mode.DEFAULT, config=cfg_for(lvl))
    g.add_source(wf.SourceBuilder(record_source(100, n_keys=1)).build()) \
        .add(wf.MapBuilder(bad).with_error_policy("dead_letter")
             .with_name("badmap").build()) \
        .add_sink(wf.SinkBuilder(sink).build())
    return g, sink


def test_dead_letter_policy_parity():
    out = {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        g, sink = dl_graph(lvl)
        g.run()
        out[lvl] = (sink.sorted(), g.dead_letters.count(),
                    g.dead_letters.counts_by_node())
    assert out[OptLevel.LEVEL0] == out[OptLevel.LEVEL2]
    # attribution names the fused-away operator's replica, not the host
    assert out[OptLevel.LEVEL2][2] == {"pipe0/badmap.0": 20}


def test_fault_plan_fires_inside_fused_segment():
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        with FaultPlan(seed=11).crash_replica("mapper", at_tuple=17) as plan:
            g = wf.PipeGraph("cr", wf.Mode.DEFAULT,
                             config=cfg_for(lvl, fault_plan=plan))
            g.add_source(wf.SourceBuilder(record_source(500)).build()) \
                .add(wf.MapBuilder(lambda t: t).with_name("mapper")
                     .build()) \
                .add_sink(wf.SinkBuilder(lambda r: None).build())
            with pytest.raises(NodeFailureError) as ei:
                g.run()
            assert any(isinstance(e, InjectedFailure)
                       for _, e in ei.value.errors), lvl


def test_skip_policy_does_not_swallow_neighbour_errors():
    """A fused 'skip' segment must quarantine only its own failures:
    an error in the downstream 'fail' segment still kills the graph."""

    def skippy(t):
        if t.id == 3:
            raise ValueError("skippable")
        return t

    def bad_sink(r):
        if r is not None and r.id == 7:
            raise RuntimeError("sink failure must be fatal")

    g = wf.PipeGraph("mix", wf.Mode.DEFAULT,
                     config=cfg_for(OptLevel.LEVEL2))
    g.add_source(wf.SourceBuilder(record_source(100, n_keys=1)).build()) \
        .add(wf.MapBuilder(skippy).with_error_policy("skip").build()) \
        .add_sink(wf.SinkBuilder(bad_sink).build())
    with pytest.raises(NodeFailureError):
        g.run()


def test_stats_totals_match_across_levels():
    totals = {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        g = wf.PipeGraph("tr", wf.Mode.DEFAULT,
                         config=cfg_for(lvl, tracing=True, log_dir="log"))
        g.add_source(wf.SourceBuilder(record_source(200)).build()) \
            .add(wf.MapBuilder(lambda t: t).with_name("m1").build()) \
            .add(wf.FilterBuilder(lambda t: t.value % 2 == 0).build()) \
            .add_sink(wf.SinkBuilder(sink).build())
        g.run()
        import json
        data = json.loads(g.stats.to_json())
        totals[lvl] = {
            o["Operator_name"]: (
                sum(r["Inputs_received"] for r in o["Replicas"]),
                sum(r["Outputs_sent"] for r in o["Replicas"]),
                all(r["Terminated"] for r in o["Replicas"]))
            for o in data["Operators"]}
    assert totals[OptLevel.LEVEL0] == totals[OptLevel.LEVEL2]


def test_checkpoint_round_trip_across_fusion_levels():
    """Snapshots stay keyed by pre-fusion node names: a LEVEL2 run's
    state restores into a LEVEL0 graph (and the restored run agrees)."""
    from windflow_tpu.utils.checkpoint import graph_state

    def build(lvl, n):
        sink = CollectSink()
        g = wf.PipeGraph("ck", wf.Mode.DEFAULT, config=cfg_for(lvl))
        g.add_source(wf.SourceBuilder(record_source(n, n_keys=2)).build()) \
            .add(wf.AccumulatorBuilder(
                lambda t, acc: setattr(acc, "value", acc.value + t.value))
                .with_initial_value(wf.BasicRecord(value=0.0)).build()) \
            .add_sink(wf.SinkBuilder(sink).build())
        return g

    g2 = build(OptLevel.LEVEL2, 40)
    g2.run()
    assert g2.fused_nodes, "accumulator chain should have fused"
    snap = graph_state(g2)
    # keys are the ORIGINAL node names, not the fused node's
    assert any("accumulator" in k for k in snap)

    g0 = build(OptLevel.LEVEL0, 40)
    for node in g0._all_nodes():
        st = snap.get(node.name)
        if st is not None:
            node.logic.load_state(st)
    acc = find_logic(g0, lambda lg: hasattr(lg, "state"), "accumulator")
    keys0 = {k: v.value for k, v in acc.state.items()}
    acc2 = find_logic(g2, lambda lg: hasattr(lg, "state"), "accumulator")
    keys2 = {k: v.value for k, v in acc2.state.items()}
    assert keys0 == keys2 and keys2


def test_deterministic_mode_unaffected():
    """Collector-guarded modes never fuse across collectors; results
    stay ordered and identical."""
    results = {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        g = wf.PipeGraph("det", wf.Mode.DETERMINISTIC, config=cfg_for(lvl))
        g.add_source(wf.SourceBuilder(record_source(150)).build()) \
            .add(wf.MapBuilder(lambda t: t).build()) \
            .add_sink(wf.SinkBuilder(sink).build())
        g.run()
        results[lvl] = sink.items  # arrival order matters here
    assert sorted(results[OptLevel.LEVEL0]) \
        == sorted(results[OptLevel.LEVEL2])


def test_opt_out_is_honoured():
    g = wf.PipeGraph("off", wf.Mode.DEFAULT,
                     config=cfg_for(OptLevel.LEVEL0))
    g.add_source(wf.SourceBuilder(record_source(10)).build()) \
        .add_sink(wf.SinkBuilder(lambda r: None).build())
    g.run()
    assert g.fused_nodes == []
    assert g.thread_count() == 2


# ---------------------------------------------------------------------------
# batched channel plane
# ---------------------------------------------------------------------------

def test_channel_put_many_get_many_roundtrip():
    from windflow_tpu.runtime.queues import Channel
    ch = Channel(capacity=8)
    pid = ch.register_producer()
    ch.put_many(pid, list(range(6)))
    got = ch.get_many(4)
    assert [it for _, it in got] == [0, 1, 2, 3]
    got = ch.get_many(10)
    assert [it for _, it in got] == [4, 5]
    ch.close(pid)
    assert ch.get_many(4) is None
    assert ch.get_many(4) is None  # sticky


def test_channel_put_many_respects_capacity_and_poison():
    from windflow_tpu.resilience.cancel import GraphCancelled
    from windflow_tpu.runtime.queues import Channel
    ch = Channel(capacity=4)
    pid = ch.register_producer()
    done = []

    def producer():
        try:
            ch.put_many(pid, list(range(100)))
            done.append("full")
        except GraphCancelled:
            done.append("cancelled")

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    got = []
    while len(got) < 20:
        out = ch.get_many(8, timeout=1.0)
        assert isinstance(out, list)
        got.extend(it for _, it in out)
    assert got == list(range(len(got)))  # FIFO preserved across bulk ops
    ch.poison()
    t.join(timeout=5)
    assert not t.is_alive() and done and done[0] == "cancelled"


def test_get_many_interleaves_multiple_producers_eos():
    from windflow_tpu.runtime.queues import Channel
    ch = Channel(capacity=16)
    p0, p1 = ch.register_producer(), ch.register_producer()
    ch.put(p0, "a")
    ch.close(p0)
    ch.put(p1, "b")
    out = ch.get_many(8)
    assert [it for _, it in out] == ["a", "b"]  # p0's EOS absorbed
    ch.close(p1)
    assert ch.get_many(8) is None


# ---------------------------------------------------------------------------
# pooled interchange
# ---------------------------------------------------------------------------

def test_column_pool_reuses_dead_buffers():
    pool = ColumnPool()
    a = pool.take(1000, np.int64)
    a_base_id = id(a.base)
    del a
    b = pool.take(900, np.int64)  # same power-of-two bucket
    assert id(b.base) == a_base_id
    assert pool.hits == 1


def test_column_pool_never_reuses_live_buffers():
    pool = ColumnPool()
    a = pool.take(100, np.float64)
    a[:] = 7.0
    b = pool.take(100, np.float64)
    assert id(b.base) != id(a.base)
    b[:] = 9.0
    assert float(a[0]) == 7.0


def test_synth_chunk_pooled_materialize_identical():
    from windflow_tpu.core.tuples import SynthChunk
    pool = ColumnPool()
    c = SynthChunk(1234, 5000, 7, 97, 1.5, 0.25)
    plain = c.materialize()
    pooled = c.materialize(pool)
    for col in ("key", "id", "ts", "value"):
        np.testing.assert_array_equal(plain[col], pooled[col])


def test_take_contiguous_run_is_view():
    b = TupleBatch({"key": np.arange(10), "id": np.arange(10),
                    "ts": np.arange(10),
                    "value": np.arange(10, dtype=np.float64)})
    mask = np.zeros(10, bool)
    mask[3:9] = True
    sub = b.take(mask)
    assert len(sub) == 6
    assert sub.key.base is not None  # a view, not a gather copy
    np.testing.assert_array_equal(sub.key, np.arange(3, 9))
    # non-contiguous picks still gather correctly
    sub2 = b.take(np.array([0, 2, 3]))
    np.testing.assert_array_equal(sub2.key, [0, 2, 3])


def test_partition_batch_pooled_matches_unpooled():
    from windflow_tpu.runtime.emitters import partition_batch
    rng = np.random.default_rng(0)
    b = TupleBatch({"key": rng.integers(0, 50, 4096),
                    "id": np.arange(4096), "ts": np.arange(4096),
                    "value": rng.random(4096)})
    dests = np.abs(b.key) % 4
    plain = {d: s for d, s in partition_batch(b, dests)}
    pooled = {d: s for d, s in partition_batch(b, dests, ColumnPool())}
    assert plain.keys() == pooled.keys()
    for d in plain:
        for col in ("key", "id", "ts", "value"):
            np.testing.assert_array_equal(plain[d][col], pooled[d][col])


def test_ingest_feed_fused_equivalence():
    """Ingest plane + LEVEL2: the credit boundary survives (the source
    keeps its outlet channel) while the engine fuses with the sink."""
    from windflow_tpu.operators.tpu.win_seq_tpu import WinSeqTPULogic
    n = 40_000
    arange = np.arange(n, dtype=np.int64)
    ids = arange // 4
    trace = TupleBatch({"key": arange % 4, "id": ids, "ts": ids,
                        "value": (arange % 31).astype(np.float64)})
    results = {}
    for lvl in (OptLevel.LEVEL0, OptLevel.LEVEL2):
        sink = CollectSink()
        src = wf.SourceBuilder.from_replay(trace, speedup=None,
                                           chunk=2048).build()
        g = wf.PipeGraph("ing", wf.Mode.DEFAULT, config=cfg_for(lvl))
        op = WinSeqTPU("sum", 256, 128, wf.WinType.TB, batch_len=256,
                       emit_batches=True)
        g.add_source(src).add(op).add_sink(Sink(sink))
        g.run()
        results[lvl] = sink.sorted()
        if lvl == OptLevel.LEVEL2:
            assert g.fused_nodes, "engine+sink should have fused"
            eng = find_logic(g, lambda lg: isinstance(lg, WinSeqTPULogic))
            assert eng is not None  # fusion-transparent lookup
    assert results[OptLevel.LEVEL0] == results[OptLevel.LEVEL2]
    assert results[OptLevel.LEVEL0], "no windows emitted"


# ---------------------------------------------------------------------------
# whole-partition device step (graph/device_step.py)
# ---------------------------------------------------------------------------

def _force_python(g):
    for _name, logic in iter_logics(g):
        if hasattr(logic, "_native"):
            logic._native = None


def _step_info(g):
    from windflow_tpu.graph.device_step import DeviceStepLogic
    return {n.name: (n.logic.chunks_in, n.logic.chunk_launches)
            for n in g._all_nodes()
            if isinstance(n.logic, DeviceStepLogic)}


def _build_app(query, g, sink):
    from windflow_tpu.models.nexmark import (build_q5_hot_items,
                                             build_q7_highest_bid)
    from windflow_tpu.models.yahoo import build_pipeline
    if query == "q5":
        build_q5_hot_items(g, 60_000, 1 << 12, 1 << 11, sink,
                           batch_size=4096, device_batch=512)
    elif query == "q7":
        build_q7_highest_bid(g, 60_000, 1 << 12, sink,
                             batch_size=4096, device_batch=512)
    else:
        build_pipeline(g, 60_000, batch_size=4096, device_batch=512,
                       sink=sink)


@pytest.mark.parametrize("force_python", [False, True])
@pytest.mark.parametrize("query", ["q5", "q7", "yahoo"])
def test_device_step_bitwise(query, force_python):
    """Device-step graphs produce bitwise-identical sink results vs
    the plain LEVEL2 graph, on both channel planes -- launch GROUPING
    changes (one per ingest chunk), launched work does not.  The source
    merges into the device segment, so the whole partition runs as one
    chunk-stepped replica."""
    results, infos = {}, {}
    for step in (False, True):
        sink = CollectSink()
        g = wf.PipeGraph(f"step_{query}", wf.Mode.DEFAULT,
                         config=cfg_for(OptLevel.LEVEL2,
                                        device_step=step))
        _build_app(query, g, sink)
        if force_python:
            _force_python(g)
        g.run()
        results[step] = sink.sorted()
        infos[step] = _step_info(g)
    assert results[True] == results[False]
    assert results[True], "no windows emitted"
    assert infos[True] and not infos[False]
    ((_name, (chunks, launches)),) = infos[True].items()
    assert chunks > 0
    # the acceptance bound: at most 2 launches per ingest chunk
    assert launches <= 2 * chunks, (chunks, launches)


def test_device_step_crash_mid_chunk():
    """A FaultPlan crash inside the step node fires mid-chunk: the
    failure surfaces exactly like any fused crash (the boundary flush
    of the dying chunk is skipped, never half-launched)."""
    from windflow_tpu.models.nexmark import build_q5_hot_items
    for step in (False, True):
        with FaultPlan(seed=11).crash_replica("q5_counts",
                                              at_tuple=5) as plan:
            g = wf.PipeGraph("step_crash", wf.Mode.DEFAULT,
                             config=cfg_for(OptLevel.LEVEL2,
                                            fault_plan=plan,
                                            device_step=step))
            build_q5_hot_items(g, 60_000, 1 << 12, 1 << 11,
                               CollectSink(), batch_size=4096,
                               device_batch=512)
            with pytest.raises(NodeFailureError) as ei:
                g.run()
            assert any(isinstance(e, InjectedFailure)
                       for _, e in ei.value.errors), step


class _CkptBatchSrcLogic(SourceLoopLogic):
    """Offset-checkpointable paced BATCH source logic (the chunk-plane
    twin of test_durability's CkptSource)."""

    def __init__(self, n, batch=512, pace_s=0.002):
        self.i = 0
        self.n = n
        self.batch = batch
        self.pace_s = pace_s
        super().__init__(self._step)

    def _step(self, emit):
        import time as _t
        i = self.i
        if i >= self.n:
            return False
        _t.sleep(self.pace_s)
        m = min(self.batch, self.n - i)
        idx = i + np.arange(m)
        self.i = i + m
        emit(TupleBatch({"key": idx % 4, "id": idx // 4,
                         "ts": idx // 4,
                         "value": (idx % 7).astype(np.float64)}))
        return True

    def state_dict(self):
        return {"i": self.i}

    def load_state(self, st):
        self.i = st["i"]

    def progress_frontier(self):
        return self.i


class CkptBatchSource(Operator):
    def __init__(self, n, name="ckpt_bsrc"):
        super().__init__(name, 1, RoutingMode.NONE, Pattern.SOURCE)
        self.n = n

    def stages(self):
        return [StageSpec(self.name, [_CkptBatchSrcLogic(self.n)],
                          StandardEmitter(), self.routing)]


@pytest.mark.parametrize("force_python", [False, True])
def test_device_step_epoch_kill_restart_bitwise(tmp_path, force_python):
    """Exactly-once across a kill-restart with the step active, on both
    channel planes: epoch barriers fence at the chunk boundary (the
    injected barrier is a control item, never held), the restart
    replays from the committed offset, and window results equal the
    uninterrupted run's bitwise."""
    from windflow_tpu.core import DurabilityConfig
    from windflow_tpu.durability import run_with_epochs
    N, WIN, SLIDE = 30_000, 256, 128

    def run(path, fault):
        wins, counts = {}, {}
        graphs = []

        def sink(r):
            if r is None:
                return
            if isinstance(r, TupleBatch):
                for j in range(len(r)):
                    k = (int(r.key[j]), int(r.id[j]))
                    wins[k] = float(r["value"][j])
                    counts[k] = counts.get(k, 0) + 1
                return
            k = (r.key, r.id)
            wins[k] = r.value
            counts[k] = counts.get(k, 0) + 1

        def factory(attempt):
            plan = fault if attempt == 0 else None
            cfg = cfg_for(OptLevel.LEVEL2,
                          durability=DurabilityConfig(
                              epoch_interval_s=0.03, path=path),
                          fault_plan=plan)
            g = wf.PipeGraph("step_dur", wf.Mode.DEFAULT, config=cfg)
            op = WinSeqTPU("sum", WIN, SLIDE, wf.WinType.TB,
                           batch_len=256, emit_batches=True,
                           name="stepwin")
            g.add_source(CkptBatchSource(N)).add(op) \
                .add_sink(wf.SinkBuilder(sink).with_exactly_once()
                          .build())
            if force_python:
                _force_python(g)
            graphs.append(g)
            return g

        g = run_with_epochs(factory, max_restarts=2)
        return g, wins, counts

    _gr, ref, ref_counts = run(str(tmp_path / "ref"), None)
    assert ref and max(ref_counts.values()) == 1
    assert _step_info(_gr), "step should be active"
    plan = FaultPlan(seed=13).crash_replica("stepwin", at_tuple=30)
    g, wins, counts = run(str(tmp_path / "chaos"), plan)
    assert getattr(g, "_epoch_restored", None) is not None
    assert max(counts.values()) == 1, "duplicate window results"
    assert wins == ref
