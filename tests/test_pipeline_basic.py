"""End-to-end tests of the basic-operator pipeline, reference style.

Mirrors the oracle of tests/mp_tests_cpu (SURVEY.md §4): build a full
PipeGraph with a synthetic source, run it several times with randomized
operator parallelisms, and assert the global aggregate is identical
across runs.
"""
import random
import threading

import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, Mode


def make_source_fn(n_keys, stream_len, replica_streams):
    """Each source replica generates a disjoint id space per key; tuples
    carry value = id (reference fixture mp_common.hpp:125-163 style)."""

    def fn(shipper, ctx):
        ridx = ctx.get_replica_index()
        state = replica_streams.setdefault(ridx, {"sent": 0})
        i = state["sent"]
        if i >= stream_len:
            return False
        key = i % n_keys
        tid = i // n_keys
        rec = BasicRecord(key, tid, ts=i * 10 + ridx, value=float(i % 17))
        shipper.push(rec)
        state["sent"] = i + 1
        return True

    return fn


class CountingSink:
    def __init__(self):
        self.lock = threading.Lock()
        self.total = 0.0
        self.count = 0
        self.ended = 0

    def __call__(self, rec):
        with self.lock:
            if rec is None:
                self.ended += 1
            else:
                self.total += rec.value
                self.count += 1


def run_pipeline(mode, src_par, fil_par, fm_par, map_par, stream_len=400,
                 n_keys=5):
    sink = CountingSink()
    g = wf.PipeGraph("test", mode)
    src = wf.SourceBuilder(make_source_fn(n_keys, stream_len, {})) \
        .with_parallelism(src_par).build()

    def odd_filter(t):
        return int(t.value) % 2 == 0

    def triple(t, shipper):
        for _ in range(3):
            shipper.push(BasicRecord(t.key, t.id, t.ts, t.value))

    def double(t):
        t.value *= 2.0

    fil = wf.FilterBuilder(odd_filter).with_parallelism(fil_par).build()
    fm = wf.FlatMapBuilder(triple).with_parallelism(fm_par).build()
    mp_ = wf.MapBuilder(double).with_parallelism(map_par).build()
    snk = wf.SinkBuilder(sink).with_parallelism(1).build()

    pipe = g.add_source(src)
    pipe.chain(fil).chain(fm).chain(mp_).chain_sink(snk)
    g.run()
    return sink


def expected_total(stream_len, src_par):
    tot = 0.0
    for _ in range(src_par):
        for i in range(stream_len):
            v = float(i % 17)
            if int(v) % 2 == 0:
                tot += 3 * (2 * v)
    return tot


@pytest.mark.parametrize("mode", [Mode.DEFAULT, Mode.DETERMINISTIC])
def test_oracle_across_parallelisms(mode):
    rnd = random.Random(42)
    stream_len = 300
    results = set()
    for _ in range(4):
        pars = [rnd.randint(1, 4) for _ in range(4)]
        sink = run_pipeline(mode, *pars, stream_len=stream_len)
        assert sink.total == expected_total(stream_len, pars[0])
        results.add(sink.total / pars[0])
    assert len(results) == 1  # normalized aggregate identical across runs


def test_sink_receives_end_marker():
    sink = run_pipeline(Mode.DEFAULT, 1, 1, 1, 1, stream_len=10)
    assert sink.ended == 1


def test_accumulator_rolling_sum():
    sink = CountingSink()
    seen = []
    lock = threading.Lock()

    def acc_fn(t, acc):
        acc.value += t.value

    def snk(rec):
        if rec is not None:
            with lock:
                seen.append((rec.key, rec.value))

    g = wf.PipeGraph("acc_test", Mode.DEFAULT)
    src = wf.SourceBuilder(make_source_fn(2, 20, {})).build()
    acc = wf.AccumulatorBuilder(acc_fn) \
        .with_initial_value(BasicRecord(value=0.0)).with_parallelism(2).build()
    snk_op = wf.SinkBuilder(snk).build()
    g.add_source(src).add(acc).add_sink(snk_op)
    g.run()
    # one output per input; final per-key values = per-key sums
    assert len(seen) == 20
    finals = {}
    for k, v in seen:
        finals[k] = max(finals.get(k, 0.0), v)
    expect = {0: 0.0, 1: 0.0}
    for i in range(20):
        expect[i % 2] += float(i % 17)
    assert finals == expect


def test_filter_transform_variant():
    """Filter returning None drops; returning a record transforms
    (the optional<result_t> signatures, API:22-25)."""
    out = []
    lock = threading.Lock()

    def keep_big(t):
        if t.value < 8:
            return None
        return BasicRecord(t.key, t.id, t.ts, t.value + 100)

    def snk(rec):
        if rec is not None:
            with lock:
                out.append(rec.value)

    g = wf.PipeGraph("f", Mode.DEFAULT)
    g.add_source(wf.SourceBuilder(make_source_fn(1, 30, {})).build()) \
        .chain(wf.FilterBuilder(keep_big).build()) \
        .chain_sink(wf.SinkBuilder(snk).build())
    g.run()
    assert all(v >= 108 for v in out)
    assert len(out) == sum(1 for i in range(30) if i % 17 >= 8)


def test_unterminated_pipe_rejected():
    g = wf.PipeGraph("bad", Mode.DEFAULT)
    g.add_source(wf.SourceBuilder(make_source_fn(1, 5, {})).build())
    with pytest.raises(RuntimeError, match="sink"):
        g.run()


def test_operator_reuse_rejected():
    g = wf.PipeGraph("reuse", Mode.DEFAULT)
    src = wf.SourceBuilder(make_source_fn(1, 5, {})).build()
    g.add_source(src)
    with pytest.raises(RuntimeError, match="already used"):
        g.add_source(src)


def test_builder_camelcase_surface():
    """Every public builder exposes the reference's camelCase spellings
    for its whole fluent surface, including methods inherited from the
    shared window mixin (builders.hpp method census, SURVEY.md §2.7)."""
    from windflow_tpu.builders import builders, builders_tpu

    checked = 0
    for mod in (builders, builders_tpu):
        for bname in dir(mod):
            cls = getattr(mod, bname)
            if (not bname.endswith("Builder") or bname.startswith("_")
                    or not isinstance(cls, type)):
                continue
            for sn in {n for k in cls.__mro__ for n in vars(k)
                       if n.startswith("with_") or n == "build_ptr"}:
                parts = sn.split("_")
                camel = parts[0] + "".join(
                    p.upper() if p in ("cb", "tb", "tpu") else p.capitalize()
                    for p in parts[1:])
                assert getattr(cls, camel) is getattr(cls, sn), \
                    f"{bname}.{camel} missing or diverged"
                checked += 1
    assert checked > 100, "alias census suspiciously small"
    # spot-check literal reference spellings (builders.hpp) so the
    # census cannot pass on a shared misspelling of the derivation rule
    from windflow_tpu.builders.builders import (KeyFarmBuilder,
                                                SourceBuilder, WinSeqBuilder)
    from windflow_tpu.builders.builders_tpu import WinSeqTPUBuilder
    for cls, names in [
        (SourceBuilder, ["withName", "withParallelism",
                         "withClosingFunction"]),
        (WinSeqBuilder, ["withCBWindows", "withTBWindows"]),
        (KeyFarmBuilder, ["withOptLevel"]),
        (WinSeqTPUBuilder, ["withBatch", "withTPUConfiguration"]),
    ]:
        for n in names:
            assert callable(getattr(cls, n)), f"{cls.__name__}.{n}"


def test_builder_camelcase_window_methods_work():
    """withCBWindows/withTBWindows (mixin-inherited, the round-4 alias
    regression) actually build working operators."""
    import windflow_tpu as wf

    op = wf.KeyFarmBuilder("sum").withCBWindows(64, 32) \
        .withParallelism(2).build()
    assert op.win_len == 64 and op.slide_len == 32
    op2 = wf.WinFarmBuilder("sum").withTBWindows(1000, 500) \
        .withParallelism(2).build()
    assert op2.win_len == 1000 and op2.slide_len == 500
