"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""
import os
from pathlib import Path

import numpy as np
import pytest

import jax

# the sharded lowering (parallel/sharded.py) uses the jax.shard_map
# entry point promoted from jax.experimental in newer releases; on JAX
# builds without it these tests cannot run -- skip cleanly instead of
# failing (same module-level guard as tests/test_mesh_farm.py)
if not hasattr(jax, "shard_map"):
    pytest.skip("this JAX build has no jax.shard_map "
                f"(jax {jax.__version__})", allow_module_level=True)

from windflow_tpu.parallel.mesh import make_mesh, key_sharding
from windflow_tpu.parallel.sharded import ShardedWindowEngine


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8, win_axis=2)


def test_mesh_shape(mesh):
    assert mesh.shape["key"] == 4
    assert mesh.shape["win"] == 2


def test_kf_path_key_sharded_sums(mesh):
    eng = ShardedWindowEngine(mesh, win_len=16, slide_len=8)
    args = eng.example_inputs()
    kf, _, _ = eng.step(*args)
    v, s, e = (np.asarray(args[0]), np.asarray(args[1]),
               np.asarray(args[2]))
    expect = np.stack([[v[k, s[k, i]:e[k, i]].sum()
                        for i in range(s.shape[1])]
                       for k in range(v.shape[0])])
    np.testing.assert_allclose(np.asarray(kf), expect, rtol=1e-5)


def test_wmr_path_psum_over_win_axis(mesh):
    eng = ShardedWindowEngine(mesh, win_len=16, slide_len=8)
    args = eng.example_inputs()
    _, wmr, _ = eng.step(*args)
    stripe = np.asarray(args[3])
    # psum over 'win' = total over stripes and stripe elements
    np.testing.assert_allclose(np.asarray(wmr)[:, 0, :],
                               stripe.sum(axis=(1, 3)), rtol=1e-5)


def test_pf_path_pane_combine(mesh):
    eng = ShardedWindowEngine(mesh, win_len=8, slide_len=4)
    args = eng.example_inputs(pane_len=4, panes_per_shard=4)
    _, _, pf = eng.step(*args)
    pane = np.asarray(args[4])  # [K, W, P_loc, pane_len]
    partials = pane.sum(axis=-1).reshape(pane.shape[0], -1)  # [K, P_tot]
    wpp, spp = 8 // 4, 4 // 4
    n_win = (partials.shape[1] - wpp) // spp + 1
    expect = np.stack([[partials[k, w * spp: w * spp + wpp].sum()
                        for w in range(n_win)]
                       for k in range(partials.shape[0])])
    np.testing.assert_allclose(np.asarray(pf), expect, rtol=1e-5)


def test_key_sharding_layout(mesh):
    import jax
    sh = key_sharding(mesh, rank=2)
    x = jax.device_put(np.zeros((8, 4)), sh)
    assert len(x.sharding.device_set) == 8  # sharded over key, replicated over win


@pytest.mark.parametrize("win_axis,win,slide,pane", [
    (2, 16, 8, 4),    # 1 hop (wpp=4 > p_loc? depends) small ring
    (4, 32, 8, 4),    # multi-chip ring, windows span chunks
    (8, 64, 16, 4),   # full 8-ring
    (4, 96, 8, 4),    # wpp > p_loc: multi-hop ring
])
def test_pf_ring_matches_numpy(win_axis, win, slide, pane):
    """Ring ppermute pane combine == replicated numpy sliding sums."""
    mesh = make_mesh(8, win_axis=win_axis)
    eng = ShardedWindowEngine(mesh, win_len=win, slide_len=slide)
    K = mesh.shape["key"] * 2       # 2 keys per shard
    p_loc = 8                       # panes per win-shard
    p_total = p_loc * win_axis
    rng = np.random.default_rng(3)
    pane_vals = rng.normal(size=(K, p_total, pane)).astype(np.float32)
    out = np.asarray(eng.compute_pf_ring(pane_vals, pane))
    # oracle: sliding window sums over the pane partial timeline
    partials = pane_vals.sum(-1)    # [K, p_total]
    wpp, spp = win // pane, slide // pane
    for k in range(K):
        for w in range(out.shape[1]):
            g = w * spp
            want = partials[k, g:g + wpp].sum() if g + wpp <= p_total else 0.0
            np.testing.assert_allclose(out[k, w], want, rtol=1e-4,
                                       err_msg=f"k={k} w={w}")


def test_make_multihost_mesh_single_process_fallback():
    from windflow_tpu.parallel.mesh import make_multihost_mesh
    mesh = make_multihost_mesh(win_axis=2)
    assert mesh.shape["win"] == 2 and mesh.shape["key"] >= 1


_MULTIHOST_WORKER = r"""
import os, sys
pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from windflow_tpu.parallel.mesh import make_multihost_mesh

mesh = make_multihost_mesh(win_axis=2)
assert jax.process_count() == 2 and jax.device_count() == 8
assert mesh.shape == {"key": 4, "win": 2}, dict(mesh.shape)
# every 'win' pair must sit inside one process (collective locality)
for row in mesh.devices:
    assert len({d.process_index for d in row}) == 1, mesh.devices

# the WMR REDUCE shape over the 2-process mesh: per-key-row sums with a
# psum over 'win' riding the cross-process transport, vs numpy
def f(x):
    return jax.lax.psum(jnp.sum(x, axis=-1), "win")

g = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=(P("key", "win"),),
                          out_specs=P("key"), check_vma=False))
rows = 8
x = np.arange(rows * 16, dtype=np.float32).reshape(rows, 16)
gx = jax.make_array_from_callback(
    (rows, 16), NamedSharding(mesh, P("key", "win")), lambda idx: x[idx])
from jax.experimental import multihost_utils
got = np.asarray(multihost_utils.process_allgather(g(gx), tiled=True))
np.testing.assert_allclose(got[:rows], x.sum(-1), rtol=1e-6)
print(f"proc {pid}: ok", flush=True)
"""


def test_multihost_mesh_two_process_dcn_exercise(tmp_path):
    """The distributed communication backend beyond the single-process
    fallback: two REAL processes form the hybrid ('key', 'win') mesh
    over the coordination service and run a cross-process psum (the
    WinMapReduce REDUCE collective) with results checked against numpy
    in each process.  CPU transport stands in for DCN; the mesh layout
    rule under test (win rows inside one process) is the same one that
    keeps the collectives on ICI on real slices."""
    import socket
    import subprocess
    import sys

    def fresh_port():
        with socket.socket() as s_:
            s_.bind(("127.0.0.1", 0))
            return s_.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_MULTIHOST_WORKER)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    root = str(Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    import time as _time

    def run_workers(port):
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            env=env, cwd=root, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in (0, 1)]
        deadline = _time.monotonic() + 150
        outs = ["", ""]
        timed_out = False
        for i, p in enumerate(procs):
            try:
                outs[i], _ = p.communicate(
                    timeout=max(1, deadline - _time.monotonic()))
            except subprocess.TimeoutExpired:
                timed_out = True
                p.kill()
                outs[i], _ = p.communicate()
        return procs, outs, timed_out

    # an ephemeral port picked here can be stolen before the
    # coordinator binds it; one retry on a fresh port covers that
    # (rare) race without masking real failures
    for attempt in range(2):
        procs, outs, timed_out = run_workers(fresh_port())
        stolen = any("EADDRINUSE" in o or "Address already in use" in o
                     for o in outs)
        if not (timed_out or stolen) or attempt == 1:
            break
    if timed_out:
        pytest.fail("multihost workers timed out:\n"
                    + "\n".join(o[-2000:] for o in outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (i, out[-2000:])
        assert f"proc {i}: ok" in out, (i, out[-2000:])
