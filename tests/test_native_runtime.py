"""Native C++ runtime tests: channel semantics, columnar kernels, and
full-graph runs over native channels."""
import threading

import numpy as np
import pytest

from windflow_tpu.runtime.native import (NativeChannel, native_available,
                                         pane_reduce)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="g++ toolchain unavailable")


class TestNativeChannel:
    def test_fifo_and_eos(self):
        ch = NativeChannel(16)
        p0 = ch.register_producer()
        p1 = ch.register_producer()
        ch.put(p0, "a")
        ch.put(p1, "b")
        ch.close(p0)
        ch.put(p1, "c")
        ch.close(p1)
        got = [ch.get() for _ in range(3)]
        assert [g[1] for g in got] == ["a", "b", "c"]
        assert got[0][0] == p0 and got[1][0] == p1
        assert ch.get() is None  # all producers closed

    def test_objects_survive_gc(self):
        import gc
        ch = NativeChannel(8)
        p = ch.register_producer()
        obj = {"payload": list(range(100))}
        ch.put(p, obj)
        del obj
        gc.collect()
        _, back = ch.get()
        assert back["payload"][-1] == 99

    def test_blocking_backpressure(self):
        ch = NativeChannel(2)
        p = ch.register_producer()
        ch.put(p, 1)
        ch.put(p, 2)
        done = threading.Event()

        def producer():
            ch.put(p, 3)  # blocks until a slot frees
            done.set()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        assert not done.wait(0.1)
        assert ch.get()[1] == 1
        assert done.wait(1.0)

    def test_cross_thread_stream(self):
        ch = NativeChannel(64)
        p = ch.register_producer()
        n = 5000
        out = []

        def consumer():
            while True:
                got = ch.get()
                if got is None:
                    return
                out.append(got[1])

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        for i in range(n):
            ch.put(p, i)
        ch.close(p)
        t.join(timeout=10)
        assert out == list(range(n))


class TestNativeKernels:
    def test_pane_sum_matches_numpy(self):
        rng = np.random.default_rng(0)
        vals = rng.normal(size=1000)
        pos = np.sort(rng.integers(0, 1000, 33))
        pos[0], pos[-1] = 0, 1000
        out = pane_reduce(vals, pos, "sum")
        cs = np.concatenate([[0], np.cumsum(vals)])
        np.testing.assert_allclose(out, cs[pos[1:]] - cs[pos[:-1]],
                                   rtol=1e-12)

    def test_pane_max_min_empty_panes(self):
        vals = np.array([3.0, 1.0, 4.0, 1.0, 5.0])
        pos = np.array([0, 2, 2, 5])  # middle pane empty
        out_max = pane_reduce(vals, pos, "max")
        assert out_max[0] == 3.0
        assert out_max[1] == -np.inf
        assert out_max[2] == 5.0
        out_min = pane_reduce(vals, pos, "min")
        assert out_min[1] == np.inf


def test_full_graph_over_native_channels():
    import windflow_tpu as wf
    from windflow_tpu.core import BasicRecord, Mode, RuntimeConfig
    from windflow_tpu.runtime.queues import make_channel

    cfg = RuntimeConfig(use_native_runtime=True)
    assert type(make_channel(cfg)).__name__ == "NativeChannel"
    state = {}
    total = {"v": 0.0}
    lock = threading.Lock()

    def src(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= 200:
            return False
        shipper.push(BasicRecord(i % 3, i // 3, i, float(i)))
        state["i"] = i + 1
        return True

    def snk(rec):
        if rec is not None:
            with lock:
                total["v"] += rec.value

    g = wf.PipeGraph("native", Mode.DEFAULT, cfg)
    g.add_source(wf.SourceBuilder(src).build()) \
        .add(wf.MapBuilder(lambda t: None).with_parallelism(2).build()) \
        .add_sink(wf.SinkBuilder(snk).build())
    g.run()
    assert total["v"] == sum(range(200))


def test_engine_int64_min_key():
    """INT64_MIN is a valid tuple key: it must not collide with the
    hash table's empty-slot sentinel (window_engine.cpp dense_of)."""
    import numpy as np
    from windflow_tpu.runtime.native import NativeWindowEngine

    eng = NativeWindowEngine(8, 4, False, 0)
    kmin = np.iinfo(np.int64).min
    keys = np.array([kmin, 5] * 40, np.int64)
    ids = np.arange(80, dtype=np.int64) // 2
    eng.ingest(keys, ids, ids, np.ones(80))
    eng.eos()
    got = {}
    while True:
        out = eng.flush(1000)
        if out is None:
            break
        vals, starts, ends, d_keys, gwids, _rts = out
        for i in range(len(d_keys)):
            got.setdefault(int(d_keys[i]), []).append(
                vals[starts[i]:ends[i]].sum())
    assert set(got) == {kmin, 5}
    assert got[kmin][0] == 8.0 and got[5][0] == 8.0


def test_engine_partial_flush_keeps_queued_window_data():
    """A flush smaller than the ready count must not evict tuples still
    needed by fired-but-unstaged windows (window_engine.cpp eviction)."""
    import numpy as np
    from windflow_tpu.runtime.native import NativeWindowEngine

    eng = NativeWindowEngine(4, 2, True, 0)
    n = 100
    eng.ingest(np.zeros(n, np.int64), np.arange(n), np.arange(n),
               np.ones(n))
    assert eng.ready() == 48
    seen = 0
    while True:
        out = eng.flush(10)
        if out is None:
            break
        vals, starts, ends, _keys, gwids, _rts = out
        for i in range(len(gwids)):
            assert vals[starts[i]:ends[i]].sum() == 4.0, int(gwids[i])
            seen += 1
    assert seen == 48


def test_engine_gapped_window_stages_empty_extent():
    """A fired window whose extent contains no tuples (gapped id space)
    must stage start==end so the device combine emits the masked
    neutral 0 -- matching the Python/XLA path -- instead of the
    +-inf pane fill (window_engine.cpp flush staging)."""
    from windflow_tpu.runtime.native import NativeWindowEngine

    for kind in ("max", "min", "sum"):
        eng = NativeWindowEngine(4, 4, False, 0, kind=kind)
        # key 0: ids 0..3 (window 0 full), then a gap to ids 12..15
        # (window 3 full); windows 1 and 2 have no tuples in extent
        ids = np.array([0, 1, 2, 3, 12, 13, 14, 15], np.int64)
        eng.ingest(np.zeros(8, np.int64), ids, ids,
                   np.full(8, 7.0))
        eng.eos()
        got = {}
        while True:
            out = eng.flush(1000)
            if out is None:
                break
            vals, starts, ends, d_keys, gwids, _rts = out
            for i in range(len(gwids)):
                w = int(gwids[i])
                seg = vals[starts[i]:ends[i]]
                if len(seg) == 0:
                    got[w] = 0.0  # empty extent -> masked neutral
                elif kind == "max":
                    got[w] = seg.max()
                elif kind == "min":
                    got[w] = seg.min()
                else:
                    got[w] = seg.sum()
        assert got[1] == 0.0 and got[2] == 0.0, (kind, got)
        assert np.isfinite(list(got.values())).all(), (kind, got)
        full = 7.0 if kind in ("max", "min") else 28.0
        assert got[0] == full and got[3] == full, (kind, got)


def test_engine_renumber_hopping_gap_after_eviction():
    """Renumber lane + hopping windows (win < slide): after a flush
    evicts up to next_fire*slide, subsequent arrivals land BELOW the
    pane ring base (they belong to no window) and must be skipped, not
    folded at a negative ring index (window_engine.cpp ingest)."""
    from windflow_tpu.runtime.native import NativeWindowEngine

    eng = NativeWindowEngine(2, 10, False, 0, renumber=True)
    ids = np.arange(4, dtype=np.int64)
    eng.ingest(np.zeros(4, np.int64), ids, ids, np.ones(4))
    assert eng.ready() == 1  # window 0 = arrivals [0, 2)
    out = eng.flush(10)      # evicts panes below next_fire*slide = 10
    assert out is not None and len(out[4]) == 1
    # arrivals 4..9 sit in the gap below the evicted frontier; 10..11
    # fill window 1 exactly
    n = 8
    ids2 = np.arange(4, 4 + n, dtype=np.int64)
    eng.ingest(np.zeros(n, np.int64), ids2, ids2, np.full(n, 3.0))
    eng.eos()
    out = eng.flush(10)
    vals, starts, ends, _keys, gwids, _rts = out[:6]
    assert list(gwids) == [1]
    assert vals[starts[0]:ends[0]].sum() == 6.0  # arrivals 10, 11 only


@pytest.mark.parametrize("win,slide,kind,start,delay,vscale,voff", [
    (32, 16, "sum", 0, 0, 1.0, 0.0),    # sliding
    (16, 16, "max", 0, 0, 1.0, 0.0),    # tumbling
    (8, 24, "sum", 0, 0, 1.0, 0.0),     # hopping (gap ids dropped)
    (1, 1, "sum", 0, 0, 1.0, 0.0),      # degenerate single-id windows
    (32, 16, "sum", 30_000, 0, 1.0, 0.0),   # mid-stream start: anchor
    (32, 16, "sum", 0, 40, 1.0, 0.0),       # TB triggering delay
    (16, 8, "min", 0, 0, -2.5, 7.0),        # value law scale/offset
])
def test_engine_synth_ingest_matches_array_ingest(win, slide, kind,
                                                  start, delay, vscale,
                                                  voff):
    """The fused generate+fold lane must stage bit-identical windows to
    ingesting the same synthetic law as materialized arrays, across
    chunk splits, geometries, kinds, anchored mid-stream starts,
    triggering delay, and the value law's scale/offset."""
    from windflow_tpu.runtime.native import NativeWindowEngine

    N, K, VMOD = 40_000, 7, 97

    def drain(eng, out):
        while True:
            r = eng.flush(1 << 20)
            if r is None:
                return
            vals, starts, ends, keys, gwids, rts = r[:6]
            agg_of = {"sum": np.sum, "max": np.max, "min": np.min}[kind]
            for b in range(len(starts)):
                seg = vals[starts[b]:ends[b]]
                out[(keys[b], gwids[b])] = (agg_of(seg) if len(seg)
                                            else 0.0)

    # reference: array ingest of the same law over events
    # [start, start + N)
    idx = start + np.arange(N, dtype=np.int64)
    keys = idx % K
    ids = idx // K
    vals = (idx % VMOD).astype(np.float64) * vscale + voff
    ref_eng = NativeWindowEngine(win, slide, True, delay, False, kind)
    ref = {}
    for lo in range(0, N, 7_000):
        hi = min(lo + 7_000, N)
        ref_eng.ingest(keys[lo:hi], ids[lo:hi], ids[lo:hi], vals[lo:hi])
        drain(ref_eng, ref)
    ref_eng.eos()
    drain(ref_eng, ref)

    # fused lane: uneven chunk boundaries exercise the per-key ranges
    eng = NativeWindowEngine(win, slide, True, delay, False, kind)
    got = {}
    for lo in range(start, start + N, 9_999):
        eng.synth_ingest(lo, min(9_999, start + N - lo), K, VMOD,
                         vscale, voff)
        drain(eng, got)
    eng.eos()
    drain(eng, got)
    assert got.keys() == ref.keys() and len(got) > 50
    for k in got:
        assert got[k] == ref[k], (k, got[k], ref[k])
    assert eng.ignored() == ref_eng.ignored()


def test_engine_deserialize_rejects_huge_length_field():
    """A corrupted checkpoint blob with an enormous vector-length field
    must fail cleanly, not overflow the bounds check into a multi-GB
    resize (window_engine.cpp get_vec)."""
    from windflow_tpu.runtime.native import NativeWindowEngine

    e1 = NativeWindowEngine(32, 16, True)
    e1.ingest(np.zeros(10, np.int64), np.arange(10, dtype=np.int64),
              np.arange(10, dtype=np.int64), np.ones(10))
    blob = bytearray(e1.serialize())
    import struct
    # parse the WFN3 snapshot framing (window_engine.cpp serialize()):
    # the 8-i64 header (magic,win,slide,delay,tb,rn,kind,nkeys) and the
    # first key's 7 fixed i64s (key,next_fire,anchor,opened_max,max_id,
    # pane_base,arrivals), then walk the four per-key vectors
    # (pacc,pcnt,plid,plts) by their length headers and corrupt the
    # first non-empty one
    off = 8 * 8 + 7 * 8
    corrupted = False
    for _ in range(4):
        n = struct.unpack_from("<q", blob, off)[0]
        assert 0 <= n <= 32  # framing sanity: a plausible ring length
        if n > 0:
            struct.pack_into("<q", blob, off, 1 << 61)
            corrupted = True
            break
        off += 8 + n * 8
    assert corrupted  # 10 ingested values: the pane ring is non-empty
    e2 = NativeWindowEngine(32, 16, True)
    with pytest.raises(ValueError):
        e2.deserialize(bytes(blob))


def test_engine_deserialize_corruption_fuzz():
    """Random bit flips and truncations of a checkpoint blob must
    always either load or raise a Python exception -- never crash the
    process (the C++ get_vec bounds checks are the only thing between a
    corrupted length field and a wild resize/read)."""
    import random

    from windflow_tpu.runtime.native import NativeWindowEngine
    eng = NativeWindowEngine(64, 32, False, 0)
    ids = np.arange(5000, dtype=np.int64)
    eng.ingest(ids % 8, ids // 8, ids // 8, np.ones(5000))
    blob = eng.serialize()
    # control: the pristine blob must load, or the fuzz is vacuous
    NativeWindowEngine(64, 32, False, 0).deserialize(blob)
    rnd = random.Random(0)
    for _trial in range(200):
        b = bytearray(blob)
        for _ in range(rnd.randint(1, 8)):
            b[rnd.randrange(len(b))] ^= 1 << rnd.randrange(8)
        e2 = NativeWindowEngine(64, 32, False, 0)
        try:
            e2.deserialize(bytes(b))
        except Exception:
            pass  # clean rejection is a pass; only a crash fails
    for cut in range(0, len(blob), max(1, len(blob) // 40)):
        e2 = NativeWindowEngine(64, 32, False, 0)
        try:
            e2.deserialize(bytes(blob[:cut]))
        except Exception:
            pass
