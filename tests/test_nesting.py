"""Complex-nesting tests: WF(PF), WF(WMR), KF(PF), KF(WMR).

Mirrors tests/mp_tests_cpu test_mp_{wf+pf, wf+wmr, kf+pf, kf+wmr}_*
(SURVEY.md §4): nested composite operators against the sequential
oracle, across window types and replica counts.
"""
import threading

import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, Mode, WinType


def ordered_source(n_keys, per_key):
    state = {}

    def fn(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= n_keys * per_key:
            return False
        key = i % n_keys
        tid = i // n_keys
        shipper.push(BasicRecord(key, tid, tid, float(tid)))
        state["i"] = i + 1
        return True

    return fn


class Collector:
    def __init__(self):
        self.lock = threading.Lock()
        self.results = []

    def __call__(self, rec):
        if rec is not None:
            with self.lock:
                self.results.append((rec.key, rec.id, rec.value))

    def by_key(self):
        out = {}
        for k, g, v in self.results:
            out.setdefault(k, {})[g] = v
        return out


def sum_win(gwid, it, result):
    result.value = sum(t.value for t in it)


def oracle(per_key, win, slide):
    out = {}
    g = 0
    while g * slide < per_key:
        out[g] = float(sum(v for v in range(per_key)
                           if g * slide <= v < g * slide + win))
        g += 1
    return out


def run_graph(op, n_keys=3, per_key=48, mode=Mode.DEFAULT):
    coll = Collector()
    g = wf.PipeGraph("t", mode)
    g.add_source(wf.SourceBuilder(ordered_source(n_keys, per_key)).build()) \
        .add(op).add_sink(wf.SinkBuilder(coll).build())
    g.run()
    return coll


WIN, SLIDE = 16, 4


def make_pf(pars=(2, 1), win_type=WinType.TB):
    b = wf.PaneFarmBuilder(sum_win, sum_win).with_parallelism(*pars)
    b = (b.with_cb_windows(WIN, SLIDE) if win_type == WinType.CB
         else b.with_tb_windows(WIN, SLIDE))
    return b.build()


def make_wmr(pars=(2, 1), win_type=WinType.TB):
    b = wf.WinMapReduceBuilder(sum_win, sum_win).with_parallelism(*pars)
    b = (b.with_cb_windows(WIN, SLIDE) if win_type == WinType.CB
         else b.with_tb_windows(WIN, SLIDE))
    return b.build()


@pytest.mark.parametrize("replicas", [1, 2, 3])
def test_wf_pf_tb(replicas):
    op = wf.WinFarmBuilder(make_pf()).with_parallelism(replicas).build()
    coll = run_graph(op)
    expect = oracle(48, WIN, SLIDE)
    assert coll.by_key() == {k: expect for k in range(3)}


@pytest.mark.parametrize("replicas", [2, 3])
def test_wf_wmr_tb(replicas):
    op = wf.WinFarmBuilder(make_wmr()).with_parallelism(replicas).build()
    coll = run_graph(op)
    expect = oracle(48, WIN, SLIDE)
    assert coll.by_key() == {k: expect for k in range(3)}


@pytest.mark.parametrize("replicas", [1, 2, 3])
@pytest.mark.parametrize("win_type", [WinType.CB, WinType.TB])
def test_kf_pf(replicas, win_type):
    op = wf.KeyFarmBuilder(make_pf(win_type=win_type)) \
        .with_parallelism(replicas).build()
    coll = run_graph(op, n_keys=5)
    expect = oracle(48, WIN, SLIDE)
    assert coll.by_key() == {k: expect for k in range(5)}


@pytest.mark.parametrize("replicas", [2, 3])
@pytest.mark.parametrize("win_type", [WinType.CB, WinType.TB])
def test_kf_wmr(replicas, win_type):
    op = wf.KeyFarmBuilder(make_wmr(pars=(3, 1), win_type=win_type)) \
        .with_parallelism(replicas).build()
    coll = run_graph(op, n_keys=5)
    expect = oracle(48, WIN, SLIDE)
    assert coll.by_key() == {k: expect for k in range(5)}


def test_wf_pf_cb_default_rejected():
    op = wf.WinFarmBuilder(make_pf(win_type=WinType.CB)) \
        .with_parallelism(2).build()
    g = wf.PipeGraph("t", Mode.DEFAULT)
    pipe = g.add_source(wf.SourceBuilder(ordered_source(1, 8)).build())
    with pytest.raises(RuntimeError, match="DEFAULT"):
        pipe.add(op)


def test_wf_pf_cb_deterministic():
    op = wf.WinFarmBuilder(make_pf(win_type=WinType.CB)) \
        .with_parallelism(2).build()
    coll = run_graph(op, mode=Mode.DETERMINISTIC)
    expect = oracle(48, WIN, SLIDE)
    assert coll.by_key() == {k: expect for k in range(3)}


def test_inner_reuse_rejected():
    pf = make_pf()
    wf.WinFarmBuilder(pf).with_parallelism(2).build()
    with pytest.raises(RuntimeError, match="nested"):
        wf.WinFarmBuilder(pf).with_parallelism(2).build()


def test_tpu_nesting_builds_device_replicas():
    """WF_TPU(PF_TPU) / KF_TPU(WMR_TPU) builder dispatch produces the
    nested structure with DEVICE engine replicas (win_farm_gpu.hpp:
    73-76, key_farm_gpu.hpp:254) -- not a silent CPU fallback."""
    from windflow_tpu.operators.nesting import NestedKeyFarm, NestedWinFarm
    from windflow_tpu.operators.tpu.win_seq_tpu import WinSeqTPULogic

    def host(gwid, it, res):
        res.value = sum(t.value for t in it)

    pf = wf.PaneFarmTPUBuilder("sum", host).with_parallelism(2, 1) \
        .with_tb_windows(WIN, SLIDE).build()
    op = wf.WinFarmTPUBuilder(pf).with_parallelism(2).build()
    assert isinstance(op, NestedWinFarm)
    stages = op.stages()
    # stage 0 = PLQ of both copies: 2 copies x plq_par 2 device logics
    assert len(stages[0].replicas) == 4
    assert all(isinstance(r, WinSeqTPULogic) for r in stages[0].replicas)
    # copies are group-wired so copy i's WLQ consumes only copy i's PLQ
    assert stages[0].groups == [0, 0, 1, 1]

    wmr = wf.WinMapReduceTPUBuilder("sum", host).with_parallelism(2, 1) \
        .with_tb_windows(WIN, SLIDE).build()
    op2 = wf.KeyFarmTPUBuilder(wmr).with_parallelism(3).build()
    assert isinstance(op2, NestedKeyFarm)
    stages2 = op2.stages()
    assert len(stages2[0].replicas) == 6  # 3 copies x map_par 2
    assert all(isinstance(r, WinSeqTPULogic) for r in stages2[0].replicas)


def test_wf_pf_degenerate_private_slide_rejected():
    """WF(PF) where the copies' private slide (slide * replicas) would
    reach the window length must fail loudly at construction, exactly
    like the reference (pane_farm.hpp:170-173 via win_farm.hpp:326):
    the pane decomposition silently miscomputes in that regime."""
    with pytest.raises(ValueError, match="private slide"):
        wf.WinFarmBuilder(make_pf()).with_parallelism(WIN // SLIDE).build()
    pf_tpu = wf.PaneFarmTPUBuilder("sum", sum_win).with_parallelism(2, 1) \
        .with_tb_windows(WIN, SLIDE).build()
    with pytest.raises(ValueError, match="private slide"):
        wf.WinFarmTPUBuilder(pf_tpu).with_parallelism(WIN // SLIDE).build()


def test_pane_farm_tumbling_rejected():
    """Standalone Pane_Farm with slide >= win is rejected
    (pane_farm.hpp:170-173 'sliding windows only'), host and device."""
    with pytest.raises(ValueError, match="sliding"):
        wf.PaneFarmBuilder(sum_win, sum_win).with_parallelism(2, 1) \
            .with_tb_windows(8, 8).build()
    with pytest.raises(ValueError, match="sliding"):
        wf.PaneFarmTPUBuilder("sum", sum_win).with_parallelism(1, 1) \
            .with_tb_windows(8, 8).build()
