"""Unit tests for StreamArchive, FlatFAT, Iterable, LocalStorage, meta."""
import random

import pytest

from windflow_tpu.core import (BasicRecord, FlatFAT, Iterable, LocalStorage,
                               RuntimeContext, StreamArchive)
from windflow_tpu.core.meta import arity, default_hash, is_rich, with_context


def rec(tid, ts=None, val=0.0):
    return BasicRecord(0, tid, ts if ts is not None else tid, val)


class TestStreamArchive:
    def test_ordered_insert(self):
        a = StreamArchive(sort_key=lambda t: t.ts)
        for ts in [5, 1, 3, 2, 4]:
            a.insert(rec(ts, ts))
        assert [t.ts for t in a.items()] == [1, 2, 3, 4, 5]

    def test_win_range_and_purge(self):
        a = StreamArchive(sort_key=lambda t: t.ts)
        for ts in range(10):
            a.insert(rec(ts, ts))
        lo, hi = a.win_range(rec(0, 3), rec(0, 7))
        assert [t.ts for t in a.slice(lo, hi)] == [3, 4, 5, 6]
        assert a.distance(rec(0, 3), rec(0, 7)) == 4
        purged = a.purge(rec(0, 4))
        assert purged == 4
        assert [t.ts for t in a.items()] == [4, 5, 6, 7, 8, 9]

    def test_open_ended_range(self):
        a = StreamArchive(sort_key=lambda t: t.ts)
        for ts in range(5):
            a.insert(rec(ts, ts))
        lo, hi = a.win_range(rec(0, 2), None)
        assert hi == len(a) and [t.ts for t in a.slice(lo, hi)] == [2, 3, 4]

    def test_duplicate_keys_keep_arrival_order(self):
        a = StreamArchive(sort_key=lambda t: t.ts)
        r1, r2 = rec(1, 5, 1.0), rec(2, 5, 2.0)
        a.insert(r1)
        a.insert(r2)
        assert a.items() == [r1, r2]


class TestFlatFAT:
    def test_sum_window(self):
        f = FlatFAT(combine=lambda a, b: a + b, empty=lambda: 0, n_leaves=8)
        f.insert_bulk([1, 2, 3, 4, 5])
        assert f.get_result() == 15
        f.remove(2)  # evict 1, 2
        assert f.get_result() == 12
        f.insert_bulk([10, 20])
        assert f.get_result() == 42

    def test_wraparound(self):
        f = FlatFAT(combine=lambda a, b: a + b, empty=lambda: 0, n_leaves=4)
        f.insert_bulk([1, 2, 3, 4])
        f.remove(3)
        f.insert_bulk([5, 6, 7])  # ring wraps
        assert f.get_result() == 4 + 5 + 6 + 7

    def test_non_commutative_order_preserved(self):
        # combine = string concat: order must be oldest->newest even wrapped
        f = FlatFAT(combine=lambda a, b: a + b, empty=lambda: "", n_leaves=4)
        f.insert_bulk(["a", "b", "c", "d"])
        assert f.get_result() == "abcd"
        f.remove(2)
        f.insert_bulk(["e", "f"])
        assert f.get_result() == "cdef"
        f.remove(3)
        assert f.get_result() == "f"

    def test_matches_naive_sliding_window(self):
        rnd = random.Random(7)
        f = FlatFAT(combine=lambda a, b: a + b, empty=lambda: 0, n_leaves=64)
        window = []
        for step in range(500):
            v = rnd.randint(-100, 100)
            f.insert(v)
            window.append(v)
            if len(window) > 50:
                f.remove(1)
                window.pop(0)
            assert f.get_result() == sum(window)

    def test_capacity_guard(self):
        f = FlatFAT(combine=lambda a, b: a + b, empty=lambda: 0, n_leaves=2)
        f.insert_bulk([1, 2])
        with pytest.raises(OverflowError):
            f.insert(3)


class TestIterable:
    def test_view(self):
        items = [rec(i) for i in range(10)]
        it = Iterable(items, 2, 6)
        assert len(it) == 4
        assert it[0].id == 2 and it.at(3).id == 5
        assert [t.id for t in it] == [2, 3, 4, 5]
        with pytest.raises(IndexError):
            it[4]


class TestContextMeta:
    def test_local_storage_default_construct(self):
        s = LocalStorage()
        v = s.get("acc", factory=lambda: [])
        v.append(1)
        assert s.get("acc") == [1]
        s.remove("acc")
        assert not s.is_contained("acc")

    def test_arity_and_rich(self):
        assert arity(lambda t: t) == 1
        assert arity(lambda t, c: t) == 2
        assert not is_rich(lambda t: t, 1)
        assert is_rich(lambda t, ctx: t, 1)
        with pytest.raises(TypeError):
            is_rich(lambda a, b, c: None, 1)

    def test_with_context_binds(self):
        ctx = RuntimeContext(4, 2)
        fn = with_context(lambda t, c: (t, c.get_replica_index()), 1, ctx)
        assert fn(5) == (5, 2)

    def test_default_hash_stable(self):
        assert default_hash(42) == 42
        assert default_hash("abc") == default_hash("abc")
        assert default_hash("abc") != default_hash("abd")


def test_tuple_batch_take_edge_cases():
    """take() fast path keeps numpy-indexing semantics: row selection on
    n-dim payload columns, loud wrong-length masks, empty index lists."""
    import numpy as np
    import pytest
    from windflow_tpu.core.tuples import TupleBatch

    n = 4
    tb = TupleBatch({
        "key": np.arange(n), "id": np.arange(n), "ts": np.arange(n),
        "value": np.arange(n, dtype=np.float64),
        "emb": np.arange(n * 3, dtype=np.float64).reshape(n, 3),
    })
    # boolean mask selects ROWS of 2-D payloads
    out = tb.take(np.array([True, False, True, False]))
    np.testing.assert_array_equal(out["emb"], tb["emb"][[0, 2]])
    # integer indices too
    out2 = tb.take(np.array([3, 1]))
    np.testing.assert_array_equal(out2["emb"], tb["emb"][[3, 1]])
    np.testing.assert_array_equal(out2.key, [3, 1])
    # wrong-length mask fails loudly, as plain numpy indexing does
    with pytest.raises(IndexError, match="mask length"):
        tb.take(np.array([True, False]))
    # empty Python list -> empty batch
    assert len(tb.take([])) == 0
    # slice stays a view (zero-copy lane)
    sl = tb.take(slice(1, 3))
    assert sl["value"].base is tb["value"]
