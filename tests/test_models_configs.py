"""The five BASELINE configs + the Yahoo flagship, end to end (small)."""
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.core import Mode
from windflow_tpu.models import configs as C


def run_config(fn, **kw):
    g = wf.PipeGraph("cfg", Mode.DEFAULT)
    coll = fn(g, **kw)
    g.run()
    return coll


def test_config1_cpu_multipipe():
    coll = run_config(C.config_cpu_multipipe, n_events=2000, n_keys=4,
                      win=50)
    # doubled values, tumbling windows + flush: total = 2 * sum of values
    per_key = 2000 // 4
    assert coll.total == 2 * 4 * sum(range(per_key))


def test_config2_win_seq_tpu():
    coll = run_config(C.config_win_seq_tpu, n_events=20000, n_keys=8,
                      win=256, slide=128, batch=64)
    assert coll.count > 0


def test_config3_pane_farm_tpu():
    coll = run_config(C.config_pane_farm_tpu, n_events=20000, n_keys=8,
                      win=256, slide=128, batch=64)
    assert coll.count > 0


def test_config4_key_farm_tpu():
    coll = run_config(C.config_key_farm_tpu, n_events=20000, n_keys=16,
                      win=256, slide=128, batch=64, parallelism=2)
    assert coll.count > 0


def test_config5_yahoo():
    coll = run_config(C.config_yahoo, n_events=50000, n_ads=100,
                      n_campaigns=10, win_len=2000, slide_len=2000,
                      batch_size=8192, device_batch=64)
    # windowed view-counts sum to the number of view events (the
    # source re-timestamps one pre-generated pool per batch)
    from windflow_tpu.models.yahoo import VIEW, synth_events
    pool = synth_events(8192, 100, seed=0)
    views = 0
    i = 0
    while i < 50000:
        n = min(8192, 50000 - i)
        views += int((pool["event_type"][:n] == VIEW).sum())
        i += n
    assert coll.total == views


def test_yahoo_step_fn_counts():
    from windflow_tpu.models.yahoo import (VIEW, example_step_args,
                                           make_step)
    fn = make_step(10, 4, 256)
    args = example_step_args(n_events=1024, n_ads=50, n_campaigns=10,
                             n_windows=4, win_len=256)
    out = np.asarray(fn(*args))
    camp, ad, et, ts, _ = args
    assert out.sum() == (et == VIEW).sum()
    # spot-check one cell
    c, w = 3, 1
    mask = (camp[ad] == c) & (et == VIEW) & (ts // 256 == w)
    assert out[c, w] == mask.sum()
