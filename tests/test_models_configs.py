"""The five BASELINE configs + the Yahoo flagship, end to end (small)."""
import threading

import numpy as np

import windflow_tpu as wf
from windflow_tpu.core import Mode
from windflow_tpu.models import configs as C


def run_config(fn, **kw):
    g = wf.PipeGraph("cfg", Mode.DEFAULT)
    coll = fn(g, **kw)
    g.run()
    return coll


def test_config1_cpu_multipipe():
    coll = run_config(C.config_cpu_multipipe, n_events=2000, n_keys=4,
                      win=50)
    # doubled values, tumbling windows + flush: total = 2 * sum of values
    per_key = 2000 // 4
    assert coll.total == 2 * 4 * sum(range(per_key))


def test_config2_win_seq_tpu():
    coll = run_config(C.config_win_seq_tpu, n_events=20000, n_keys=8,
                      win=256, slide=128, batch=64)
    assert coll.count > 0


def test_config3_pane_farm_tpu():
    coll = run_config(C.config_pane_farm_tpu, n_events=20000, n_keys=8,
                      win=256, slide=128, batch=64)
    assert coll.count > 0


def test_config4_key_farm_tpu():
    coll = run_config(C.config_key_farm_tpu, n_events=20000, n_keys=16,
                      win=256, slide=128, batch=64, parallelism=2)
    assert coll.count > 0


def test_config5_yahoo():
    coll = run_config(C.config_yahoo, n_events=50000, n_ads=100,
                      n_campaigns=10, win_len=2000, slide_len=2000,
                      batch_size=8192, device_batch=64)
    # windowed view-counts sum to the number of view events (the
    # source re-timestamps one pre-generated pool per batch)
    from windflow_tpu.models.yahoo import VIEW, synth_events
    pool = synth_events(8192, 100, seed=0)
    views = 0
    i = 0
    while i < 50000:
        n = min(8192, 50000 - i)
        views += int((pool["event_type"][:n] == VIEW).sum())
        i += n
    assert coll.total == views


def test_yahoo_step_fn_counts():
    from windflow_tpu.models.yahoo import (VIEW, example_step_args,
                                           make_step)
    fn = make_step(10, 4, 256)
    args = example_step_args(n_events=1024, n_ads=50, n_campaigns=10,
                             n_windows=4, win_len=256)
    out = np.asarray(fn(*args))
    camp, ad, et, ts, _ = args
    assert out.sum() == (et == VIEW).sum()
    # spot-check one cell
    c, w = 3, 1
    mask = (camp[ad] == c) & (et == VIEW) & (ts // 256 == w)
    assert out[c, w] == mask.sum()


class TestNexmark:
    """NEXMark query set (models/nexmark.py) against numpy oracles."""

    def test_q1_q2_stateless(self):
        from windflow_tpu.core.tuples import TupleBatch
        from windflow_tpu.models.nexmark import (DOL_TO_EUR, q1_currency,
                                                 make_q2_selection,
                                                 synth_bids)

        pool = synth_bids(10_000, n_auctions=50)
        tb = TupleBatch({"key": pool["auction"], "id": pool["ts"],
                         "ts": pool["ts"], "value": pool["price"]})
        out = q1_currency(tb)
        np.testing.assert_allclose(out["value"],
                                   pool["price"] * DOL_TO_EUR)
        q2 = make_q2_selection({3, 7, 11})
        mask = q2(tb)
        assert set(np.unique(tb.key[mask])) <= {3, 7, 11}
        assert mask.sum() == np.isin(pool["auction"], [3, 7, 11]).sum()

    def test_q5_hot_items(self):
        import threading

        from windflow_tpu.core.tuples import TupleBatch
        from windflow_tpu.models.nexmark import synth_bids

        N, NA, WINL, SL = 60_000, 40, 8192, 4096
        got = {}
        lock = threading.Lock()

        def sink(item):
            if item is None:
                return
            with lock:
                if isinstance(item, TupleBatch):
                    for j in range(len(item)):
                        got[(int(item.key[j]), int(item.id[j]))] = \
                            float(item["value"][j])
                else:
                    got[(item.key, item.id)] = item.value

        from windflow_tpu.models.nexmark import build_q5_hot_items
        g = wf.PipeGraph("q5", wf.Mode.DEFAULT)
        build_q5_hot_items(g, N, WINL, SL, sink, n_auctions=NA,
                           batch_size=16_384, device_batch=512)
        g.run()

        # oracle: counts per (auction, window)
        pool = synth_bids(16_384, NA)
        auctions = np.concatenate([
            pool["auction"][:min(16_384, N - i)]
            for i in range(0, N, 16_384)])
        ts = np.arange(N)
        expect = {}
        for k in range(NA):
            kts = ts[auctions == k]
            w = 0
            while w * SL <= kts.max():
                expect[(k, w)] = float(
                    ((kts >= w * SL) & (kts < w * SL + WINL)).sum())
                w += 1
        assert got == expect

    def test_q7_highest_bid(self):
        import threading

        from windflow_tpu.models.nexmark import (DOL_TO_EUR,
                                                 build_q7_highest_bid,
                                                 synth_bids)

        N, WINL = 50_000, 10_000
        got = {}
        lock = threading.Lock()

        def sink(rec):
            if rec is not None:
                with lock:
                    got[rec.id] = rec.value

        g = wf.PipeGraph("q7", wf.Mode.DEFAULT)
        build_q7_highest_bid(g, N, WINL, sink, batch_size=16_384,
                             device_batch=256)
        g.run()
        pool = synth_bids(16_384, 1000)
        prices = np.concatenate([
            pool["price"][:min(16_384, N - i)]
            for i in range(0, N, 16_384)]) * DOL_TO_EUR
        for w in range(N // WINL):
            exp = prices[w * WINL:(w + 1) * WINL].max()
            # device computes in float32
            assert abs(got[w] - exp) <= 1e-5 * abs(exp), (w, got[w], exp)
