"""Multi-tenant serving plane (windflow_tpu/serving/;
docs/SERVING.md): dynamic graph submission/teardown against one shared
runtime, per-tenant credit budgets + admission control under a global
capacity cap, lifecycle-leak census, and the SLO-driven cross-tenant
arbiter -- donor scaled down / credits reassigned to restore a
breaching victim's SLO, every decision an ``arbitration`` flight event
the doctor explains.

Acceptance covered here: a >= 8-graph concurrent soak where one
tenant's injected crash surfaces as a FAILED handle while every other
tenant ends with balanced ledgers; a thread/fd census across repeated
submit/evict cycles (including crash, mid-run stop and active elastic
controller paths); and a scripted noisy-neighbor run where the
arbiter's actions restore the victim tenant's declared SLO
(slo_recovered fires) with victim, donor, action and evidence named
in flight + doctor.
"""
import json
import threading
import time
import urllib.request
import warnings

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.core.basic import RuntimeConfig
from windflow_tpu.core.tuples import TupleBatch
from windflow_tpu.diagnosis import build_report, render_text
from windflow_tpu.elastic import ElasticityConfig
from windflow_tpu.resilience import FaultPlan
from windflow_tpu.serving import (AdmissionError, ArbiterConfig,
                                  Donation, Server, TenantSpec,
                                  TenantState, TenantView,
                                  plan_arbitration, plan_restitution,
                                  process_census)
from windflow_tpu.serving.arbiter import (_spare_credits as _sp,
                                          describe_actions)

WAIT_S = 120


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def record_source(n, pace_s=0.0, endless=False):
    state = {}

    def fn(shipper, ctx):
        i = state.setdefault("i", 0)
        if not endless and i >= n:
            return False
        if pace_s:
            time.sleep(pace_s)
        shipper.push(wf.BasicRecord(i % 4, i // 4, i, float(i)))
        state["i"] = i + 1
        return True

    return fn


def simple_build(n=2000, sink_list=None, pace_s=0.0, endless=False):
    def build(g):
        sink = (lambda r: sink_list.append(r)) if sink_list is not None \
            else (lambda r: None)
        g.add_source(wf.SourceBuilder(
            record_source(n, pace_s, endless)).build()) \
            .add(wf.MapBuilder(lambda t: None).with_name("m").build()) \
            .add_sink(wf.SinkBuilder(sink).build())
    return build


def quiet_cfg(tmp_path, **kw):
    kw.setdefault("log_dir", str(tmp_path))
    kw.setdefault("elasticity", ElasticityConfig(enabled=False))
    return RuntimeConfig(**kw)


def make_trace(n, n_keys=4):
    ar = np.arange(n, dtype=np.int64)
    return TupleBatch({"key": ar % n_keys, "id": ar // n_keys,
                       "ts": ar // n_keys,
                       "value": np.ones(n, np.float64)})


@pytest.fixture
def server():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        srv = Server(capacity=1 << 16, arbiter=False)
        try:
            yield srv
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# spec validation + admission control
# ---------------------------------------------------------------------------

def test_tenant_spec_validation():
    with pytest.raises(ValueError):
        TenantSpec(credits=0)
    with pytest.raises(ValueError):
        TenantSpec(weight=0.0)
    with pytest.raises(ValueError):
        TenantSpec(credits=100, min_credits=200)
    with pytest.raises(ValueError):
        TenantSpec(pool_buffers=0)
    blk = TenantSpec(priority=3, weight=2.0).block()
    assert blk["Priority"] == 3 and blk["Weight"] == 2.0


def test_admission_over_cap_rejected_and_capacity_released(server,
                                                           tmp_path):
    cfg = quiet_cfg(tmp_path)
    h = server.submit("a", simple_build(500),
                      TenantSpec(credits=40_000), config=cfg)
    assert server.granted == 40_000
    with pytest.raises(AdmissionError, match="global cap"):
        server.submit("b", simple_build(500),
                      TenantSpec(credits=40_000), config=cfg)
    # duplicate names rejected while registered
    with pytest.raises(ValueError, match="already submitted"):
        server.submit("a", simple_build(500), config=cfg)
    assert h.wait(WAIT_S) == TenantState.COMPLETED
    # terminal tenants release their reservation back to the cap...
    assert server.granted == 0
    server.evict("a")
    # ...and eviction frees the name
    h2 = server.submit("a", simple_build(300),
                       TenantSpec(credits=40_000), config=cfg)
    assert h2.wait(WAIT_S) == TenantState.COMPLETED


def test_failed_build_releases_reservation(server, tmp_path):
    def bad_build(g):
        raise RuntimeError("boom at build time")

    with pytest.raises(RuntimeError, match="boom"):
        server.submit("bad", bad_build, TenantSpec(credits=1024),
                      config=quiet_cfg(tmp_path))
    assert server.granted == 0
    assert server.get("bad") is None


# ---------------------------------------------------------------------------
# lifecycle: run to completion, stop mid-run, crash isolation
# ---------------------------------------------------------------------------

def test_submit_runs_and_publishes_tenant_block(server, tmp_path):
    got = []
    h = server.submit("alpha", simple_build(2000, got),
                      TenantSpec(credits=1024, priority=2, weight=1.5),
                      config=quiet_cfg(tmp_path))
    assert h.wait(WAIT_S) == TenantState.COMPLETED
    assert len(got) >= 2000
    stats = json.loads(h.graph.stats.to_json(0, 0))
    t = stats["Tenant"]
    assert t["Name"] == "alpha" and t["State"] == "COMPLETED"
    assert t["Priority"] == 2 and t["Credits"] == 1024
    # clean end: the tenant's own ledger closed balanced
    cons = stats["Conservation"]
    assert cons["Edges_balanced"] and not cons["Violations_total"]
    row = server.stats()["Tenants"][0]
    assert row["Name"] == "alpha" and row["State"] == "COMPLETED"


def test_stop_midrun_reclaims_and_reports_stopped(server, tmp_path):
    h = server.submit("endless", simple_build(0, endless=True,
                                              pace_s=0.0005),
                      TenantSpec(credits=1024),
                      config=quiet_cfg(tmp_path))
    time.sleep(0.5)
    assert h.state == TenantState.RUNNING
    assert server.evict("endless").state == TenantState.STOPPED
    assert h.error is None
    assert server.granted == 0
    # pool arena drained at teardown
    pool = h.graph.buffer_pool
    if pool is not None:
        assert pool.stats()["buffers"] == 0


def test_crash_isolated_as_failed_handle(server, tmp_path):
    got = []
    fp = FaultPlan(seed=7).crash_replica("m.0", at_tuple=50)
    h_bad = server.submit("crashy", simple_build(5000),
                          TenantSpec(credits=512),
                          config=quiet_cfg(tmp_path, fault_plan=fp))
    h_ok = server.submit("steady", simple_build(3000, got),
                         TenantSpec(credits=512),
                         config=quiet_cfg(tmp_path))
    assert h_bad.wait(WAIT_S) == TenantState.FAILED
    assert isinstance(h_bad.error, wf.NodeFailureError)
    # the neighbour never noticed
    assert h_ok.wait(WAIT_S) == TenantState.COMPLETED
    assert len(got) >= 3000
    stats = json.loads(h_ok.graph.stats.to_json(0, 0))
    assert stats["Conservation"]["Edges_balanced"]


# ---------------------------------------------------------------------------
# acceptance: >= 8-graph soak, one tenant killed mid-run
# ---------------------------------------------------------------------------

def test_soak_eight_tenants_one_crash(tmp_path):
    N_TENANTS, N_RECORDS = 8, 1500
    sinks = {i: [] for i in range(N_TENANTS)}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        srv = Server(capacity=1 << 16, arbiter=False)
        try:
            handles = {}
            for i in range(N_TENANTS):
                cfg = quiet_cfg(tmp_path)
                if i == 3:  # the tenant that dies mid-run
                    cfg.fault_plan = FaultPlan(seed=i).crash_replica(
                        "m.0", at_tuple=200)
                handles[i] = srv.submit(
                    f"tenant-{i}", simple_build(N_RECORDS, sinks[i]),
                    TenantSpec(credits=1024, priority=i % 3),
                    config=cfg)
            for i, h in handles.items():
                want = TenantState.FAILED if i == 3 \
                    else TenantState.COMPLETED
                assert h.wait(WAIT_S) == want, (i, h.state, h.error)
            # every surviving tenant: all records delivered and its own
            # ledger balanced with zero violations at wait_end
            for i, h in handles.items():
                if i == 3:
                    continue
                assert len(sinks[i]) >= N_RECORDS
                stats = json.loads(h.graph.stats.to_json(0, 0))
                cons = stats["Conservation"]
                assert cons["Edges_balanced"], (i, cons)
                assert not cons["Violations_total"], (i, cons)
                assert stats["Tenant"]["Name"] == f"tenant-{i}"
            # per-tenant stats JSON is per-graph: 8 distinct reports
            names = {json.loads(h.graph.stats.to_json(0, 0))
                     ["PipeGraph_name"] for h in handles.values()}
            assert len(names) == N_TENANTS
            # teardown reclaims: census returns to the server baseline
            for i in range(N_TENANTS):
                srv.evict(f"tenant-{i}")
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# satellite: lifecycle-leak census across repeated cycles
# ---------------------------------------------------------------------------

def _census_settled(base, deadline_s=20.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        cen = process_census()
        if cen["threads"] <= base["threads"] \
                and (base["fds"] < 0 or cen["fds"] <= base["fds"]):
            return cen
        time.sleep(0.2)
    return process_census()


def test_census_no_thread_or_fd_leak_across_cycles(tmp_path):
    def build(g):
        g.add_source(wf.SourceBuilder(record_source(1500)).build()) \
            .add(wf.MapBuilder(lambda t: None).with_name("m")
                 .with_key_by().with_parallelism(2)
                 .with_elasticity(1, 4).build()) \
            .add_sink(wf.SinkBuilder(lambda r: None).build())

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        srv = Server(capacity=1 << 16, arbiter=False)
        try:
            # warmup cycle: lazily-built singletons must not read as
            # leaks (jax state, first monitor socket, ...)
            srv.submit("warm", build, TenantSpec(credits=512),
                       config=quiet_cfg(tmp_path)).wait(WAIT_S)
            srv.evict("warm")
            base = _census_settled(process_census())
            for cycle in range(2):
                # clean completion with the elastic controller ACTIVE
                # (SignalSampler is a census suspect)
                h = srv.submit("el", build, TenantSpec(credits=512),
                               config=RuntimeConfig(
                                   log_dir=str(tmp_path)))
                assert h.wait(WAIT_S) == TenantState.COMPLETED
                srv.evict("el")
                # injected crash (failure teardown path)
                fp = FaultPlan(seed=cycle).crash_replica("m.0",
                                                         at_tuple=100)
                h = srv.submit("crash", build, TenantSpec(credits=512),
                               config=quiet_cfg(tmp_path,
                                                fault_plan=fp))
                assert h.wait(WAIT_S) == TenantState.FAILED
                srv.evict("crash")
                # cancelled mid-run (stop teardown path)
                h = srv.submit(
                    "run", lambda g: simple_build(
                        0, endless=True, pace_s=0.0005)(g),
                    TenantSpec(credits=512),
                    config=quiet_cfg(tmp_path))
                time.sleep(0.4)
                assert srv.evict("run").state == TenantState.STOPPED
            cen = _census_settled(base)
            extra = [n for n in cen["names"]
                     if n not in base["names"]]
            assert cen["threads"] <= base["threads"], (base, cen, extra)
            if base["fds"] >= 0:
                assert cen["fds"] <= base["fds"], (base, cen)
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# arbiter policy (pure planner)
# ---------------------------------------------------------------------------

def _view(name, **kw):
    kw.setdefault("credits", 4096)
    kw.setdefault("min_credits", 256)
    return TenantView(name=name, **kw)


CFG = ArbiterConfig(breach_ticks=2, cooldown_s=5.0)


def test_plan_no_victim_or_no_donor_is_noop():
    views = [_view("a", breached=False), _view("b", breached=False)]
    assert plan_arbitration(views, CFG, {}, {}, 0.0) is None
    # a breached victim with no other tenant: nothing to take
    views = [_view("a", breached=True)]
    assert plan_arbitration(views, CFG, {"a": 5}, {}, 0.0) is None
    # the only donor is itself breached
    views = [_view("a", breached=True),
             _view("b", breached=True)]
    assert plan_arbitration(views, CFG, {"a": 5, "b": 5}, {}, 0.0) \
        is None


def test_plan_respects_breach_hysteresis_and_cooldown():
    views = [_view("a", breached=True), _view("b", breached=False)]
    # breach not yet sustained breach_ticks
    assert plan_arbitration(views, CFG, {"a": 1}, {}, 0.0) is None
    # sustained: decision fires
    d = plan_arbitration(views, CFG, {"a": 2}, {}, 0.0)
    assert d and d["victim"] == "a" and d["donor"] == "b"
    # donor inside its cooldown window: hold
    assert plan_arbitration(views, CFG, {"a": 2}, {"b": 10.0}, 5.0) \
        is None
    assert plan_arbitration(views, CFG, {"a": 2}, {"b": 10.0}, 11.0)


def test_plan_priority_and_weight_ordering():
    views = [
        _view("low-vic", breached=True, priority=1),
        _view("high-vic", breached=True, priority=5),
        _view("heavy-donor", breached=False, priority=0, weight=4.0),
        _view("light-donor", breached=False, priority=0, weight=1.0),
        _view("vip-donor", breached=False, priority=9),
    ]
    runs = {v.name: 9 for v in views}
    d = plan_arbitration(views, CFG, runs, {}, 0.0)
    # worst victim first; cheapest donor first (lowest priority, then
    # lowest weight) -- the priority-9 tenant is never squeezed for a
    # priority-5 victim... but IS eligible for nobody here
    assert d["victim"] == "high-vic"
    assert d["donor"] == "light-donor"
    # a donor of strictly higher priority than the victim is exempt
    views2 = [_view("vic", breached=True, priority=1),
              _view("vip", breached=False, priority=2)]
    assert plan_arbitration(views2, CFG, {"vic": 9, "vip": 0},
                            {}, 0.0) is None


def test_plan_actions_halve_parallelism_and_move_spare_credits():
    views = [
        _view("vic", breached=True, violating=("throughput",),
              values={"throughput_rps": 3.0}, burn_fast=10.0),
        _view("don", breached=False, credits=4096, min_credits=256,
              elastic=[("pipe0/burn", 4, 1, 8)]),
    ]
    d = plan_arbitration(views, CFG, {"vic": 2, "don": 0}, {}, 0.0)
    kinds = {a["type"]: a for a in d["actions"]}
    assert kinds["rescale"]["old"] == 4 and kinds["rescale"]["new"] == 2
    # half the SPARE lease (above the floor), per the documented step
    assert kinds["credits"]["moved"] == (4096 - 256) // 2
    assert d["evidence"]["violating"] == ["throughput"]
    # a donor hugging its floor still converges (min step 1), and the
    # step can never dig below the floor
    tight = _view("don2", breached=False, credits=260, min_credits=256)
    assert 1 <= _sp(tight, 0.5) <= 4
    assert _sp(_view("don3", credits=256, min_credits=256), 0.5) == 0
    # at the floors there is nothing left to give
    views[1] = _view("don", breached=False, credits=256,
                     min_credits=256, elastic=[("pipe0/burn", 1, 1, 8)])
    assert plan_arbitration(views, CFG, {"vic": 2, "don": 0},
                            {}, 0.0) is None


def test_plan_restitution_after_clear_or_departure():
    cfg = ArbiterConfig(clear_ticks=3)
    don = [Donation(victim="vic", donor="don", operator="op",
                    old_parallelism=4, new_parallelism=2),
           Donation(victim="vic", donor="don", credits_moved=512)]
    views = [_view("vic", breached=False), _view("don")]
    # not clear long enough yet
    assert plan_restitution(views, cfg, don, {"vic": 2}) is None
    # clear: newest donation returns first
    d = plan_restitution(views, cfg, don, {"vic": 3})
    assert d is don[1]
    # a departed victim releases its squeezes too
    assert plan_restitution([_view("don")], cfg, don, {}) is don[1]
    # still breached: hold
    views[0] = _view("vic", breached=True)
    assert plan_restitution(views, cfg, don, {"vic": 0}) is None


def test_stacked_rescale_donations_unwind_lifo(server, tmp_path):
    """Two squeezes on one operator store absolute parallelisms;
    restoring the OLDER one while the newer is still applied would
    silently undo an active squeeze (review finding) -- restitution
    must unwind strictly newest-first."""
    stop = threading.Event()

    def build(g):
        g.add_source(wf.SourceBuilder(
            record_source(0, pace_s=0.001, endless=True)).build()) \
            .add(wf.MapBuilder(lambda t: None).with_name("m")
                 .with_key_by().with_parallelism(4)
                 .with_elasticity(1, 4).build()) \
            .add_sink(wf.SinkBuilder(lambda r: None).build())

    h = server.submit("don", build, TenantSpec(credits=1024),
                      config=quiet_cfg(tmp_path))
    try:
        op = next(iter(h.graph.elastic))
        h.graph.rescale(op, 2)   # the squeezes the donations recorded
        h.graph.rescale(op, 1)
        d1 = Donation(victim="x", donor="don", operator=op,
                      old_parallelism=4, new_parallelism=2,
                      victim_departed=True)
        d2 = Donation(victim="y", donor="don", operator=op,
                      old_parallelism=2, new_parallelism=1,
                      victim_departed=True)
        # older first: current parallelism (1) != d1.new (2) -> held
        assert not server.apply_restitution(d1)
        assert d1.operator == op          # still ledgered, not moot
        assert next(iter(h.graph.elastic.values())).parallelism == 1
        # newest first: restores 1 -> 2, then d1 restores 2 -> 4
        assert server.apply_restitution(d2)
        assert next(iter(h.graph.elastic.values())).parallelism == 2
        assert server.apply_restitution(d1)
        assert next(iter(h.graph.elastic.values())).parallelism == 4
    finally:
        stop.set()
        h.graph.cancel()
        h.wait(WAIT_S)


def test_forget_scrubs_donation_ledger_on_name_reuse():
    """A re-submitted name must not inherit a departed namesake's
    ledger: its donations die with it, and donations OWED BY the
    departed victim fire as restitution instead of resolving against
    the new tenant (review finding)."""
    from windflow_tpu.serving import CrossTenantArbiter
    arb = CrossTenantArbiter.__new__(CrossTenantArbiter)
    arb._state_lock = threading.Lock()
    arb._breach_runs, arb._clear_runs, arb._cooldowns = {}, {}, {}
    arb.donations = [
        Donation(victim="v", donor="reused", credits_moved=100),
        Donation(victim="reused", donor="other", credits_moved=200),
    ]
    arb._breach_runs["reused"] = 5
    arb.forget("reused")
    assert len(arb.donations) == 1          # donor's squeeze died
    assert arb.donations[0].victim == "reused"
    assert arb.donations[0].victim_departed  # flagged, not resolved
    assert "reused" not in arb._breach_runs
    # plan_restitution treats the flagged entry's victim as gone even
    # though a live view carries the reused name
    views = [_view("reused", breached=True), _view("other")]
    d = plan_restitution(views, ArbiterConfig(), arb.donations, {})
    assert d is arb.donations[0]


def test_describe_actions_strings():
    s = describe_actions(
        [{"type": "rescale", "operator": "pipe0/acc", "old": 4,
          "new": 2},
         {"type": "credits", "moved": 2048}], "tenant-b", "tenant-a")
    assert "scaled pipe0/acc@tenant-b 4→2" in s
    assert "granted 2048 credits to tenant-a" in s
    s = describe_actions([{"type": "rescale", "operator": "op",
                           "old": 1, "new": 4}], "d", "v",
                         restore=True)
    assert "restored" in s


# ---------------------------------------------------------------------------
# credit actuation against live ingest gates
# ---------------------------------------------------------------------------

def ingest_build(n):
    def build(g):
        src = wf.SourceBuilder.from_replay(make_trace(n), speedup=None,
                                           chunk=256).build()
        g.add_source(src).add_sink(
            wf.SinkBuilder(lambda b: None).build())
    return build


def test_credit_moves_resize_live_gates(server, tmp_path):
    h_a = server.submit("ing-a", ingest_build(20_000),
                        TenantSpec(credits=4096),
                        config=quiet_cfg(tmp_path))
    h_b = server.submit("ing-b", ingest_build(20_000),
                        TenantSpec(credits=4096),
                        config=quiet_cfg(tmp_path))
    assert len(h_a._ingest) == 1 and len(h_b._ingest) == 1
    assert h_a._ingest[0].gate.budget == 4096
    decision = {"victim": "ing-a", "donor": "ing-b",
                "actions": [{"type": "credits", "moved": 2048}],
                "evidence": {"violating": ["throughput"]}}
    assert server.apply_arbitration(decision)
    assert h_b.credits == 2048 and h_a.credits == 4096 + 2048
    assert h_b._ingest[0].gate.budget == 2048
    assert h_a._ingest[0].gate.budget == 4096 + 2048
    # both tenants' flight rings carry the arbitration evidence
    for h in (h_a, h_b):
        evs = [e for e in h.graph.flight.snapshot()
               if e["kind"] == "arbitration"]
        assert evs and evs[0]["victim"] == "ing-a" \
            and evs[0]["donor"] == "ing-b"
        assert "granted 2048 credits" in evs[0]["action"]
    # restitution returns the credits
    assert server.apply_restitution(
        Donation(victim="ing-a", donor="ing-b", credits_moved=2048))
    assert h_b.credits == 4096 and h_a.credits == 4096
    assert h_a.wait(WAIT_S) == TenantState.COMPLETED
    assert h_b.wait(WAIT_S) == TenantState.COMPLETED
    # shed/dead letters (none here) stay per-tenant by construction
    assert h_a.graph.dead_letters.count() == 0
    # terminal tenants refuse further credit moves (the lease already
    # returned to the cap; a grant now would corrupt the accounting)
    assert server._transfer_credits(h_b, h_a, 100) == 0
    assert not server.apply_arbitration(
        {"victim": "ing-a", "donor": "ing-b",
         "actions": [{"type": "credits", "moved": 100}],
         "evidence": {}})


def test_live_gate_resize_never_wedges_blocked_acquire():
    """The arbiter resizes CreditGates on RUNNING tenants: an acquire
    blocked against the OLD budget must re-read the new one, or a
    downward squeeze wedges the donor source forever (review
    finding -- release() clamps available at the new budget, so a
    stale `need` above it could never be satisfied)."""
    from windflow_tpu.ingest import CreditGate
    gate = CreditGate(4096)
    assert gate.acquire(4096)          # drain the whole budget
    got = threading.Event()

    def blocked():
        gate.acquire(1024)             # need > post-resize budget
        got.set()

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not got.is_set()
    gate.resize(256)                   # live squeeze below the need
    gate.release(4096)                 # consumer drains; avail -> 256
    assert got.wait(5.0), "blocked acquire wedged against old budget"
    # an upward resize wakes waiters promptly too
    gate2 = CreditGate(64)
    assert gate2.acquire(64)
    got2 = threading.Event()
    t2 = threading.Thread(
        target=lambda: (gate2.acquire(128), got2.set()), daemon=True)
    t2.start()
    time.sleep(0.1)
    gate2.resize(512)                  # grows available by 448 >= 128
    assert got2.wait(5.0)


def test_restitution_after_victim_left_still_recorded(server,
                                                      tmp_path):
    """A restitution firing after the victim was evicted must still
    restore the donor AND record an arbitration event on the donor's
    ring (every actuation is explained -- review finding)."""
    h_b = server.submit("donor", ingest_build(50_000),
                        TenantSpec(credits=2048),
                        config=quiet_cfg(tmp_path))
    granted0 = server.granted
    assert server.apply_restitution(
        Donation(victim="long-gone", donor="donor",
                 credits_moved=512))
    assert h_b.credits == 2048 + 512
    assert server.granted == granted0 + 512   # re-reserved under cap
    evs = [e for e in h_b.graph.flight.snapshot()
           if e["kind"] == "arbitration"]
    assert evs and evs[-1]["victim"] == "long-gone"
    assert "returned 512 credits" in evs[-1]["action"]
    assert h_b.arbitrations == 1
    assert h_b.wait(WAIT_S) == TenantState.COMPLETED


def test_partial_restitution_keeps_remainder_ledgered(tmp_path):
    """When the cap can only absorb part of a gone victim's give-back,
    the Donation keeps its remainder for a later tick instead of
    forfeiting the donor's lease (review finding)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        srv = Server(capacity=4096, arbiter=False)
        try:
            h = srv.submit("don", ingest_build(50_000),
                           TenantSpec(credits=2048),
                           config=quiet_cfg(tmp_path))
            d = Donation(victim="gone", donor="don",
                         credits_moved=4096)     # > cap room (2048)
            assert srv.apply_restitution(d)
            assert h.credits == 2048 + 2048      # clamped give-back
            assert d.credits_moved == 2048       # remainder survives
            assert srv.granted == 4096
            assert h.wait(WAIT_S) == TenantState.COMPLETED
        finally:
            srv.close()


def test_failed_restitution_stays_ledgered(server, tmp_path):
    """A restore that cannot apply keeps its Donation ledgered so the
    arbiter retries instead of stranding the donor squeezed (review
    finding): with the donor gone, the entry is dropped instead.  An
    operator that no longer resolves in the elastic registry is moot
    and its entry drops immediately."""
    from windflow_tpu.serving import CrossTenantArbiter
    arb = CrossTenantArbiter(server, ArbiterConfig(clear_ticks=1))

    def build(g):
        g.add_source(wf.SourceBuilder(
            record_source(0, pace_s=0.001, endless=True)).build()) \
            .add(wf.MapBuilder(lambda t: None).with_name("m")
                 .with_key_by().with_parallelism(2)
                 .with_elasticity(1, 4).build()) \
            .add_sink(wf.SinkBuilder(lambda r: None).build())

    h = server.submit("don", build, TenantSpec(credits=1024),
                      config=quiet_cfg(tmp_path))
    op = next(iter(h.graph.elastic))
    # old_parallelism above max_replicas: the restore rescale RAISES
    # -> applied False -> the donation must survive for a retry
    arb.donations.append(Donation(victim="gone", donor="don",
                                  operator=op, old_parallelism=8,
                                  new_parallelism=2,
                                  victim_departed=True))
    arb.tick(now=0.0)
    assert len(arb.donations) == 1, "failed restore dropped the ledger"
    # an unresolvable operator is moot: dropped, not retried forever
    arb.donations.append(Donation(victim="gone", donor="don",
                                  operator="no/such_op",
                                  old_parallelism=4,
                                  new_parallelism=2,
                                  victim_departed=True))
    arb.tick(now=1.0)
    assert len(arb.donations) == 1
    assert arb.donations[0].old_parallelism == 8
    # donor terminal: nothing left to restore to -> entry dropped
    h.graph.cancel()
    h.wait(WAIT_S)
    arb.tick(now=2.0)
    assert not arb.donations


# ---------------------------------------------------------------------------
# acceptance: scripted noisy neighbour, arbiter restores the SLO
# ---------------------------------------------------------------------------

def burner_source(stop_evt):
    state = {}

    def fn(shipper, ctx):
        if stop_evt.is_set():
            return False
        i = state.setdefault("i", 0)
        shipper.push(wf.BasicRecord(i % 64, i, i, 1.0))
        state["i"] = i + 1
        return True

    return fn


def burn_10ms(t):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.01:
        pass
    return None


def test_noisy_neighbor_arbiter_restores_victim_slo(tmp_path):
    """The ISSUE-14 acceptance script: tenant-a declares a throughput
    SLO and is starved by tenant-b's CPU burners; the arbiter scales
    the donor down (and reassigns credits), the victim's SLO recovers
    (slo_recovered fires), and flight + doctor name victim, donor,
    action and evidence for every decision."""
    stop = threading.Event()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        srv = Server(
            capacity=1 << 16,
            arbiter=ArbiterConfig(interval_s=0.25, breach_ticks=2,
                                  cooldown_s=1.0,
                                  clear_ticks=10 ** 6))
        try:
            vcfg = quiet_cfg(tmp_path, diagnosis_interval_s=0.2,
                             audit_interval_s=0.1)
            bcfg = quiet_cfg(tmp_path, queue_capacity=32)

            def build_victim(g):
                g.add_source(wf.SourceBuilder(
                    record_source(10 ** 6, pace_s=0.001)).build()) \
                    .add(wf.MapBuilder(lambda t: None)
                         .with_name("vmap").build()) \
                    .add_sink(wf.SinkBuilder(lambda r: None).build())

            def build_noisy(g):
                g.add_source(wf.SourceBuilder(
                    burner_source(stop)).build()) \
                    .add(wf.MapBuilder(burn_10ms).with_name("burn")
                         .with_key_by().with_parallelism(4)
                         .with_elasticity(1, 4).build()) \
                    .add_sink(wf.SinkBuilder(lambda r: None).build())

            hv = srv.submit(
                "tenant-a", build_victim,
                TenantSpec(credits=1024, priority=5,
                           slo=dict(min_throughput_rps=60.0,
                                    target=0.9, fast_window_s=3.0,
                                    slow_window_s=30.0,
                                    warmup_ticks=1,
                                    fast_burn=2.0)),
                config=vcfg)
            hb = srv.submit("tenant-b", build_noisy,
                            TenantSpec(credits=4096, priority=0),
                            config=bcfg)
            # phase A: contention starves the victim -> breach
            deadline = time.monotonic() + WAIT_S
            while time.monotonic() < deadline:
                tr = hv.graph.diagnosis.slo
                if tr is not None and tr.breached:
                    break
                time.sleep(0.2)
            assert hv.graph.diagnosis.slo.breached, \
                "victim never breached under contention"
            # phase B: the arbiter squeezes the donor until the
            # victim's episode closes
            recovered = False
            deadline = time.monotonic() + WAIT_S
            while time.monotonic() < deadline:
                kinds = [e["kind"]
                         for e in hv.graph.flight.snapshot()]
                if "slo_recovered" in kinds:
                    recovered = True
                    break
                time.sleep(0.25)
            decisions = srv.arbiter.decisions
            assert decisions, "arbiter never actuated"
            assert recovered, \
                (f"victim SLO never recovered; donor at "
                 f"{[h.parallelism for h in hb.graph.elastic.values()]}, "
                 f"{len(decisions)} decisions")
            # the donor was actually scaled down
            assert all(h.parallelism < 4
                       for h in hb.graph.elastic.values())
            # every decision names victim, donor, action, evidence
            for h in (hv, hb):
                evs = [e for e in h.graph.flight.snapshot()
                       if e["kind"] == "arbitration"]
                assert evs
                for e in evs:
                    assert e["victim"] == "tenant-a"
                    assert e["donor"] == "tenant-b"
                    assert e["action"]
                    assert "violating" in e["evidence"]
            # ...and the doctor explains them in prose
            txt = render_text(srv.explain("tenant-a"))
            assert "arbitrations (cross-tenant):" in txt
            assert "tenant-b -> tenant-a" in txt
            assert "scaled" in txt or "granted" in txt
            # server-level stats carry the arbitration counts
            rows = {r["Name"]: r
                    for r in srv.stats()["Tenants"]}
            assert rows["tenant-a"]["Arbitrations"] >= 1
            assert rows["tenant-b"]["Arbitrations"] >= 1
        finally:
            stop.set()
            srv.close()


# ---------------------------------------------------------------------------
# observability: dashboard index/tenants endpoints, /metrics families,
# doctor Arbitrations block
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode()


def test_dashboard_index_tenants_and_metrics(tmp_path):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        srv = Server(capacity=1 << 16, arbiter=False, http_port=0)
        try:
            port = srv.httpd.server_address[1]
            base = f"http://127.0.0.1:{port}"
            h = srv.submit("web-tenant",
                           simple_build(0, endless=True,
                                        pace_s=0.0005),
                           TenantSpec(credits=2048, priority=1),
                           config=quiet_cfg(tmp_path))
            # wait for the tenant's first monitor report to land
            deadline = time.monotonic() + WAIT_S
            idx = {}
            while time.monotonic() < deadline:
                idx = json.loads(_get(base + "/index"))
                if any((v.get("tenant") or {}).get("Name")
                       == "web-tenant" for v in idx.values()):
                    break
                time.sleep(0.2)
            rows = [v for v in idx.values()
                    if (v.get("tenant") or {}).get("Name")
                    == "web-tenant"]
            assert rows, idx
            row = rows[0]
            assert row["graph"] == "web-tenant" and row["active"]
            assert set(row["links"]) == {"apps", "explain", "flight",
                                         "metrics"}
            aid = row["links"]["apps"].split("=")[-1]
            # per-app filter narrows /apps to the requested app
            filtered = json.loads(_get(base + f"/apps?app={aid}"))
            assert list(filtered) == [aid]
            # /tenants: per-app Tenant blocks + the Server's own view
            tens = json.loads(_get(base + "/tenants"))
            assert tens["apps"][aid]["Name"] == "web-tenant"
            assert tens["server"]["Tenants"][0]["Name"] == "web-tenant"
            assert tens["server"]["Capacity"] == 1 << 16
            # /metrics: the windflow_tenant_* families
            metrics = _get(base + "/metrics")
            assert 'windflow_tenant_up{' in metrics
            assert 'tenant="web-tenant"' in metrics
            assert "windflow_tenant_credits" in metrics
            assert "windflow_tenant_arbitrations_total" in metrics
            try:  # strict parser, when available (as in test_audit)
                from prometheus_client.openmetrics import parser
                list(parser.text_string_to_metric_families(metrics))
            except ImportError:
                pass
            assert h.state == TenantState.RUNNING
        finally:
            srv.close()


def test_report_arbitrations_block_and_rendering():
    flight = [
        {"t": 1.0, "seq": 0, "kind": "arbitration",
         "victim": "tenant-a", "donor": "tenant-b",
         "action": "scaled acc@tenant-b 4→2, granted 2k credits to "
                   "tenant-a",
         "detail": "p99 42 ms over budget, 42% budget burned",
         "evidence": {"violating": ["e2e_p99"]}},
        {"t": 2.0, "seq": 1, "kind": "shed", "node": "x"},
    ]
    rep = build_report({"PipeGraph_name": "g"}, flight)
    assert len(rep["Arbitrations"]) == 1
    a = rep["Arbitrations"][0]
    assert a["victim"] == "tenant-a" and a["donor"] == "tenant-b"
    txt = render_text(rep)
    assert "arbitrations (cross-tenant):" in txt
    assert "tenant-b -> tenant-a: scaled acc@tenant-b 4→2" in txt
    assert "p99 42 ms over budget" in txt
    # absent entirely when no arbitration happened
    rep2 = build_report({"PipeGraph_name": "g"}, [])
    assert rep2["Arbitrations"] == []
    assert "arbitrations" not in render_text(rep2)
