"""Failure-containment tests (resilience/): graph cancellation on
replica death, per-operator error policies + dead-letter quarantine,
the stall watchdog, and the deterministic fault-injection harness.

Every failure scenario here is driven by resilience.faults.FaultPlan
(or an explicit user-function failure), never by timing races: the
recovery paths must fire deterministically.
"""
import json
import threading
import time
import warnings

import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, RuntimeConfig
from windflow_tpu.graph.pipegraph import NodeFailureError, StallError
from windflow_tpu.resilience import (CancelToken, DeadLetterStore,
                                     FaultPlan, GraphCancelled,
                                     InjectedFailure)
from windflow_tpu.runtime.queues import Channel

WAIT_S = 60  # generous outer bound; the paths under test finish in ms


def counting_source(n, state=None):
    state = state if state is not None else {}

    def fn(shipper, ctx):
        i = state.setdefault("i", 0)
        if i >= n:
            return False
        shipper.push(BasicRecord(i % 2, i // 2, i, float(i)))
        state["i"] = i + 1
        return True

    return fn


class CollectingSink:
    def __init__(self):
        self.lock = threading.Lock()
        self.values = []

    def __call__(self, rec):
        if rec is not None:
            with self.lock:
                self.values.append(rec.value)


def run_in_thread(fn, timeout=WAIT_S):
    """Run fn on a worker thread; fail the test (instead of hanging
    the suite) if it does not finish in time.  Returns the exception
    fn raised, or None."""
    box = {}

    def target():
        try:
            fn()
        except BaseException as e:
            box["error"] = e

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "graph run did not complete: deadlock?"
    return box.get("error")


# ---------------------------------------------------------------------------
# channel poisoning / CancelToken
# ---------------------------------------------------------------------------

def test_channel_poison_unblocks_blocked_put():
    ch = Channel(capacity=2)
    pid = ch.register_producer()
    ch.put(pid, "a")
    ch.put(pid, "b")  # full now
    raised = threading.Event()

    def blocked_put():
        try:
            ch.put(pid, "c")  # blocks on the bounded buffer
        except GraphCancelled:
            raised.set()

    t = threading.Thread(target=blocked_put, daemon=True)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()  # genuinely blocked
    ch.poison()
    assert raised.wait(5), "poison did not unblock the producer"


def test_channel_poison_unblocks_blocked_get():
    ch = Channel(capacity=2)
    ch.register_producer()
    raised = threading.Event()

    def blocked_get():
        try:
            ch.get()
        except GraphCancelled:
            raised.set()

    t = threading.Thread(target=blocked_get, daemon=True)
    t.start()
    time.sleep(0.05)
    ch.poison()
    assert raised.wait(5), "poison did not unblock the consumer"
    # post-poison operations fail immediately
    with pytest.raises(GraphCancelled):
        ch.get(timeout=0.5)
    ch.close(0)  # close after poison is a silent no-op


def test_native_channel_poison_unblocks_blocked_put():
    from windflow_tpu.runtime.native import NativeChannel, native_available
    if not native_available():
        pytest.skip("native runtime unavailable")
    ch = NativeChannel(2)
    pid = ch.register_producer()
    ch.put(pid, "a")
    ch.put(pid, "b")
    raised = threading.Event()

    def blocked_put():
        try:
            ch.put(pid, "c")
        except GraphCancelled:
            raised.set()

    t = threading.Thread(target=blocked_put, daemon=True)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()
    ch.poison()
    assert raised.wait(5), "poison did not unblock the native producer"
    with pytest.raises(GraphCancelled):
        ch.get(timeout=0.2)


def test_cancel_token_idempotent_and_late_registration():
    tok = CancelToken()
    ch1, ch2 = Channel(4), Channel(4)
    tok.register(ch1)
    err = RuntimeError("boom")
    assert tok.cancel(err, origin="n1")
    assert not tok.cancel(RuntimeError("later"), origin="n2")
    assert tok.reason is err and tok.origin == "n1"
    assert ch1.poisoned
    tok.register(ch2)  # registered after the cancel: poisoned at once
    assert ch2.poisoned


# ---------------------------------------------------------------------------
# the deadlock regression (satellite): replica dies with a full channel
# ---------------------------------------------------------------------------

def test_replica_crash_with_full_channel_does_not_deadlock():
    """The seed behaviour this PR removes: a middle replica dies, its
    bounded input channel fills, the source blocks in put() forever and
    wait_end never returns.  With graph cancellation the run must end
    and raise NodeFailureError naming the dead replica."""
    plan = FaultPlan(seed=1).crash_replica("map", at_tuple=5)
    cfg = RuntimeConfig(queue_capacity=4, fault_plan=plan)
    sink = CollectingSink()
    g = wf.PipeGraph("deadlock", config=cfg)
    g.add_source(wf.SourceBuilder(counting_source(50_000)).build()) \
        .add(wf.MapBuilder(lambda t: None).with_name("map").build()) \
        .add_sink(wf.SinkBuilder(sink).build())

    err = run_in_thread(g.run)
    assert isinstance(err, NodeFailureError), err
    assert err.errors, "NodeFailureError.errors must list the failures"
    names = [n for n, _ in err.errors]
    assert any("map" in n for n in names), names
    assert all(isinstance(e, InjectedFailure) for _, e in err.errors)


def test_wait_end_collects_every_failed_replica():
    """Both replicas of a 2-parallel map fail (a barrier guarantees
    each has taken a tuple before either raises): wait_end must report
    BOTH, not just errors[0]."""
    barrier = threading.Barrier(2)

    def failing(t):
        barrier.wait(timeout=30)
        raise ValueError(f"replica poisoned tuple {t.id}")

    g = wf.PipeGraph("all-errors")
    g.add_source(wf.SourceBuilder(counting_source(100)).build()) \
        .add(wf.MapBuilder(failing).with_parallelism(2)
             .with_name("boom").build()) \
        .add_sink(wf.SinkBuilder(lambda r: None).build())

    err = run_in_thread(g.run)
    assert isinstance(err, NodeFailureError)
    failed = sorted(n for n, _ in err.errors)
    assert len(failed) == 2 and all("boom" in n for n in failed), failed
    # every pair is in the message too
    for name, _ in err.errors:
        assert name in str(err)


def test_sibling_replicas_unwind_clean_on_cancel():
    """When one replica dies, its siblings are cancelled, not failed:
    they must not appear in .errors."""
    plan = FaultPlan(seed=3).crash_replica("map.0", at_tuple=3)
    cfg = RuntimeConfig(queue_capacity=8, fault_plan=plan)
    g = wf.PipeGraph("sibling", config=cfg)
    g.add_source(wf.SourceBuilder(counting_source(100_000)).build()) \
        .add(wf.MapBuilder(lambda t: None).with_parallelism(2)
             .with_name("map").build()) \
        .add_sink(wf.SinkBuilder(lambda r: None).build())
    err = run_in_thread(g.run)
    assert isinstance(err, NodeFailureError)
    assert [n for n, _ in err.errors] == ["pipe0/map.0"], err.errors


def test_user_cancel_raises_node_failure():
    stop = threading.Event()

    def slow_source(shipper, ctx):
        stop.wait(0.005)
        shipper.push(BasicRecord(0, 0, 0, 1.0))
        return True  # endless until cancelled

    g = wf.PipeGraph("user-cancel")
    g.add_source(wf.SourceBuilder(slow_source).build()) \
        .add_sink(wf.SinkBuilder(lambda r: None).build())
    g.start()
    time.sleep(0.05)
    assert g.cancel()
    err = run_in_thread(g.wait_end)
    assert isinstance(err, NodeFailureError)
    assert "user" in str(err)


# ---------------------------------------------------------------------------
# error policies + dead letters
# ---------------------------------------------------------------------------

def pipeline_with_policy(policy, tmp_path=None, tracing=False):
    def poisoned(t):
        if int(t.value) % 7 == 3:
            raise ValueError(f"bad tuple {t.value}")

    cfg = RuntimeConfig(tracing=tracing,
                        log_dir=str(tmp_path) if tmp_path else "log")
    sink = CollectingSink()
    g = wf.PipeGraph("policy", config=cfg)
    g.add_source(wf.SourceBuilder(counting_source(70)).build()) \
        .add(wf.MapBuilder(poisoned).with_name("fragile")
             .with_error_policy(policy).build()) \
        .add_sink(wf.SinkBuilder(sink).build())
    return g, sink


def test_skip_policy_keeps_replica_alive():
    g, sink = pipeline_with_policy("skip")
    g.run()  # completes despite 10 poisoned tuples
    assert sorted(sink.values) == sorted(
        float(v) for v in range(70) if v % 7 != 3)
    assert g.dead_letters.count() == 0  # skip does not quarantine


def test_dead_letter_policy_quarantines_tuples(tmp_path):
    g, sink = pipeline_with_policy("dead_letter", tmp_path, tracing=True)
    g.run()
    assert sorted(sink.values) == sorted(
        float(v) for v in range(70) if v % 7 != 3)
    dls = g.dead_letters
    assert dls.count() == 10
    entries = dls.entries
    assert len(entries) == 10
    for e in entries:
        assert "fragile" in e.node
        assert isinstance(e.error, ValueError)
        assert "bad tuple" in e.traceback  # full traceback retained
        assert int(e.item.value) % 7 == 3  # the offending tuple itself
    assert dls.counts_by_node() == {"pipe0/fragile.0": 10}

    # counters are visible in the monitoring JSON (dumped by wait_end
    # under tracing into log_dir)
    import glob
    import os
    files = glob.glob(os.path.join(str(tmp_path), "*_policy.json"))
    assert files, os.listdir(str(tmp_path))
    report = json.loads(open(files[0]).read())
    assert report["Svc_failures"] == 10
    assert report["Dead_letter_tuples"] == 10
    fragile = next(o for o in report["Operators"]
                   if "fragile" in o["Operator_name"])
    assert fragile["Replicas"][0]["Svc_failures"] == 10


def test_fail_policy_still_cancels():
    g, _ = pipeline_with_policy("fail")
    err = run_in_thread(g.run)
    assert isinstance(err, NodeFailureError)


def test_chain_falls_back_to_add_for_policied_operator():
    """chain() must not fuse a skip-policy operator into its upstream
    tail (the policy would swallow the upstream half's errors too)."""
    g = wf.PipeGraph("chain-policy")
    pipe = g.add_source(wf.SourceBuilder(counting_source(10)).build())
    pipe.chain(wf.MapBuilder(lambda t: None)
               .with_error_policy("skip").with_name("m1").build())
    assert any("m1" in n.name for n in g._all_nodes()), \
        "skip-policy operator was fused away instead of added"


def test_chain_does_not_inherit_tail_policy():
    """The reverse direction: a default-'fail' operator chained after a
    skip-policy tail must not be fused into it (it would silently
    inherit 'skip' and its failures would vanish)."""
    def bad_sink(rec):
        if rec is not None:
            raise ValueError("sink must fail loudly")

    g = wf.PipeGraph("chain-inherit")
    pipe = g.add_source(wf.SourceBuilder(counting_source(10)).build())
    pipe.add(wf.MapBuilder(lambda t: None)
             .with_error_policy("skip").with_name("skippy").build())
    pipe.chain_sink(wf.SinkBuilder(bad_sink).build())
    err = run_in_thread(g.run)
    assert isinstance(err, NodeFailureError), \
        "sink failure was swallowed by the upstream skip policy"
    assert any("sink" in n for n, _ in err.errors), err.errors


def test_fault_rules_do_not_bind_to_collectors():
    plan = FaultPlan().crash_replica("winseq", at_tuple=5)
    assert plan.for_node("pipe0/winseq.0") is not None
    assert plan.for_node("pipe0/winseq.coll0") is None
    assert plan.for_node("pipe0/winseq.collector") is None
    assert plan.for_node("pipe0/winseq.coll.g1") is None


def test_channel_capacity_zero_is_unbounded():
    """queue_capacity=0 meant 'unbounded' in the queue.Queue-backed
    channel; the rewrite must preserve that."""
    ch = Channel(capacity=0)
    pid = ch.register_producer()
    for i in range(10_000):  # would deadlock on a bounded channel
        ch.put(pid, i)
    assert ch.qsize() == 10_000


def test_native_lowering_forfeited_under_resilience_config():
    """A lowerable declared pipeline must fall back to the RtNode plane
    when a FaultPlan or watchdog is configured (the lowered run has no
    replicas/channels for them to act on)."""
    from windflow_tpu.graph.native_lowering import _lower_plan
    from windflow_tpu.core.expr import F
    from windflow_tpu.core.basic import WinType
    from windflow_tpu.operators.synth import SyntheticSource
    from windflow_tpu.operators.basic_ops import Filter, Sink
    from windflow_tpu.operators.win_seq import WinSeq

    def build(cfg):
        g = wf.PipeGraph("lowerable", config=cfg)
        g.add_source(SyntheticSource(1000, 2)) \
            .add(Filter(F.value % 2 == 0)) \
            .add(WinSeq("sum", 8, 4, WinType.CB)) \
            .add_sink(Sink(lambda r: None))
        return g

    base = _lower_plan(build(RuntimeConfig()))
    if base is None:
        pytest.skip("pipeline not lowerable here (no native runtime)")
    assert _lower_plan(build(RuntimeConfig(
        fault_plan=FaultPlan().crash_replica("filter", 1)))) is None
    assert _lower_plan(build(RuntimeConfig(watchdog_timeout_s=5.0))) is None


def test_source_builder_rejects_nonfail_policy():
    """A source has no per-tuple svc boundary: skip/dead_letter must be
    rejected at build time, not silently ignored at runtime."""
    with pytest.raises(ValueError, match="fail hard"):
        wf.SourceBuilder(lambda s, c: False).with_error_policy("skip")
    # the default policy remains expressible
    wf.SourceBuilder(lambda s, c: False).with_error_policy("fail").build()


def test_dead_letter_store_bounded():
    store = DeadLetterStore(max_entries=3)
    for i in range(10):
        store.add("n", i, ValueError(str(i)))
    assert store.count() == 10          # exact count
    assert len(store.entries) == 3      # bounded retention
    # the traceback reflects the PASSED error even outside an except
    # block (format_exc would have recorded "NoneType: None")
    assert "ValueError: 0" in store.entries[0].traceback
    store.clear()
    assert store.count() == 0 and not store


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------

def test_watchdog_cancels_stalled_graph(tmp_path):
    """A sink that blocks forever would hang wait_end for good; the
    watchdog must detect zero progress, dump diagnostics and cancel."""
    block = threading.Event()  # never set

    def stuck_sink(rec):
        if rec is not None:
            block.wait()  # simulates a wedged external system

    cfg = RuntimeConfig(watchdog_timeout_s=0.5, cancel_grace_s=0.5,
                        log_dir=str(tmp_path), queue_capacity=8)
    g = wf.PipeGraph("stall", config=cfg)
    g.add_source(wf.SourceBuilder(counting_source(10_000)).build()) \
        .add_sink(wf.SinkBuilder(stuck_sink).build())

    err = run_in_thread(g.run)
    assert isinstance(err, StallError), err
    assert isinstance(err, NodeFailureError)  # retryable by recovery
    # the diagnostic dump exists and names the stuck channel state
    path = g._watchdog.report_path
    assert path is not None
    report = json.loads(open(path).read())
    assert any(row["node"].endswith("sink.0") for row in report["nodes"])
    assert "thread_stacks" in report and "stuck_sink" in \
        report["thread_stacks"]


def test_watchdog_quiet_on_healthy_graph(tmp_path):
    cfg = RuntimeConfig(watchdog_timeout_s=5.0, log_dir=str(tmp_path))
    sink = CollectingSink()
    g = wf.PipeGraph("healthy", config=cfg)
    g.add_source(wf.SourceBuilder(counting_source(200)).build()) \
        .add_sink(wf.SinkBuilder(sink).build())
    g.run()
    assert not g._watchdog.fired
    assert len(sink.values) == 200


# ---------------------------------------------------------------------------
# fault-injection harness
# ---------------------------------------------------------------------------

def test_fault_plan_crash_is_deterministic():
    """The same plan against the same pipeline crashes at the same
    tuple every run (no sleeps, no races)."""
    for _ in range(3):
        taken = []
        plan = FaultPlan(seed=9).crash_replica("victim", at_tuple=7)
        cfg = RuntimeConfig(fault_plan=plan)

        def observer(t):
            taken.append(int(t.value))

        g = wf.PipeGraph("det", config=cfg)
        g.add_source(wf.SourceBuilder(counting_source(1000)).build()) \
            .add(wf.MapBuilder(observer).with_name("victim").build()) \
            .add_sink(wf.SinkBuilder(lambda r: None).build())
        err = run_in_thread(g.run)
        assert isinstance(err, NodeFailureError)
        assert len(taken) == 6  # tuples 1..6 processed, 7th injected


def test_fault_plan_put_delays_apply():
    plan = FaultPlan(seed=2).delay_puts("source", delay_s=0.004)
    cfg = RuntimeConfig(fault_plan=plan)
    g = wf.PipeGraph("slow", config=cfg)
    g.add_source(wf.SourceBuilder(counting_source(50)).build()) \
        .add_sink(wf.SinkBuilder(lambda r: None).build())
    t0 = time.monotonic()
    g.run()
    assert time.monotonic() - t0 >= 50 * 0.004  # sleeps really ran


def test_forced_native_build_failure_and_channel_warning():
    """fail_native_build() forces the toolchain probe down; make_channel
    must fall back to the Python channel and warn exactly once."""
    import os
    if os.environ.get("WINDFLOW_NATIVE", "1") == "0":
        pytest.skip("warning is deliberately suppressed when the native "
                    "plane is disabled via WINDFLOW_NATIVE=0")
    from windflow_tpu.runtime import queues
    from windflow_tpu.runtime.native import native_available

    with FaultPlan().fail_native_build():
        assert not native_available()
        queues._native_warned = False  # fresh warn-once state
        cfg = RuntimeConfig(use_native_runtime=True)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            ch1 = queues.make_channel(cfg)
            ch2 = queues.make_channel(cfg)
        assert type(ch1).__name__ == "Channel"
        assert type(ch2).__name__ == "Channel"
        runtime_warns = [w for w in caught
                         if issubclass(w.category, RuntimeWarning)]
        assert len(runtime_warns) == 1  # once, not per channel
        assert "native runtime unavailable" in str(runtime_warns[0].message)
    queues._native_warned = False


def test_run_with_recovery_after_injected_midstream_crash(tmp_path):
    """The headline acceptance path: a FaultPlan kills a mid-pipeline
    replica on attempt 0 (full-channel conditions), the contained
    failure surfaces as NodeFailureError, and run_with_recovery
    restores the accumulator checkpoint and completes on attempt 1."""
    from windflow_tpu.utils.checkpoint import run_with_recovery

    ckpt = str(tmp_path / "rec.pkl")
    observed = {"attempts": 0, "failures": []}

    def acc_fn(t, acc):
        acc.value += t.value

    def factory(attempt):
        observed["attempts"] += 1
        plan = (FaultPlan(seed=4).crash_replica("accumulator", at_tuple=20)
                if attempt == 0 else None)
        cfg = RuntimeConfig(queue_capacity=4, fault_plan=plan)
        g = wf.PipeGraph("recover", config=cfg)
        g.add_source(wf.SourceBuilder(counting_source(5000)).build()) \
            .add(wf.AccumulatorBuilder(acc_fn)
                 .with_initial_value(BasicRecord(value=0.0)).build()) \
            .add_sink(wf.SinkBuilder(lambda r: None).build())
        return g

    def on_failure(attempt, error, graph):
        observed["failures"].append((attempt, error))

    box = {}

    def run():
        box["graph"] = run_with_recovery(factory, ckpt, max_restarts=2,
                                         on_failure=on_failure)

    err = run_in_thread(run)
    assert err is None, err
    assert observed["attempts"] == 2
    (attempt0, e0), = observed["failures"]
    assert attempt0 == 0 and isinstance(e0, NodeFailureError)
    assert any(isinstance(x, InjectedFailure) for _, x in e0.errors)
    # the successful attempt produced the full per-key sums (the
    # LEVEL2 compile pass may have fused the accumulator: look its
    # logic up fusion-transparently)
    from windflow_tpu.graph.fuse import find_logic
    g = box["graph"]
    acc = find_logic(g, lambda lg: hasattr(lg, "state"), "accumulator")
    finals = {k: v.value for k, v in acc.state.items()}
    assert finals == {0: sum(float(v) for v in range(5000) if v % 2 == 0),
                      1: sum(float(v) for v in range(5000) if v % 2 == 1)}
