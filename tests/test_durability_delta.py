"""Incremental (delta) epoch snapshots (durability/delta.py;
docs/RESILIENCE.md "Delta snapshots"): content-addressed blob chains
beside the manifest, O(changed keys) commit cost, refcounted blob GC
honoring retention, and the tolerant reader's fallback to the newest
fully-loadable epoch when a chain loses a link -- with zero duplicate
or lost sink effects across that fallback."""
import collections
import os
import pickle

import pytest

import windflow_tpu as wf
from windflow_tpu.core import BasicRecord, DurabilityConfig
from windflow_tpu.durability import (EpochStore, EpochTaggedStore,
                                     run_with_epochs)
from windflow_tpu.durability.delta import (BlobRef, BlobStore,
                                           DeltaEncoder, KeyedCapture,
                                           pack_keyed, resolve_chain,
                                           unpack_keyed)
from windflow_tpu.resilience import FaultPlan

from test_durability import (CkptSource, _acc_graph, _acc_oracle,
                             _assert_exactly_once, _per_key)


# ---------------------------------------------------------------------------
# blob store: content addressing, digest verification
# ---------------------------------------------------------------------------

def test_blob_store_content_addressed_and_digest_checked(tmp_path):
    import hashlib
    store = BlobStore(str(tmp_path / "blobs"))
    payload = b"windflow delta payload"
    digest = hashlib.sha256(payload).hexdigest()
    p = store.write(digest, payload)
    assert store.read(digest) == payload
    assert store.digests_on_disk() == [digest]
    # skip-if-exists: rewriting is a no-op (same mtime path exists)
    assert store.write(digest, payload) == p
    # a torn blob fails its content digest -- actionable error, not a
    # bad unpickle deep inside restore
    with open(p, "wb") as f:
        f.write(payload[: len(payload) // 2])
    with pytest.raises(RuntimeError, match="content digest"):
        store.read(digest)
    store.unlink(digest)
    with pytest.raises(RuntimeError, match="missing or unreadable"):
        store.read(digest)


# ---------------------------------------------------------------------------
# encoder: dirty diffing, chain growth, compaction, zero-change reuse
# ---------------------------------------------------------------------------

def _capture(d):
    return KeyedCapture({k: pickle.dumps(v) for k, v in d.items()})


def test_delta_encoder_chain_growth_compaction_and_reuse(tmp_path):
    store = BlobStore(str(tmp_path / "blobs"))
    enc = DeltaEncoder(chain_max=3)
    state = {k: 0.0 for k in range(100)}

    def commit():
        writes = {}
        chain = enc.encode(_capture(state), writes)
        for dg, payload in writes.items():
            store.write(dg, payload)
        return chain, writes

    chain1, w1 = commit()           # first commit: full base
    assert len(chain1) == 1 and chain1[0].base
    base_bytes = chain1[0].nbytes
    # 1% dirty -> one small delta link appended
    state[3] = 1.0
    chain2, w2 = commit()
    assert len(chain2) == 2 and not chain2[1].base
    assert chain2[0] == chain1[0]   # base shared by reference
    assert chain2[1].nbytes < base_bytes / 10
    # an epoch that changed nothing reuses the chain verbatim: zero
    # new bytes staged
    chain3, w3 = commit()
    assert chain3 == chain2 and w3 == {}
    # deleting a key rides a delta link too
    del state[7]
    chain4, _ = commit()
    assert len(chain4) == 3
    # chain_max reached -> next dirty epoch compacts to a fresh base
    state[11] = 2.0
    chain5, _ = commit()
    assert len(chain5) == 1 and chain5[0].base
    # the resolved chain equals the live state at every step
    resolved = {k: pickle.loads(v)
                for k, v in resolve_chain(store, chain5).items()}
    assert resolved == state
    assert 7 not in resolved


def test_resolve_chain_rejects_headless_and_missing_links(tmp_path):
    store = BlobStore(str(tmp_path / "blobs"))
    enc = DeltaEncoder(chain_max=8)
    writes = {}
    chain = enc.encode(_capture({1: "a"}), writes)
    state = {1: "a", 2: "b"}
    chain = enc.encode(_capture(state), writes)
    for dg, payload in writes.items():
        store.write(dg, payload)
    assert len(chain) == 2
    # a chain whose base link went missing raises (the tolerant scan
    # turns this into epoch_abort(blob_missing))
    store.unlink(chain[0].digest)
    with pytest.raises(RuntimeError, match="missing or unreadable"):
        resolve_chain(store, chain)
    # a delta-first chain is structurally invalid
    with pytest.raises(RuntimeError, match="base link missing"):
        resolve_chain(store, [chain[1]])
    assert resolve_chain(store, []) == {}


def test_keyed_marker_payload_roundtrip():
    entries = {k: pickle.dumps(k * 2.0) for k in range(5)}
    blob = pack_keyed(entries)
    doc = pickle.loads(blob)
    assert unpack_keyed(doc) == {k: k * 2.0 for k in range(5)}


# ---------------------------------------------------------------------------
# store-level: commit bytes, GC honoring retention
# ---------------------------------------------------------------------------

def test_delta_commit_bytes_order_of_magnitude_under_low_churn(tmp_path):
    """The headline property at store granularity: under a 1%-dirty
    keyed workload a delta commit writes >= 10x fewer bytes than
    re-pickling the full state each epoch."""
    n_keys = 2000
    state = {k: float(k) for k in range(n_keys)}
    full_store = EpochStore(str(tmp_path / "full"), retained=3)
    delta_store = EpochStore(str(tmp_path / "delta"), retained=3)
    enc = DeltaEncoder(chain_max=8)
    full_bytes, delta_bytes = [], []
    for e in range(1, 7):
        # 1% of keys dirty per epoch
        for k in range(e * 20, e * 20 + n_keys // 100):
            state[k % n_keys] += 1.0
        _, nb = full_store.commit(
            e, {"acc.0": pickle.dumps(state)}, {"src": e})
        full_bytes.append(nb)
        writes = {}
        chain = enc.encode(_capture(state), writes)
        _, nb = delta_store.commit(
            e, {"acc.0": {"keyed_chain": chain}}, {"src": e},
            blob_writes=writes)
        delta_bytes.append(nb)
    # steady state (past the first base blob): >= 10x smaller
    assert sum(delta_bytes[1:]) * 10 <= sum(full_bytes[1:]), \
        (delta_bytes, full_bytes)
    # both stores restore the identical final state
    _, full_payload = full_store.latest()
    _, delta_payload = delta_store.latest()
    assert pickle.loads(full_payload["states"]["acc.0"]) == state
    decoded = pickle.loads(delta_payload["states"]["acc.0"])
    assert unpack_keyed(decoded) == state


def test_blob_gc_honors_retention_and_damage_veto(tmp_path):
    store = EpochStore(str(tmp_path / "ep"), retained=2)
    enc = DeltaEncoder(chain_max=50)  # no compaction: chains only grow
    state = {}
    for e in range(1, 8):
        state[e] = b"x" * 256
        writes = {}
        chain = enc.encode(_capture(state), writes)
        store.commit(e, {"acc.0": {"keyed_chain": chain}}, {},
                     blob_writes=writes)
    # only the retained manifests' chains survive the sweep
    live = set()
    for e in (6, 7):
        m = store._load_raw(e)
        for ref in m["states"]["acc.0"]["keyed_chain"]:
            live.add(ref.digest)
    assert set(store.blobs.digests_on_disk()) == live
    # every retained manifest still resolves after GC
    for e in (6, 7):
        assert store.load(e)["epoch"] == e
    # a damaged retained manifest vetoes the sweep entirely: unknown
    # references must never be deleted
    p = store.manifest_path(6)
    blob = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(blob[: len(blob) // 2])
    before = set(store.blobs.digests_on_disk())
    store._gc_blobs()
    assert set(store.blobs.digests_on_disk()) == before


# ---------------------------------------------------------------------------
# end-to-end: delta pipeline clean run, chaos restart, torn chain
# ---------------------------------------------------------------------------

def test_delta_pipeline_clean_run_exactly_once(tmp_path):
    N = 4000
    effects = []
    g = _acc_graph(N, str(tmp_path), effects, interval=0.04,
                   pace_every=128, pace_s=0.002, delta=True)
    g.run()
    _assert_exactly_once(effects, N, g)
    dur = g.durability
    assert dur.delta and dur.commits >= 2
    # manifests on disk reference blob chains and resolve cleanly
    store = EpochStore(os.path.join(str(tmp_path), "epochs"))
    e, payload = store.latest()
    assert e == dur.committed
    raw = store._load_raw(e)
    chains = [v for v in raw["states"].values()
              if isinstance(v, dict) and "keyed_chain" in v]
    assert chains, "no keyed replica rode the blob-chain path"
    assert store.blobs.digests_on_disk()
    # the stats/doctor surfaces carry the commit sizing
    import json
    block = json.loads(g.stats.to_json())["Durability"]
    assert block["Delta"] and block["Last_commit_bytes"] > 0
    from windflow_tpu.telemetry.metrics import render_openmetrics
    text = render_openmetrics(
        {"1": {"report": json.loads(g.stats.to_json()),
               "active": False}})
    assert "windflow_epoch_commit_bytes{" in text


def test_delta_chaos_restart_exactly_once(tmp_path):
    """Kill-restart through delta manifests: the restored cut resolves
    chains back to per-key state and the rerun is bitwise-equal."""
    N = 4000
    effects = []

    def factory(attempt):
        plan = (FaultPlan(seed=3).crash_replica("accumulator",
                                                at_tuple=1200)
                if attempt == 0 else None)
        return _acc_graph(N, str(tmp_path), effects, fault_plan=plan,
                          delta=True)

    g = run_with_epochs(factory, max_restarts=2)
    assert getattr(g, "_epoch_restored", None) is not None
    assert g._epoch_restored >= 1
    _assert_exactly_once(effects, N, g)
    assert g.durability.committed > g._epoch_restored


def _newest_only_blob(store):
    """A blob digest referenced by the newest manifest but by no older
    retained manifest -- deleting it tears exactly one epoch's chain."""
    from windflow_tpu.durability.delta import chain_refs
    epochs = store._epochs_on_disk()
    assert len(epochs) >= 2, "need at least two committed manifests"
    newest = {r.digest for r in chain_refs(
        store._load_raw(epochs[-1])["states"])}
    older = set()
    for e in epochs[:-1]:
        older |= {r.digest for r in chain_refs(
            store._load_raw(e)["states"])}
    only = newest - older
    assert only, "newest manifest shares every blob with older ones"
    return epochs[-1], sorted(only)[0]


def test_torn_delta_chain_falls_back_with_blob_missing(tmp_path):
    """The tolerant-reader fallback end to end: the newest manifest's
    chain loses a link between crash and restart; recovery records
    ``epoch_abort(blob_missing)``, restores the newest fully-loadable
    epoch, and the idempotent-sink rerun produces zero duplicate or
    lost effects."""
    N = 4000
    store_path = os.path.join(str(tmp_path), "epochs")
    sink_store = EpochTaggedStore()
    torn = {}

    def factory(attempt):
        if attempt == 1:
            # sabotage AFTER the crash, BEFORE recovery reads the
            # manifests: unlink a blob only the newest chain references
            st = EpochStore(store_path)
            torn["epoch"], digest = _newest_only_blob(st)
            st.blobs.unlink(digest)
        plan = (FaultPlan(seed=13).crash_replica("accumulator",
                                                 at_tuple=1600)
                if attempt == 0 else None)

        def acc(t, a):
            a.value += t.value

        cfg = wf.RuntimeConfig(
            durability=DurabilityConfig(epoch_interval_s=0.03,
                                        path=store_path, delta=True),
            fault_plan=plan)
        g = wf.PipeGraph("dur_torn_delta", wf.Mode.DEFAULT, config=cfg)
        g.add_source(CkptSource(N, pace_every=64, pace_s=0.004)) \
            .add(wf.MapBuilder(lambda t: None).with_key_by()
                 .with_parallelism(2).build()) \
            .add(wf.AccumulatorBuilder(acc)
                 .with_initial_value(BasicRecord(value=0.0))
                 .with_parallelism(2).build()) \
            .add_sink(wf.SinkBuilder(sink_store)
                      .with_exactly_once("idempotent").build())
        return g

    g = run_with_epochs(
        factory, max_restarts=2,
        on_restore=lambda g_, e, payload: sink_store.truncate_above(e))
    # the fallback: restored strictly BELOW the torn epoch, with the
    # damage named in the flight ring
    assert getattr(g, "_epoch_restored", None) is not None
    assert g._epoch_restored < torn["epoch"]
    aborts = [e for e in g.flight.snapshot()
              if e["kind"] == "epoch_abort"
              and e.get("reason") == "blob_missing"]
    assert aborts and aborts[0]["epoch"] == torn["epoch"]
    # zero duplicate / lost effects despite replaying the torn gap
    effects = [(r.key, r.id, r.value) for r in sink_store.items()]
    assert len(effects) == N and len(set(effects)) == N
    got, oracle = _per_key(effects), _acc_oracle(N)
    for k in oracle:
        assert sorted(got[k]) == oracle[k]
    # the doctor explains the fallback
    import json
    from windflow_tpu.diagnosis.report import build_report, render_text
    rep = build_report(json.loads(g.stats.to_json()),
                       flight=g.flight.snapshot())
    assert rep["Recovery_fallbacks"]
    assert rep["Recovery_fallbacks"][-1]["reason"] == "blob_missing"
    assert "recovery fell back past" in rep["Verdict"]
    assert "blob_missing" in render_text(rep)


def test_delta_restore_into_different_parallelism(tmp_path):
    """Delta manifests compose with elastic restore: the chain resolves
    to per-key entries, which repartition through hash % n."""
    N = 4000
    effects = []

    def factory(attempt):
        par = 2 if attempt == 0 else 4
        plan = (FaultPlan(seed=5).crash_replica("accumulator",
                                                at_tuple=1200)
                if attempt == 0 else None)
        return _acc_graph(N, str(tmp_path), effects, fault_plan=plan,
                          acc_par=par, delta=True)

    g = run_with_epochs(factory, max_restarts=2,
                        parallelism_overrides={"accumulator": 4})
    assert getattr(g, "_epoch_restored", None) is not None
    _assert_exactly_once(effects, N, g)
    ev = [e for e in g.flight.snapshot() if e["kind"] == "epoch_restore"]
    assert ev and ev[-1].get("repartitioned") == ["accumulator"]
