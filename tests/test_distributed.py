"""Distributed runtime plane (docs/DISTRIBUTED.md): the shared wire
codec + shuffle message layer, the partition planner, the
credit-backpressured shuffle transport (both channel planes), FaultPlan
network actions, per-worker log naming, the merged one-graph view --
and the real 2-process acceptance runs: bitwise-equal NexMark Q5,
drop_link flagged with exact edge + count, a doctor verdict naming a
remote worker's operator, and kill_worker + run-from-epoch recovery
matching the uninterrupted oracle.

NOTE this file doubles as the worker-side build module: the 2-process
tests' build/config functions are imported by fresh worker interpreters
(distributed/runtime._load_ref), so everything at module level must
import cleanly without pytest fixtures, conftest, or JAX.
"""
import collections
import json
import os
import threading
import time

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.core.basic import Pattern, RoutingMode, RuntimeConfig
from windflow_tpu.core.tuples import BasicRecord, TupleBatch
from windflow_tpu.distributed import wire
from windflow_tpu.distributed.partition import (PartitionError,
                                                node_owner, plan_partition)
from windflow_tpu.distributed.runtime import DistributedSpec
from windflow_tpu.distributed.transport import (EdgeState,
                                                RemoteEdgeSender,
                                                ShuffleServer)
from windflow_tpu.operators.base import Operator, StageSpec
from windflow_tpu.resilience import FaultPlan
from windflow_tpu.runtime.emitters import StandardEmitter
from windflow_tpu.runtime.node import EOSMarker, SourceLoopLogic
from windflow_tpu.runtime.queues import EpochBarrier, make_channel

N_KEYS = 8


def _batch(lo, n, keys=N_KEYS):
    i = np.arange(lo, lo + n)
    return TupleBatch({"key": i % keys, "id": i // keys, "ts": i,
                       "value": (i % 13).astype(np.float64)})


# ---------------------------------------------------------------------------
# wire codec: shared framing + shuffle message layer
# ---------------------------------------------------------------------------

class TestWireCodec:
    def test_legacy_import_path_still_works(self):
        from windflow_tpu.ingest import codec as legacy
        b = _batch(0, 100)
        rt = legacy.decode_batch(legacy.encode_batch(b)[8:])
        assert np.array_equal(rt.key, b.key)
        # the shim exposes the whole promoted surface, with a warning
        with pytest.warns(DeprecationWarning):
            assert legacy.MsgDecoder is wire.MsgDecoder
        # and the canonical home is the distributed plane
        assert legacy.encode_batch is wire.encode_batch
        assert legacy.StreamDecoder is wire.StreamDecoder

    def test_msg_roundtrip_fuzzed_partial_frames(self):
        msgs = []
        for i in range(40):
            kind, payload, _c = wire.encode_item(_batch(i * 50, 50))
            msgs.append((kind, i % 3, i + 1, payload))
        msgs.append((wire.MSG_EOS, 0, 41, b""))
        blob = b"".join(wire.encode_msg(*m) for m in msgs)
        rng = np.random.default_rng(7)
        for _ in range(5):
            dec = wire.MsgDecoder()
            got = []
            off = 0
            while off < len(blob):
                n = int(rng.integers(1, 97))
                got.extend(dec.feed(blob[off:off + n]))
                off += n
            assert len(got) == len(msgs)
            for (k, p, s, pl), (k2, p2, s2, pl2) in zip(msgs, got):
                assert (k, p, s) == (k2, p2, s2) and pl == pl2
            assert dec.pending_bytes() == 0

    def test_stream_decoder_fuzzed_partials(self):
        batches = [_batch(i * 100, 100) for i in range(10)]
        blob = b"".join(wire.encode_batch(b) for b in batches)
        rng = np.random.default_rng(3)
        dec = wire.StreamDecoder()
        got = []
        off = 0
        while off < len(blob):
            n = int(rng.integers(1, 61))
            got.extend(dec.feed(blob[off:off + n]))
            off += n
        assert len(got) == len(batches)
        for a, b in zip(got, batches):
            assert np.array_equal(a.key, b.key)
            assert np.array_equal(a["value"], b["value"])

    def test_zero_tuple_frame(self):
        empty = TupleBatch({"key": np.array([], np.int64),
                            "id": np.array([], np.int64),
                            "ts": np.array([], np.int64),
                            "value": np.array([], np.float64)})
        rt = wire.decode_batch(wire.encode_batch(empty)[8:])
        assert len(rt) == 0 and set(rt.cols) == set(empty.cols)
        kind, payload, cost = wire.encode_item(empty)
        assert kind == wire.MSG_DATA and cost == 1  # min credit charge
        item, cost2 = wire.decode_item(kind, payload, "e")
        assert len(item) == 0 and cost2 == 1

    def test_oversized_frame_rejected(self):
        big = wire.encode_msg(wire.MSG_RECORD, 0, 1, b"x" * 256)
        dec = wire.MsgDecoder(max_frame_bytes=64)
        with pytest.raises(ValueError, match="exceeds"):
            dec.feed(big)
        sd = wire.StreamDecoder(max_frame_bytes=64)
        with pytest.raises(ValueError, match="exceeds"):
            sd.feed(wire.encode_batch(_batch(0, 1000)))
        with pytest.raises(ValueError, match="desync"):
            wire.MsgDecoder().feed(b"JUNKJUNKJUNKJUNKJUNKJUNK")

    def test_item_kinds_roundtrip(self):
        rec = BasicRecord(3, 7, 11, 2.5)
        for item, want_kind in (
                (rec, wire.MSG_RECORD),
                (EOSMarker(rec), wire.MSG_RECORD),
                (EpochBarrier(9), wire.MSG_BARRIER),
                (EpochBarrier(-1, final=True), wire.MSG_BARRIER)):
            kind, payload, _c = wire.encode_item(item)
            assert kind == want_kind
            back, _c2 = wire.decode_item(kind, payload, "e")
            if isinstance(item, EpochBarrier):
                assert type(back) is EpochBarrier
                assert (back.epoch, back.final) == (item.epoch, item.final)
            elif isinstance(item, EOSMarker):
                assert isinstance(back, EOSMarker)
                assert back.record.key == rec.key
            else:
                assert (back.key, back.id, back.value) == (3, 7, 2.5)

    def test_trace_rides_the_frame_as_wire_hop(self):
        from windflow_tpu.telemetry.trace import TraceContext
        b = _batch(0, 10)
        t0 = time.perf_counter() - 0.050
        ctx = TraceContext("pipe0/src", t0)
        ctx.hop("pipe0/map", t0 + 0.010, t0 + 0.030)
        b.trace = ctx
        kind, payload, _c = wire.encode_item(b)
        assert b.trace is ctx  # sender-side context untouched
        item, _cost = wire.decode_item(kind, payload, "pipe0/agg.0")
        rb = item.trace
        assert rb is not None and rb.src == "pipe0/src"
        names = [h[0] for h in rb.hops]
        assert names == ["pipe0/map", "pipe0/agg.0@wire"]
        # rebased offsets survive the boundary (~10ms hop arrival)
        a = rb.hops[0][1] - rb.t0
        assert 0.005 < a < 0.02
        # attribution charges the crossing to the 'wire' class
        from windflow_tpu.diagnosis.attribution import trace_breakdown
        t_end = time.perf_counter()
        bd = trace_breakdown(rb.to_dict(t_end))
        assert bd is not None and bd["classes"]["wire"] > 0.0

    def test_attribution_classes_sum_with_wire(self):
        from windflow_tpu.diagnosis.attribution import trace_breakdown
        rec = {"e2e_ms": 10.0,
               "hops": [["src", 0.0, 1.0], ["agg.0@wire", 1.0, 5.0],
                        ["agg.0", 6.0, 9.0]]}
        bd = trace_breakdown(rec)
        total = sum(bd["classes"].values())
        assert abs(total - 10.0) < 1e-6
        assert abs(bd["classes"]["wire"] - 4.0) < 1e-6
        # the 5->6 gap before agg's arrival + the 9->10 trailing close
        assert abs(bd["classes"]["queueing"] - 2.0) < 1e-6
        assert abs(bd["classes"]["service"] - 4.0) < 1e-6


# ---------------------------------------------------------------------------
# partition planner
# ---------------------------------------------------------------------------

def _keyed_pipeline(g, acc_par=2):
    out = []

    def src(shipper):
        return False

    def fold(t, acc):
        acc.value += t.value

    g.add_source(wf.SourceBuilder(src).with_name("psrc").build()) \
        .add(wf.AccumulatorBuilder(fold).with_name("pfold")
             .with_parallelism(acc_par).build()) \
        .add_sink(wf.SinkBuilder(out.append).with_name("psink").build())
    return g


class TestPartition:
    def test_auto_cut_at_keyby_edge(self):
        g = _keyed_pipeline(wf.PipeGraph("p"))
        plan = plan_partition(g, 2)
        assert plan["pipe0/psrc"] == 0
        assert plan["pipe0/pfold.0"] == plan["pipe0/pfold.1"] \
            == plan["pipe0/psink.0"] == 1

    def test_single_worker_collapses(self):
        g = _keyed_pipeline(wf.PipeGraph("p1"))
        plan = plan_partition(g, 1)
        assert set(plan.values()) == {0}

    def test_forward_chain_stays_colocated(self):
        g = wf.PipeGraph("pf")
        g.add_source(wf.SourceBuilder(lambda s: False)
                     .with_name("fsrc").build()) \
            .add(wf.MapBuilder(lambda t: t).with_name("fmap").build()) \
            .add_sink(wf.SinkBuilder(lambda r: None)
                      .with_name("fsink").build())
        plan = plan_partition(g, 2)
        assert len(set(plan.values())) == 1  # no shuffle edge: no cut

    def test_pins_cut_forward_edges(self):
        g = wf.PipeGraph("pp")
        g.add_source(wf.SourceBuilder(lambda s: False)
                     .with_name("asrc").with_worker(0).build()) \
            .add(wf.MapBuilder(lambda t: t).with_name("amap")
                 .with_worker(1).build()) \
            .add_sink(wf.SinkBuilder(lambda r: None)
                      .with_name("asink").build())
        plan = plan_partition(g, 2)
        assert plan["pipe0/asrc"] == 0
        assert plan["pipe0/amap.0"] == 1
        assert plan["pipe0/asink.0"] == 1  # FORWARD glue follows the pin

    def test_conflicting_pins_in_one_group_raise(self):
        g = wf.PipeGraph("pc")
        g.add_source(wf.SourceBuilder(lambda s: False)
                     .with_name("csrc").build()) \
            .add(wf.MapBuilder(lambda t: t).with_name("cmap").build()) \
            .add_sink(wf.SinkBuilder(lambda r: None)
                      .with_name("csink").build())
        with pytest.raises(PartitionError, match="conflicting"):
            plan_partition(g, 2, overrides={"csrc": 0, "csink": 1})

    def test_override_assignment_beats_auto(self):
        g = _keyed_pipeline(wf.PipeGraph("po"))
        plan = plan_partition(g, 2, overrides={"pfold": 0, "psrc": 1})
        assert plan["pipe0/psrc"] == 1
        assert plan["pipe0/pfold.0"] == 0

    def test_pin_survives_chaining(self):
        g = wf.PipeGraph("pch")
        g.add_source(wf.SourceBuilder(lambda s: False)
                     .with_name("hsrc").build()) \
            .add(wf.MapBuilder(lambda t: t).with_name("hmap").build()) \
            .chain_sink(wf.SinkBuilder(lambda r: None)
                        .with_name("hsink").with_worker(1).build())
        # the sink fused into the map's node; its pin must pin the
        # merged node (and, via FORWARD glue, the whole group)
        plan = plan_partition(g, 2)
        assert set(plan.values()) == {1}

    def test_fusion_respects_partition(self):
        from windflow_tpu.graph.fuse import fuse_graph
        g = wf.PipeGraph("pfz")
        g.add_source(wf.SourceBuilder(lambda s: False)
                     .with_name("zsrc").with_worker(0).build()) \
            .add(wf.MapBuilder(lambda t: t).with_name("zmap")
                 .with_worker(1).build()) \
            .add_sink(wf.SinkBuilder(lambda r: None)
                      .with_name("zsink").with_worker(1).build())
        plan = plan_partition(g, 2)
        fuse_graph(g)
        for n in g._all_nodes():
            node_owner(n, plan)  # raises if a fused node straddles


# ---------------------------------------------------------------------------
# shuffle transport, in-process over loopback (both channel planes)
# ---------------------------------------------------------------------------

def _planes():
    planes = ["python"]
    from windflow_tpu.runtime.native import native_available
    if native_available():
        planes.append("native")
    return planes


def _channel_for(plane, capacity=2048):
    cfg = RuntimeConfig(queue_capacity=capacity,
                        use_native_runtime=(plane == "native"))
    return make_channel(cfg)


class _Rig:
    """One in-process shuffle edge: consumer graph + server on worker 1,
    producer graph + sender on worker 0."""

    EDGE = "pipe0/rig_sink.0"

    def __init__(self, plane, n_pids=2, capacity=2048, wire_credits=1 << 15,
                 grace_s=0.5, faults=None):
        self.chan = _channel_for(plane, capacity)
        self.pids = [self.chan.register_producer() for _ in range(n_pids)]
        self.cgraph = wf.PipeGraph("rig_consumer")
        self.pgraph = wf.PipeGraph("rig_producer")
        cspec = DistributedSpec(1, 2, [("127.0.0.1", 0), ("127.0.0.1", 0)],
                                reconnect_grace_s=grace_s)
        self.edge = EdgeState(self.EDGE, self.chan, {0: set(self.pids)})
        self.server = ShuffleServer(self.cgraph, cspec,
                                    {self.EDGE: self.edge})
        self.server.start()
        pspec = DistributedSpec(0, 2, [("127.0.0.1", 0),
                                       ("127.0.0.1", self.server.port)],
                                wire_credits=wire_credits)
        self.sender = RemoteEdgeSender(self.EDGE, "127.0.0.1",
                                       self.server.port, self.pgraph,
                                       self.pids, pspec)
        if faults is not None:
            self.sender.faults = faults.for_link(self.EDGE)

    def drain(self, timeout=10.0):
        out = []
        deadline = time.monotonic() + timeout
        while True:
            got = self.chan.get(timeout=0.2)
            if got is None:
                return out
            if isinstance(got, tuple):
                out.append(got)
            if time.monotonic() > deadline:
                raise AssertionError(f"drain timed out with {len(out)}")

    def close(self):
        self.server.stop()


@pytest.mark.parametrize("plane", _planes())
class TestTransport:
    def test_roundtrip_data_records_eos(self, plane):
        rig = _Rig(plane)
        try:
            for i in range(10):
                rig.sender.put(rig.pids[i % 2], _batch(i * 64, 64))
            rig.sender.put(rig.pids[0], BasicRecord(1, 2, 3, 4.0))
            for pid in rig.pids:
                rig.sender.close(pid)
            got = rig.drain()
            batches = [it for _pid, it in got
                       if isinstance(it, TupleBatch)]
            recs = [it for _pid, it in got
                    if isinstance(it, BasicRecord)]
            assert len(batches) == 10 and len(recs) == 1
            assert sum(len(b) for b in batches) == 640
            assert rig.sender.flush(5.0)      # every frame acked
            assert rig.sender.tuples_sent == 641
            assert rig.sender.gets == rig.sender.puts
            assert rig.sender.qsize() == 0
            # credits fully replenished once the consumer drained
            deadline = time.monotonic() + 2.0
            while rig.sender.gate.available < rig.sender.gate.budget:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            rows = rig.edge.blocks()
            assert sum(r["tuples"] for r in rows) == 641
            assert rig.edge.completed
            assert not rig.cgraph._cancel.cancelled
        finally:
            rig.close()

    def test_credit_backpressure_throttles_producer(self, plane):
        rig = _Rig(plane, n_pids=1, capacity=4, wire_credits=8)
        try:
            sent = []

            def producer():
                for i in range(64):
                    rig.sender.put(rig.pids[0], _batch(i, 1))
                    sent.append(i)
                rig.sender.close(rig.pids[0])

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            time.sleep(0.8)
            # consumer never polled: the producer must be credit-stalled
            # well short of the stream (window + channel bound)
            assert len(sent) < 40
            stalled_at = len(sent)
            got = rig.drain()
            t.join(timeout=5.0)
            assert not t.is_alive()
            assert len(got) == 64 > stalled_at
            assert rig.sender.gate.credit_waits > 0
        finally:
            rig.close()

    def test_reconnect_mid_stream_no_loss_no_dup(self, plane):
        rig = _Rig(plane, n_pids=1)
        try:
            for i in range(10):
                rig.sender.put(rig.pids[0], _batch(i * 10, 10))
            assert rig.sender.flush(5.0)
            # transport blip: kill the socket under the sender
            sock = rig.sender._sock
            assert sock is not None
            sock.close()
            for i in range(10, 20):
                rig.sender.put(rig.pids[0], _batch(i * 10, 10))
            rig.sender.close(rig.pids[0])
            got = rig.drain()
            ids = sorted(int(b.ts[0]) for _pid, b in got)
            assert ids == [i * 10 for i in range(20)]  # exactly once
            assert rig.sender.reconnects >= 1
            assert not rig.cgraph._cancel.cancelled
            assert rig.edge.completed
        finally:
            rig.close()

    def test_broken_link_cancels_consumer_after_grace(self, plane):
        rig = _Rig(plane, n_pids=1, grace_s=0.3)
        try:
            rig.sender.put(rig.pids[0], _batch(0, 5))
            assert rig.sender.flush(5.0)
            rig.sender._cancelled = True   # producer goes silent...
            rig.sender._close_sock()       # ...and the socket dies
            deadline = time.monotonic() + 5.0
            while not rig.cgraph._cancel.cancelled:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert "rig_sink" in str(rig.cgraph._cancel.reason)
        finally:
            rig.close()

    def test_drop_link_flags_edge_and_count(self, plane):
        plan = FaultPlan().drop_link("rig_sink", at_frame=3)
        rig = _Rig(plane, n_pids=1, faults=plan)
        try:
            for i in range(6):
                rig.sender.put(rig.pids[0], _batch(i * 10, 10))
            rig.sender.close(rig.pids[0])
            got = rig.drain()
            assert len(got) == 5          # frame 3 lost on the wire
            assert rig.sender.frames_dropped == 1
            assert rig.sender.tuples_sent == 60
            rows = rig.edge.blocks()
            assert rows[0]["tuples"] == 50 and rows[0]["gaps"] == 1
            # the consumer flags the loss online with edge + count
            events = rig.cgraph.flight.snapshot()
            assert any(e.get("kind") == "wire_gap"
                       and e.get("edge") == "pipe0/rig_sink.0"
                       for e in events)
            assert any(e.get("kind") == "conservation_violation"
                       and e.get("edge") == "pipe0/rig_sink.0"
                       and e.get("count") == 10
                       for e in events)
        finally:
            rig.close()

    def test_delay_link_applies(self, plane):
        plan = FaultPlan().delay_link("rig_sink", delay_ms=40, every_n=2)
        rig = _Rig(plane, n_pids=1, faults=plan)
        try:
            t0 = time.monotonic()
            for i in range(6):
                rig.sender.put(rig.pids[0], _batch(i, 4))
            elapsed = time.monotonic() - t0
            assert elapsed >= 0.10        # 3 delayed frames x 40ms
            rig.sender.close(rig.pids[0])
            assert len(rig.drain()) == 6
        finally:
            rig.close()

    def test_barriers_ride_frames(self, plane):
        rig = _Rig(plane, n_pids=2)
        try:
            rig.sender.put(rig.pids[0], _batch(0, 8))
            rig.sender.put(rig.pids[0], EpochBarrier(1))
            rig.sender.put(rig.pids[1], EpochBarrier(1))
            for pid in rig.pids:
                rig.sender.close(pid)
            got = rig.drain()
            barriers = [(pid, it) for pid, it in got
                        if type(it) is EpochBarrier]
            assert len(barriers) == 2
            assert {pid for pid, _ in barriers} == set(rig.pids)
            assert all(b.epoch == 1 for _pid, b in barriers)
            assert rig.sender.barriers_sent == 2
            rows = rig.edge.blocks()
            assert sum(r["barriers"] for r in rows) == 2
        finally:
            rig.close()


# ---------------------------------------------------------------------------
# per-worker log/snapshot naming + merged view
# ---------------------------------------------------------------------------

class TestWorkerArtifacts:
    def test_worker_suffix_in_flight_dump(self, tmp_path, monkeypatch):
        from windflow_tpu.telemetry.recorder import FlightRecorder
        monkeypatch.setenv("WINDFLOW_WORKER_ID", "3")
        fr = FlightRecorder(8)
        fr.record("x", a=1)
        path = fr.dump(str(tmp_path), "gname")
        assert path.endswith(f"{os.getpid()}_gname_w3_flight.jsonl")
        monkeypatch.delenv("WINDFLOW_WORKER_ID")
        path2 = fr.dump(str(tmp_path), "gname")
        assert path2.endswith(f"{os.getpid()}_gname_flight.jsonl")

    def test_worker_identity_helpers(self, monkeypatch):
        from windflow_tpu.distributed.identity import (worker_id,
                                                       worker_suffix)
        monkeypatch.delenv("WINDFLOW_WORKER_ID", raising=False)
        assert worker_id() is None and worker_suffix() == ""
        monkeypatch.setenv("WINDFLOW_WORKER_ID", "7")
        assert worker_id() == 7 and worker_suffix() == "_w7"
        monkeypatch.setenv("WINDFLOW_WORKER_ID", "junk")
        assert worker_id() is None

    def test_merge_stats_flags_wire_imbalance(self):
        from windflow_tpu.distributed.observe import (
            check_wire_conservation, merge_stats)
        w0 = {"PipeGraph_name": "g", "Worker": 0, "Schema_version": 5,
              "Operators": [{"Operator_name": "pipe0/src",
                             "Replicas": []}],
              "Wire": {"Worker": 0, "in": [], "out": [
                  {"edge": "pipe0/agg.0", "tuples": 100, "frames": 12,
                   "barriers": 0, "dropped_frames": 1}]}}
        w1 = {"PipeGraph_name": "g", "Worker": 1, "Schema_version": 5,
              "Operators": [{"Operator_name": "pipe0/agg",
                             "Replicas": []}],
              "Conservation": {"Edges_balanced": True,
                               "Final_check": True},
              "Wire": {"Worker": 1, "out": [], "in": [
                  {"edge": "pipe0/agg.0", "from_worker": 0,
                   "tuples": 90, "frames": 11, "barriers": 0,
                   "gaps": 1}]}}
        merged = merge_stats([w0, w1])
        assert merged["Operator_number"] == 2
        assert {op["Worker"] for op in merged["Operators"]} == {0, 1}
        wire_block = merged["Wire"]
        assert not wire_block["Balanced"]
        row = wire_block["Edges"][0]
        assert row["edge"] == "pipe0/agg.0"
        assert row["missing_tuples"] == 10
        v = merged["Conservation"]["Violations"]
        assert any(x["kind"] == "lost_wire_delivery"
                   and x["edge"] == "pipe0/agg.0" and x["count"] == 10
                   for x in v)
        assert check_wire_conservation([w0, w1]) \
            == [{"kind": "lost_wire_delivery", "edge": "pipe0/agg.0",
                 "count": 10}]


# ---------------------------------------------------------------------------
# 2-process runs (real worker processes over localhost)
# ---------------------------------------------------------------------------

def _dist_records(n):
    for i in range(n):
        yield i % N_KEYS, i // N_KEYS, i, float(i % 13)


def _acc_oracle(n):
    out = collections.defaultdict(list)
    sums = collections.defaultdict(float)
    for k, tid, _ts, v in _dist_records(n):
        sums[k] += v
        out[k].append((tid, sums[k]))
    return dict(out)


def _keyed_build(g, sink_fn, pace_every=0, pace_s=0.0,
                 fold_name="dist_fold"):
    """source -> KEYBY rolling fold (2 replicas) -> sink."""
    import windflow_tpu as _wf
    from windflow_tpu.core.tuples import BasicRecord as _Rec
    n = int(os.environ["WFT_DIST_N"])
    it = iter(enumerate(_dist_records(n)))

    def src(shipper):
        for i, (k, tid, ts, v) in it:
            if pace_every and i % pace_every == 0:
                time.sleep(pace_s)
            shipper.push(_Rec(k, tid, ts, v))
            return True
        return False

    def fold(t, acc):
        acc.value += t.value

    g.add_source(_wf.SourceBuilder(src).with_name("dist_src").build()) \
        .add(_wf.AccumulatorBuilder(fold).with_name(fold_name)
             .with_parallelism(2).build()) \
        .add_sink(sink_fn)
    return g


def _rows_sink(out_path):
    import windflow_tpu as _wf
    rows = []

    def sink(rec):
        if rec is None:
            with open(out_path, "w") as f:
                json.dump(sorted(rows), f)
        else:
            rows.append([rec.key, rec.id, rec.value])

    return _wf.SinkBuilder(sink).with_name("dist_sink").build()


# -- worker-side build/config functions (imported by worker processes) --

def build_basic(g):
    _keyed_build(g, _rows_sink(os.environ["WFT_DIST_OUT"]))


def config_counters(worker_id):
    # stats records without per-item trace stamping: the merged view
    # needs Operators rows, not sampled traces
    return RuntimeConfig(tracing=True, trace_sample=0,
                         log_dir=os.environ.get("WFT_LOG_DIR", "log"))


def config_drop_link(worker_id):
    plan = FaultPlan().drop_link("dist_fold", at_frame=5)
    return RuntimeConfig(fault_plan=plan,
                         log_dir=os.environ.get("WFT_LOG_DIR", "log"))


def build_slow_remote(g):
    import windflow_tpu as _wf
    out_path = os.environ["WFT_DIST_OUT"]
    n = int(os.environ["WFT_DIST_N"])
    it = iter(range(n))

    def src(shipper):
        for i in it:
            shipper.push(BasicRecord(i % N_KEYS, i // N_KEYS, i,
                                     float(i % 13)))
            return True
        return False

    def slow(t):
        time.sleep(0.001)
        return t

    done = []

    def sink(rec):
        if rec is None:
            with open(out_path, "w") as f:
                json.dump({"count": len(done)}, f)
        else:
            done.append(1)

    g.add_source(_wf.SourceBuilder(src).with_name("fast_src").build()) \
        .add(_wf.MapBuilder(slow).with_name("slow_remote")
             .with_key_by().build()) \
        .add_sink(_wf.SinkBuilder(sink).with_name("obs_sink").build())


def config_traced(worker_id):
    return RuntimeConfig(tracing=True, trace_sample=32,
                         log_dir=os.environ.get("WFT_LOG_DIR", "log"))


class FileEpochWriter:
    """File-backed idempotent sink target (``write(epoch, item)``):
    every effect appends as a JSONL row tagged (attempt, epoch); a
    restarted attempt first appends a truncation marker carrying its
    restore epoch, and :func:`resolve_epoch_file` replays markers in
    order -- exactly ``EpochTaggedStore.truncate_above`` applied at
    read time, which is what makes effects durable across worker
    processes."""

    def __init__(self, path=None):
        self.path = path or os.environ["WFT_DIST_OUT"]
        self.attempt = int(os.environ.get("WINDFLOW_DIST_ATTEMPT", "0"))
        restore = int(os.environ.get("WINDFLOW_DIST_RESTORE", "0"))
        with open(self.path, "a") as f:
            f.write(json.dumps({"marker": True, "a": self.attempt,
                                "truncate_above": restore}) + "\n")

    def write(self, epoch, item):
        with open(self.path, "a") as f:
            f.write(json.dumps({"a": self.attempt, "e": epoch,
                                "k": item.key, "t": item.id,
                                "v": item.value}) + "\n")
            f.flush()
            os.fsync(f.fileno())


def resolve_epoch_file(path):
    """Fold the JSONL effect log: each attempt's truncation marker
    drops earlier attempts' rows above its restore epoch (the
    uncommitted tail a crashed attempt applied)."""
    rows = []
    with open(path) as f:
        for line in f:
            doc = json.loads(line)
            if doc.get("marker"):
                rows = [r for r in rows
                        if r["e"] <= doc["truncate_above"]]
            else:
                rows.append(doc)
    return rows


class _DistCkptSourceLogic(SourceLoopLogic):
    def __init__(self, n, pace_every, pace_s):
        self.i = 0
        self.n = n
        self.pace_every = pace_every
        self.pace_s = pace_s
        super().__init__(self._step)

    def _step(self, emit):
        i = self.i
        if i >= self.n:
            return False
        if self.pace_every and i % self.pace_every == 0:
            time.sleep(self.pace_s)
        emit(BasicRecord(i % N_KEYS, i // N_KEYS, i, float(i % 13)))
        self.i = i + 1
        return True

    def state_dict(self):
        return {"i": self.i}

    def load_state(self, st):
        self.i = st["i"]

    def progress_frontier(self):
        return self.i


class DistCkptSource(Operator):
    def __init__(self, n, pace_every=8, pace_s=0.003):
        super().__init__("dur_src", 1, RoutingMode.NONE, Pattern.SOURCE)
        self.n = n
        self.pace_every = pace_every
        self.pace_s = pace_s

    def stages(self):
        logic = _DistCkptSourceLogic(self.n, self.pace_every, self.pace_s)
        return [StageSpec(self.name, [logic], StandardEmitter(),
                          self.routing)]


def build_durable(g):
    import windflow_tpu as _wf
    n = int(os.environ["WFT_DIST_N"])

    def fold(t, acc):
        acc.value += t.value

    g.add_source(DistCkptSource(n)) \
        .add(_wf.AccumulatorBuilder(fold).with_name("dur_fold")
             .with_parallelism(2).build()) \
        .add_sink(_wf.SinkBuilder(FileEpochWriter())
                  .with_exactly_once("idempotent")
                  .with_name("dur_sink").build())


def config_durable(worker_id):
    from windflow_tpu.core import DurabilityConfig
    plan = FaultPlan()
    kill_at = int(os.environ.get("WFT_KILL_AT", "0"))
    if kill_at:
        plan.kill_worker(0, at_tuple=kill_at)
    return RuntimeConfig(
        durability=DurabilityConfig(
            epoch_interval_s=0.05,
            path=os.environ["WFT_EPOCH_DIR"], retained=64),
        fault_plan=plan,
        log_dir=os.environ.get("WFT_LOG_DIR", "log"))


def build_q5(g):
    from windflow_tpu.models.nexmark import build_q5_hot_items
    out_path = os.environ["WFT_Q5_OUT"]
    n = int(os.environ["WFT_Q5_N"])
    rows = []

    def sink(item):
        if item is None:
            with open(out_path, "w") as f:
                json.dump(sorted(rows), f)
            return
        if isinstance(item, TupleBatch):
            for j in range(len(item)):
                rows.append([int(item.key[j]), int(item.id[j]),
                             float(item["value"][j])])
        else:
            rows.append([int(item.key), int(item.id),
                         float(item.value)])

    build_q5_hot_items(g, n, 8192, 4096, sink, n_auctions=40,
                       batch_size=16_384, device_batch=512,
                       parallelism=2, placement="host")


def config_q5(worker_id):
    return RuntimeConfig(log_dir=os.environ.get("WFT_LOG_DIR", "log"))


@pytest.fixture()
def dist_env(tmp_path, monkeypatch):
    monkeypatch.setenv("WFT_LOG_DIR", str(tmp_path / "log"))
    return tmp_path


class TestTwoProcess:
    def test_smoke_bitwise_and_balanced(self, dist_env):
        from windflow_tpu.distributed import smoke
        assert smoke.main(["6000"]) == 0

    def test_keyed_run_matches_local_and_ledger_closes(self, dist_env,
                                                       monkeypatch):
        from windflow_tpu.distributed.runtime import run_distributed
        n = 4000
        out = dist_env / "rows.json"
        monkeypatch.setenv("WFT_DIST_N", str(n))
        monkeypatch.setenv("WFT_DIST_OUT", str(out))
        report = run_distributed(
            build_basic, n_workers=2, config_fn=config_counters,
            graph_name="tp_basic",
            workdir=str(dist_env / "work"), timeout_s=120.0)
        got = json.loads(out.read_text())
        per_key = collections.defaultdict(list)
        for k, tid, v in got:
            per_key[k].append((tid, v))
        assert {k: sorted(vs) for k, vs in per_key.items()} \
            == {k: v for k, v in _acc_oracle(n).items()}
        merged = report["merged"]
        assert merged["Wire"]["Balanced"]
        assert merged["Conservation"]["Edges_balanced"]
        assert merged["Conservation"]["Final_check"]
        # one logical graph, two workers, disjoint operator sets
        assert {op["Worker"] for op in merged["Operators"]} == {0, 1}

    def test_drop_link_flagged_with_exact_edge_and_count(self, dist_env,
                                                         monkeypatch):
        from windflow_tpu.distributed.runtime import run_distributed
        n = 2000
        out = dist_env / "rows.json"
        monkeypatch.setenv("WFT_DIST_N", str(n))
        monkeypatch.setenv("WFT_DIST_OUT", str(out))
        report = run_distributed(
            build_basic, n_workers=2, config_fn=config_drop_link,
            graph_name="tp_drop", workdir=str(dist_env / "work"),
            timeout_s=120.0)
        merged = report["merged"]
        assert not merged["Wire"]["Balanced"]
        bad = [r for r in merged["Wire"]["Edges"] if not r["balanced"]]
        # frame 5 of EACH fold replica's edge was a 1-record DATA frame
        assert sorted(r["edge"] for r in bad) \
            == ["pipe0/dist_fold.0", "pipe0/dist_fold.1"]
        assert all(r["missing_tuples"] == 1 for r in bad)
        assert all(r["dropped_frames"] == 1 for r in bad)
        # ...and the consumer worker flagged it ONLINE, per edge
        v = merged["Conservation"]["Violations"]
        for edge in ("pipe0/dist_fold.0", "pipe0/dist_fold.1"):
            assert any(x["kind"] == "lost_wire_delivery"
                       and x["edge"] == edge and x["count"] == 1
                       for x in v)
        got = json.loads(out.read_text())
        assert len(got) == n - 2          # exactly the dropped tuples

    def test_doctor_names_remote_bottleneck(self, dist_env, monkeypatch):
        from windflow_tpu.diagnosis.report import build_report
        from windflow_tpu.distributed.runtime import run_distributed
        n = 2600
        out = dist_env / "obs.json"
        monkeypatch.setenv("WFT_DIST_N", str(n))
        monkeypatch.setenv("WFT_DIST_OUT", str(out))
        report = run_distributed(
            build_slow_remote, n_workers=2, config_fn=config_traced,
            graph_name="tp_doctor", workdir=str(dist_env / "work"),
            timeout_s=180.0)
        merged = report["merged"]
        # the slow operator lives on the REMOTE worker (not the source's)
        by_name = {op["Operator_name"]: op for op in merged["Operators"]}
        assert by_name["pipe0/slow_remote"]["Worker"] == 1
        assert by_name["pipe0/fast_src"]["Worker"] == 0
        rep = build_report(merged)
        assert rep["Bottleneck"]["Operator"] == "pipe0/slow_remote"
        assert rep["Bottleneck"]["Verdict"] in ("backpressure",
                                                "mild_pressure",
                                                "service_bound")
        # the doctor CLI folds the same per-worker dumps with --merge
        from windflow_tpu.doctor import main as doctor_main
        rc = doctor_main([*report["stats_paths"], "--merge"])
        assert rc == 0

    def test_kill_worker_epoch_restart_matches_oracle(self, dist_env,
                                                      monkeypatch):
        from windflow_tpu.distributed.runtime import run_distributed
        from windflow_tpu.distributed.wiring import KILL_EXIT
        n = 4000
        out = dist_env / "effects.jsonl"
        monkeypatch.setenv("WFT_DIST_N", str(n))
        monkeypatch.setenv("WFT_DIST_OUT", str(out))
        monkeypatch.setenv("WFT_EPOCH_DIR", str(dist_env / "epochs"))
        monkeypatch.setenv("WFT_KILL_AT", "2000")
        report = run_distributed(
            build_durable, n_workers=2, config_fn=config_durable,
            graph_name="tp_kill", workdir=str(dist_env / "work"),
            max_restarts=2, timeout_s=240.0)
        assert report["attempts"] >= 2
        assert report["exit_codes"][0][0] == KILL_EXIT  # the kill fired
        # the restarted fleet resumed from a committed epoch, not zero
        restores = [e for e in report["merged"].get("Flight") or []
                    if e.get("kind") == "epoch_restore"]
        assert restores and all(e["epoch"] >= 1 for e in restores)
        rows = resolve_epoch_file(out)
        per_key = collections.defaultdict(list)
        for r in rows:
            per_key[r["k"]].append((r["t"], r["v"]))
        oracle = _acc_oracle(n)
        assert {k: sorted(set(vs)) for k, vs in per_key.items()} \
            == {k: v for k, v in oracle.items()}
        # exactly-once: no duplicates survive the restart either
        for k, vs in per_key.items():
            assert len(vs) == len(set(vs)) == len(oracle[k])
        assert report["merged"]["Wire"]["Balanced"]

    def test_nexmark_q5_bitwise_equal_two_process(self, dist_env,
                                                  monkeypatch):
        from windflow_tpu.distributed.runtime import run_distributed
        n = 60_000
        monkeypatch.setenv("WFT_Q5_N", str(n))
        # oracle: the SAME build, single process, in this interpreter
        local_out = dist_env / "q5_local.json"
        monkeypatch.setenv("WFT_Q5_OUT", str(local_out))
        g = wf.PipeGraph("q5_local",
                         config=config_q5(0))
        build_q5(g)
        g.run()
        dist_out = dist_env / "q5_dist.json"
        monkeypatch.setenv("WFT_Q5_OUT", str(dist_out))
        report = run_distributed(
            build_q5, n_workers=2, config_fn=config_q5,
            graph_name="tp_q5", workdir=str(dist_env / "work"),
            timeout_s=240.0)
        # bitwise equality of the serialized result sets
        assert dist_out.read_bytes() == local_out.read_bytes()
        merged = report["merged"]
        assert merged["Wire"]["Balanced"]
        assert merged["Conservation"]["Edges_balanced"]
        assert merged["Conservation"]["Final_check"]
        # KeyFarmTPU coalesces to one engine replica (farms_tpu), so
        # Q5's shuffle is one wire edge carrying every bid; the
        # 2-replica-edge case is covered by the keyed-run test above
        wire_edges = merged["Wire"]["Edges"]
        assert len(wire_edges) >= 1
        assert sum(r["tuples_sent"] for r in wire_edges) >= n
