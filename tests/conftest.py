"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
tests run on XLA's host platform with 8 virtual devices (the driver
separately dry-run-compiles the multi-chip path via __graft_entry__).
Must run before the first jax import anywhere in the test process.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
