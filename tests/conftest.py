"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
tests run on XLA's host platform with 8 virtual devices (the driver
separately dry-run-compiles the multi-chip path via __graft_entry__).

The environment may pin JAX_PLATFORMS to a hardware plugin at
interpreter start; ``jax.config.update`` after import takes precedence,
and XLA_FLAGS must be set before the backend initializes (it does so
lazily, so doing it here is early enough).
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: high-cardinality soaks -- deselected by the tier-1 "
        "\"-m 'not slow'\" gate, run by the dedicated CI soak steps")
